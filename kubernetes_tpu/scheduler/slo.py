"""Declarative latency SLOs over the observability stack — regression GATES.

ROADMAP direction 5: the PR 3/PR 5 observability was a dashboard; this turns
it into assertions. A spec is a plain dict (JSON-serializable, loadable from
a file for `ktl sched slo --spec`):

    {
      "stage_p99_ms":         {"solve": 5000, "bind": 8000, ...},
      "submit_to_bound_p99_s": 30.0,
      "solver_compiles":       0,
      "instrumentation_frac":  0.02
    }

  stage_p99_ms           per-stage p99 ceilings in ms, checked against the
                         flight recorder's stage table (flightrec.py). A
                         stage absent from the stats is a SKIP, not a pass —
                         except that a FAILED check it would have produced is
                         exactly what the consumer must decide about, so
                         skips are reported separately.
  submit_to_bound_p99_s  ceiling on the all-pods submit->bound p99
                         (scheduler/podtrace.py latency histogram).
  watch_propagation_p99_s  ceiling on the store watch bus's commit->dequeue
                         p99 across all kinds (ISSUE 9; the "watch" section
                         of a stats payload — sched_stats() or the bench's
                         assembled dict — carries the settled summary).
  reconcile_p99_ms       ceiling on the WORST controller's per-loop sync
                         p99 (obs/reconcile.py rollup: one dark-slow
                         controller must fail the ceiling, not be averaged
                         away). SKIPs on a payload with no "reconcile"
                         section — a live `ktl sched slo` has one scheduler,
                         not the controller registry.
  solver_compiles        max jit compiles inside the measured window (the
                         retrace guard as an SLO; needs the caller to supply
                         the count via `extra` — bench.py does, a live `ktl
                         sched slo` cannot and the check reports SKIP).
  instrumentation_frac   recorder+tracer self-time / wall ceiling (the <2%
                         budget as a first-class SLO; also `extra`-supplied).

Steady-state trend/leak gates (ISSUE 13) — these consume the "windows"
section (obs/timeseries.py window records, each carrying per-stage p50/p99
and the resource-sampler probe columns), so they see the SHAPE of a run over
time where the whole-run keys above only see its aggregate:

  stage_p99_ms_per_window  per-stage ceiling checked against EVERY window's
                         p99 (actual = the worst window) — a single stalled
                         window fails even when the whole-run p99 absorbs it.
  rss_slope_mb_per_min   least-squares slope over the windows' rss_mb in
                         MB/minute — the heap-pin detector (the PR-11
                         parked-bind-worker class). FAILS on sustained
                         growth; a flat-but-high RSS passes (capacity is a
                         different spec).
  alloc_block_slope_per_s  slope over sys.getallocatedblocks() per second —
                         the deterministic live-OBJECT leak signal (RSS is
                         allocator-noisy; leaked objects always grow this).
  p99_drift_ratio        worst over stages of median(last third of window
                         p99s) / median(first third) — "is the tail creeping
                         under steady load". Sub-millisecond stages are
                         excluded (pure noise); monotonic growth reads >1.

Trend checks SKIP (reported, never silently passed) under
TREND_MIN_WINDOWS windows — a slope over two points is an opinion.

evaluate_slo() consumes a sched_stats()-shaped payload (the /debug/schedstats
document, or the dict bench.py assembles) and returns
{"pass", "checks": [{name, limit, actual, ok}], "failed", "skipped"} where
ok is True/False/None(=skipped). The bench rungs gate on "pass" and
tests/test_bench_quick.py asserts it, so the BENCH_r* series tracks tails,
not just pods/s.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

# The NorthStar_100k_10k_endtoend gate. Ceilings are sized for the FULL
# 100k-pod run on the noisy 2-core CI rig (one batch, so per-stage p99 ==
# that batch's wall share) with ~4x headroom over BENCH_r07 — the gate
# catches order-of-magnitude tail regressions (a stalled chunk, a retrace,
# a serialization bug), not scheduling jitter.
NORTH_STAR_SLO: Dict = {
    "stage_p99_ms": {
        "ingest": 6000.0,
        "queue_add": 4000.0,
        "pop": 2000.0,
        "tensorize": 3000.0,
        "build_pod_batch": 5000.0,
        "solve": 8000.0,
        "assume": 6000.0,
        "dispatch": 2000.0,
        "bind": 8000.0,
        "bind_wait": 8000.0,
    },
    "submit_to_bound_p99_s": 30.0,
    "solver_compiles": 0,
    "instrumentation_frac": 0.02,
}

# The ChaosChurn_20k gate: under injected solver faults, bind faults, a
# worker kill, and a mid-run resync, the p99 is SUPPOSED to show an excursion
# (breaker cooldown + backoff tiers) — the SLO asserts the excursion stays
# BOUNDED and the tracer keeps working, not that chaos is latency-free.
CHAOS_SLO: Dict = {
    "submit_to_bound_p99_s": 120.0,
}

# The ControlPlane_churn gate (ISSUE 9): deployment rollout + node drain +
# eviction/replace driven through the controllers on the noisy 2-core rig.
# Ceilings catch order-of-magnitude regressions (a backlogged watcher, a
# controller gone quadratic), not scheduling jitter: propagation is
# microseconds in-process, reconcile loops are single-digit ms.
CONTROL_PLANE_SLO: Dict = {
    "watch_propagation_p99_s": 10.0,
    "reconcile_p99_ms": 2000.0,
}

# The NorthStar_1M soak gate (ISSUE 13): sustained create/bind/delete churn
# at steady state. The windowed keys assert the run's SHAPE — no stalled
# window, no monotonic RSS/live-object growth, no creeping tail — with
# ceilings sized for the noisy co-scheduled CI rig (order-of-magnitude
# detectors; the leak fixture in tests/test_timeseries.py proves they bite).
# bench.py quick mode loosens the slope ceilings: a time-compressed run
# divides the same absolute noise by a much shorter baseline.
SOAK_SLO: Dict = {
    "stage_p99_ms_per_window": {
        "solve": 8000.0,
        "assume": 6000.0,
        "bind": 8000.0,
    },
    "rss_slope_mb_per_min": 30.0,
    "alloc_block_slope_per_s": 100_000.0,
    "p99_drift_ratio": 10.0,
}

# a trend over fewer windows than this is a SKIP, not a verdict
TREND_MIN_WINDOWS = 4
# stages whose first-third median p99 sits under this are excluded from the
# drift check — a 0.02ms dispatch stage doubling is noise, not a regression
DRIFT_FLOOR_MS = 1.0

# what `ktl sched slo` checks when no --spec file is given
DEFAULT_SLO = NORTH_STAR_SLO

KNOWN_SPEC_KEYS = frozenset((
    "stage_p99_ms", "submit_to_bound_p99_s", "solver_compiles",
    "instrumentation_frac", "watch_propagation_p99_s", "reconcile_p99_ms",
    "stage_p99_ms_per_window", "rss_slope_mb_per_min",
    "alloc_block_slope_per_s", "p99_drift_ratio"))


def load_slo_spec(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def _trend_checks(windows: List[Dict], spec: Dict, checks: List[Dict]) -> None:
    """The steady-state gates (ISSUE 13) over the "windows" section."""
    from ..obs.timeseries import drift_ratio, extract_series, fit_slope

    for stage, limit in sorted(
            (spec.get("stage_p99_ms_per_window") or {}).items()):
        pts = extract_series(windows, "stages", stage, "p99_ms")
        worst = max((v for _t, v in pts), default=None)
        checks.append(_check(f"stage_p99_ms_per_window:{stage}", limit,
                             worst))
    if "rss_slope_mb_per_min" in spec:
        pts = extract_series(windows, "resource", "rss_mb")
        slope = (fit_slope(pts) if len(pts) >= TREND_MIN_WINDOWS else None)
        checks.append(_check(
            "rss_slope_mb_per_min", spec["rss_slope_mb_per_min"],
            slope * 60.0 if slope is not None else None))
    if "alloc_block_slope_per_s" in spec:
        pts = extract_series(windows, "resource", "alloc_blocks")
        slope = (fit_slope(pts) if len(pts) >= TREND_MIN_WINDOWS else None)
        checks.append(_check(
            "alloc_block_slope_per_s", spec["alloc_block_slope_per_s"],
            slope))
    if "p99_drift_ratio" in spec:
        stages = sorted({s for rec in windows
                         for s in (rec.get("stages") or {})})
        worst = None
        if len(windows) >= TREND_MIN_WINDOWS:
            for stage in stages:
                vals = [v for _t, v in
                        extract_series(windows, "stages", stage, "p99_ms")]
                if len(vals) < TREND_MIN_WINDOWS:
                    continue
                third = max(1, len(vals) // 3)
                head = sorted(vals[:third])
                if head[len(head) // 2] < DRIFT_FLOOR_MS:
                    continue  # sub-ms stage: drift is noise, not regression
                d = drift_ratio(vals)
                if d is not None and (worst is None or d > worst):
                    worst = d
        checks.append(_check("p99_drift_ratio", spec["p99_drift_ratio"],
                             worst))


def _check(name: str, limit, actual) -> Dict:
    ok: Optional[bool]
    if actual is None:
        ok = None  # data unavailable: SKIP (reported, never silently passed)
    else:
        ok = actual <= limit  # every spec value is a ceiling
    return {"name": name, "limit": limit,
            "actual": round(actual, 6) if isinstance(actual, float) else actual,
            "ok": ok}


def evaluate_slo(stats: Dict, spec: Dict,
                 extra: Optional[Dict] = None) -> Dict:
    """Evaluate one scheduler's stats payload against a spec.

    stats: sched_stats()-shaped — needs "stages" (stage table rows with
    p99_ms) for stage ceilings and "latency" (podtrace latency_stats) for the
    submit->bound ceiling. extra: out-of-band numbers only the harness knows
    (solver_compiles, instrumentation_frac)."""
    extra = extra or {}
    checks: List[Dict] = []
    # a typoed spec key ("stage_p99ms") must not yield a vacuous PASS that
    # checks nothing: unknown keys are FAILING checks, visible in the table
    for key in sorted(set(spec) - KNOWN_SPEC_KEYS):
        checks.append({"name": f"unknown_spec_key:{key}", "limit": None,
                       "actual": spec[key], "ok": False})
    stages = stats.get("stages") or {}
    for stage, limit in sorted((spec.get("stage_p99_ms") or {}).items()):
        row = stages.get(stage) or {}
        checks.append(_check(f"stage_p99_ms:{stage}", limit,
                             row.get("p99_ms")))
    if "submit_to_bound_p99_s" in spec:
        lat = stats.get("latency") or {}
        checks.append(_check("submit_to_bound_p99_s",
                             spec["submit_to_bound_p99_s"],
                             lat.get("p99_s")))
    if "watch_propagation_p99_s" in spec:
        prop = (stats.get("watch") or {}).get("propagation") or {}
        checks.append(_check("watch_propagation_p99_s",
                             spec["watch_propagation_p99_s"],
                             prop.get("p99_s")))
    if "reconcile_p99_ms" in spec:
        rec = stats.get("reconcile") or {}
        checks.append(_check("reconcile_p99_ms", spec["reconcile_p99_ms"],
                             rec.get("p99_ms")))
    if ("stage_p99_ms_per_window" in spec or "rss_slope_mb_per_min" in spec
            or "alloc_block_slope_per_s" in spec
            or "p99_drift_ratio" in spec):
        _trend_checks(stats.get("windows") or [], spec, checks)
    if "solver_compiles" in spec:
        checks.append(_check("solver_compiles", spec["solver_compiles"],
                             extra.get("solver_compiles")))
    if "instrumentation_frac" in spec:
        checks.append(_check("instrumentation_frac",
                             spec["instrumentation_frac"],
                             extra.get("instrumentation_frac")))
    failed = [c["name"] for c in checks if c["ok"] is False]
    skipped = [c["name"] for c in checks if c["ok"] is None]
    return {"pass": not failed, "failed": failed, "skipped": skipped,
            "checks": checks}
