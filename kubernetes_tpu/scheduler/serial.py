"""Serial scheduler — the CPU oracle/fallback loop.

reference: pkg/scheduler/schedule_one.go — ScheduleOne :65, schedulingCycle :138,
schedulePod :410, findNodesThatFitPod :462, findNodesThatPassFilters :590,
numFeasibleNodesToFind :675 (adaptive 50 - nodes/125 %, floor 5%, min 100),
prioritizeNodes :754, selectHost :872, assume :945, bind :967,
handleSchedulingFailure :1022.

Semantics-identical to the reference's default-plugin pipeline; used as the
parity oracle for the TPU batch path. One deliberate divergence: selectHost
breaks score ties by lowest node index (deterministic) instead of reservoir
sampling — the TPU argmax does the same, making parity exact.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import Pod
from ..snapshot.class_compiler import pod_class_signature
from ..store import (ADDED, DELETED, MODIFIED, APIStore, CoalescedEvent,
                     NotFoundError, pod_structural_clone)
from ..utils import Clock
from .cache import Cache
from .framework import CycleState, NodeInfo, Snapshot, Status
from .queue import QueuedPodInfo, SchedulingQueue
from .runtime import Framework

# storage kinds mirrored into the volume plugins' VolumeLister handles
STORAGE_KINDS = ("persistentvolumeclaims", "persistentvolumes",
                 "storageclasses", "csinodes")

MIN_FEASIBLE_NODES_TO_FIND = 100  # schedule_one.go:52
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # schedule_one.go:57

import itertools as _itertools

_scheduler_origin_seq = _itertools.count()


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int = 0) -> int:
    """schedule_one.go:675-701."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND:
        return num_all_nodes
    if percentage == 0:
        percentage = int(50 - num_all_nodes / 125)
        if percentage < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            percentage = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    if percentage >= 100:
        return num_all_nodes
    num = num_all_nodes * percentage // 100
    return max(num, MIN_FEASIBLE_NODES_TO_FIND)


@dataclass
class ScheduleResult:
    suggested_host: str = ""
    evaluated_nodes: int = 0
    feasible_nodes: int = 0
    status: Status = field(default_factory=Status.success)
    # node name -> failure status for PostFilter/preemption
    failed_nodes: Dict[str, Status] = field(default_factory=dict)
    scores: Dict[str, int] = field(default_factory=dict)
    # the cycle's state, threaded through Reserve/Permit/Bind (one CycleState
    # per cycle — the reference passes the same state end to end)
    state: Optional[CycleState] = None


class Scheduler:
    """Wires store watch -> cache + queue -> scheduling loop -> bind writes."""

    def __init__(self, store: APIStore, framework: Optional[Framework] = None,
                 clock: Optional[Clock] = None,
                 percentage_of_nodes_to_score: int = 100,
                 profiles: Optional[Dict[str, Framework]] = None,
                 extenders: Optional[List] = None,
                 pod_initial_backoff: Optional[float] = None,
                 pod_max_backoff: Optional[float] = None):
        from ..api.types import DEFAULT_SCHEDULER_NAME

        self.store = store
        # Profiles: one framework per pod.Spec.SchedulerName (profile/profile.go);
        # a bare framework is a single default profile.
        if profiles is None:
            if framework is None:
                raise ValueError("need framework or profiles")
            profiles = {DEFAULT_SCHEDULER_NAME: framework}
        elif framework is not None:
            raise ValueError("pass framework or profiles, not both")
        self.profiles = profiles
        self.framework = (profiles.get(DEFAULT_SCHEDULER_NAME)
                          or next(iter(profiles.values())))
        self.extenders = list(extenders or [])
        self.clock = clock or Clock()
        self.cache = Cache(clock=self.clock)
        # Wire the QueueSort plugin (from the default profile; the reference
        # requires all profiles share one QueueSort — validation.go). The
        # default PrioritySort is special-cased to the queue's fast tuple sort
        # key (identical ordering, cheaper heap ops).
        from .plugins.node_plugins import PrioritySort

        qs = self.framework.queue_sort_plugin
        backoff_kw = {}
        if pod_initial_backoff is not None:
            backoff_kw["initial_backoff"] = pod_initial_backoff
        if pod_max_backoff is not None:
            backoff_kw["max_backoff"] = pod_max_backoff
        self.queue = SchedulingQueue(
            clock=self.clock,
            less=qs.less if qs is not None and not isinstance(qs, PrioritySort) else None,
            pre_enqueue=lambda pod: (self._fw(pod) or self.framework
                                     ).run_pre_enqueue(pod).is_success(),
            **backoff_kw,
        )
        self.percentage = percentage_of_nodes_to_score
        # gang directory (scheduler/gang.py) — installed by BatchScheduler;
        # None on the serial loop, and every hook below is gated on it
        self.gangs = None
        # gang preemptor (scheduler/gangpreempt.py, ISSUE 14) — installed by
        # BatchScheduler; the DELETED ingest below checks victims off its
        # in-flight covers (gated on has_waiting: one attr read when idle)
        self.gangpreempt = None
        self._watch = None
        # pipeline flight recorder (scheduler/flightrec.py) — installed by
        # BatchScheduler; None on the serial loop, every hook gated on it
        self.flightrec = None
        # sampled pod lifecycle tracer (scheduler/podtrace.py) — installed by
        # BatchScheduler; None on the serial loop, hooks gated on it
        self.podtrace = None
        # coalesced watch ingest: batched store writes arrive as ONE
        # CoalescedEvent; _bind_origin tags our own bind_many batches so
        # their MODIFIED events short-circuit to a bulk assume-confirm
        self.watch_coalesce = True
        self._bind_origin = f"scheduler-{next(_scheduler_origin_seq)}"
        # partitioned dispatch hooks (scheduler/partition.py, ISSUE 12) —
        # None on a standalone scheduler, in which case every path below is
        # byte-identical to the unhooked code:
        #   _node_filter(node) -> bool: this pipeline's node shard (takes
        #       the Node OBJECT — zone partitioning reads its labels); a
        #       filtered-out node never enters the cache, so the solver can
        #       only place onto the shard.
        #   _pod_gate(etype, pod) -> bool: is this pod event MINE to ingest
        #       (pending pods route by the dispatch layer's fingerprint,
        #       bound pods by their node's shard)? The gate may also clean a
        #       stale queue entry for a pod another partition just bound.
        self._node_filter = None
        self._pod_gate = None
        # bind origins of PEER partition pipelines (disjoint shards): a
        # coalesced MODIFIED batch tagged with one is entirely the peer's
        # own-shard binds — nothing for this pipeline's cache or queue — so
        # ingest skips it in O(1) instead of gating 50k events one by one
        # (measured: the per-event loop alone cost each pipeline ~0.5s per
        # 100k-pod A/B run). A stale local queue entry for a pod a peer won
        # (the double-routing race) self-heals through the bind-conflict
        # path; the residual pass's origin is deliberately NOT a peer (its
        # binds may land on ANY shard and must be ingested).
        self._peer_bind_origins: frozenset = frozenset()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.scheduled_count = 0
        self.failed_count = 0
        self.preemption_count = 0
        # QueueingHintMap per framework (buildQueueingHintMap, scheduler.go:405):
        # (resource, action) -> {plugin name: [hint fn | None]}
        self._hint_maps: Dict[int, Dict] = {}
        # event narration (EventRecorder, schedule_one.go:1008,1098) —
        # best-effort, aggregated, never blocks scheduling
        from ..api.events import EventRecorder

        self.recorder = EventRecorder(store, component="default-scheduler",
                                      clock=self.clock)
        # ns labels for InterPodAffinity namespaceSelector
        self._ns_labels: Dict[str, Dict[str, str]] = {}
        # plugins needing framework/store handles (e.g. DefaultPreemption);
        # the recorder is shared so plugin events use the same clock/aggregation
        for fw in self.profiles.values():
            for p in fw.plugins:
                if hasattr(p, "set_handles"):
                    try:
                        p.set_handles(fw, store, recorder=self.recorder)
                    except TypeError:
                        p.set_handles(fw, store)
        # volume plugins share VolumeLister handles fed from the store's
        # storage kinds (the reference reaches these via shared informers)
        self._volume_listers = []
        seen = set()
        for fw in self.profiles.values():
            for p in fw.plugins:
                lister = getattr(p, "lister", None)
                if lister is not None and id(lister) not in seen and hasattr(lister, "add"):
                    seen.add(id(lister))
                    self._volume_listers.append(lister)

    def _fw(self, pod: Pod) -> Optional[Framework]:
        """frameworkForPod (schedule_one.go:378): profile by SchedulerName."""
        return self.profiles.get(pod.spec.scheduler_name)

    @classmethod
    def from_config(cls, store: APIStore, config=None, clock: Optional[Clock] = None,
                    volume_lister=None) -> "Scheduler":
        """Build from a KubeSchedulerConfiguration (dict or object): profiles,
        extenders, backoff, percentage (cmd/kube-scheduler/app/server.go Setup)."""
        from .config import KubeSchedulerConfiguration, build_profiles

        if config is None or isinstance(config, dict):
            config = KubeSchedulerConfiguration.from_dict(config)
        profiles, extenders = build_profiles(config, volume_lister)
        # 0 = adaptive percentage (numFeasibleNodesToFind, schedule_one.go:675)
        return cls(store, clock=clock, profiles=profiles, extenders=extenders,
                   percentage_of_nodes_to_score=config.percentage_of_nodes_to_score,
                   pod_initial_backoff=config.pod_initial_backoff_seconds,
                   pod_max_backoff=config.pod_max_backoff_seconds)

    # -- informer-equivalent event handling (eventhandlers.go:364) -------------

    def sync(self) -> None:
        """Initial LIST: populate cache with nodes + bound pods, queue with
        pending pods; then start WATCH from that RV. All kinds are listed under
        one consistent RV so no event can fall between list and watch."""
        lists, rv = self.store.list_many(
            ("nodes", "pods", "namespaces", "podgroups") + STORAGE_KINDS)
        nf = self._node_filter
        for n in lists["nodes"]:
            if nf is None or nf(n):
                self.cache.add_node(n)
        if self.gangs is not None:
            # quorums must be known BEFORE pods are ingested, or the gang
            # members of the initial backlog would all park waiting
            for pg in lists["podgroups"]:
                self.gangs.observe_podgroup(ADDED, pg)
        for p in lists["pods"]:
            self._handle_pod(ADDED, p)
        for ns in lists["namespaces"]:
            self._ns_labels[ns.metadata.name] = dict(ns.metadata.labels)
        for kind in STORAGE_KINDS:
            for obj in lists[kind]:
                for lister in self._volume_listers:
                    lister.add(obj)
        self._push_ns_labels()
        # generous buffer — the scheduler drains every cycle; if it still
        # falls behind it is evicted and relists (pump_events). Subscribed to
        # exactly the kinds _handle_event consumes: high-volume kinds it would
        # ignore (its own Scheduled/FailedScheduling events!) never enqueue.
        # Coalesced: a 100k bind_many backlog is a handful of buffered items.
        self._watch = self.store.watch(
            kind=self._watched_kinds(), since_rv=rv, maxsize=200_000,
            coalesce=self.watch_coalesce)
        # watch-propagation tap (ISSUE 9): inline settlement on OUR drain
        # thread bills the flight recorder's <2% budget (batch path only —
        # the serial oracle has no recorder and pays read-side settle only)
        self._watch.stat_sink = getattr(self, "flightrec", None)

    def _push_ns_labels(self):
        for fw in self.profiles.values():
            for p in fw.plugins:
                if hasattr(p, "set_namespace_labels"):
                    p.set_namespace_labels(self._ns_labels)

    @staticmethod
    def _watched_kinds() -> tuple:
        """The kinds _handle_event consumes (eventhandlers.go informer set)."""
        return (("nodes", "pods", "namespaces", "podgroups") + STORAGE_KINDS
                + ("resourceclaims", "resourceslices", "deviceclasses"))

    def pump_events(self, max_events: int = 10_000) -> int:
        """Drain pending watch events into cache/queue (deterministic test path;
        the run loop calls this between cycles). An evicted (slow) watch forces
        a full relist — the Reflector contract on 410/terminated streams."""
        if self._watch is None:
            return 0
        if self._watch.terminated:
            self._relist()
            return 0
        n = 0
        # bounded drain: events beyond the cap STAY in the watch buffer for
        # the next pump (a plain drain() dequeues everything — breaking out
        # of that list discarded the rest of a large backlog). A coalesced
        # batch counts as one buffered item but reports its true size.
        for ev in self._watch.drain(max_events):
            if type(ev) is CoalescedEvent:
                n += self._handle_coalesced(ev)
            else:
                self._handle_event(ev)
                n += 1
        return n

    def _handle_coalesced(self, cev: CoalescedEvent) -> int:
        """Batched ingest of one CoalescedEvent (a bind_many / create_many
        chunk). Two bulk fast paths, both falling back to the per-event
        handler for anything that doesn't match:

          - our own bind MODIFIED batch (origin == _bind_origin): NOTHING to
            ingest — the bind worker confirmed the assumes chunk-by-chunk,
            piggybacked on the same bind_many commits (and re-ingests the
            rare leftovers via _drain_bind_results), so the old confirm
            re-ingest stage is gone from the scheduling thread entirely;
          - pending-pod ADDED batch: PreEnqueue-gate per pod, then ONE
            SchedulingQueue.add_batch admission (single lock + heapify).

        Returns the number of per-object events ingested."""
        # NOTE (ISSUE 15): a columnar store's bind batches carry a LAZY
        # events sequence — len() is O(1) and materializes nothing, so the
        # self/peer origin skips below keep the steady state allocation-free
        # end to end (store commit AND ingest); only the foreign-batch
        # fallthrough, which actually reads ev.obj, pays materialization.
        events = cev.events
        if cev.kind != "pods":
            for ev in events:
                self._handle_event(ev)
            return len(events)
        if (cev.type == MODIFIED and cev.origin is not None
                and cev.origin == self._bind_origin):
            return len(events)
        if (cev.type == MODIFIED and cev.origin is not None
                and cev.origin in self._peer_bind_origins):
            # a peer partition's bind batch: every event is a pod committed
            # onto THAT pipeline's disjoint node shard (see __init__).
            # MODIFIED only — an origin-tagged DELETE batch (victim
            # deletion) frees capacity a later resync must not be the
            # first to notice
            return len(events)
        if cev.type == ADDED:
            admit: List[Pod] = []
            gate = self._pod_gate
            for ev in events:
                pod = ev.obj
                if (pod.spec.node_name or pod.is_terminal()
                        or self._fw(pod) is None):
                    self._handle_pod(ADDED, pod)  # not a plain pending pod
                elif gate is not None and not gate(ADDED, pod):
                    continue  # another partition's pod (dispatch layer)
                elif self._gate_pending_pod(pod):
                    admit.append(pod)
            self.queue.add_batch(admit, pre_gated=True)
            return len(events)
        for ev in events:
            self._handle_event(ev)
        return len(events)

    def _gate_pending_pod(self, pod: Pod) -> bool:
        """PreEnqueue-gate one unbound pod (shared by the per-event and
        coalesced ingest paths so the two can't drift): True means admit to
        the active queue; a gated pod is parked unschedulable with its
        rejecting plugin recorded, exactly as handleSchedulingFailure
        would."""
        st = (self._fw(pod) or self.framework).run_pre_enqueue(pod)
        if st.is_success():
            # prime the pod-carried class-signature memo at ADMISSION: the
            # fused per-pod loop in build_pod_batch then does a dict hit
            # instead of the ~6µs signature recompute (ROADMAP open lever),
            # and ingest overlaps the previous batch's bind commits while
            # build_pod_batch sits on the serial critical path
            pod_class_signature(pod)
            return True
        self.queue.add_unschedulable(QueuedPodInfo(
            pod=pod, timestamp=self.clock.now(),
            unschedulable_plugins=(st.plugin,)))
        return False

    def _relist(self) -> None:
        """Rebuild cache + listers from a fresh consistent LIST and rewatch
        (reflector.go ListAndWatch restart after a dead watch). Queue state is
        preserved: tracked pods keep their backoff/attempt counts; pods the
        list no longer contains are deleted, new pending pods are added."""
        if hasattr(self, "flush_binds"):
            # batch path: in-flight async binds must commit before the list,
            # or their pods would be listed as pending and scheduled twice
            self.flush_binds()
        self._rebuild_from_store(preserve_queue=True)

    def _rebuild_from_store(self, preserve_queue: bool = True) -> Dict[str, int]:
        """Shared body of _relist (watch eviction: queue state preserved) and
        resync_from_store (crash restart: queue state DISCARDED — a restarted
        scheduler has no memory of attempts/backoff, so every pending pod
        re-enters fresh from the LIST). Returns {nodes, bound, pending}."""
        if self._watch is not None:
            self._watch.stop()
        self.cache = Cache(clock=self.clock)
        if self.podtrace is not None and not preserve_queue:
            # crash resync discards the queue, so in-flight spans reference
            # QueuedPodInfos that are about to be replaced and can never
            # complete — drop them (counted as evicted) like the rest of the
            # pre-crash in-memory state. A routine _relist KEEPS the queue's
            # objects (and their trace_span links), so those spans still
            # finish normally and must not be evicted.
            self.podtrace.drop_live()
        for lister in self._volume_listers:
            if hasattr(lister, "clear"):
                lister.clear()
        self._ns_labels.clear()
        if not preserve_queue:
            self.queue.clear()
        lists, rv = self.store.list_many(
            ("nodes", "pods", "namespaces", "podgroups") + STORAGE_KINDS)
        known_pending = set()
        bound = pending = 0
        nf = self._node_filter
        gate = self._pod_gate
        for n in lists["nodes"]:
            if nf is None or nf(n):
                self.cache.add_node(n)
        if self.gangs is not None:
            self.gangs.reset()
            for pg in lists["podgroups"]:
                self.gangs.observe_podgroup(ADDED, pg)
        for p in lists["pods"]:
            if self.gangs is not None:
                # BEFORE the partition gate: gang quorums count bound
                # members wherever they run (cluster-scoped), and the
                # pre-partition behavior observed every listed pod
                self.gangs.observe_pod(ADDED, p)
            if gate is not None and not gate(ADDED, p):
                continue  # another partition's pod (dispatch layer routing)
            if p.spec.node_name:
                if not p.is_terminal():
                    self.cache.add_pod(p)
                    bound += 1
            elif not p.is_terminal():
                known_pending.add(p.key)
                pending += 1
                if preserve_queue:
                    if not self.queue.update(p):  # unknown: enqueue
                        self._handle_pod(ADDED, p)
                else:
                    self._handle_pod(ADDED, p)
        if preserve_queue:
            # drop queued pods (ALL tiers) that no longer exist as pending
            # pods — deleted or bound-by-another-leader during the outage; no
            # DELETED event will ever arrive for them on the new watch
            for key in self.queue.tracked_keys():
                if key not in known_pending:
                    self.queue.delete_key(key)
        for ns in lists["namespaces"]:
            self._ns_labels[ns.metadata.name] = dict(ns.metadata.labels)
        for kind in STORAGE_KINDS:
            for obj in lists[kind]:
                for lister in self._volume_listers:
                    lister.add(obj)
        self._push_ns_labels()
        self._watch = self.store.watch(
            kind=self._watched_kinds(), since_rv=rv, maxsize=200_000,
            coalesce=self.watch_coalesce)
        self._watch.stat_sink = getattr(self, "flightrec", None)
        self.queue.move_all_to_active_or_backoff()
        return {"nodes": len(lists["nodes"]), "bound": bound,
                "pending": pending}

    _EVENT_ACTION = {ADDED: "add", MODIFIED: "update", DELETED: "delete"}

    def _hint_map(self, fw: Framework) -> Tuple[Dict, frozenset]:
        """Returns ((resource, action) -> {plugin: [hints]}, names of plugins
        that registered ANY event). A rejecting plugin that registered nothing
        is treated as interested in every event (the reference registers
        non-EnqueueExtensions plugins for all events — scheduler.go:405)."""
        got = self._hint_maps.get(id(fw))
        if got is None:
            hmap: Dict = {}
            registered = set()
            for p in fw.plugins:
                for ev in getattr(p, "events_to_register", lambda: ())():
                    registered.add(p.name)
                    hmap.setdefault((ev.resource, ev.action), {}) \
                        .setdefault(p.name, []).append(ev.hint)
            got = (hmap, frozenset(registered))
            self._hint_maps[id(fw)] = got
        return got

    def _move_for_event(self, resource: str, etype: str, obj) -> None:
        """Hint-gated requeue on a cluster event (scheduling_queue.go:263,1028
        QueueingHintMap + podMatchesEvent): an unschedulable pod moves only if
        one of its rejecting plugins registered this event and its hint (if
        any) returns Queue. Pods with no recorded rejector move conservatively;
        hint errors queue conservatively. SchedulerQueueingHints=false restores
        the pre-hints move-everything behavior."""
        from ..utils.featuregate import feature_gates

        try:
            hints_on = feature_gates.enabled("SchedulerQueueingHints")
        except KeyError:
            hints_on = True
        if not hints_on:
            self.queue.move_all_to_active_or_backoff()
            return
        action = self._EVENT_ACTION.get(etype, etype)

        def should_move(qp: QueuedPodInfo) -> bool:
            if not qp.unschedulable_plugins:
                return True
            fw = self._fw(qp.pod) or self.framework
            hmap, registered = self._hint_map(fw)
            entries = hmap.get((resource, action), {})
            for name in qp.unschedulable_plugins:
                if not name or name not in registered:
                    # unattributed rejection, or a rejector that declared no
                    # events at all: conservative move on any event
                    return True
                hints = entries.get(name)
                if hints is None:
                    continue  # this plugin doesn't care about the event
                for h in hints:
                    if h is None:
                        return True
                    try:
                        if h(qp.pod, obj):
                            return True
                    except Exception:
                        return True  # hint error -> Queue (reference behavior)
            return False

        self.queue.move_pods_for_event(should_move)

    def _handle_event(self, ev) -> None:
        if ev.kind == "nodes":
            nf = self._node_filter
            if nf is not None and not nf(ev.obj):
                # not (or NO LONGER) this pipeline's shard: a routing
                # migration (zone mode — the zone label appearing after a
                # hash-fallback placement) re-slots a node to another
                # partition, and the old owner must drop it or two solvers
                # would each see the node's full capacity. remove_node on a
                # never-owned node is a dict-miss no-op; bound pods keep a
                # snapshot-invisible placeholder until their events re-route.
                self.cache.remove_node(ev.obj.metadata.name)
                return
            if ev.type == DELETED:
                self.cache.remove_node(ev.obj.metadata.name)
            else:
                self.cache.add_node(ev.obj)
            self._move_for_event("nodes", ev.type, ev.obj)
        elif ev.kind == "pods":
            self._handle_pod(ev.type, ev.obj)
        elif ev.kind == "namespaces":
            self._ns_labels[ev.obj.metadata.name] = dict(ev.obj.metadata.labels)
        elif ev.kind in STORAGE_KINDS:
            for lister in self._volume_listers:
                if ev.type == DELETED:
                    lister.remove(ev.obj)
                else:
                    lister.add(ev.obj)
            # a new/changed PV or class can unblock pending claims
            self._move_for_event(ev.kind, ev.type, ev.obj)
        elif ev.kind == "podgroups":
            # gang quorum plumbing (scheduler/gang.py): a created or raised
            # PodGroup can complete a staged gang's quorum, a delete orphans
            # its members (they schedule as ordinary pods from then on)
            if self.gangs is not None:
                self.gangs.observe_podgroup(ev.type, ev.obj)
                self.queue.reconsider_gangs()
            self._move_for_event("podgroups", ev.type, ev.obj)
        elif ev.kind in ("resourceclaims", "resourceslices", "deviceclasses"):
            # DRA objects gate pods via DynamicResources' hints (claims read
            # through the store lister — no local cache to update)
            self._move_for_event(ev.kind, ev.type, ev.obj)

    def _handle_pod(self, etype: str, pod: Pod) -> None:
        # Unassigned pods of a scheduler we have no profile for are not ours
        # (eventhandlers.go responsibleForPod); bound pods still feed the cache.
        if not pod.spec.node_name and self._fw(pod) is None:
            return
        if etype == DELETED or pod.is_terminal():
            # gang preemption (ISSUE 14): a terminating victim checks off
            # its cover; the LAST one releases the parked gang to re-stage.
            # has_waiting is one attribute read for the idle ~100%.
            gp = self.gangpreempt
            if gp is not None and gp.has_waiting:
                gp.note_pod_deleted(pod.key)
        gate = self._pod_gate
        if gate is not None and not gate(etype, pod):
            # routed to another partition (dispatch layer) — but gang
            # quorum accounting is CLUSTER-scoped: a member bound on a
            # foreign shard still counts toward this pipeline's gang
            # directory (one labels.get fast-out for the unlabeled ~100%),
            # and a membership change still re-evaluates staged quorums
            if self.gangs is not None and self.gangs.active:
                self.gangs.observe_pod(etype, pod)
                if etype == DELETED or pod.is_terminal() \
                        or pod.spec.node_name:
                    from ..api.podgroup import pod_group_key

                    if pod_group_key(pod):
                        self.queue.reconsider_gangs()
            return
        if self.gangs is not None:
            # gang quorum accounting: bound members count, deletes/terminals
            # free the slot (one labels.get for unlabeled pods). Our own bind
            # confirmations bypass this path — they were counted at assume.
            self.gangs.observe_pod(etype, pod)
            if self.gangs.active and (etype == DELETED or pod.is_terminal()
                                      or pod.spec.node_name):
                from ..api.podgroup import pod_group_key

                # membership changed: a staged gang may have reached quorum
                # (e.g. a straggler whose siblings are now bound). Gated on
                # actual gang membership — unlabeled pod churn must not pay
                # a queue-lock + staging scan per event.
                if pod_group_key(pod):
                    self.queue.reconsider_gangs()
        # Pod informer filters terminal pods (scheduler.go:582); a queued pod
        # turning terminal generates a queue delete (predicate stops matching).
        if pod.is_terminal():
            if pod.spec.node_name:
                self.cache.remove_pod(pod)
                # a bound pod turning terminal frees its resources — same
                # schedulability signal as an assigned-pod delete
                self._move_for_event("pods", DELETED, pod)
            else:
                self.queue.delete(pod)
            return
        if etype == DELETED:
            # sampled-span eviction tap (ISSUE 9): close out a sampled span
            # and remember the owner for the evict->replace causal link.
            # O(1) for unsampled pods (two membership probes inside).
            pt = self.podtrace
            if pt is not None and pt.enabled:
                pt.note_deleted(pod)
            if pod.spec.node_name:
                self.cache.remove_pod(pod)
                self._move_for_event("pods", DELETED, pod)
            else:
                self.queue.delete(pod)
            return
        if pod.spec.node_name:
            if self.cache.is_assumed(pod.key):
                self.cache.add_pod(pod)  # confirm assumed
            elif etype == MODIFIED:
                # keep labels/requests fresh — affinity/spread counts read them
                self.cache.update_pod(pod)
                self._move_for_event("pods", MODIFIED, pod)
            else:
                self.cache.add_pod(pod)
                self._move_for_event("pods", ADDED, pod)
        else:
            if etype == MODIFIED and self.queue.update(pod):
                return  # status-only updates of queued pods don't requeue
            if self._gate_pending_pod(pod):
                self.queue.add(pod)

    # -- core scheduling (schedule_one.go) -------------------------------------

    def schedule_pod(self, pod: Pod, snapshot: Optional[Snapshot] = None) -> ScheduleResult:
        """schedulePod :410 — snapshot, prefilter, filter, score, select.
        Traced with the reference's 100ms log threshold (schedule_one.go:411)."""
        from ..utils.tracing import Trace

        trace = Trace("Scheduling", pod=pod.key)
        try:
            return self._schedule_pod_traced(pod, snapshot, trace)
        finally:
            trace.log_if_long(0.1)

    def _schedule_pod_traced(self, pod: Pod, snapshot: Optional[Snapshot],
                             trace) -> ScheduleResult:
        if snapshot is None:
            # serial plugins walk snapshot pod lists — collapse any columnar
            # cache rows (batch-scheduler row mode, scheduler/cachecols.py)
            # before snapshotting; a no-op on the pure serial path
            self.cache.materialize_columnar_rows()
            snapshot = self.cache.update_snapshot()
            trace.step("Snapshotting scheduler cache done")
        res = ScheduleResult()
        if len(snapshot) == 0:
            res.status = Status.unschedulable("no nodes available to schedule pods")
            return res
        framework = self._fw(pod) or self.framework
        state = CycleState()
        res.state = state
        pre_res, st = framework.run_pre_filter(state, pod, snapshot)
        if not st.is_success():
            res.status = st
            if st.is_rejected():
                # all nodes failed at prefilter
                res.failed_nodes = {ni.node.metadata.name: st for ni in snapshot.node_info_list}
            return res

        nodes = snapshot.node_info_list
        if pre_res.node_names is not None:
            nodes = [ni for ni in nodes if ni.node.metadata.name in pre_res.node_names]

        # Nominated-node fast path (:492): try the nominated node first —
        # extenders must also pass it (evaluateNominatedNode runs the full
        # findNodesThatFitPod including findNodesThatPassExtenders).
        if pod.status.nominated_node_name:
            ni = snapshot.get(pod.status.nominated_node_name)
            if ni is not None and framework.run_filter(state, pod, ni).is_success():
                ok = True
                if self.extenders:
                    from .extender import find_nodes_that_pass_extenders

                    names, err = find_nodes_that_pass_extenders(
                        self.extenders, pod, [ni.node.metadata.name], {})
                    ok = err is None and bool(names)
                if ok:
                    res.evaluated_nodes = 1
                    return self._score_and_select(state, pod, [ni], res)

        percentage = getattr(framework, "percentage_of_nodes_to_score", None)
        if percentage is None:
            percentage = self.percentage
        limit = num_feasible_nodes_to_find(len(nodes), percentage)
        feasible: List[NodeInfo] = []
        for ni in nodes:
            st = framework.run_filter(state, pod, ni)
            res.evaluated_nodes += 1
            if st.is_success():
                feasible.append(ni)
                if len(feasible) >= limit:
                    break
            else:
                res.failed_nodes[ni.node.metadata.name] = st
        # findNodesThatPassExtenders (:703) — HTTP round trip per extender.
        if feasible and self.extenders:
            from .extender import find_nodes_that_pass_extenders

            ext_failed: Dict[str, str] = {}
            names = [ni.node.metadata.name for ni in feasible]
            names, err = find_nodes_that_pass_extenders(
                self.extenders, pod, names, ext_failed)
            if err is not None:
                res.status = Status.error(err)
                return res
            for name, msg in ext_failed.items():
                res.failed_nodes.setdefault(name, Status.unschedulable(msg))
            keep = set(names)
            feasible = [ni for ni in feasible if ni.node.metadata.name in keep]
        res.feasible_nodes = len(feasible)
        trace.step("Computing predicates done",
                   evaluated=res.evaluated_nodes, feasible=len(feasible))
        if not feasible:
            res.status = Status.unschedulable(
                f"0/{len(snapshot)} nodes are available", plugin="")
            return res
        out = self._score_and_select(state, pod, feasible, res)
        trace.step("Prioritizing done")
        return out

    def _score_and_select(self, state: CycleState, pod, feasible: List[NodeInfo],
                          res: ScheduleResult) -> ScheduleResult:
        framework = self._fw(pod) or self.framework
        res.feasible_nodes = len(feasible)
        if len(feasible) == 1 and not self.extenders:
            res.suggested_host = feasible[0].node.metadata.name
            return res
        st = framework.run_pre_score(state, pod, feasible)
        if not st.is_success():
            res.status = st
            return res
        totals = framework.run_score(state, pod, feasible)
        if self.extenders:
            from .extender import merge_extender_priorities

            merge_extender_priorities(
                self.extenders, pod,
                [ni.node.metadata.name for ni in feasible], totals)
        res.scores = totals
        # selectHost :872 — deterministic: max score, lowest list index on ties.
        best_name, best_score = None, None
        for ni in feasible:
            name = ni.node.metadata.name
            s = totals[name]
            if best_score is None or s > best_score:
                best_name, best_score = name, s
        res.suggested_host = best_name
        return res

    # -- the loop --------------------------------------------------------------

    def schedule_one(self, timeout: Optional[float] = 0.1) -> bool:
        """One ScheduleOne iteration. Returns False when no pod was popped."""
        from ..server import metrics as m

        self.pump_events()
        qp = self.queue.pop(timeout=timeout)
        if qp is None:
            return False
        t0 = time.perf_counter()
        pod = qp.pod
        result = self.schedule_pod(pod)
        m.scheduling_attempts.inc(
            result="scheduled" if result.suggested_host else "unschedulable")
        m.scheduling_attempt_duration.observe(time.perf_counter() - t0)
        active, backoff, unsched = self.queue.lengths()
        m.pending_pods.set(active, queue="active")
        m.pending_pods.set(backoff, queue="backoff")
        m.pending_pods.set(unsched, queue="unschedulable")
        if not result.suggested_host:
            self._maybe_preempt(qp, result)
            self._handle_failure(qp, result.status, result.failed_nodes)
            return True
        self._commit_cycle(qp, result)
        return True

    def _commit_cycle(self, qp: QueuedPodInfo, result: ScheduleResult) -> bool:
        """assume (:945) -> Reserve -> Permit -> PreBind -> bind (:967) ->
        PostBind; binds synchronously. The assumed pod is a STRUCTURAL clone
        (schedule_one.go:148 DeepCopy analog, tuned like store.bind): own
        metadata/spec/status objects, shared immutable innards — plugins may
        mutate the cloned top-level fields but must treat containers/
        tolerations/affinity as read-only, the same contract informer objects
        carry. Shared by the serial loop and the batch scheduler's serial
        fallback (fallback pods rely on these extension points)."""
        pod = qp.pod
        framework = self._fw(pod) or self.framework
        assumed = pod_structural_clone(pod)
        try:
            self.cache.assume_pod(assumed, result.suggested_host)
        except ValueError:
            self._handle_failure(qp, Status.error("pod already in cache"))
            return False
        state = result.state if result.state is not None else CycleState()
        st = framework.run_reserve(state, assumed, result.suggested_host)
        if not st.is_success():
            self.cache.forget_pod(assumed)
            self._handle_failure(qp, st)
            return False
        st = framework.run_permit(state, assumed, result.suggested_host)
        if not st.is_success():
            framework.run_unreserve(state, assumed, result.suggested_host)
            self.cache.forget_pod(assumed)
            self._handle_failure(qp, st)
            return False
        try:
            st = framework.run_pre_bind(state, assumed, result.suggested_host)
            if not st.is_success():
                raise RuntimeError(f"prebind: {st.message()}")
            self._bind(pod, result.suggested_host)
            self.cache.finish_binding(assumed)
            if self.gangs is not None:
                self.gangs.note_assumed(assumed)
            framework.run_post_bind(state, assumed, result.suggested_host)
            self.scheduled_count += 1
            pt = self.podtrace
            if pt is not None and pt.enabled:
                # serial fallback pods join the submit->bound distribution
                # and (if sampled) complete their lifecycle span here — the
                # serial loop is per-pod by design, so this is its granularity
                pt.pod_bound(qp, self.clock.now())
            self.recorder.event(
                pod, "Normal", "Scheduled",
                f"Successfully assigned {pod.key} to {result.suggested_host}")
        except Exception as e:
            # handleBindingCycleError (:344): Unreserve + ForgetPod + requeue
            framework.run_unreserve(state, assumed, result.suggested_host)
            self.cache.forget_pod(assumed)
            self._handle_failure(qp, Status.error(str(e)))
            return False
        return True

    def _bind(self, pod: Pod, node_name: str) -> None:
        """extendersBinding (:981): a binder extender interested in the pod
        binds it; otherwise the default binder POSTs the Binding subresource."""
        for ext in self.extenders:
            if getattr(ext, "is_binder", False) and ext.is_interested(pod):
                ext.bind(pod, node_name)
                return
        self.store.bind(pod.metadata.namespace, pod.metadata.name, node_name)

    def _maybe_preempt(self, qp: QueuedPodInfo, result: ScheduleResult) -> None:
        """RunPostFilterPlugins on an Unschedulable cycle (schedule_one.go:175)."""
        from .framework import Code

        if result.status.code != Code.UNSCHEDULABLE:
            return
        framework = self._fw(qp.pod) or self.framework
        if not framework.post_filter_plugins or not result.failed_nodes:
            return
        state = result.state if result.state is not None else CycleState()
        nominated, st = framework.run_post_filter(state, qp.pod, result.failed_nodes)
        if st.is_success() and nominated:
            qp.pod.status.nominated_node_name = nominated
            self.preemption_count += 1

    def _handle_failure(self, qp: QueuedPodInfo, status: Status,
                        failed_nodes: Optional[Dict[str, Status]] = None) -> None:
        """handleSchedulingFailure :1022 — requeue + patch PodScheduled
        condition. Records the rejecting plugins (QueuedPodInfo
        UnschedulablePlugins) so hint-gated requeue knows which events matter."""
        self.failed_count += 1
        plugins = set()
        if failed_nodes:
            # keep "" for unattributed per-node rejections (extender vetoes):
            # should_move treats it as move-on-any-event
            plugins = {st.plugin for st in failed_nodes.values()}
        elif status.plugin:
            plugins = {status.plugin}
        qp.unschedulable_plugins = tuple(sorted(plugins))
        self.queue.add_unschedulable(qp)
        self.recorder.event(qp.pod, "Warning", "FailedScheduling",
                            status.message())
        try:
            def set_cond(st):
                st.phase = "Pending"
                from ..api.types import PodCondition

                st.conditions = [c for c in st.conditions if c.type != "PodScheduled"]
                st.conditions.append(PodCondition(
                    type="PodScheduled", status="False", reason="Unschedulable",
                    message=status.message()))

            self.store.update_pod_status(qp.pod.metadata.namespace, qp.pod.metadata.name, set_cond)
        except Exception:
            pass

    def sweep_expired_assumes(self) -> List[str]:
        """Expire assumed pods whose bind never confirmed (cache.go's
        durationToExpireAssumedPod cleanup, scheduler.go:57-59) and CONSUME
        the consequences instead of leaking them:

          - gang quorums count the expired members back OUT (the
            scheduler_gang_quorum_expired_assumes leak the PR 3 gauge made
            measurable) — a gang waiting on quorum re-evaluates against
            reality instead of silently under-counting;
          - the pods themselves re-enter the queue if they still exist
            pending in the store (an expired assume means our bind never
            landed; without this they strand in limbo until a relist), which
            re-STAGES gang members under their group.

        Returns the expired pod keys."""
        expired = self.cache.cleanup_expired_assumed_pods()
        if not expired:
            return expired
        if self.gangs is not None and self.gangs.active:
            self.gangs.note_expired_keys(expired)
        for key in expired:
            try:
                pod = self.store.get("pods", key)
            except NotFoundError:
                continue
            if not pod.spec.node_name and not pod.is_terminal():
                self._handle_pod(ADDED, pod)
        return expired

    def run_until_idle(self, max_cycles: int = 100_000) -> int:
        """Drive the loop until the active queue drains (test/bench harness)."""
        n = 0
        while n < max_cycles:
            if not self.schedule_one(timeout=0.0):
                self.pump_events()
                if not self.schedule_one(timeout=0.0):
                    break
            n += 1
        return n

    def start(self) -> None:
        """Background loop (wait.UntilWithContext(sched.ScheduleOne, 0))."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if not self.schedule_one(timeout=0.05):
                    self.queue.flush_backoff_completed()
                    self.queue.flush_unschedulable_left_over()
                    self.sweep_expired_assumes()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._watch is not None:
            self._watch.stop()
            self._watch = None
