"""schedlint — the project-native static analyzer for the scheduler's
concurrency and clone-discipline invariants (see analysis/schedlint.py).

The rules are the hack/verify-* analog of the reference Kubernetes: each one
encodes an invariant that is documented in prose somewhere in this tree
(store/store.py lock ordering, the event read-only contract, the jit static
gates) and that tier-1's behavioral tests cannot see until it has already
cost a deadlock, a corrupted watcher, or a mid-run XLA recompile.
"""

from .schedlint import Finding, run, run_paths  # noqa: F401
