"""MU001 — mutation of store-returned / event objects.

The static complement of the PR 4 runtime mutation detector
(store/store.py MutationDetector): event objects (`ev.obj` / `ev.prev`) and
store reads (`<...>store.get/list(...)`) carry the client-go read-only
contract — consumers must clone before writing. The rule runs a per-function
taint walk:

  sources      `X.obj` / `X.prev` attribute loads (event payloads; `self.obj`
               excluded) and `<recv>.get/list/list_many(...)` where the
               receiver's last segment contains "store". `.list()` results
               are CONTAINER-tainted: the returned list itself is freshly
               allocated (sorting/slicing it is fine) but its elements are
               object-tainted the moment they are indexed or iterated.
               ISSUE 15/16: `<store|cache>.pod_columns()` is an OBJECT
               source — the columnar read paths (store rows AND scheduler
               cache rows) hand out live views (read-only numpy views + the
               live key/pod/table lists), so writing through the view
               (attribute or element stores, mutator calls on its members)
               is flagged exactly like mutating an event object.
  propagation  plain data flow only: name assignment, attribute/subscript
               LOADS, tuple unpack, for-loop iteration. Calls launder taint —
               which makes every clone helper (deepcopy,
               pod_structural_clone, to_dict, dict(), .clone(), ...) a
               sanitizer for free.
  sinks        attribute/subscript STORES and aug-assigns whose base chain
               roots in a tainted value, mutating container methods
               (append/update/pop/...), and object.__setattr__/setattr on a
               tainted first argument.

Local-only by design: parameters are never tainted (callers that pass event
objects onward are covered at the site where the `.obj` load happens), so
the whole-tree run stays at zero false positives.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..findings import Finding
from ..index import FuncInfo, ProjectIndex

EVENT_ATTRS = ("obj", "prev")
MUTATORS = {"append", "extend", "insert", "add", "update", "pop", "popitem",
            "remove", "discard", "clear", "sort", "reverse", "setdefault",
            "__setattr__", "__delitem__"}
_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _recv_segment(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _recv_is_store(expr: ast.AST) -> bool:
    seg = _recv_segment(expr)
    return seg is not None and "store" in seg.lower()


def _recv_is_columnar_owner(expr: ast.AST) -> bool:
    """Receivers that hand out live columnar views via pod_columns():
    stores (ISSUE 15) and scheduler caches (ISSUE 16 — Cache.pod_columns
    returns a CacheColumnsView over the live row table)."""
    seg = _recv_segment(expr)
    return seg is not None and ("store" in seg.lower()
                                or "cache" in seg.lower())


OBJ = "obj"            # the value itself is contract-covered
CONTAINER = "container"  # fresh container of contract-covered elements


def _store_read_level(call: ast.Call) -> Optional[str]:
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "pod_columns" and _recv_is_columnar_owner(f.value):
        # pod_columns() hands out the LIVE columnar view (ISSUE 15 store
        # rows; ISSUE 16 cache rows): the value itself is contract-covered,
        # like a get() result
        return OBJ
    if (f.attr in ("get", "list", "list_many")
            and _recv_is_store(f.value)):
        return OBJ if f.attr == "get" else CONTAINER
    return None


class _Taint:
    """Per-function forward taint walk (single pass, statement order)."""

    def __init__(self, info: FuncInfo, findings: List[Finding]):
        self.info = info
        self.findings = findings
        self.tainted: Dict[str, str] = {}  # name -> OBJ | CONTAINER

    # -- expression taint ------------------------------------------------------

    def expr_tainted(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.tainted.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if expr.attr in EVENT_ATTRS and not (
                    isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return OBJ
            return OBJ if self.expr_tainted(expr.value) else None
        if isinstance(expr, ast.Subscript):
            # indexing a fresh .list() container yields contract elements
            return OBJ if self.expr_tainted(expr.value) else None
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value)
        if isinstance(expr, ast.IfExp):
            return self.expr_tainted(expr.body) or \
                self.expr_tainted(expr.orelse)
        if isinstance(expr, ast.Call):
            return _store_read_level(expr)  # every other call launders
        if isinstance(expr, (ast.Tuple, ast.List)):
            levels = [self.expr_tainted(e) for e in expr.elts]
            if OBJ in levels:
                return OBJ
            return CONTAINER if CONTAINER in levels else None
        return None

    def _root_tainted(self, target: ast.AST) -> Optional[str]:
        """Walk an attr/subscript STORE chain to its base; an object-tainted
        base (or an event-payload link in the chain) marks the write. A
        container-tainted base only counts once the chain steps INTO the
        container (bare `items.sort()` is fine — the list is fresh). A call
        anywhere in the chain breaks taint (call results are private)."""
        node = target
        via = None
        had_step = False
        while True:
            if isinstance(node, ast.Attribute):
                if node.attr in EVENT_ATTRS and not (
                        isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    via = f".{node.attr}"
                node = node.value
                had_step = True
            elif isinstance(node, ast.Subscript):
                node = node.value
                had_step = True
            elif isinstance(node, ast.Name):
                level = self.tainted.get(node.id)
                if level == OBJ or (level == CONTAINER and had_step):
                    return node.id
                return via and f"event payload ({via})"
            else:
                return via and f"event payload ({via})" \
                    if not isinstance(node, ast.Call) else None

    # -- statements ------------------------------------------------------------

    def walk(self, body) -> None:
        for stmt in body:
            self.stmt(stmt)

    def _assign_target(self, target: ast.AST, level: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            if level:
                self.tainted[target.id] = level
            else:
                self.tainted.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_target(e, level)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, level)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            root = self._root_tainted(target)
            if root:
                self._report(target, f"write to {root}")

    def _report(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "MU001", self.info.file.rel, node.lineno,
            f"{self.info.qualname}: {what} mutates a store-returned/event "
            "object",
            hint="clone first (pod_structural_clone / copy.deepcopy) or go "
                 "through a store write API; event objects are read-only "
                 "(store/store.py MutationDetector contract)"))

    def _scan_calls(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, _NESTED) or not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "__setattr__"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "object" and node.args
                    and self.expr_tainted(node.args[0])):
                self._report(node, "object.__setattr__() on a tainted value")
            elif isinstance(f, ast.Attribute) and f.attr in MUTATORS:
                root = self._root_tainted(f.value) if isinstance(
                    f.value, (ast.Name, ast.Attribute, ast.Subscript)) \
                    else None
                # .update()/.pop()/.get() on untainted receivers is ordinary
                if root:
                    self._report(node, f".{f.attr}() on {root}")
            elif isinstance(f, ast.Name) and f.id == "setattr" and node.args:
                if self.expr_tainted(node.args[0]):
                    self._report(node, "setattr() on a tainted value")

    def stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, _NESTED):
            return
        if isinstance(stmt, ast.Assign):
            self._scan_calls(stmt.value)
            t = self.expr_tainted(stmt.value)
            for target in stmt.targets:
                self._assign_target(target, t)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._scan_calls(stmt.value)
            self._assign_target(stmt.target, self.expr_tainted(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_calls(stmt.value)
            if isinstance(stmt.target, (ast.Attribute, ast.Subscript)):
                root = self._root_tainted(stmt.target)
                if root:
                    self._report(stmt.target, f"augmented write to {root}")
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_calls(stmt.iter)
            # iterating a fresh .list() container yields contract elements
            self._assign_target(
                stmt.target, OBJ if self.expr_tainted(stmt.iter) else None)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    root = self._root_tainted(t)
                    if root:
                        self._report(t, f"del on {root}")
            return
        # generic recursion: scan expressions, walk nested statement lists
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._scan_calls(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self.stmt(v)
                    elif isinstance(v, ast.expr):
                        self._scan_calls(v)
                    elif isinstance(v, ast.ExceptHandler):
                        self.walk(v.body)


def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for fi in index.files:
        for info in fi.functions:
            _Taint(info, findings).walk(info.node.body)
    return findings
