"""HP001 — per-pod instrumentation inside batch loops of scheduler/batch.py.

The flight recorder's contract (scheduler/flightrec.py, ROADMAP
instrumentation budget <2%) is "per BATCH, never per pod": stage marks,
histogram observations, recorder narration, and logging happen a handful of
times per schedule_batch call. A perf_counter read or metrics observe inside
a loop over the pod batch multiplies that by 100k and the budget is gone —
exactly the regression class tier-1's behavioral tests cannot see.

Batch loops are identified by the iterable's root name (the module's
pod-scale locals: qps, to_bind, items, rejected, ...), looking through
enumerate/zip/sorted/reversed wrappers, `.tolist()` and 1/2-arg `range(len(
...))`. Three-arg `range(0, len(x), chunk)` loops are CHUNK loops (pods /
bind_chunk iterations) and are exempt — per-chunk timing is the recorder's
own design.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..findings import Finding
from ..index import ProjectIndex

HOT_FILE_SUFFIXES = ("scheduler/batch.py",)

POD_SCALE = re.compile(
    r"^(qps|pods|pending|items|to_bind|bind_rows|bind_nodes|bind_gang|"
    r"triples|bindings|prepared|rejected|members|pairs|leftovers|errs|"
    r"errors|victims|device_idx|fallback_idx|assign_list|assignment|"
    r"events|batch|chunk)$")

INSTRUMENTATION_CALLS = {"observe", "inc", "set", "mark", "record", "step",
                         "add_outside", "note_self_time", "event", "log",
                         "info", "warning", "debug", "error", "exception"}
_METRICY = re.compile(r"^(m|metrics|fr|flightrec|clock|trace|recorder|"
                      r"logger|logging|log)$")


def _root_name(expr: ast.AST) -> Optional[str]:
    node = expr
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            f = node.func
            # look through .tolist()/.items()/.values() etc
            if isinstance(f, ast.Attribute):
                node = f.value
            elif isinstance(f, ast.Name) and f.id in (
                    "enumerate", "zip", "sorted", "reversed", "list",
                    "tuple"):
                if not node.args:
                    return None
                node = node.args[0]
            elif isinstance(f, ast.Name) and f.id == "range":
                if len(node.args) >= 3:
                    return None  # chunk loop: range(lo, len(x), step)
                node = node.args[-1] if node.args else None
                if node is None:
                    return None
            else:
                return None
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _is_pod_scale_loop(loop: ast.For) -> bool:
    root = _root_name(loop.iter)
    return root is not None and bool(POD_SCALE.match(root))


def _instrumentation_desc(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr == "perf_counter":
            return "time.perf_counter()"
        if f.attr in INSTRUMENTATION_CALLS:
            # receiver chain must look metric/recorder/logger-ish; plain
            # container .add()/.update() etc. are data structure ops
            node = f.value
            segs = []
            while isinstance(node, ast.Attribute):
                segs.append(node.attr)
                node = node.value
            if isinstance(node, ast.Name):
                segs.append(node.id)
            if any(_METRICY.match(s) for s in segs):
                return f"instrumentation call .{f.attr}() on " \
                       f"'{segs[-1]}...'"
    elif isinstance(f, ast.Name):
        if f.id == "perf_counter":
            return "perf_counter()"
        if f.id == "Trace":
            return "Trace() construction"
        if f.id == "print":
            return "print()"
    return None


def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for fi in index.files:
        norm = fi.path.replace("\\", "/")
        if not any(norm.endswith(sfx) for sfx in HOT_FILE_SUFFIXES):
            continue
        for info in fi.functions:
            for loop in ast.walk(info.node):
                if not isinstance(loop, ast.For) or \
                        not _is_pod_scale_loop(loop):
                    continue
                for node in ast.walk(loop):
                    if node is loop.iter or not isinstance(node, ast.Call):
                        continue
                    desc = _instrumentation_desc(node)
                    if desc is None:
                        continue
                    findings.append(Finding(
                        "HP001", fi.rel, node.lineno,
                        f"{info.qualname}: {desc} inside a pod-scale batch "
                        "loop",
                        hint="instrument per BATCH (StageClock marks / one "
                             "flight record), never per pod — see "
                             "scheduler/flightrec.py"))
    return findings
