"""HP001 — per-pod instrumentation inside batch loops of the hot scheduler
files (scheduler/batch.py and scheduler/podtrace.py) and the controller
reconcile loops (controllers/base.py, ISSUE 9).

The flight recorder's contract (scheduler/flightrec.py, ROADMAP
instrumentation budget <2%) is "per BATCH, never per pod": stage marks,
histogram observations, recorder narration, and logging happen a handful of
times per schedule_batch call. A perf_counter read or metrics observe inside
a loop over the pod batch multiplies that by 100k and the budget is gone —
exactly the regression class tier-1's behavioral tests cannot see.

Batch loops are identified by the iterable's root name (the module's
pod-scale locals: qps, to_bind, items, rejected, ...), looking through
enumerate/zip/sorted/reversed wrappers, `.tolist()` and 1/2-arg `range(len(
...))`. Three-arg `range(0, len(x), chunk)` loops are CHUNK loops (pods /
bind_chunk iterations) and are exempt — per-chunk timing is the recorder's
own design.

Sampled-tracing exception (ISSUE 7): the pod tracer's lifecycle stamps ARE
per-pod work — legal ONLY behind a membership check against the sampled set
(`if key in self._sampled: span.stamp(...)`), which bounds the paying
population to K reservoir slots while unsampled pods pay one set lookup.
Instrumentation calls lexically inside an `if` whose test contains an
`x in <something named *sampled*>` comparison are therefore allowed; the
same call unguarded is a finding.

Reconcile loops (ISSUE 9): controllers/base.py drains its workqueue
(`for key in keys:`) and its watch buffer (`for ev in <watch>.drain(...):`)
at event scale — a 10k-object relist marks 10k keys per drain. The
ReconcileRecorder taps are per LOOP (two perf_counter reads around the
whole drain, one recorder.loop()/pump() call); per-key instrumentation
inside those loops is the same multiplier bug as per-pod stamping in
batch.py. `.drain(...)` iterables are recognized as event-scale regardless
of the receiver expression.

Steady-state telemetry (ISSUE 13): obs/timeseries.py and obs/resource.py
are hot files too — their contract is taps per WINDOW close / per SAMPLE
tick, never per pod. A note_batch/note_stage call is one tap per batch by
design; anything instrumenting inside a pod-scale loop of these files
(someone feeding the window per pod "for accuracy") is the same 100k
multiplier the flight recorder's budget forbids.

Trace timeline (ISSUE 18): obs/tracebuf.py and obs/critpath.py carry the
same contract — trace-buffer taps (note_batch/note_span/instant/counter)
are per batch / per chunk / per cycle / per window, NEVER per pod outside
a sampled-set membership check, and the analyzers iterate the ≤K-sampled
span set only. A `tracebuf.ACTIVE.instant(...)` inside a pod-scale loop
would turn the <1% armed budget into a per-pod ring append at 100k scale.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..findings import Finding
from ..index import FuncInfo, ProjectIndex, render_chain

HOT_FILE_SUFFIXES = ("scheduler/batch.py", "scheduler/podtrace.py",
                     "controllers/base.py", "obs/timeseries.py",
                     "obs/resource.py", "obs/tracebuf.py",
                     "obs/critpath.py")

POD_SCALE = re.compile(
    r"^(qps|pods|pending|items|to_bind|bind_rows|bind_nodes|bind_gang|"
    r"triples|bindings|prepared|rejected|members|pairs|leftovers|errs|"
    r"errors|victims|device_idx|fallback_idx|assign_list|assignment|"
    r"events|batch|chunk|keys)$")

INSTRUMENTATION_CALLS = {"observe", "observe_many", "inc", "set", "mark",
                         "record", "step", "stamp", "add_outside",
                         "note_self_time", "event", "log", "info", "warning",
                         "debug", "error", "exception",
                         # trace-buffer taps (obs/tracebuf.py, ISSUE 18)
                         "instant", "counter", "note_span", "note_batch"}
_METRICY = re.compile(r"^(m|metrics|fr|flightrec|clock|trace|recorder|"
                      r"logger|logging|log|sp|span|spans|tracer|podtrace|"
                      r"pt|latency|tracebuf|_tracebuf|tb|buf|ACTIVE)$")

# the membership guard that legalizes per-pod stamping: any name segment of
# the `in` comparator matching this (self._sampled, sampled, sampled_set)
_SAMPLED = re.compile(r"sampled")

# terminal-path helpers (failure/requeue/rollback/serial-fallback handlers)
# are exempt from the interprocedural form: every pod on those paths owes a
# terminal status by contract, so per-pod narration there is the design,
# not the multiplier bug
_TERMINAL_PATH = re.compile(
    r"fail|error|serial|fallback|reject|requeue|veto|evict|preempt|"
    r"rollback|cancel")

# how deep the via-call-chain form follows hot-file helpers
_VIA_DEPTH = 3


def _root_name(expr: ast.AST) -> Optional[str]:
    node = expr
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            f = node.func
            # look through .tolist()/.items()/.values() etc
            if isinstance(f, ast.Attribute):
                if f.attr == "drain":
                    # a watch-buffer drain is event-scale whatever the
                    # receiver is called (self._watch.drain(n), w.drain())
                    return "events"
                node = f.value
            elif isinstance(f, ast.Name) and f.id in (
                    "enumerate", "zip", "sorted", "reversed", "list",
                    "tuple"):
                if not node.args:
                    return None
                node = node.args[0]
            elif isinstance(f, ast.Name) and f.id == "range":
                if len(node.args) >= 3:
                    return None  # chunk loop: range(lo, len(x), step)
                node = node.args[-1] if node.args else None
                if node is None:
                    return None
            else:
                return None
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _name_segments(node: ast.AST) -> List[str]:
    segs: List[str] = []
    while isinstance(node, ast.Attribute):
        segs.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        segs.append(node.id)
    return segs


def _is_pod_scale_loop(loop: ast.For) -> bool:
    root = _root_name(loop.iter)
    return root is not None and bool(POD_SCALE.match(root))


def _has_sampled_guard(test: ast.AST) -> bool:
    """True when the if-test contains `x in <...sampled...>` — the
    membership check that bounds per-pod stamping to the K-slot sample."""
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, ast.In) and any(
                    _SAMPLED.search(s) for s in _name_segments(comp)):
                return True
    return False


def _instrumentation_desc(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr == "perf_counter":
            return "time.perf_counter()"
        if f.attr in INSTRUMENTATION_CALLS:
            # receiver chain must look metric/recorder/logger/tracer-ish;
            # plain container .add()/.update() etc. are data structure ops
            segs = _name_segments(f.value)
            if any(_METRICY.match(s) for s in segs):
                return f"instrumentation call .{f.attr}() on " \
                       f"'{segs[-1]}...'"
    elif isinstance(f, ast.Name):
        if f.id == "perf_counter":
            return "perf_counter()"
        if f.id == "Trace":
            return "Trace() construction"
        if f.id == "print":
            return "print()"
    return None


def _scan_loop_body(node: ast.AST, guarded: bool, hits: List) -> None:
    """Collect unguarded instrumentation calls, tracking sampled-set guards:
    descending into an `if <... in ...sampled...>` body flips guarded on;
    the orelse branch keeps the surrounding state."""
    if isinstance(node, ast.If) and _has_sampled_guard(node.test):
        for child in node.body:
            _scan_loop_body(child, True, hits)
        for child in node.orelse:
            _scan_loop_body(child, guarded, hits)
        return
    if isinstance(node, ast.Call) and not guarded:
        desc = _instrumentation_desc(node)
        if desc is not None:
            hits.append((node, desc))
    for child in ast.iter_child_nodes(node):
        _scan_loop_body(child, guarded, hits)


def _scan_loop_calls(node: ast.AST, guarded: bool, calls: List) -> None:
    """Collect the Call nodes in a loop body with the sampled-guard state
    at each site (guarded calls are legal whatever their callee does)."""
    if isinstance(node, ast.If) and _has_sampled_guard(node.test):
        for child in node.body:
            _scan_loop_calls(child, True, calls)
        for child in node.orelse:
            _scan_loop_calls(child, guarded, calls)
        return
    if isinstance(node, ast.Call) and not guarded:
        calls.append(node)
    for child in ast.iter_child_nodes(node):
        _scan_loop_calls(child, guarded, calls)


def _func_instrumentation(info: FuncInfo) -> List:
    """Unguarded instrumentation calls anywhere in a function body (the
    sampled-set guard exception applies exactly as in the loop scan)."""
    hits: List = []
    for stmt in info.node.body:
        _scan_loop_body(stmt, False, hits)
    return hits


def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    hot_files = []
    hot_infos = set()
    for fi in index.files:
        norm = fi.path.replace("\\", "/")
        if any(norm.endswith(sfx) for sfx in HOT_FILE_SUFFIXES):
            hot_files.append(fi)
            hot_infos.update(fi.functions)

    def _follow(_caller, _call, callee):
        return callee in hot_infos and not _TERMINAL_PATH.search(callee.name)

    for fi in hot_files:
        for info in fi.functions:
            for loop in ast.walk(info.node):
                if not isinstance(loop, ast.For) or \
                        not _is_pod_scale_loop(loop):
                    continue
                hits: List = []
                # the iterable expression runs per pod too (a clock.mark()
                # in a sort key multiplies just like one in the body)
                _scan_loop_body(loop.iter, False, hits)
                for stmt in loop.body + loop.orelse:
                    _scan_loop_body(stmt, False, hits)
                for node, desc in hits:
                    findings.append(Finding(
                        "HP001", fi.rel, node.lineno,
                        f"{info.qualname}: {desc} inside a pod-scale batch "
                        "loop",
                        hint="instrument per BATCH (StageClock marks / one "
                             "flight record), never per pod — or guard the "
                             "stamp behind the sampled-set membership check "
                             "(`if key in ...sampled...:`); see "
                             "scheduler/flightrec.py + scheduler/podtrace.py"))

                # interprocedural form (ISSUE 20): an unguarded call from
                # the pod-scale loop into a hot-file helper that instruments
                # unconditionally is the same multiplier, one hop removed
                calls: List = []
                _scan_loop_calls(loop.iter, False, calls)
                for stmt in loop.body + loop.orelse:
                    _scan_loop_calls(stmt, False, calls)
                reported = set()
                for call in calls:
                    callee = index.resolve_call(fi, info, call)
                    if callee is None or not _follow(info, call, callee):
                        continue
                    offender = chain = None
                    if _func_instrumentation(callee):
                        offender, chain = callee, [info, callee]
                    else:
                        reached = index.callgraph.reachable_from(
                            [callee], depth=_VIA_DEPTH, follow=_follow)
                        for f2, ch in sorted(
                                reached.items(),
                                key=lambda kv: len(kv[1])):
                            if _func_instrumentation(f2):
                                offender, chain = f2, [info] + ch
                                break
                    if offender is None or call.lineno in reported:
                        continue
                    reported.add(call.lineno)
                    ihits = _func_instrumentation(offender)
                    findings.append(Finding(
                        "HP001", fi.rel, call.lineno,
                        f"{info.qualname}: per-pod call reaches {ihits[0][1]}"
                        f" in {offender.qualname} via call chain "
                        f"{render_chain(chain)} — instrumentation one helper"
                        " deep still multiplies per pod",
                        hint="instrument per BATCH, or guard the call behind"
                             " the sampled-set membership check; terminal-"
                             "path helpers (fail/requeue/serial) are exempt"
                             " by name"))
    return findings
