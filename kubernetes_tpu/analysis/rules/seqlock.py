"""SEQ001 / SEQ002 — the shm seqlock protocol (ISSUE 20).

`store/shm.py`'s arena header carries a version word (`_H_VER`): the
writer bumps it to ODD, writes the protected row count (`_H_NROWS`), and
bumps it back to EVEN (`ShmArena.publish`); a reader snapshots the
version, reads the data, and RE-CHECKS the version — an odd or changed
version means the read tore mid-publish and must retry
(`ShmArenaReader.nrows`). These rules police that protocol in the files
that own it (`store/shm.py`, `scheduler/mpsched.py`,
`scheduler/mpworker.py`):

SEQ001 (reader side)
  * a function that reads the version word and then protected data but
    never re-checks the version AFTER the data read (a `v0 % 2 == 0`
    parity test alone is not a re-check — the version must be READ again
    and compared) has the torn-read bug;
  * a raw numpy view of the shared segment (a header/column subscript, or
    anything rooted in an `.arrays` map) must not outlive the retry
    scope: returning it or storing it on `self` from a retry-protocol
    function escapes a view whose contents the next publish will shear.
    Laundering through `int()`/`float()`/`.copy()`/`.tolist()`/`list()`
    (a value copy) is the fix — `np.asarray` is NOT laundering, it
    aliases.

SEQ002 (writer side)
  * a write to the protected row-count word needs the version bump on
    BOTH sides (the publish() shape) — a bump on only one side leaves a
    window where a reader sees a torn count with an even version;
  * arena column-array writes (`arrs["cpu"][i] = ...`) must be followed
    by a `.publish(...)` in the same function — columns written but never
    published are invisible to every reader (or worse, half-visible
    under the OLD count).

Fresh-segment builders (`grow`, `_alloc_segment`) legitimately write
without the bracket — readers cannot map a generation before the control
word flips — and carry `# schedlint: allow(SEQ002)` suppressions saying
exactly that, which keeps the exemption documented where it lives.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from ..findings import Finding
from ..index import FuncInfo, ProjectIndex

SEQ_FILE_SUFFIXES = ("store/shm.py", "scheduler/mpsched.py",
                     "scheduler/mpworker.py")

_VER = re.compile(r"VER")
_NROWS = re.compile(r"NROWS")
_ARRAYS_SEG = re.compile(r"^(arrays|arrs|narrs)$")

# value-copy wrappers that launder a raw view into a private value
_LAUNDER_CALLS = frozenset({"int", "float", "bool", "str", "len", "list",
                            "tuple", "dict", "set", "array"})
_LAUNDER_METHODS = frozenset({"copy", "tolist", "item", "sum", "all",
                              "any", "min", "max"})

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _subscript_index_matches(node: ast.Subscript, pat) -> bool:
    sl = node.slice
    if isinstance(sl, ast.Name):
        return bool(pat.search(sl.id))
    if isinstance(sl, ast.Attribute):
        return bool(pat.search(sl.attr))
    return False


def _root_segments(node: ast.AST) -> List[str]:
    segs: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            segs.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            segs.append(node.id)
            return segs
        else:
            return segs


def _walk_no_nested(root: ast.AST):
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _NESTED):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _FuncSeq:
    """Seqlock-relevant facts about one function."""

    def __init__(self, info: FuncInfo, arrays_names: Set[str]):
        self.info = info
        self.ver_reads: List[ast.Subscript] = []
        self.ver_writes: List[ast.AST] = []
        self.nrows_reads: List[ast.Subscript] = []
        self.nrows_writes: List[ast.AST] = []
        self.col_writes: List[ast.AST] = []
        self.has_publish = False
        self.recheck = False
        self.arrays_names = arrays_names
        self._collect()

    def _is_arrays_rooted(self, node: ast.AST) -> bool:
        segs = _root_segments(node)
        return any(_ARRAYS_SEG.match(s) for s in segs) or \
            any(s in self.arrays_names for s in segs)

    def _collect(self) -> None:
        node = self.info.node
        for sub in _walk_no_nested(node):
            if isinstance(sub, ast.Subscript):
                store = isinstance(sub.ctx, (ast.Store, ast.AugStore)) \
                    if hasattr(ast, "AugStore") else \
                    isinstance(sub.ctx, ast.Store)
                if _subscript_index_matches(sub, _VER):
                    (self.ver_writes if store else
                     self.ver_reads).append(sub)
                elif _subscript_index_matches(sub, _NROWS):
                    (self.nrows_writes if store else
                     self.nrows_reads).append(sub)
                elif store and self._is_arrays_rooted(sub.value):
                    self.col_writes.append(sub)
            elif isinstance(sub, ast.AugAssign) and \
                    isinstance(sub.target, ast.Subscript):
                if _subscript_index_matches(sub.target, _VER):
                    self.ver_writes.append(sub.target)
                elif _subscript_index_matches(sub.target, _NROWS):
                    self.nrows_writes.append(sub.target)
            elif isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr == "publish":
                    self.has_publish = True
            elif isinstance(sub, ast.Compare):
                for part in [sub.left] + list(sub.comparators):
                    for n2 in ast.walk(part):
                        if isinstance(n2, ast.Subscript) and \
                                _subscript_index_matches(n2, _VER):
                            self.recheck = True


def _collect_arrays_names(info: FuncInfo) -> Set[str]:
    """Local names bound to an `.arrays` map (ba = reader.arrays) — their
    subscripts are raw shared views."""
    out: Set[str] = set()
    for sub in _walk_no_nested(info.node):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Attribute) and \
                _ARRAYS_SEG.match(sub.value.attr):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _is_laundered(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in _LAUNDER_CALLS:
            return True
        if isinstance(f, ast.Attribute) and f.attr in _LAUNDER_METHODS:
            return True
    return False


def _raw_view_expr(expr: ast.AST, seq: "_FuncSeq",
                   raw_names: Set[str]) -> Optional[str]:
    """A short label when `expr` is (or aliases) a raw shared view."""
    if _is_laundered(expr):
        return None
    if isinstance(expr, ast.Name) and expr.id in raw_names:
        return expr.id
    if isinstance(expr, ast.Subscript):
        segs = _root_segments(expr.value)
        if any(_ARRAYS_SEG.match(s) for s in segs) or \
                any(s in seq.arrays_names for s in segs) or \
                any("hdr" in s for s in segs):
            return ".".join(reversed(segs))
    if isinstance(expr, ast.Attribute) and _ARRAYS_SEG.match(expr.attr):
        return expr.attr
    return None


def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for fi in index.files:
        norm = fi.path.replace("\\", "/")
        if not any(norm.endswith(sfx) for sfx in SEQ_FILE_SUFFIXES):
            continue
        for info in fi.functions:
            arrays_names = _collect_arrays_names(info)
            seq = _FuncSeq(info, arrays_names)

            # SEQ001: version read + protected data read, no re-check
            if seq.ver_reads and seq.nrows_reads and not seq.recheck:
                findings.append(Finding(
                    "SEQ001", fi.rel, seq.nrows_reads[0].lineno,
                    f"{info.qualname}: reads the seqlock version word but "
                    f"never re-checks it AFTER the data read — a publish "
                    f"racing this read tears the value undetected",
                    hint="retry loop: v0 = hdr[_H_VER]; read; accept only "
                         "if v0 is even and hdr[_H_VER] == v0 still "
                         "(store/shm.py ShmArenaReader.nrows)"))

            # SEQ001: raw views escaping a retry-protocol function
            if seq.ver_reads:
                raw_names: Set[str] = set()
                for sub in _walk_no_nested(info.node):
                    if isinstance(sub, ast.Assign):
                        label = _raw_view_expr(sub.value, seq, raw_names)
                        if label:
                            for tgt in sub.targets:
                                if isinstance(tgt, ast.Name):
                                    raw_names.add(tgt.id)
                        escape = label if label else None
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) and \
                                    tgt.value.id == "self" and escape:
                                findings.append(Finding(
                                    "SEQ001", fi.rel, sub.lineno,
                                    f"{info.qualname}: raw shared-segment "
                                    f"view `{escape}` stored on self — it "
                                    f"outlives the retry scope and the "
                                    f"next publish shears it",
                                    hint="launder the value (int()/.copy()/"
                                         ".tolist()) inside the retry "
                                         "scope; np.asarray aliases, it "
                                         "does not copy"))
                    elif isinstance(sub, ast.Return) and \
                            sub.value is not None:
                        label = _raw_view_expr(sub.value, seq, raw_names)
                        if label:
                            findings.append(Finding(
                                "SEQ001", fi.rel, sub.lineno,
                                f"{info.qualname}: returns raw shared-"
                                f"segment view `{label}` — it outlives the "
                                f"retry scope and the next publish shears "
                                f"it",
                                hint="launder the value (int()/.copy()/"
                                     ".tolist()) inside the retry scope; "
                                     "np.asarray aliases, it does not "
                                     "copy"))

            # SEQ002: protected-word write without the both-sides bump
            for w in seq.nrows_writes:
                before = any(v.lineno < w.lineno for v in seq.ver_writes)
                after = any(v.lineno > w.lineno for v in seq.ver_writes)
                if not (before and after):
                    findings.append(Finding(
                        "SEQ002", fi.rel, w.lineno,
                        f"{info.qualname}: writes the protected row-count "
                        f"word without the version bump on BOTH sides — a "
                        f"reader can accept a torn count under an even "
                        f"version",
                        hint="publish() shape: hdr[_H_VER] += 1; "
                             "hdr[_H_NROWS] = n; hdr[_H_VER] += 1 "
                             "(store/shm.py)"))

            # SEQ002: column writes never published
            if seq.col_writes and not seq.has_publish and \
                    not seq.nrows_writes:
                findings.append(Finding(
                    "SEQ002", fi.rel, seq.col_writes[0].lineno,
                    f"{info.qualname}: writes arena column arrays but "
                    f"never calls .publish(...) — the rows are invisible "
                    f"(or half-visible under the old count) to every "
                    f"reader",
                    hint="write the columns, then publish(n) — the "
                         "version bump pair makes the new rows visible "
                         "atomically (scheduler/mpsched.py "
                         "_publish_round)"))
    return findings
