"""LK001 / LK002 — the store's lock discipline.

LK001 (lock-order inversion): the module docstring of store/store.py
mandates the RANKED chain `_lock` (global RV, rank 0) -> `_pods_lock` (pods
shard, rank 1) -> `_nodes_lock` (nodes shard, rank 2; ISSUE 15 satellite) —
acquire strictly in ascending rank, never backwards. We build a
per-function acquisition model over `with` statements (including the
`_pods_pair` / `_nodes_pair` / `_store_chain` / `_kind_lock()` /
`transaction()` composite acquirers, which enter in rank order and are
therefore order-safe to ENTER but count as a fresh global acquisition),
close "may acquire" summaries over the resolved call graph, and flag any
point where a shard is definitely held, the global lock is not, and an
acquisition of LOWER rank (the global lock, a composite, or a lower-ranked
shard — direct or via a call path) follows.

LK001 partition extension (ISSUE 12): the partitioned dispatch layer's
locks — `PartitionRouter._route_lock` and
`PartitionedScheduler._dispatch_lock` (scheduler/partition.py) — are LEAF
locks ordered strictly after the whole store chain. While one is
definitely held, ANY store-lock acquisition (global, shard, or the
composite pair — direct or via a resolved call path) is an inversion.

LK002 (blocking while locked): within any recognized lock region — and in
every function reachable from one through resolved calls — flag calls that
can block or dispatch long work: time.sleep, zero-arg .join(), blocking
queue .get()/.put() (queue-ish receivers, `_nowait` excluded), jax/jnp
dispatch (including calls to known-jitted functions), watch-callback
delivery (`on_event`), and the GIL-RELEASING native kernels (ISSUE 11: the
ctypes-CDLL entry points in native/hostsched.py drop the GIL for the call's
duration — releasing it inside a store/scheduler lock region invites the
classic GIL/lock interleavings; see the NATIVE LOCK RULE in store/store.py.
The PyDLL commit-engine entries in native/hostcommit.py HOLD the GIL and
are deliberately NOT in this set — being called under the store locks is
their whole point). Lock identity is qualified by the enclosing class, so
Cache._lock and APIStore._lock never alias.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..findings import Finding
from ..index import FileIndex, FuncInfo, ProjectIndex

GLOBAL = ("APIStore", "_lock")
SHARD = ("APIStore", "_pods_lock")
NODES_SHARD = ("APIStore", "_nodes_lock")
PAIR = ("APIStore", "<pair>")  # global-then-shard(s) composite (order-safe)

# the ranked shard set (store/store.py ordering table). Generalizing LK001
# (ISSUE 15 satellite): holding a shard of rank r, any acquisition of rank
# < r — the global lock, a composite (which starts at the global lock), or
# a lower-ranked shard — is an inversion.
SHARD_RANKS = {SHARD: 1, NODES_SHARD: 2}

# Partitioned-dispatch locks (ISSUE 12, scheduler/partition.py): LEAF locks
# ordered strictly AFTER the store chain — code holding one may touch only
# the router/coordinator's own bookkeeping. Acquiring (directly or via any
# resolved call path) the store's global/shard locks while a dispatch lock
# is held is an LK001 inversion: every pipeline's commit path takes the
# store locks, and a store call under a dispatch lock would deadlock
# against any store client that consults the router.
PART_LOCKS = frozenset({
    ("PartitionRouter", "_route_lock"),
    ("PartitionedScheduler", "_dispatch_lock"),
})
STORE_LOCKS = frozenset({GLOBAL, SHARD, NODES_SHARD, PAIR})

_QUEUEISH = re.compile(r"(^|_)q$|queue", re.IGNORECASE)

# subprocess entry points that block until the child runs (ISSUE 20
# satellite: the pinned interprocedural regression hides one of these a
# helper deep under a store lock)
_SUBPROCESS_CALLS = frozenset({
    "run", "call", "check_call", "check_output", "Popen"})

# GIL-releasing native entry points (ctypes CDLL wrappers in
# native/hostsched.py): blocking under LK002 — the call drops the GIL until
# the C kernel returns. The PyDLL commit engine (native/hostcommit.py
# bind_prepare/bind_commit/delete_commit/assume_structural/batch_rows) holds
# the GIL and is NOT listed.
_NATIVE_GIL_RELEASING = frozenset({
    "native_greedy_solve",
    "native_commit_deltas",
})

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _last_segment(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _local_assignments(func_node: ast.AST, name: str) -> List[ast.AST]:
    out = []
    for node in ast.walk(func_node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    out.append(node.value)
    return out


class _FuncModel:
    """Everything the two rules need to know about one function."""

    def __init__(self, info: FuncInfo):
        self.info = info
        self.direct_acquires: Set[Tuple[str, str]] = set()
        self.calls: List[Tuple[ast.Call, Optional[FuncInfo]]] = []
        # calls made while >= 1 lock frame is held (entry points of the
        # reachable-under-lock BFS)
        self.locked_calls: List[Tuple[ast.Call, Optional[FuncInfo], str]] = []
        self.blocking_sites: List[Tuple[ast.AST, str]] = []
        # LK001 candidates: (call node, callee, definitely-held shard rank)
        self.inversion_call_sites: List[Tuple[ast.Call, FuncInfo, int]] = []
        self.inversion_direct: List[Tuple[ast.AST, str]] = []
        # calls made while a partition/dispatch LEAF lock is definitely held
        # (ISSUE 12): any callee that may acquire a store lock is an LK001
        self.part_call_sites: List[Tuple[ast.Call, FuncInfo]] = []


def _classify_lock(expr: ast.AST, func: FuncInfo,
                   depth: int = 0) -> Optional[Set[Tuple[str, str]]]:
    """Lock tokens a with-item may acquire; None = not a lock region."""
    cls = func.class_name or "<module>"
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        if attr in ("_pods_pair", "_nodes_pair", "_store_chain"):
            return {PAIR}
        if "lock" in attr or attr.endswith("_pair"):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return {(cls, attr)}
            return {("<other>", attr)}
        return None
    if isinstance(expr, ast.Call):
        seg = _last_segment(expr.func)
        if seg in ("_kind_lock", "transaction"):
            return {PAIR}
        return None
    if isinstance(expr, ast.IfExp) and depth < 4:
        # conditional lock selection (get()'s per-kind shard pick): either
        # branch may be the acquired lock
        toks: Set[Tuple[str, str]] = set()
        for sub in (expr.body, expr.orelse):
            got = _classify_lock(sub, func, depth + 1)
            if got:
                toks |= got
        return toks or None
    if isinstance(expr, ast.Name) and depth < 4:
        toks: Set[Tuple[str, str]] = set()
        for rhs in _local_assignments(func.node, expr.id):
            sub_exprs = ([rhs.body, rhs.orelse]
                         if isinstance(rhs, ast.IfExp) else [rhs])
            for sub in sub_exprs:
                got = _classify_lock(sub, func, depth + 1)
                if got:
                    toks |= got
        return toks or None
    return None


def _is_jax_root(expr: ast.AST) -> bool:
    node = expr
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in ("jax", "jnp")


def _blocking_desc(call: ast.Call, func: FuncInfo, index: ProjectIndex,
                   jitted_names: Set[str], fi: FileIndex) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        recv_seg = _last_segment(f.value)
        if f.attr == "sleep" and isinstance(f.value, ast.Name) \
                and f.value.id == "time":
            return "time.sleep()"
        if f.attr in _SUBPROCESS_CALLS and isinstance(f.value, ast.Name) \
                and fi.imports.get(f.value.id, f.value.id) == "subprocess":
            return f"subprocess.{f.attr}() (blocks on the child process)"
        if f.attr == "join" and not call.args and not call.keywords:
            return "blocking .join()"
        if f.attr in ("get", "put") and recv_seg \
                and _QUEUEISH.search(recv_seg):
            return f"blocking queue .{f.attr}() (use the _nowait form or " \
                   "move it outside the lock)"
        if f.attr == "block_until_ready":
            return "device sync .block_until_ready()"
        if f.attr == "on_event":
            return "watch callback delivery (on_event)"
        if f.attr in _NATIVE_GIL_RELEASING:
            return (f"GIL-releasing native kernel ({f.attr}: ctypes CDLL "
                    "drops the GIL — store/store.py NATIVE LOCK RULE)")
        if _is_jax_root(f):
            return f"jax dispatch ({ast.unparse(f)})" \
                if hasattr(ast, "unparse") else "jax dispatch"
    elif isinstance(f, ast.Name):
        if f.id in _NATIVE_GIL_RELEASING:
            return (f"GIL-releasing native kernel ({f.id}: ctypes CDLL "
                    "drops the GIL — store/store.py NATIVE LOCK RULE)")
        if f.id == "sleep" and fi.imports.get("sleep", "").startswith("time"):
            return "time.sleep()"
        if f.id in _SUBPROCESS_CALLS \
                and fi.imports.get(f.id, "").startswith("subprocess"):
            return f"subprocess.{f.id}() (blocks on the child process)"
        if f.id in jitted_names:
            return f"jitted-solver call ({f.id})"
        # a local callable loaded from an `on_event` attribute (the store's
        # `cb = self.on_event; cb()` delivery ping)
        for rhs in _local_assignments(func.node, f.id):
            if isinstance(rhs, ast.Attribute) and rhs.attr == "on_event":
                return "watch callback delivery (on_event)"
    return None


class _Walker:
    """Statement walk with a with-lock frame stack (nested defs skipped)."""

    def __init__(self, model: _FuncModel, index: ProjectIndex,
                 jitted_names: Set[str]):
        self.m = model
        self.index = index
        self.jitted_names = jitted_names
        self.frames: List[Set[Tuple[str, str]]] = []

    # lock-state queries -------------------------------------------------------

    def _definite_shard_rank(self) -> int:
        """Highest rank among frames that are DEFINITELY one held shard
        (a single-token frame naming a ranked shard); 0 = none held."""
        r = 0
        for fr in self.frames:
            if len(fr) == 1:
                rank = SHARD_RANKS.get(next(iter(fr)), 0)
                if rank > r:
                    r = rank
        return r

    def _part_definite(self) -> bool:
        return any(fr and fr <= PART_LOCKS for fr in self.frames)

    def _global_possible(self) -> bool:
        return any(GLOBAL in fr or PAIR in fr for fr in self.frames)

    def _any_lock_held(self) -> bool:
        return bool(self.frames)

    # traversal ----------------------------------------------------------------

    def walk_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, _NESTED):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self._scan_expr(item.context_expr)
                toks = _classify_lock(item.context_expr, self.m.info)
                if toks:
                    self._note_acquisition(item.context_expr, toks)
                    self.frames.append(set(toks))
                    pushed += 1
            self.walk_body(stmt.body)
            for _ in range(pushed):
                self.frames.pop()
            return
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._scan_expr(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self.walk_stmt(v)
                    elif isinstance(v, ast.expr):
                        self._scan_expr(v)
                    elif isinstance(v, ast.ExceptHandler):
                        self.walk_body(v.body)

    def _note_acquisition(self, node: ast.AST,
                          toks: Set[Tuple[str, str]]) -> None:
        # a composite may be any of the pair/chain helpers: it may acquire
        # the global lock and any shard (conservative for the call-graph
        # closure; always order-safe to enter directly)
        self.m.direct_acquires |= (({GLOBAL} | set(SHARD_RANKS))
                                   if PAIR in toks else toks)
        held = self._definite_shard_rank()
        if held and not self._global_possible():
            if GLOBAL in toks or PAIR in toks:
                self.m.inversion_direct.append(
                    (node, "acquires the global RV lock while holding a "
                           "kind shard"))
        if held:
            for tok in toks:
                if SHARD_RANKS.get(tok, held) < held:
                    self.m.inversion_direct.append(
                        (node, f"acquires {tok[1]} while holding a "
                               "higher-ranked kind shard (ascending-rank "
                               "rule, store/store.py ordering table)"))
        if self._part_definite() and toks & STORE_LOCKS:
            self.m.inversion_direct.append(
                (node, "acquires a store lock while holding a partition/"
                       "dispatch leaf lock (scheduler/partition.py lock "
                       "discipline)"))

    def _scan_expr(self, expr: ast.expr) -> None:
        for node in ast.walk(expr):
            if isinstance(node, _NESTED):
                continue
            if not isinstance(node, ast.Call):
                continue
            callee = self.index.resolve_call(self.m.info.file, self.m.info,
                                             node)
            self.m.calls.append((node, callee))
            if self._any_lock_held():
                lock_desc = "/".join(sorted(
                    f"{c}.{a}" for fr in self.frames for c, a in fr))
                self.m.locked_calls.append((node, callee, lock_desc))
            desc = _blocking_desc(node, self.m.info, self.index,
                                  self.jitted_names, self.m.info.file)
            if desc is not None:
                self.m.blocking_sites.append((node, desc))
            held = self._definite_shard_rank()
            if callee is not None and held \
                    and not self._global_possible():
                self.m.inversion_call_sites.append((node, callee, held))
            if callee is not None and self._part_definite():
                self.m.part_call_sites.append((node, callee))


def check(index: ProjectIndex) -> List[Finding]:
    from .jit import jitted_local_names

    findings: List[Finding] = []
    models: Dict[FuncInfo, _FuncModel] = {}
    jit_names_by_file = jitted_local_names(index)

    for fi in index.files:
        names = jit_names_by_file.get(fi.path, set())
        for info in fi.functions:
            m = _FuncModel(info)
            w = _Walker(m, index, names)
            w.walk_body(info.node.body)
            models[info] = m

    # may-acquire closure over the resolved call graph (fixpoint)
    acquires: Dict[FuncInfo, Set[Tuple[str, str]]] = {
        info: set(m.direct_acquires) for info, m in models.items()}
    changed = True
    while changed:
        changed = False
        for info, m in models.items():
            for _call, callee in m.calls:
                if callee is not None and callee in acquires:
                    extra = acquires[callee] - acquires[info]
                    if extra:
                        acquires[info] |= extra
                        changed = True

    # LK001
    for info, m in models.items():
        for node, why in m.inversion_direct:
            findings.append(Finding(
                "LK001", info.file.rel, node.lineno,
                f"{info.qualname}: {why}",
                hint="store/store.py rule: _lock (global) -> _pods_lock "
                     "(shard), never reversed; release the shard first "
                     "(bind_many's two-phase pattern)"))
        for call, callee, held in m.inversion_call_sites:
            acq = acquires.get(callee, set())
            lower = GLOBAL in acq or any(
                SHARD_RANKS.get(tok, held) < held for tok in acq)
            if lower:
                findings.append(Finding(
                    "LK001", info.file.rel, call.lineno,
                    f"{info.qualname}: call to {callee.qualname} can acquire "
                    "a lower-ranked store lock while a kind shard is held",
                    hint="hoist the call out of the shard-only section or "
                         "take the locks in the ordering table's ascending "
                         "rank (store/store.py: _lock -> _pods_lock -> "
                         "_nodes_lock)"))
        for call, callee in m.part_call_sites:
            if acquires.get(callee, set()) & STORE_LOCKS:
                findings.append(Finding(
                    "LK001", info.file.rel, call.lineno,
                    f"{info.qualname}: call to {callee.qualname} can acquire "
                    "a store lock while a partition/dispatch leaf lock is "
                    "held",
                    hint="dispatch locks are LEAVES (scheduler/partition.py "
                         "lock discipline): compute the routing decision "
                         "under the lock, release, then call the store/"
                         "queue/cache"))

    # LK002: functions reachable from any lock region, carrying the FULL
    # resolved call chain (ISSUE 20 — the interprocedural closure), bounded
    # by the shared callgraph depth cap
    cg = index.callgraph
    reachable: Dict[FuncInfo, Tuple[str, List[str]]] = {}
    frontier: List[FuncInfo] = []
    for info, m in models.items():
        for _call, callee, lock_desc in m.locked_calls:
            if callee is not None and callee not in reachable:
                reachable[callee] = (lock_desc,
                                     [info.qualname, callee.qualname])
                frontier.append(callee)
    depth = 1
    while frontier and depth < cg.DEPTH_CAP:
        depth += 1
        nxt: List[FuncInfo] = []
        for cur in frontier:
            lock_desc, chain = reachable[cur]
            for _call, callee in models.get(cur, _FuncModel(cur)).calls:
                if callee is not None and callee not in reachable:
                    reachable[callee] = (lock_desc,
                                         chain + [callee.qualname])
                    nxt.append(callee)
        frontier = nxt
    if reachable:
        deepest = max(len(c) - 1 for _d, c in reachable.values())
        if deepest > cg.max_depth_seen:
            cg.max_depth_seen = deepest

    seen: Set[Tuple[str, int]] = set()
    for info, m in models.items():
        lock_lines = {c.lineno for c, _cal, _d in m.locked_calls}
        for node, desc in m.blocking_sites:
            direct = node.lineno in lock_lines
            via = reachable.get(info)
            if not direct and via is None:
                continue
            key = (info.file.rel, node.lineno)
            if key in seen:
                continue
            seen.add(key)
            if direct:
                origin = "while holding a lock"
            else:
                lock_desc, chain = via
                origin = (f"reachable under {lock_desc} via call chain "
                          f"{' -> '.join(chain)}")
            findings.append(Finding(
                "LK002", info.file.rel, node.lineno,
                f"{info.qualname}: {desc} {origin}",
                hint="move blocking work outside the critical section (or "
                     "suppress with a written non-blocking argument)"))
    return findings
