"""JT001 / JT002 — jit hygiene for the batched solvers.

The solver's throughput lives and dies on the XLA jit cache staying hot
(ROADMAP: a mid-run recompile costs tens of seconds on TPU), and on traced
bodies never forcing a host round-trip.

JT001: a call site of a jitted function passes a per-batch-varying
expression to a `static_argnames` parameter. Bad atoms are `len(...)`,
`.item()`, `.size` loads, and `int()/float()` over non-constants — each a
value that changes with batch/cluster content and therefore keys a fresh
compile. Neutralizers are the project's blessed bucketing idioms:
`bool(...)` (binary key), `1 << (...).bit_length()` (pow2 bucket, see
models/waterfill.py). Badness follows simple local variable chains and the
finding is anchored at the WITNESS (the assignment/expression that
introduces the raw value), so one reasoned suppression covers every static
arg the value flows into.

JT002: host-sync or numpy calls lexically inside a jit-traced body — the
jitted functions themselves plus every helper reachable from them through
resolved in-tree calls (`.item()`, `int/float/bool` of non-constants,
`np.*`, `.block_until_ready`, `jax.device_get`).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..findings import Finding
from ..index import FileIndex, FuncInfo, ProjectIndex

_NESTED_SCOPES = (ast.Lambda,)  # jit bodies DO include nested defs


@dataclass
class JitFn:
    info: FuncInfo
    static_names: Tuple[str, ...]
    # param name -> positional index (for static args passed positionally)
    param_index: Dict[str, int] = field(default_factory=dict)
    # alias-form registrations (`fn = jax.jit(target, ...)`) only match call
    # sites in the file that created the alias
    file_scope: Optional[str] = None


def _tuple_of_strings(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value,
                                                                   str))
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return ()


def _is_jax_jit(expr: ast.AST) -> bool:
    """`jax.jit` or a bare `jit` imported from jax."""
    if isinstance(expr, ast.Attribute) and expr.attr == "jit" \
            and isinstance(expr.value, ast.Name) and expr.value.id == "jax":
        return True
    return isinstance(expr, ast.Name) and expr.id == "jit"


def _jit_decoration(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """static_argnames if `node` is a jax.jit decoration/wrapping call."""
    if not isinstance(node, ast.Call):
        return None
    # functools.partial(jax.jit, static_argnames=(...)) / partial(jit, ...)
    f = node.func
    is_partial = (isinstance(f, ast.Attribute) and f.attr == "partial") or \
        (isinstance(f, ast.Name) and f.id == "partial")
    if is_partial and node.args and _is_jax_jit(node.args[0]):
        for kw in node.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                return _tuple_of_strings(kw.value)
        return ()
    # jax.jit(fn, static_argnames=(...))
    if _is_jax_jit(f):
        for kw in node.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                return _tuple_of_strings(kw.value)
        return ()
    return None


def _param_indices(fn_node) -> Dict[str, int]:
    args = getattr(fn_node, "args", None)
    if args is None:
        return {}
    names = [a.arg for a in (args.posonlyargs + args.args)]
    return {n: i for i, n in enumerate(names)}


def collect_jit_functions(index: ProjectIndex) -> Dict[str, List[JitFn]]:
    """name -> JitFns; includes `alias = jax.jit(target, ...)` rebindings."""
    out: Dict[str, List[JitFn]] = {}
    for fi in index.files:
        for info in fi.functions:
            for dec in getattr(info.node, "decorator_list", ()):
                statics = _jit_decoration(dec)
                if statics is None and _is_jax_jit(dec):
                    statics = ()  # bare @jax.jit
                if statics is not None:
                    jf = JitFn(info, statics, _param_indices(info.node))
                    out.setdefault(info.name, []).append(jf)
        # alias-form: fn = jax.jit(target, static_argnames=...)
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            statics = _jit_decoration(node.value)
            if statics is None or not node.value.args:
                continue
            tgt = node.value.args[0]
            if not isinstance(tgt, ast.Name):
                continue
            wrapped = index.resolve_name(fi, tgt.id)
            if wrapped is None:
                continue
            jf = JitFn(wrapped, statics, _param_indices(wrapped.node),
                       file_scope=fi.path)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(jf)
    return out


def jitted_local_names(index: ProjectIndex) -> Dict[str, Set[str]]:
    """file path -> names that are jitted callables there (for LK002)."""
    jits = collect_jit_functions(index)
    by_file: Dict[str, Set[str]] = {}
    for name, fns in jits.items():
        for jf in fns:
            by_file.setdefault(jf.info.file.path, set()).add(name)
    for fi in index.files:
        for local, target in fi.imports.items():
            leaf = target.rpartition(".")[2]
            if leaf in jits:
                by_file.setdefault(fi.path, set()).add(local)
    return by_file


# -- JT001 -----------------------------------------------------------------


class _Badness:
    """Does an expression (following local variable chains) carry a
    per-batch-varying atom that no bucketing idiom neutralizes?"""

    def __init__(self, func: FuncInfo):
        self.func = func
        self._visiting: Set[str] = set()

    def witness(self, expr: ast.AST) -> Optional[ast.AST]:
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name):
                if f.id == "bool":
                    return None  # binary jit key — always safe
                if f.id == "len":
                    return expr
                if f.id in ("int", "float") and expr.args and not isinstance(
                        expr.args[0], ast.Constant):
                    return expr
            if isinstance(f, ast.Attribute):
                if f.attr == "bit_length":
                    return None  # pow2 bucketing idiom
                if f.attr == "item":
                    return expr
            for sub in list(expr.args) + [kw.value for kw in expr.keywords]:
                got = self.witness(sub)
                if got is not None:
                    return got
            return None
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.LShift):
            return None  # 1 << (...).bit_length() bucket
        if isinstance(expr, ast.Attribute):
            if expr.attr == "size":
                return expr
            return self.witness(expr.value)
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self._visiting:
                return None
            self._visiting.add(name)
            try:
                for node in ast.walk(self.func.node):
                    if isinstance(node, ast.Assign):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name) and tgt.id == name:
                                got = self.witness(node.value)
                                if got is not None:
                                    # anchor at the DEEPEST witness: the
                                    # expression that introduces the raw
                                    # value, so one reasoned suppression
                                    # there covers every static arg the
                                    # value flows into
                                    return got
            finally:
                self._visiting.discard(name)
            return None
        for sub in ast.iter_child_nodes(expr):
            if isinstance(sub, ast.expr):
                got = self.witness(sub)
                if got is not None:
                    return got
        return None


def _check_jt001(index: ProjectIndex,
                 jits: Dict[str, List[JitFn]]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for fi in index.files:
        for info in fi.functions:
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Name):
                    continue
                name = node.func.id
                for jf in jits.get(name, ()):
                    # only sites that actually resolve to this jitted fn
                    if jf.file_scope is not None and jf.file_scope != fi.path:
                        continue
                    resolved = index.resolve_name(fi, name)
                    if resolved is not None and resolved != jf.info and \
                            jf.info.file.path != fi.path:
                        continue
                    static_exprs = []
                    for kw in node.keywords:
                        if kw.arg in jf.static_names:
                            static_exprs.append((kw.arg, kw.value))
                    for pname in jf.static_names:
                        pi = jf.param_index.get(pname)
                        if pi is not None and pi < len(node.args):
                            static_exprs.append((pname, node.args[pi]))
                    for pname, expr in static_exprs:
                        wit = _Badness(info).witness(expr)
                        if wit is None:
                            continue
                        key = (fi.rel, wit.lineno)
                        if key in seen:
                            continue
                        seen.add(key)
                        findings.append(Finding(
                            "JT001", fi.rel, wit.lineno,
                            f"{info.qualname}: per-batch-varying value flows "
                            f"into static arg '{pname}' of "
                            f"{jf.info.qualname} (jit retrace per distinct "
                            "value)",
                            hint="bucket it (1 << (n-1).bit_length(), see "
                                 "models/waterfill.py) or make the argument "
                                 "dynamic"))
    return findings


# -- JT002 -----------------------------------------------------------------


def _jit_reachable(index: ProjectIndex,
                   jits: Dict[str, List[JitFn]]) -> Dict[FuncInfo, str]:
    reachable: Dict[FuncInfo, str] = {}
    frontier: List[FuncInfo] = []
    for fns in jits.values():
        for jf in fns:
            if jf.info not in reachable:
                reachable[jf.info] = "jitted"
                frontier.append(jf.info)
    while frontier:
        cur = frontier.pop()
        for node in ast.walk(cur.node):
            if not isinstance(node, ast.Call):
                continue
            callee = index.resolve_call(cur.file, cur, node)
            if callee is not None and callee not in reachable:
                reachable[callee] = f"traced via {cur.qualname}"
                frontier.append(callee)
    return reachable


def _check_jt002(index: ProjectIndex,
                 jits: Dict[str, List[JitFn]]) -> List[Finding]:
    findings: List[Finding] = []
    for info, how in _jit_reachable(index, jits).items():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            desc = None
            if isinstance(f, ast.Attribute):
                root = f
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in ("np", "numpy") \
                        and f is not root:
                    desc = f"numpy call ({ast.unparse(f)})"
                elif f.attr == "item":
                    desc = "host sync .item()"
                elif f.attr == "block_until_ready":
                    desc = "host sync .block_until_ready()"
                elif f.attr == "device_get":
                    desc = "host sync jax.device_get()"
            elif isinstance(f, ast.Name) and f.id in ("int", "float", "bool") \
                    and node.args and not isinstance(node.args[0],
                                                     ast.Constant):
                desc = f"host sync {f.id}() on a traced value"
            if desc is not None:
                findings.append(Finding(
                    "JT002", info.file.rel, node.lineno,
                    f"{info.qualname}: {desc} inside a jit body ({how})",
                    hint="keep traced code device-pure (jnp ops); do host "
                         "conversion before the jit boundary"))
    return findings


def check(index: ProjectIndex) -> List[Finding]:
    jits = collect_jit_functions(index)
    return _check_jt001(index, jits) + _check_jt002(index, jits)
