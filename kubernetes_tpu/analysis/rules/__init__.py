"""schedlint rule modules. Each exposes `check(index) -> List[Finding]`."""

from . import alloc, hotpath, jit, locks, mproc, mutation, seqlock

ALL_RULE_MODULES = (locks, mutation, jit, hotpath, mproc, alloc, seqlock)

RULE_DOCS = {
    "LK001": "lock-order inversion: the pods shard must never be held when "
             "the global RV lock is acquired (store/store.py docstring rule)",
    "LK002": "blocking call (sleep, queue put/get, join, jax dispatch, watch "
             "callback) on a path that holds a store/scheduler lock",
    "MU001": "mutation of a store-returned or event object (the read-only "
             "contract the runtime mutation detector polices)",
    "JT001": "per-batch-varying expression flows into a static_argnames "
             "parameter of a jitted solver (retrace churn)",
    "JT002": "host-sync / numpy call inside a jit-traced body",
    "HP001": "per-pod instrumentation inside a batch loop of "
             "scheduler/batch.py (per BATCH, never per pod)",
    "MP001": "Pod/PodInfo object crosses a process boundary (mp queue "
             "put/send) — columns or integer keys only",
    "MP002": "SharedMemory/ShmArena create without a paired close+unlink "
             "on a finally/stop path (leaks a named /dev/shm segment)",
    "AL001": "pod-object allocation (ctor/clone/.copy()/dict()) on the "
             "zero-alloc steady-state schedule/bind path outside a "
             "declared gate or materialization barrier (pod_obj_allocs==0)",
    "AL002": "comprehension materializing pod objects per element on the "
             "zero-alloc steady-state path",
    "SEQ001": "shm seqlock reader breaks the torn-read protocol (missing "
              "version re-check after the read, or a raw view of the "
              "shared segment escapes the retry scope)",
    "SEQ002": "shm seqlock writer breaks the publish protocol (data-word "
              "write without the version bump on both sides, or arena "
              "column writes with no publish() in the same function)",
    "SL001": "schedlint suppression without a written reason",
}
