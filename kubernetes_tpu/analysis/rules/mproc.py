"""MP001 / MP002 — cross-process hygiene for the multi-process scheduler.

The mpsched boundary contract (scheduler/mpworker.py docstring): only ints
and small tuples of ints cross a process boundary. A Pod/PodInfo shoved
into an mp queue pickles the whole object graph — slow, and the copy
silently diverges from the live store, so any decision made on it is
stale the moment it arrives. MP001 flags pod-shaped values inside
`.put(...)` / `.put_nowait(...)` / `.send(...)` arguments in any module
that touches multiprocessing or the shm arenas.

MP002 polices segment lifecycle: every module that CREATES shared memory
(`SharedMemory(..., create=True)` or an `ShmArena(...)` construction)
must also contain the paired teardown — a `.close()` / `.unlink()` /
`.shm_close()` call reachable from a cleanup context, meaning inside a
`finally:` block or inside a function whose name marks it as the stop
path (close/stop/shutdown/teardown/__exit__/__del__). A create without
that pairing leaks a named /dev/shm segment past process exit.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..findings import Finding
from ..index import FuncInfo, ProjectIndex, render_chain

_SEND_METHODS = ("put", "put_nowait", "send")

# names that denote a pod object (not a scalar extracted FROM one:
# `pod.key` launders — see _podlike)
_POD_NAMES = frozenset({
    "pod", "pods", "qp", "qps", "podinfo", "pod_info", "queued_pod",
    "queued_pods", "pending_pod", "pending_pods",
})

_CLEANUP_FUNC_MARKERS = (
    "close", "stop", "shutdown", "teardown", "__exit__", "__del__",
)

_CLEANUP_CALLS = ("close", "unlink", "shm_close")

# how deep the chain-based cleanup/boundary searches follow helpers
_VIA_DEPTH = 3


def _imports_mp(fi) -> bool:
    for target in fi.imports.values():
        if "multiprocessing" in target or "shared_memory" in target:
            return True
        if target == "shm" or target.endswith(".shm"):
            return True
    return False


def _name_is_podlike(name: str) -> bool:
    low = name.lower().lstrip("_")
    return (low in _POD_NAMES or low.endswith("podinfo")
            or low.endswith("pod_info"))


def _podlike(expr: ast.AST):
    """Return the offending name if this expression carries a pod OBJECT
    across the boundary, else None. Field access (`pod.key`, `pod.rv`)
    and calls (`key_of(pod)`, `str(pod)`) extract/launder — only the bare
    object, or a container literal holding one, is flagged."""
    if isinstance(expr, ast.Name):
        return expr.id if _name_is_podlike(expr.id) else None
    if isinstance(expr, ast.Attribute):
        # `self.pod` / `qp.pod` is the object; `pod.key` is a field
        return expr.attr if _name_is_podlike(expr.attr) else None
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        for elt in expr.elts:
            hit = _podlike(elt)
            if hit:
                return hit
        return None
    if isinstance(expr, ast.Dict):
        for v in expr.values:
            if v is not None:
                hit = _podlike(v)
                if hit:
                    return hit
        return None
    if isinstance(expr, ast.Starred):
        return _podlike(expr.value)
    if isinstance(expr, ast.Subscript):
        # pods[i] is still a pod object
        return _podlike(expr.value)
    return None


def _is_create_site(node: ast.Call) -> str:
    """'' if not a shared-memory create; else a short label for it."""
    func = node.func
    name = None
    if isinstance(func, ast.Name):
        name = func.id
    elif isinstance(func, ast.Attribute):
        name = func.attr
    if name == "ShmArena":
        return "ShmArena"
    if name == "SharedMemory":
        for kw in node.keywords:
            if kw.arg == "create" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return "SharedMemory(create=True)"
    return ""


def _contains_cleanup_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _CLEANUP_CALLS):
            return True
    return False


def _stop_path_funcs(fi) -> List[FuncInfo]:
    return [info for info in fi.functions
            if any(m in info.name.lower() for m in _CLEANUP_FUNC_MARKERS)]


def _has_cleanup(fi, index: ProjectIndex) -> bool:
    # a cleanup call inside any finally: block
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                if _contains_cleanup_call(stmt):
                    return True
    # or inside a function whose NAME is the stop path
    roots = _stop_path_funcs(fi)
    for info in roots:
        if _contains_cleanup_call(info.node):
            return True
    # or (ISSUE 20) a helper deep: follow the resolved call graph from the
    # stop-path functions — `stop()` delegating to a teardown helper in
    # another module still pairs the create
    if roots:
        reached = index.callgraph.reachable_from(roots, depth=_VIA_DEPTH)
        for f2 in reached:
            if _contains_cleanup_call(f2.node):
                return True
    return False


def _podlike_send_sites(info: FuncInfo) -> List[Tuple[ast.Call, str]]:
    """`.put/.put_nowait/.send` sites in a function whose argument carries
    a pod object."""
    out: List[Tuple[ast.Call, str]] = []
    for node in ast.walk(info.node):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SEND_METHODS):
            continue
        args = list(node.args) \
            + [kw.value for kw in node.keywords if kw.arg is None
               or kw.arg not in ("timeout", "block")]
        for arg in args:
            hit = _podlike(arg)
            if hit:
                out.append((node, hit))
                break
    return out


def _call_passes_podlike(call: ast.Call) -> Optional[str]:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        hit = _podlike(arg)
        if hit:
            return hit
    return None


def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    mp_infos = {info for fi in index.files if _imports_mp(fi)
                for info in fi.functions}
    seen_sites = set()

    for fi in index.files:
        mp_file = _imports_mp(fi)

        if mp_file:
            for info in fi.functions:
                for node, hit in _podlike_send_sites(info):
                    seen_sites.add((fi.rel, node.lineno))
                    findings.append(Finding(
                        "MP001", fi.rel, node.lineno,
                        f"{info.qualname}: pod object `{hit}` "
                        f"crosses a process boundary via "
                        f".{node.func.attr}() — pickling a "
                        f"Pod/PodInfo ships a stale copy",
                        hint="send column rows / integer keys "
                             "only; the owner re-reads the live "
                             "store (mpworker.py protocol)"))

            # interprocedural form (ISSUE 20): a pod handed from an
            # mp-touching function into a helper OUTSIDE the mp file gate
            # that then puts/sends it is the same pickle, laundered through
            # one call — follow edges that pass a pod object
            def _follow(_caller, call, callee):
                return (callee not in mp_infos
                        and _call_passes_podlike(call) is not None)

            for info in fi.functions:
                reached = index.callgraph.reachable_from(
                    [info], depth=_VIA_DEPTH, follow=_follow)
                for f2, chain in sorted(reached.items(),
                                        key=lambda kv: len(kv[1])):
                    for node, hit in _podlike_send_sites(f2):
                        key = (f2.file.rel, node.lineno)
                        if key in seen_sites:
                            continue
                        seen_sites.add(key)
                        findings.append(Finding(
                            "MP001", f2.file.rel, node.lineno,
                            f"{f2.qualname}: pod object `{hit}` crosses a "
                            f"process boundary via .{node.func.attr}(), "
                            f"reached via call chain {render_chain(chain)} "
                            f"— the helper hides the pickle from the "
                            f"boundary module",
                            hint="send column rows / integer keys only; "
                                 "the owner re-reads the live store "
                                 "(mpworker.py protocol)"))

        create_sites = []
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Call):
                label = _is_create_site(node)
                if label:
                    create_sites.append((node.lineno, label))
        if create_sites and not _has_cleanup(fi, index):
            for lineno, label in create_sites:
                findings.append(Finding(
                    "MP002", fi.rel, lineno,
                    f"{label} created here but this module has no paired "
                    f"close/unlink on a finally or stop path (searched the "
                    f"resolved call graph {_VIA_DEPTH} levels deep "
                    f"from the stop-path functions) — the "
                    f"named /dev/shm segment outlives the process",
                    hint="pair every create with .close()+unlink on the "
                         "owner's stop()/finally path (store/shm.py "
                         "ShmArena.close is the one-call teardown)"))
    return findings
