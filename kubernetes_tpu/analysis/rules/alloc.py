"""AL001 / AL002 — steady-state allocation discipline (ISSUE 20).

The static complement of the runtime `pod_obj_allocs == 0` gauge (PR 15's
zero per-pod-object-allocation steady state, the property behind the +13%
same-box A/B): on the designated hot paths — `scheduler/batch.py`'s
schedule path, the whole of `scheduler/cachecols.py`, and
`store/columnar.py`'s bind path — pod OBJECTS must not be built. Column
writes, interning, and integer/array work are the steady state; a
`Pod(...)` / `PodInfo(...)` construction, a clone helper
(`pod_structural_clone` / `pod_bind_clone` / `deepcopy`), a `.copy()` or
`dict(...)` of a pod, or a comprehension materializing any of those is a
finding — unless it sits behind a DECLARED gate:

  * a fallback/materialization gate predicate in an enclosing `if` /
    ternary test (GATE_PREDICATES: `cols_rows_ok`, `use_columnar`,
    `fallback`, `materialize`, `numpy`/`available` feature probes, ...) —
    the shipped shape `qp.pod if cols_rows_ok else clone(qp.pod)` is the
    canonical gated clone;
  * a materialization-barrier function (name matching
    `materialize`/`fallback`/`serial`): those functions ARE the declared
    exit from the zero-alloc regime (`materialize_columnar_rows`,
    `_serial_one`), so their bodies are exempt and the closure does not
    descend into them;
  * an `except` handler — error paths are not steady state;
  * or an explicit `# schedlint: allow(AL001) <reason>`.

AL001 anchors on the allocation expression; AL002 on dict/list/set
comprehensions (and generator expressions) whose element expression
materializes a pod object. Both carry a "via call chain" form: an
allocation inside a helper reachable from a hot root through ungated
resolved calls (bounded depth) is reported with the chain.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..findings import Finding
from ..index import FuncInfo, ProjectIndex, render_chain
from .mproc import _name_is_podlike

# (file suffix, function names) designating the zero-alloc hot roots;
# None = every function in the file
AL_HOT: Tuple[Tuple[str, Optional[frozenset]], ...] = (
    ("scheduler/batch.py", frozenset({"schedule_batch",
                                      "_schedule_batch_inner"})),
    ("scheduler/cachecols.py", None),
    ("store/columnar.py", frozenset({"bind_prepare", "commit_bind"})),
)

# the registered fallback/materialization gate predicates: an enclosing
# if/ternary test naming one of these declares "we are leaving (or probing
# for) the zero-alloc regime here"
GATE_PREDICATES = re.compile(
    r"cols_rows_ok|use_columnar|columnar|fallback|materiali[sz]e|numpy|"
    r"available|native|degraded|constrained")

# functions that ARE the declared materialization barrier / fallback path —
# plus the terminal/event paths (preempt, reject, requeue, rollback, veto,
# failure handling, event emission): pods leaving the steady state owe real
# objects by contract. Exempt wholesale; the hot closure does not descend
# into them.
_BARRIER_FUNC = re.compile(
    r"materiali[sz]e|fallback|serial|preempt|reject|requeue|rollback|"
    r"veto|fail|event")

# pod-object constructors and clone helpers
_POD_CTOR = re.compile(r"^(Pod|PodInfo|QueuedPodInfo|V1Pod)$")
_CLONE_FUNC = re.compile(r"(^|_)clone($|_)|clone$|^deepcopy$")

_VIA_DEPTH = 2  # how deep the hot closure follows ungated helpers

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
_COMPS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _simple_callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _alloc_desc(call: ast.Call) -> Optional[str]:
    """AL001 form: does this call build a pod object?"""
    name = _simple_callee_name(call)
    if name is None:
        return None
    if _POD_CTOR.match(name):
        return f"pod object construction {name}(...)"
    if _CLONE_FUNC.search(name):
        return f"pod clone {name}(...)"
    if name == "copy" and isinstance(call.func, ast.Attribute):
        recv = call.func.value
        seg = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else None)
        if seg is not None and _name_is_podlike(seg):
            return f".copy() of pod object `{seg}`"
        return None
    if name in ("dict", "to_dict") and call.args:
        hit = call.args[0]
        seg = hit.attr if isinstance(hit, ast.Attribute) else (
            hit.id if isinstance(hit, ast.Name) else None)
        if seg is not None and _name_is_podlike(seg):
            return f"dict(...) materialization of pod object `{seg}`"
    return None


def _comp_desc(comp: ast.AST) -> Optional[str]:
    """AL002 form: a comprehension whose element materializes pod
    objects (one allocation per element = one per pod)."""
    elts = []
    if isinstance(comp, ast.DictComp):
        elts = [comp.key, comp.value]
    elif isinstance(comp, _COMPS):
        elts = [comp.elt]
    for e in elts:
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                desc = _alloc_desc(node)
                if desc:
                    kind = type(comp).__name__
                    return f"{kind} materializes a pod object per element " \
                           f"({desc})"
    return None


def _gate_test(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and GATE_PREDICATES.search(node.id):
            return True
        if isinstance(node, ast.Attribute) and \
                GATE_PREDICATES.search(node.attr):
            return True
    return False


class _AllocScan:
    """One function's ungated allocation forms and outgoing ungated calls
    (the closure follows only calls on the steady-state straight line)."""

    def __init__(self):
        self.allocs: List[Tuple[ast.AST, str, str]] = []  # node, rule, desc
        self.calls: List[ast.Call] = []

    def scan(self, info: FuncInfo) -> "_AllocScan":
        for stmt in info.node.body:
            self._stmt(stmt, False)
        return self

    def _stmt(self, stmt: ast.stmt, gated: bool) -> None:
        if isinstance(stmt, _NESTED):
            return
        if isinstance(stmt, (ast.If, ast.While)):
            g = gated or _gate_test(stmt.test)
            self._expr(stmt.test, gated)
            for s in stmt.body:
                self._stmt(s, g)
            for s in stmt.orelse:
                self._stmt(s, g)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._stmt(s, gated)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s, True)  # error path: not steady state
            for s in stmt.orelse:
                self._stmt(s, gated)
            for s in stmt.finalbody:
                self._stmt(s, gated)
            return
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._expr(value, gated)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._stmt(v, gated)
                    elif isinstance(v, ast.expr):
                        self._expr(v, gated)

    def _expr(self, node: ast.AST, gated: bool) -> None:
        if isinstance(node, _NESTED):
            return
        if isinstance(node, ast.IfExp):
            g = gated or _gate_test(node.test)
            self._expr(node.test, gated)
            self._expr(node.body, g)
            self._expr(node.orelse, g)
            return
        if isinstance(node, _COMPS):
            desc = _comp_desc(node)
            if desc and not gated:
                self.allocs.append((node, "AL002", desc))
        elif isinstance(node, ast.Call):
            desc = _alloc_desc(node)
            if desc is not None:
                if not gated:
                    self.allocs.append((node, "AL001", desc))
            elif not gated:
                self.calls.append(node)
        for child in ast.iter_child_nodes(node):
            self._expr(child, gated)


def _hot_roots(index: ProjectIndex) -> List[FuncInfo]:
    roots: List[FuncInfo] = []
    for fi in index.files:
        norm = fi.path.replace("\\", "/")
        for sfx, names in AL_HOT:
            if not norm.endswith(sfx):
                continue
            for info in fi.functions:
                if names is None or info.name in names:
                    roots.append(info)
    return roots


def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    roots = _hot_roots(index)
    if not roots:
        return findings

    scans: Dict[FuncInfo, _AllocScan] = {}

    def scan_of(info: FuncInfo) -> _AllocScan:
        got = scans.get(info)
        if got is None:
            got = scans[info] = _AllocScan().scan(info)
        return got

    hint = ("the steady-state schedule/bind path must not build pod "
            "objects (pod_obj_allocs == 0, PR 15): write columns, intern "
            "strings, carry integer rows — or put the allocation behind a "
            "registered gate predicate (cols_rows_ok / use_columnar / "
            "fallback / numpy probe) or a materialize*/fallback/serial "
            "barrier function")

    root_set = set(roots)
    for info in roots:
        for node, rule, desc in scan_of(info).allocs:
            findings.append(Finding(
                rule, info.file.rel, node.lineno,
                f"{info.qualname}: {desc} on the zero-alloc steady-state "
                f"path", hint=hint))

    # via-call-chain form: ungated calls out of the hot roots, bounded
    # depth, never through a barrier function (those declare the exit
    # from the zero-alloc regime)
    ungated_calls: Dict[FuncInfo, set] = {}

    def _follow(caller: FuncInfo, call: ast.Call, callee: FuncInfo) -> bool:
        if callee in root_set or _BARRIER_FUNC.search(callee.name):
            return False
        allowed = ungated_calls.get(caller)
        if allowed is None:
            allowed = ungated_calls[caller] = {
                id(c) for c in scan_of(caller).calls}
        return id(call) in allowed

    reached = index.callgraph.reachable_from(
        roots, depth=_VIA_DEPTH, follow=_follow)
    for info, chain in sorted(reached.items(),
                              key=lambda kv: (len(kv[1]),
                                              kv[0].qualname)):
        for node, rule, desc in scan_of(info).allocs:
            findings.append(Finding(
                rule, info.file.rel, node.lineno,
                f"{info.qualname}: {desc} reachable from the zero-alloc "
                f"steady-state path via call chain {render_chain(chain)}",
                hint=hint))
    return findings
