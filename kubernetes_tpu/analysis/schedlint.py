"""schedlint — CLI driver for the project-native static analyzer.

Usage:
    python -m kubernetes_tpu.analysis.schedlint [--json] [paths...]
    ktl vet [-o json] [paths...]          (same engine, CLI-integrated)

Walks the given paths (default: the kubernetes_tpu package), parses every
.py file once, and runs the rule suite:

    LK001  lock-order inversion (store global RV lock vs pods shard)
    LK002  blocking call while a lock is held
    MU001  mutation of store-returned / event objects
    JT001  per-batch-varying value into a jit static_argnames parameter
    JT002  host-sync / numpy call inside a jit body
    HP001  per-pod instrumentation inside batch loops (scheduler/batch.py)
    SL001  suppression without a written reason

Inline suppressions: `# schedlint: allow(RULE) <reason>` on the finding line
(or alone on the line above it). The reason is mandatory — a bare
suppression is itself a finding (SL001), so every exception to an invariant
is documented where it lives. Exit status: 0 clean, 1 findings, 2 usage or
parse failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from .findings import Finding  # noqa: F401  (re-exported API)
from .index import ProjectIndex

DEFAULT_EXCLUDE_PARTS = ("__pycache__",)


def package_root() -> str:
    """The kubernetes_tpu package directory (the default analysis target)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(index: ProjectIndex) -> Tuple[List[Finding], Dict]:
    """Run every rule over a built index; returns (unsuppressed findings,
    stats). Suppressed findings are dropped; reasonless suppressions become
    SL001 findings (never themselves suppressible)."""
    from .rules import ALL_RULE_MODULES

    raw: List[Finding] = []
    for mod in ALL_RULE_MODULES:
        raw.extend(mod.check(index))

    kept: List[Finding] = []
    suppressed = 0
    for f in raw:
        fi = index.file_by_path(_abs_for(index, f.file))
        sup = index.suppressed(fi, f.line, f.rule) if fi else None
        if sup is not None:
            suppressed += 1
            continue
        kept.append(f)

    for fi in index.files:
        for sup in fi.suppressions.values():
            if not sup.reason:
                kept.append(Finding(
                    "SL001", fi.rel, sup.line,
                    "suppression without a reason — write down WHY the "
                    "invariant does not apply here",
                    hint="# schedlint: allow(RULE) <one-line reason>"))

    # unreadable/unparseable/typo'd inputs are findings too (never
    # suppressible): an analyzer that can't see the code must not pass
    for path, err in index.errors:
        kept.append(Finding("PARSE", path, 1, err,
                            hint="fix the path/syntax; exit code 2"))
    kept.sort(key=lambda f: (f.file, f.line, f.rule))
    stats = {
        "files": len(index.files),
        "parse_errors": len(index.errors),
        "findings": len(kept),
        "suppressed": suppressed,
    }
    return kept, stats


def exit_code(findings: List[Finding]) -> int:
    """0 clean, 1 invariant findings, 2 the analyzer could not do its job
    (parse/path failure). Shared by the module CLI and `ktl vet`."""
    if any(f.rule == "PARSE" for f in findings):
        return 2
    return 1 if findings else 0


def _abs_for(index: ProjectIndex, rel: str) -> str:
    for fi in index.files:
        if fi.rel == rel:
            return fi.path
    return rel


def run_paths(paths: Optional[List[str]] = None
              ) -> Tuple[List[Finding], Dict]:
    """Build the index for `paths` (default: the package) and run the suite."""
    t0 = time.perf_counter()
    index = ProjectIndex.from_paths(list(paths) if paths else [package_root()])
    findings, stats = run(index)
    stats["wall_s"] = round(time.perf_counter() - t0, 3)
    return findings, stats


def analyze_source(source: str, filename: str = "fixture.py",
                   module: str = "fixture") -> List[Finding]:
    """Single-source entry point for rule fixture tests."""
    return run(ProjectIndex.from_source(source, filename, module))[0]


def render_text(findings: List[Finding], stats: Dict) -> str:
    lines = [f.render() for f in findings]
    lines.append(
        f"schedlint: {stats['findings']} finding(s), "
        f"{stats['suppressed']} suppressed, {stats['files']} files "
        f"in {stats.get('wall_s', 0.0):.2f}s")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="schedlint",
        description="project-native static analyzer for the scheduler's "
                    "concurrency and clone-discipline invariants")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the kubernetes_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        from .rules import RULE_DOCS

        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    findings, stats = run_paths(args.paths or None)
    if args.json:
        print(json.dumps({"findings": [f.as_dict() for f in findings],
                          "stats": stats}, indent=2))
    else:
        print(render_text(findings, stats))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
