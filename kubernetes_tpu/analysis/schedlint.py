"""schedlint — CLI driver for the project-native static analyzer.

Usage:
    python -m kubernetes_tpu.analysis.schedlint [--json] [paths...]
    ktl vet [-o json] [--diff [REF]] [--lock-graph] [paths...]

Walks the given paths (default: the kubernetes_tpu package), parses every
.py file once, builds the bounded interprocedural call graph
(module-qualified resolution, DEPTH_CAP/FANOUT_CAP published in stats),
and runs the rule suite:

    LK001  lock-order inversion (store global RV lock vs pods shard)
    LK002  blocking call while a lock is held (direct or via call chain)
    MU001  mutation of store-returned / event objects
    JT001  per-batch-varying value into a jit static_argnames parameter
    JT002  host-sync / numpy call inside a jit body
    HP001  per-pod instrumentation inside batch loops (direct or via chain)
    MP001  pod object crossing a process boundary (direct or via helper)
    MP002  multiprocess resource without a reachable cleanup path
    AL001  pod-object allocation on the zero-alloc steady-state path
    AL002  comprehension materializing pod objects on the steady-state path
    SEQ001 shm seqlock reader without a version re-check / raw-view escape
    SEQ002 shm seqlock writer without the version bump on both sides
    SL001  suppression without a written reason

Inline suppressions: `# schedlint: allow(RULE) <reason>` on the finding line
(or alone on the line above it). The reason is mandatory — a bare
suppression is itself a finding (SL001), so every exception to an invariant
is documented where it lives. Exit status: 0 clean, 1 findings, 2 usage or
parse failure.

`--diff [REF]` (default HEAD) narrows the findings to the files changed
against REF plus everything that imports or calls into them (the
reverse closure over the import map and the resolved call graph) — the
whole-program index is still built, so interprocedural findings keep
their chains. `--lock-graph` renders the runtime lock-graph witness
(store/lockgraph.py): from a LOCK_GRAPH_EXPORT JSON if present, else by
exercising a scratch store in-process. JSON output carries a `baseline`
stats block: findings by rule, every suppression with its written
reason, and parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from .findings import Finding  # noqa: F401  (re-exported API)
from .index import ProjectIndex

DEFAULT_EXCLUDE_PARTS = ("__pycache__",)


def package_root() -> str:
    """The kubernetes_tpu package directory (the default analysis target)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(index: ProjectIndex) -> Tuple[List[Finding], Dict]:
    """Run every rule over a built index; returns (unsuppressed findings,
    stats). Suppressed findings are dropped; reasonless suppressions become
    SL001 findings (never themselves suppressible)."""
    from .rules import ALL_RULE_MODULES

    raw: List[Finding] = []
    for mod in ALL_RULE_MODULES:
        raw.extend(mod.check(index))

    kept: List[Finding] = []
    suppressed = 0
    for f in raw:
        fi = index.file_by_path(_abs_for(index, f.file))
        sup = index.suppressed(fi, f.line, f.rule) if fi else None
        if sup is not None:
            suppressed += 1
            continue
        kept.append(f)

    for fi in index.files:
        for sup in fi.suppressions.values():
            if not sup.reason:
                kept.append(Finding(
                    "SL001", fi.rel, sup.line,
                    "suppression without a reason — write down WHY the "
                    "invariant does not apply here",
                    hint="# schedlint: allow(RULE) <one-line reason>"))

    # unreadable/unparseable/typo'd inputs are findings too (never
    # suppressible): an analyzer that can't see the code must not pass
    for path, err in index.errors:
        kept.append(Finding("PARSE", path, 1, err,
                            hint="fix the path/syntax; exit code 2"))
    kept.sort(key=lambda f: (f.file, f.line, f.rule))

    by_rule: Dict[str, int] = {}
    for f in kept:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    sup_records = [
        {"file": fi.rel, "line": sup.line,
         "rules": sorted(sup.rules) or ["*"], "reason": sup.reason}
        for fi in index.files
        for sup in sorted(fi.suppressions.values(), key=lambda s: s.line)]

    cg = index.callgraph
    stats = {
        "files": len(index.files),
        "parse_errors": len(index.errors),
        "findings": len(kept),
        "suppressed": suppressed,
        "callgraph_edges": cg.edge_count,
        "resolve_depth": cg.max_depth_seen,
        "callgraph": cg.stats(),
        # the baseline block: what the tree looks like to the analyzer
        # RIGHT NOW — findings per rule, every suppression with its
        # written reason, parse errors. CI diffs this against the
        # committed expectation instead of grepping rendered text.
        "baseline": {
            "findings_by_rule": by_rule,
            "suppressions": sup_records,
            "suppression_count": len(sup_records),
            "parse_errors": [{"path": p, "error": e}
                             for p, e in index.errors],
        },
    }
    return kept, stats


def exit_code(findings: List[Finding]) -> int:
    """0 clean, 1 invariant findings, 2 the analyzer could not do its job
    (parse/path failure). Shared by the module CLI and `ktl vet`."""
    if any(f.rule == "PARSE" for f in findings):
        return 2
    return 1 if findings else 0


def _abs_for(index: ProjectIndex, rel: str) -> str:
    for fi in index.files:
        if fi.rel == rel:
            return fi.path
    return rel


def run_paths(paths: Optional[List[str]] = None
              ) -> Tuple[List[Finding], Dict]:
    """Build the index for `paths` (default: the package) and run the suite."""
    t0 = time.perf_counter()
    index = ProjectIndex.from_paths(list(paths) if paths else [package_root()])
    findings, stats = run(index)
    stats["wall_s"] = round(time.perf_counter() - t0, 3)
    return findings, stats


def analyze_source(source: str, filename: str = "fixture.py",
                   module: str = "fixture") -> List[Finding]:
    """Single-source entry point for rule fixture tests."""
    return run(ProjectIndex.from_source(source, filename, module))[0]


def analyze_sources(sources: List[Tuple[str, str, str]],
                    module_qualified: bool = True) -> List[Finding]:
    """Multi-file fixture entry point: (source, filename, module) triples.
    `module_qualified=False` pins the pre-interprocedural resolver (the
    LK002-via-helper regression runs the same fixture both ways)."""
    return run(ProjectIndex.from_sources(
        sources, module_qualified=module_qualified))[0]


# -- --diff scope ----------------------------------------------------------


def _git_lines(repo: str, *args: str) -> List[str]:
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", repo, *args],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return []
    if out.returncode != 0:
        return []
    return [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]


def changed_files(ref: str = "HEAD",
                  repo: Optional[str] = None) -> List[str]:
    """Absolute paths of .py files changed against `ref` (worktree diff
    plus untracked files)."""
    repo = repo or os.path.dirname(package_root())
    rels = set(_git_lines(repo, "diff", "--name-only", ref, "--"))
    rels.update(_git_lines(repo, "ls-files", "--others",
                           "--exclude-standard"))
    return sorted(os.path.join(repo, r) for r in rels if r.endswith(".py"))


def diff_scope(index: ProjectIndex, changed: List[str]) -> set:
    """The rel-paths of every indexed file in the diff blast radius: the
    changed files plus the transitive reverse closure over (a) the import
    map and (b) the resolved call graph — if A imports or calls into a
    changed module, A's findings may have changed too, so it is in scope."""
    real = {os.path.realpath(p) for p in changed}
    changed_mods = {fi.module for fi in index.files
                    if os.path.realpath(fi.path) in real}

    # forward deps per module: imports that resolve in-index, plus call
    # edges (the call graph sees through `from x import f` re-exports)
    fwd: Dict[str, set] = {fi.module: set() for fi in index.files}
    for fi in index.files:
        for target in fi.imports.values():
            for mod in (target, target.rpartition(".")[0]):
                if mod and mod != fi.module and mod in index.module_files:
                    fwd[fi.module].add(mod)
    for caller, outs in index.callgraph.edges.items():
        for _call, callee in outs:
            if callee.module != caller.module:
                fwd[caller.module].add(callee.module)

    rev: Dict[str, set] = {}
    for mod, deps in fwd.items():
        for dep in deps:
            rev.setdefault(dep, set()).add(mod)

    scope = set(changed_mods)
    frontier = list(changed_mods)
    while frontier:
        mod = frontier.pop()
        for dependent in rev.get(mod, ()):
            if dependent not in scope:
                scope.add(dependent)
                frontier.append(dependent)
    return {fi.rel for fi in index.files if fi.module in scope}


# -- --lock-graph ----------------------------------------------------------


def _witness_from_export(path: str):
    from ..store.lockgraph import LockGraphWitness

    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    w = LockGraphWitness()
    for e in doc.get("edges", []):
        w.edges[(e["held"], e["acquired"])] = dict(e)
    return w, doc.get("order_table")


def lock_graph_report(export: Optional[str] = None) -> Tuple[str, bool]:
    """Render the runtime lock-graph witness. Prefers a JSON export (the
    `export` arg, else $LOCK_GRAPH_EXPORT) written by a real tier-1 run;
    with neither, exercises a scratch in-process store so the canonical
    ascending edges are witnessed. Returns (text, clean)."""
    path = export or os.environ.get("LOCK_GRAPH_EXPORT")
    if path and os.path.isfile(path):
        w, table = _witness_from_export(path)
        report = w.diff(table)
        return (f"[from export {path}]\n" + w.render(table),
                report["clean"])

    from ..store.lockgraph import LockGraphWitness
    from ..store.store import APIStore

    w = LockGraphWitness()
    store = APIStore(lock_order_check=True)
    for lk in (store._lock, store._pods_lock, store._nodes_lock):
        lk._witness = w
    # walk the full legal ordering once: global RV -> pods shard ->
    # nodes shard, witnessing every ascending edge
    with store._lock, store._pods_lock, store._nodes_lock:
        pass
    report = w.diff()
    return ("[in-process scratch store]\n" + w.render(), report["clean"])


def render_text(findings: List[Finding], stats: Dict) -> str:
    lines = [f.render() for f in findings]
    lines.append(
        f"schedlint: {stats['findings']} finding(s), "
        f"{stats['suppressed']} suppressed, {stats['files']} files "
        f"in {stats.get('wall_s', 0.0):.2f}s")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="schedlint",
        description="project-native static analyzer for the scheduler's "
                    "concurrency and clone-discipline invariants")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the kubernetes_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--diff", nargs="?", const="HEAD", default=None,
                        metavar="REF",
                        help="narrow findings to files changed vs REF "
                             "(default HEAD) plus their reverse "
                             "import/call dependents; the whole-program "
                             "index is still built")
    parser.add_argument("--lock-graph", action="store_true",
                        help="render the runtime lock-graph witness "
                             "(from $LOCK_GRAPH_EXPORT if set, else a "
                             "scratch in-process store)")
    args = parser.parse_args(argv)

    if args.list_rules:
        from .rules import RULE_DOCS

        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    if args.lock_graph:
        text, clean = lock_graph_report()
        print(text)
        return 0 if clean else 1

    t0 = time.perf_counter()
    index = ProjectIndex.from_paths(
        list(args.paths) if args.paths else [package_root()])
    findings, stats = run(index)

    if args.diff is not None:
        changed = changed_files(args.diff)
        scope = diff_scope(index, changed)
        findings = [f for f in findings
                    if f.file in scope or f.rule == "PARSE"]
        stats["findings"] = len(findings)
        stats["diff"] = {
            "ref": args.diff,
            "changed_files": len(changed),
            "scope_files": len(scope),
        }
    stats["wall_s"] = round(time.perf_counter() - t0, 3)

    if args.json:
        print(json.dumps({"findings": [f.as_dict() for f in findings],
                          "stats": stats}, indent=2))
    else:
        if args.diff is not None:
            d = stats["diff"]
            print(f"schedlint --diff {d['ref']}: {d['changed_files']} "
                  f"changed file(s), {d['scope_files']} in scope "
                  f"(reverse import/call closure)")
        print(render_text(findings, stats))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
