"""schedlint's project model: parsed files, function/class tables, import
maps, inline suppressions, and name-based call resolution.

The resolution strategy is deliberately project-native rather than sound:
`self.m()` resolves within the enclosing class (then its in-tree bases),
bare names resolve through module-level defs and `from x import y` maps,
`mod.f()` resolves through the file's import map when `mod` names an
analyzed module (ISSUE 20 — the interprocedural closure; disable with
`module_qualified=False` to get the legacy per-file resolver), and `obj.m()`
resolves only when exactly ONE class in the analyzed tree defines `m` —
ambiguous names stay unresolved and the rules treat them as opaque. That
trades missed paths for near-zero false positives, which is what lets the
whole-tree run gate tier-1 at zero findings.

`ProjectIndex.callgraph` is the whole-program view built on top of that
resolver: a bounded-depth, cycle-safe transitive call graph (depth and
fan-out caps published in stats) that the rules use to see through helpers
— a blocking call or per-pod allocation one function deep is reported with
the resolved call chain.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*schedlint:\s*allow\(\s*([A-Za-z0-9_\s,]*?)\s*\)\s*(.*)$")


@dataclass
class Suppression:
    line: int
    rules: Set[str]            # empty set = allow everything on the line
    reason: str
    comment_only: bool         # suppression on its own line applies to line+1

    def covers(self, rule: str) -> bool:
        return not self.rules or rule in self.rules


@dataclass
class FuncInfo:
    name: str
    qualname: str              # module.Class.name or module.name
    class_name: Optional[str]
    module: str
    file: "FileIndex"
    node: ast.AST              # FunctionDef / AsyncFunctionDef

    def __hash__(self):
        return id(self.node)

    def __eq__(self, other):
        return isinstance(other, FuncInfo) and other.node is self.node


@dataclass
class ClassInfo:
    name: str
    bases: List[str]
    methods: Dict[str, FuncInfo] = field(default_factory=dict)


@dataclass
class FileIndex:
    path: str                  # absolute (or fixture) path
    rel: str                   # display path
    module: str                # dotted module name
    tree: ast.Module
    lines: List[str]
    suppressions: Dict[int, Suppression] = field(default_factory=dict)
    functions: List[FuncInfo] = field(default_factory=list)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    # local name -> dotted import target (module or module.attr); collected
    # from every Import/ImportFrom in the file, nested ones included (the
    # tree imports heavy deps at function scope on purpose)
    imports: Dict[str, str] = field(default_factory=dict)


def _collect_suppressions(fi: FileIndex) -> None:
    for lineno, raw in enumerate(fi.lines, start=1):
        m = SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2).strip()
        comment_only = raw[: m.start()].strip() == ""
        fi.suppressions[lineno] = Suppression(lineno, rules, reason,
                                              comment_only)


def _collect_imports(fi: FileIndex) -> None:
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                fi.imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                # relative import: resolve against this file's module
                parts = fi.module.split(".")
                parts = parts[: len(parts) - node.level]
                base = ".".join(parts + ([node.module] if node.module else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                fi.imports[alias.asname or alias.name] = \
                    f"{base}.{alias.name}" if base else alias.name


def _collect_defs(fi: FileIndex) -> None:
    for node in fi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi.functions.append(FuncInfo(
                node.name, f"{fi.module}.{node.name}", None, fi.module,
                fi, node))
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(node.name,
                           [b.id for b in node.bases
                            if isinstance(b, ast.Name)])
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = FuncInfo(
                        sub.name, f"{fi.module}.{node.name}.{sub.name}",
                        node.name, fi.module, fi, sub)
                    ci.methods[sub.name] = info
                    fi.functions.append(info)
            fi.classes[node.name] = ci


class ProjectIndex:
    """The analyzed tree: every parsed file plus cross-file lookup tables."""

    def __init__(self, module_qualified: bool = True):
        self.files: List[FileIndex] = []
        self.errors: List[Tuple[str, str]] = []  # (path, parse error)
        # ISSUE 20: module-qualified resolution (`mod.f()` through the
        # import map). False = the legacy per-file resolver, kept so the
        # pinned interprocedural regression can prove the old false
        # negative stays fixed.
        self.module_qualified = module_qualified
        # lookup tables (built by _finish)
        self.module_files: Dict[str, FileIndex] = {}
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        self.module_funcs: Dict[Tuple[str, str], FuncInfo] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self._callgraph: Optional["CallGraph"] = None

    # -- construction ----------------------------------------------------------

    @classmethod
    def from_paths(cls, paths: List[str],
                   module_qualified: bool = True) -> "ProjectIndex":
        idx = cls(module_qualified=module_qualified)
        for path in paths:
            if os.path.isdir(path):
                before = len(idx.files) + len(idx.errors)
                for dirpath, dirnames, filenames in os.walk(path):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"
                                   and not d.startswith(".")]
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            idx.add_file(os.path.join(dirpath, fn))
                if len(idx.files) + len(idx.errors) == before:
                    idx.errors.append((path, "directory contains no .py "
                                             "files — nothing analyzed"))
            elif os.path.isfile(path) and path.endswith(".py"):
                idx.add_file(path)
            else:
                # a typo'd target must NOT report a clean tree with exit 0
                idx.errors.append(
                    (path, "no such file/directory (or not a .py file)"))
        idx._finish()
        return idx

    @classmethod
    def from_source(cls, source: str, filename: str = "fixture.py",
                    module: str = "fixture",
                    module_qualified: bool = True) -> "ProjectIndex":
        idx = cls(module_qualified=module_qualified)
        idx.add_source(source, filename, module)
        idx._finish()
        return idx

    @classmethod
    def from_sources(cls, sources: List[Tuple[str, str, str]],
                     module_qualified: bool = True) -> "ProjectIndex":
        """Multi-file fixture entry point: (source, filename, module)
        triples — the interprocedural tests need at least two modules."""
        idx = cls(module_qualified=module_qualified)
        for source, filename, module in sources:
            idx.add_source(source, filename, module)
        idx._finish()
        return idx

    def add_file(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            self.errors.append((path, str(e)))
            return
        self.add_source(source, path, _module_name(path))

    def add_source(self, source: str, path: str, module: str) -> None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.errors.append((path, f"syntax error: {e}"))
            return
        rel = os.path.relpath(path) if os.path.isabs(path) else path
        fi = FileIndex(path=path, rel=rel, module=module, tree=tree,
                       lines=source.splitlines())
        _collect_suppressions(fi)
        _collect_imports(fi)
        _collect_defs(fi)
        self.files.append(fi)

    def _finish(self) -> None:
        for fi in self.files:
            self.module_files[fi.module] = fi
            for info in fi.functions:
                if info.class_name is None:
                    self.module_funcs[(fi.module, info.name)] = info
                else:
                    self.methods_by_name.setdefault(info.name, []).append(info)
            for ci in fi.classes.values():
                self.classes_by_name.setdefault(ci.name, []).append(ci)

    # -- resolution ------------------------------------------------------------

    def resolve_name(self, fi: FileIndex, name: str) -> Optional[FuncInfo]:
        """A bare-name call: module-level def in this file, else an imported
        project function (from x import y)."""
        got = self.module_funcs.get((fi.module, name))
        if got is not None:
            return got
        target = fi.imports.get(name)
        if target and "." in target:
            mod, _, attr = target.rpartition(".")
            return self.module_funcs.get((mod, attr))
        return None

    def _method_in_class(self, class_name: str, method: str,
                         seen: Optional[Set[str]] = None
                         ) -> Optional[FuncInfo]:
        seen = seen or set()
        if class_name in seen:
            return None
        seen.add(class_name)
        for ci in self.classes_by_name.get(class_name, ()):
            if method in ci.methods:
                return ci.methods[method]
            for base in ci.bases:
                got = self._method_in_class(base, method, seen)
                if got is not None:
                    return got
        return None

    # Method names owned by ubiquitous library types (ndarray reductions,
    # dict/list/set/queue protocol): `arr.all()` in a jitted kernel must NOT
    # resolve to the one project class that happens to define `all` — that
    # exact chain (greedy_scan_solve -> DynamicRegistry.all -> Watch.drain)
    # dragged the whole watch bus into JT002's traced set. Uniqueness-based
    # resolution skips these; self.m() and bare-name calls still resolve.
    _LIBRARY_METHODS = frozenset((
        "all", "any", "sum", "mean", "min", "max", "item", "items", "keys",
        "values", "get", "put", "pop", "append", "extend", "add", "update",
        "clear", "copy", "sort", "join", "split", "read", "write", "close",
        "tolist", "astype", "reshape"))

    def resolve_call(self, fi: FileIndex, caller: Optional[FuncInfo],
                     call: ast.Call) -> Optional[FuncInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            return self.resolve_name(fi, func.id)
        if isinstance(func, ast.Attribute):
            # self.m(): enclosing class, then in-tree bases
            if (isinstance(func.value, ast.Name) and func.value.id == "self"
                    and caller is not None and caller.class_name):
                got = self._method_in_class(caller.class_name, func.attr)
                if got is not None:
                    return got
            # mod.f() (ISSUE 20): when the receiver chain names an analyzed
            # module — through the import map or literally — that module is
            # AUTHORITATIVE: resolve its top-level def or stay opaque (a
            # class/constant attribute must not fall through to the
            # unique-method guess)
            if self.module_qualified:
                mod = self._qualified_module(fi, func)
                if mod is not None:
                    return self.module_funcs.get((mod, func.attr))
            # obj.m(): unique method name across the analyzed tree
            if func.attr in self._LIBRARY_METHODS:
                return None
            candidates = self.methods_by_name.get(func.attr, ())
            if len(candidates) == 1:
                return candidates[0]
        return None

    def _qualified_module(self, fi: FileIndex,
                          func: ast.Attribute) -> Optional[str]:
        """The analyzed module a call receiver chain denotes, if any:
        `shm.attach(...)` via `from ..store import shm`, an alias
        (`import x.y as z`), or the literal dotted chain."""
        segs: List[str] = []
        node = func.value
        while isinstance(node, ast.Attribute):
            segs.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        segs.append(node.id)
        segs.reverse()
        imp = fi.imports.get(segs[0])
        candidates = []
        if imp is not None:
            candidates.append(".".join([imp] + segs[1:]))
        candidates.append(".".join(segs))
        for mod in candidates:
            if mod in self.module_files and mod != fi.module:
                return mod
        return None

    # -- interprocedural closure (ISSUE 20) ------------------------------------

    @property
    def callgraph(self) -> "CallGraph":
        """The bounded-depth whole-program call graph, built lazily once."""
        if self._callgraph is None:
            self._callgraph = CallGraph(self)
        return self._callgraph

    # -- suppression check -----------------------------------------------------

    def suppressed(self, fi: FileIndex, line: int, rule: str
                   ) -> Optional[Suppression]:
        """A suppression covers a finding on its own line, or — when it
        opens a comment-only block — every line the block immediately
        precedes (multi-line reasons are encouraged)."""
        sup = fi.suppressions.get(line)
        if sup is not None and sup.covers(rule):
            return sup
        lno = line - 1
        while 1 <= lno <= len(fi.lines):
            raw = fi.lines[lno - 1].strip()
            if not raw.startswith("#"):
                break
            sup = fi.suppressions.get(lno)
            if sup is not None:
                return sup if sup.comment_only and sup.covers(rule) else None
            lno -= 1
        return None

    def file_by_path(self, path: str) -> Optional[FileIndex]:
        for fi in self.files:
            if fi.path == path:
                return fi
        return None


class CallGraph:
    """Bounded-depth transitive call graph over the analyzed tree.

    Direct edges come from `resolve_call` (so every edge is a resolution
    the rules would trust anyway); the closure helpers are cycle-safe BFS
    walks bounded by DEPTH_CAP levels, and a function contributing more
    than FANOUT_CAP distinct callees stops growing (both caps — and how
    often the fan-out cap actually bit — are published in stats, so a cap
    silently truncating coverage shows up in BENCH rather than nowhere).
    """

    DEPTH_CAP = 12    # max call-chain length any closure follows
    FANOUT_CAP = 64   # max distinct callees expanded per function

    def __init__(self, index: ProjectIndex):
        self.index = index
        # func -> [(call node, callee)] with distinct callees capped
        self.edges: Dict[FuncInfo, List[Tuple[ast.Call, FuncInfo]]] = {}
        self.edge_count = 0
        self.fanout_capped = 0
        self.max_depth_seen = 0
        self._build()

    def _build(self) -> None:
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)
        for fi in self.index.files:
            for info in fi.functions:
                outs: List[Tuple[ast.Call, FuncInfo]] = []
                distinct: Set[FuncInfo] = set()
                capped = False
                stack = list(info.node.body)
                while stack:
                    node = stack.pop()
                    if isinstance(node, skip):
                        continue
                    if isinstance(node, ast.Call):
                        callee = self.index.resolve_call(fi, info, node)
                        if callee is not None and callee is not info:
                            if callee in distinct:
                                outs.append((node, callee))
                            elif len(distinct) < self.FANOUT_CAP:
                                distinct.add(callee)
                                outs.append((node, callee))
                            else:
                                capped = True
                    stack.extend(ast.iter_child_nodes(node))
                if capped:
                    self.fanout_capped += 1
                self.edges[info] = outs
                self.edge_count += len(distinct)

    def callees(self, info: FuncInfo) -> List[Tuple[ast.Call, FuncInfo]]:
        return self.edges.get(info, [])

    def reachable_from(self, roots: List[FuncInfo],
                       depth: Optional[int] = None,
                       follow=None) -> Dict[FuncInfo, List[FuncInfo]]:
        """Every function reachable from `roots` (roots excluded unless
        re-reached), mapped to one full call chain [root, ..., func].
        `follow(caller, call, callee)` may veto individual edges."""
        cap = self.DEPTH_CAP if depth is None else min(depth, self.DEPTH_CAP)
        chains: Dict[FuncInfo, List[FuncInfo]] = {}
        frontier = [(r, [r]) for r in roots]
        seen: Set[FuncInfo] = set(roots)
        level = 0
        while frontier and level < cap:
            level += 1
            nxt: List[Tuple[FuncInfo, List[FuncInfo]]] = []
            for cur, chain in frontier:
                for call, callee in self.edges.get(cur, ()):
                    if callee in seen:
                        continue
                    if follow is not None and \
                            not follow(cur, call, callee):
                        continue
                    seen.add(callee)
                    chains[callee] = chain + [callee]
                    nxt.append((callee, chains[callee]))
            frontier = nxt
        if chains:
            deepest = max(len(c) - 1 for c in chains.values())
            if deepest > self.max_depth_seen:
                self.max_depth_seen = deepest
        return chains

    def stats(self) -> Dict[str, int]:
        return {
            "edges": self.edge_count,
            "depth_cap": self.DEPTH_CAP,
            "fanout_cap": self.FANOUT_CAP,
            "fanout_capped": self.fanout_capped,
            "resolve_depth": self.max_depth_seen,
        }


def render_chain(chain: List[FuncInfo]) -> str:
    return " -> ".join(f.qualname for f in chain)


def _module_name(path: str) -> str:
    """Dotted module name from a path: everything from the last
    `kubernetes_tpu` component down (fallback: bare stem)."""
    parts = os.path.normpath(path).split(os.sep)
    stem = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    try:
        i = len(parts) - 1 - parts[::-1].index("kubernetes_tpu")
        comps = parts[i:-1] + ([] if stem == "__init__" else [stem])
        return ".".join(comps)
    except ValueError:
        return stem
