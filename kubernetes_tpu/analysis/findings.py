"""The schedlint finding record (its own module so rule modules can import
it without touching the driver)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Finding:
    rule: str
    file: str
    line: int
    message: str
    hint: str = ""

    def render(self) -> str:
        out = f"{self.file}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def as_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "hint": self.hint}
