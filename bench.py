"""Benchmark ladder: the reference's scheduler_perf workloads on the TPU path.

Measures the batch device path end-to-end per workload (tensorize + device
upload + solve + host readback on fresh state — what a long-running scheduler
executes per batch) against the reference's enforced CI thresholds
(BASELINE.md; sources in test/integration/scheduler_perf/*/performance-config
.yaml). The churn row runs the full BatchScheduler against the API store with
binds enabled and background churn — the honest end-to-end number.

Prints ONE JSON line: the headline metric is SchedulingBasic throughput; the
`workloads` map carries every rung (pods/s + vs_baseline), `min_vs_baseline`
the weakest rung.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"

# reference thresholds (pods/s) — BASELINE.md
BASE_BASIC = 270.0          # misc/performance-config.yaml:63
BASE_PTS = 85.0             # misc/performance-config.yaml:186  TopologySpreading
BASE_ANTI = 60.0            # affinity/performance-config.yaml:68  PodAntiAffinity
BASE_AFF = 35.0             # affinity/performance-config.yaml:135 PodAffinity
BASE_NSANTI = 24.0          # affinity/performance-config.yaml:480 RequiredPodAntiAffinityWithNSSelector
BASE_CHURN = 265.0          # misc/performance-config.yaml:586 SchedulingWithMixedChurn
BASE_PREEMPT = 18.0         # misc/performance-config.yaml:363 PreemptionBasic (500 nodes)
NORTH_STAR = 100_000.0      # BASELINE.json: 100k pods / 10k nodes / <1s


def _nodes(n, cpu="8", mem="32Gi", zones=0):
    from kubernetes_tpu.testing import MakeNode

    out = []
    for i in range(n):
        labels = {HOST: f"node-{i}"}
        if zones:
            labels[ZONE] = f"zone-{i % zones}"
        out.append(MakeNode(f"node-{i}").labels(labels)
                   .capacity({"cpu": cpu, "memory": mem, "pods": "110"}).obj())
    return out


def make_snapshot(nodes, bound_pods=()):
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.utils import FakeClock

    cache = Cache(clock=FakeClock())
    for n in nodes:
        cache.add_node(n)
    for p in bound_pods:
        cache.add_pod(p)
    return cache.update_snapshot()


def device_solve(snap, pods, solver):
    """One full device pass: tensorize + upload + solve + readback. Returns
    (assignment ndarray, seconds)."""
    import numpy as np

    from kubernetes_tpu.models.waterfill import make_groups, waterfill_solve
    from kubernetes_tpu.ops.solver import greedy_scan_solve, make_inputs
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch

    t0 = time.perf_counter()
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, d_max = make_inputs(cluster, batch)
    if solver == "waterfill":
        a = np.asarray(waterfill_solve(inputs, make_groups(batch)))
    else:
        assignment, _, _ = greedy_scan_solve(inputs, d_max)
        a = np.asarray(assignment)
    return a, time.perf_counter() - t0


def run_rung(name, snap, pods, solver, baseline, min_placed=None, results=None):
    """Warm-up (compile) + timed pass; records pods/s and vs_baseline."""
    try:
        device_solve(snap, pods, solver)
        a, dt = device_solve(snap, pods, solver)
        placed = int((a >= 0).sum())
        want = len(pods) if min_placed is None else min_placed
        assert placed >= want, f"{name}: only {placed}/{want} placed"
        pods_per_sec = len(pods) / dt
        results[name] = {
            "pods_per_sec": round(pods_per_sec, 1),
            "vs_baseline": round(pods_per_sec / baseline, 2),
            "placed": placed,
            "pods": len(pods),
            "solver": solver,
        }
        print(f"{name:>28}: {pods_per_sec:>9.0f} pods/s  "
              f"({placed}/{len(pods)} placed, {results[name]['vs_baseline']}x baseline "
              f"{baseline:.0f}, {solver})", file=sys.stderr)
    except Exception as e:  # a failed rung must not kill the whole bench
        results[name] = {"error": str(e)[:200]}
        print(f"{name:>28}: ERROR {e}", file=sys.stderr)


def rung_basic(results):
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(5000))
    pods = [MakePod(f"pod-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
            for i in range(10000)]
    run_rung("SchedulingBasic", snap, pods, "waterfill", BASE_BASIC, results=results)
    run_rung("SchedulingBasic_scan", snap, pods, "scan", BASE_BASIC, results=results)


def rung_topology_spread(results):
    # TopologySpreading: every pod spreads over zones with DoNotSchedule
    # (misc/performance-config.yaml:145-186 shape)
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(5000, zones=10))
    pods = [MakePod(f"sp-{i}").labels({"app": "spread"})
            .req({"cpu": "200m", "memory": "256Mi"})
            .topology_spread(1, ZONE, "DoNotSchedule", {"app": "spread"})
            .obj() for i in range(5000)]
    run_rung("TopologySpreading", snap, pods, "scan", BASE_PTS, results=results)


def rung_pod_anti_affinity(results):
    # PodAntiAffinity: 50 groups x 40 pods, each group hostname-anti-affine
    # (affinity/performance-config.yaml:23-68 shape: anti-affine batches)
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(5000))
    pods = []
    for g in range(50):
        for i in range(40):
            pods.append(MakePod(f"anti-{g}-{i}").labels({"grp": f"g{g}"})
                        .pod_anti_affinity(HOST, {"grp": f"g{g}"})
                        .req({"cpu": "200m"}).obj())
    run_rung("PodAntiAffinity", snap, pods, "scan", BASE_ANTI, results=results)


def rung_pod_affinity(results):
    # PodAffinity: seed pods labeled per zone; incoming pods require
    # colocation with their seed (affinity/performance-config.yaml:85-135)
    from kubernetes_tpu.testing import MakePod

    nodes = _nodes(5000, zones=50)
    seeds = [MakePod(f"seed-{z}").labels({"svc": f"s{z}"})
             .node(f"node-{z}").req({"cpu": "100m"}).obj() for z in range(50)]
    snap = make_snapshot(nodes, bound_pods=seeds)
    pods = [MakePod(f"aff-{i}").labels({"peer": "1"})
            .pod_affinity(ZONE, {"svc": f"s{i % 50}"})
            .req({"cpu": "200m"}).obj() for i in range(5000)]
    run_rung("PodAffinity", snap, pods, "scan", BASE_AFF, results=results)


def rung_anti_affinity_ns_selector(results):
    # RequiredPodAntiAffinityWithNSSelector: pods across namespaces,
    # anti-affinity scoped by namespaceSelector
    # (affinity/performance-config.yaml:480 — the reference's worst case, 24)
    from kubernetes_tpu.api.types import Affinity, PodAffinityTerm
    from kubernetes_tpu.api.labels import Selector
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(5000))
    ns_labels = {f"team-{t}": {"team": "x"} for t in range(10)}
    pods = []
    for g in range(50):
        term = PodAffinityTerm(
            topology_key=HOST,
            selector=Selector.from_match_labels({"grp": f"g{g}"}),
            namespace_selector=Selector.from_match_labels({"team": "x"}),
        )
        for i in range(40):
            p = MakePod(f"nsa-{g}-{i}", namespace=f"team-{(g + i) % 10}").labels(
                {"grp": f"g{g}"}).req({"cpu": "200m"}).obj()
            p.spec.affinity = Affinity(pod_anti_affinity_required=[term])
            pods.append(p)

    # ns_labels flow through build_pod_batch
    import numpy as np

    from kubernetes_tpu.ops.solver import greedy_scan_solve, make_inputs
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch

    def solve():
        t0 = time.perf_counter()
        cluster = build_cluster_tensors(snap)
        batch = build_pod_batch(pods, snap, cluster, ns_labels=ns_labels)
        inputs, d_max = make_inputs(cluster, batch)
        assignment, _, _ = greedy_scan_solve(inputs, d_max)
        return np.asarray(assignment), time.perf_counter() - t0

    try:
        solve()
        a, dt = solve()
        placed = int((a >= 0).sum())
        assert placed == len(pods), f"only {placed}/{len(pods)}"
        pps = len(pods) / dt
        results["AntiAffinityNSSelector"] = {
            "pods_per_sec": round(pps, 1), "vs_baseline": round(pps / BASE_NSANTI, 2),
            "placed": placed, "pods": len(pods), "solver": "scan"}
        print(f"{'AntiAffinityNSSelector':>28}: {pps:>9.0f} pods/s  "
              f"({placed}/{len(pods)} placed, {pps / BASE_NSANTI:.0f}x baseline 24, scan)",
              file=sys.stderr)
    except Exception as e:
        results["AntiAffinityNSSelector"] = {"error": str(e)[:200]}
        print(f"AntiAffinityNSSelector: ERROR {e}", file=sys.stderr)


def rung_mixed_churn(results):
    """End-to-end: BatchScheduler against the API store, binds enabled,
    background churn between batches (SchedulingWithMixedChurn shape —
    misc/performance-config.yaml:527-586). Wall clock covers watch ingestion,
    cache updates, tensorize, solve, and pipelined store binds."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakeNode, MakePod

    try:
        n_nodes, n_pods = 5000, 10000
        store = APIStore()
        for n in _nodes(n_nodes):
            store.create("nodes", n)
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=2500, solver="auto")
        sched.sync()
        # warm-up: compile the solver at this node count
        store.create("pods", MakePod("warm").req({"cpu": "100m"}).obj())
        sched.run_until_idle()

        for i in range(n_pods):
            store.create("pods", MakePod(f"ch-{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj())
        t0 = time.perf_counter()
        done = 0
        churn_i = 0
        while done < n_pods:
            handled = sched.schedule_batch(timeout=0.0)
            if handled == 0:
                sched.flush_binds()
                sched.pump_events()
                if sched.schedule_batch(timeout=0.0) == 0:
                    break
            done = sched.scheduled_count + sched.failed_count - 1  # minus warm pod
            # mixed churn: node updates + unrelated pod create/delete
            for _ in range(10):
                nm = f"node-{churn_i % n_nodes}"
                node = store.get("nodes", nm)
                node.metadata.labels["churn"] = str(churn_i)
                store.update("nodes", node, check_rv=False)
                churn_i += 1
        sched.flush_binds()
        dt = time.perf_counter() - t0
        bound = sum(1 for p in store.list("pods")[0] if p.spec.node_name)
        pps = (bound - 1) / dt
        results["MixedChurn_endtoend"] = {
            "pods_per_sec": round(pps, 1), "vs_baseline": round(pps / BASE_CHURN, 2),
            "placed": bound - 1, "pods": n_pods, "solver": "auto+store-binds"}
        print(f"{'MixedChurn_endtoend':>28}: {pps:>9.0f} pods/s  "
              f"({bound - 1}/{n_pods} bound through store, "
              f"{pps / BASE_CHURN:.1f}x baseline 265)", file=sys.stderr)
    except Exception as e:
        results["MixedChurn_endtoend"] = {"error": str(e)[:200]}
        print(f"MixedChurn_endtoend: ERROR {e}", file=sys.stderr)


def rung_preemption(results):
    """PreemptionBasic (misc/performance-config.yaml:363 shape): 500 full
    nodes, 500 higher-priority preemptors. End-to-end through the scheduler:
    dry-run victim selection, victim deletion, nomination, backoff, rebind."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    try:
        n_nodes = 500
        store = APIStore()
        for n in _nodes(n_nodes, cpu="4"):
            store.create("nodes", n)
        for i in range(n_nodes):
            low = MakePod(f"low-{i}").priority(1).req({"cpu": "3"}).obj()
            low.spec.node_name = f"node-{i}"
            store.create("pods", low)
        # warm-up: compile the solver at the same [P=500, N=500] shapes on a
        # throwaway cluster so the timed run measures scheduling, not XLA
        warm_store = APIStore()
        for n in _nodes(n_nodes, cpu="4"):
            warm_store.create("nodes", n)
        warm = BatchScheduler(warm_store, Framework(default_plugins()), solver="auto")
        warm.sync()
        for i in range(n_nodes):
            warm_store.create("pods", MakePod(f"w-{i}").priority(100).req(
                {"cpu": "2"}).obj())
        warm.run_until_idle()

        sched = BatchScheduler(store, Framework(default_plugins()), solver="auto")
        sched.sync()
        sched.run_until_idle()
        for i in range(n_nodes):
            store.create("pods", MakePod(f"high-{i}").priority(100).req(
                {"cpu": "2"}).obj())
        t0 = time.perf_counter()
        deadline = t0 + 120
        while time.perf_counter() < deadline:
            sched.run_until_idle()
            bound = sum(1 for p in store.list("pods")[0]
                        if p.metadata.name.startswith("high") and p.spec.node_name)
            if bound >= n_nodes:
                break
            sched.queue.flush_backoff_completed()
            sched.queue.flush_unschedulable_left_over()
            time.sleep(0.05)
        dt = time.perf_counter() - t0
        pps = bound / dt
        results["PreemptionBasic"] = {
            "pods_per_sec": round(pps, 1), "vs_baseline": round(pps / BASE_PREEMPT, 2),
            "placed": bound, "pods": n_nodes, "solver": "serial-preempt+batch"}
        print(f"{'PreemptionBasic':>28}: {pps:>9.0f} pods/s  "
              f"({bound}/{n_nodes} preempted+bound, {pps / BASE_PREEMPT:.1f}x baseline 18)",
              file=sys.stderr)
    except Exception as e:
        results["PreemptionBasic"] = {"error": str(e)[:200]}
        print(f"PreemptionBasic: ERROR {e}", file=sys.stderr)


def rung_north_star(results):
    # 100k pods / 10k nodes (BASELINE.json ladder top; constraint-free shape)
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(10000, cpu="16", mem="64Gi"))
    pods = [MakePod(f"ns-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
            for i in range(100_000)]
    try:
        device_solve(snap, pods, "waterfill")
        a, dt = device_solve(snap, pods, "waterfill")
        placed = int((a >= 0).sum())
        pps = len(pods) / dt
        results["NorthStar_100k_10k"] = {
            "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
            "vs_target": round(pps / NORTH_STAR, 2),
            "placed": placed, "pods": len(pods), "solver": "waterfill"}
        print(f"{'NorthStar_100k_10k':>28}: {pps:>9.0f} pods/s  "
              f"({placed}/100000 placed in {dt:.3f}s; target <1s)", file=sys.stderr)
    except Exception as e:
        results["NorthStar_100k_10k"] = {"error": str(e)[:200]}
        print(f"NorthStar_100k_10k: ERROR {e}", file=sys.stderr)


def main():
    results = {}
    rung_basic(results)
    rung_topology_spread(results)
    rung_pod_anti_affinity(results)
    rung_pod_affinity(results)
    rung_anti_affinity_ns_selector(results)
    rung_mixed_churn(results)
    rung_preemption(results)
    rung_north_star(results)

    ratios = [w["vs_baseline"] for w in results.values() if "vs_baseline" in w]
    headline = results.get("SchedulingBasic", {})
    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes_10000pods",
        "value": headline.get("pods_per_sec", 0.0),
        "unit": "pods/s",
        "vs_baseline": headline.get("vs_baseline", 0.0),
        "min_vs_baseline": min(ratios) if ratios else 0.0,
        "workloads": results,
    }))


if __name__ == "__main__":
    main()
