"""Benchmark: SchedulingBasic-equivalent workload (5000 nodes, 10000 pods) on
the batch TPU solver, end-to-end from cluster snapshot to assignments.

Mirrors the reference's scheduler_perf SchedulingBasic/5000Nodes_10000Pods
workload (test/integration/scheduler_perf/misc/performance-config.yaml:63,
threshold 270 pods/s on the serial scheduler). Prints ONE JSON line.

Steady-state throughput: one warm-up pass compiles the solver, then a timed
pass measures tensorize + upload + solve on fresh state (what a long-running
scheduler executes per batch). The water-filling solver is used — the fast
path for constraint-light batches; the exact scan solver's number is also
computed and reported on stderr for reference.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:63


def build_state(n_nodes, n_pods):
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.testing import MakeNode, MakePod
    from kubernetes_tpu.utils import FakeClock

    cache = Cache(clock=FakeClock())
    for i in range(n_nodes):
        cache.add_node(
            MakeNode(f"node-{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .obj()
        )
    snap = cache.update_snapshot()
    pods = [
        MakePod(f"pod-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
        for i in range(n_pods)
    ]
    return snap, pods


def solve_once(snap, pods, fast):
    import numpy as np

    from kubernetes_tpu.models.waterfill import make_groups, waterfill_solve
    from kubernetes_tpu.ops.solver import greedy_scan_solve, make_inputs
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch

    t0 = time.perf_counter()
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, d_max = make_inputs(cluster, batch)
    if fast:
        a = waterfill_solve(inputs, make_groups(batch))
    else:
        assignment, _, _ = greedy_scan_solve(inputs, d_max)
        a = np.asarray(assignment)
    dt = time.perf_counter() - t0
    return a, dt


def main():
    n_nodes, n_pods = 5000, 10000
    snap, pods = build_state(n_nodes, n_pods)

    solve_once(snap, pods, fast=True)  # warm-up/compile
    a, dt = solve_once(snap, pods, fast=True)
    scheduled = int((a >= 0).sum())
    assert scheduled == n_pods, f"only {scheduled}/{n_pods} scheduled"
    pods_per_sec = n_pods / dt

    solve_once(snap, pods, fast=False)
    a2, dt2 = solve_once(snap, pods, fast=False)
    print(f"exact scan solver: {n_pods / dt2:.0f} pods/s "
          f"({int((a2 >= 0).sum())}/{n_pods} placed)", file=sys.stderr)

    from kubernetes_tpu.native import native_available, native_greedy_solve
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch

    if native_available():
        t0 = time.perf_counter()
        cluster = build_cluster_tensors(snap)
        batch = build_pod_batch(pods, snap, cluster)
        a3, placed = native_greedy_solve(cluster, batch)
        dt3 = time.perf_counter() - t0
        print(f"native C++ engine (CPU fallback, scan parity): "
              f"{n_pods / dt3:.0f} pods/s ({placed}/{n_pods} placed)",
              file=sys.stderr)

    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes_10000pods",
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
