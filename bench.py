"""Benchmark: SchedulingBasic-equivalent workload (5000 nodes, 10000 pods) on
the batch TPU solver, end-to-end from cluster objects to assignments.

Mirrors the reference's scheduler_perf SchedulingBasic/5000Nodes_10000Pods
workload (test/integration/scheduler_perf/misc/performance-config.yaml:63,
threshold 270 pods/s on the serial scheduler). Prints ONE JSON line.

Steady-state throughput: the solve is run once to compile, then timed on a
fresh state (the compiled program is what a long-running scheduler executes
per batch; tensorize cost is included in the timed region, Python object
construction is not — it is the test harness, not the scheduler).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:63


def main():
    import numpy as np

    from kubernetes_tpu.ops.solver import greedy_scan_solve, make_inputs
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch
    from kubernetes_tpu.testing import MakeNode, MakePod
    from kubernetes_tpu.utils import FakeClock

    n_nodes, n_pods = 5000, 10000
    cache = Cache(clock=FakeClock())
    for i in range(n_nodes):
        cache.add_node(
            MakeNode(f"node-{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .obj()
        )
    snap = cache.update_snapshot()
    pods = [
        MakePod(f"pod-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
        for i in range(n_pods)
    ]

    # warm-up: tensorize + compile + run once
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, d_max = make_inputs(cluster, batch)
    assignment, _, _ = greedy_scan_solve(inputs, d_max)
    assignment.block_until_ready()

    # timed: steady-state batch — tensorize, upload, solve
    t0 = time.perf_counter()
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, d_max = make_inputs(cluster, batch)
    assignment, _, _ = greedy_scan_solve(inputs, d_max)
    assignment.block_until_ready()
    dt = time.perf_counter() - t0

    a = np.asarray(assignment)
    scheduled = int((a >= 0).sum())
    assert scheduled == n_pods, f"only {scheduled}/{n_pods} scheduled"
    pods_per_sec = n_pods / dt

    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes_10000pods",
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
