"""Benchmark ladder: the reference's scheduler_perf workloads on the TPU path.

Measures the batch device path end-to-end per workload (tensorize + device
upload + solve + host readback on fresh state — what a long-running scheduler
executes per batch) against the reference's enforced CI thresholds
(BASELINE.md; sources in test/integration/scheduler_perf/*/performance-config
.yaml). The churn row runs the full BatchScheduler against the API store with
binds enabled and background churn — the honest end-to-end number.

Prints ONE JSON line: the headline metric is SchedulingBasic throughput; the
`workloads` map carries every rung (pods/s + vs_baseline), `min_vs_baseline`
the weakest rung.

Robustness (the round-2 rc=124 failure mode):
  - fails FAST (<=60s) with a recorded error when the TPU backend is down,
  - on device failure, RE-EXECS itself with JAX_PLATFORMS=cpu and runs the
    FULL ladder on the host platform (labeled "platform": "cpu") — a TPU
    outage degrades the numbers' hardware, never their existence (the
    round-4 blackout: BENCH_r04.json recorded nothing but the error),
  - checkpoints partial results to BENCH_partial.json after every rung,
  - skips remaining rungs once the global wall-clock budget is spent, so a
    slow chip degrades coverage instead of producing nothing.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_partial.json")
# BENCH_SMOKE=1 shrinks every rung ~64x for a fast CPU harness check —
# validates the ladder end to end without TPU hardware (numbers meaningless)
SMOKE = os.environ.get("BENCH_SMOKE", "") not in ("", "0")


def sz(n: int, floor: int = 8) -> int:
    return max(floor, n // 64) if SMOKE else n
GLOBAL_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1500"))
# a rung is skipped when less than this much budget remains (quick mode
# shrinks it along with the budget)
MIN_RUNG_BUDGET_S = 60.0
_START = time.monotonic()


def budget_left() -> float:
    return GLOBAL_BUDGET_S - (time.monotonic() - _START)


def checkpoint(results) -> None:
    """Persist partial results after every rung — a later crash/timeout still
    leaves an inspectable record."""
    try:
        with open(PARTIAL_PATH, "w") as f:
            json.dump(results, f)
    except OSError:
        pass


def ensure_device_alive(timeout_s: float = 60.0) -> str:
    """Fail fast when the backend can't run a trivial op. Returns the platform
    name or raises RuntimeError after timeout_s."""
    import threading

    if os.environ.get("BENCH_FORCE_DEVICE_FAIL", "") not in ("", "0"):
        # test hook for the cpu_fallback path (cleared for the child so the
        # fallback run itself can come up on the host platform)
        os.environ.pop("BENCH_FORCE_DEVICE_FAIL")
        raise RuntimeError("device backend unresponsive (forced by test hook)")

    out = {}

    def probe():
        try:
            import jax

            if os.environ.get("JAX_PLATFORMS"):
                # the env var alone doesn't always win over sitecustomize's
                # PJRT plugin registration (see tests/conftest.py)
                try:
                    jax.config.update("jax_platforms",
                                      os.environ["JAX_PLATFORMS"])
                except Exception:
                    pass
            import jax.numpy as jnp

            devs = jax.devices()
            (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
            out["platform"] = devs[0].platform
        except Exception as e:  # pragma: no cover - depends on environment
            out["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        raise RuntimeError(f"device backend unresponsive after {timeout_s:.0f}s")
    if "error" in out:
        raise RuntimeError(f"device backend failed: {out['error']}")
    return out.get("platform", "unknown")

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"

# reference thresholds (pods/s) — BASELINE.md
BASE_BASIC = 270.0          # misc/performance-config.yaml:63
BASE_PTS = 85.0             # misc/performance-config.yaml:186  TopologySpreading
BASE_ANTI = 60.0            # affinity/performance-config.yaml:68  PodAntiAffinity
BASE_AFF = 35.0             # affinity/performance-config.yaml:135 PodAffinity
BASE_NSANTI = 24.0          # affinity/performance-config.yaml:480 RequiredPodAntiAffinityWithNSSelector
BASE_CHURN = 265.0          # misc/performance-config.yaml:586 SchedulingWithMixedChurn
BASE_PREEMPT = 18.0         # misc/performance-config.yaml:363 PreemptionBasic (500 nodes)
NORTH_STAR = 100_000.0      # BASELINE.json: 100k pods / 10k nodes / <1s


def _nodes(n, cpu="8", mem="32Gi", zones=0):
    from kubernetes_tpu.testing import MakeNode

    out = []
    for i in range(n):
        labels = {HOST: f"node-{i}"}
        if zones:
            labels[ZONE] = f"zone-{i % zones}"
        out.append(MakeNode(f"node-{i}").labels(labels)
                   .capacity({"cpu": cpu, "memory": mem, "pods": "110"}).obj())
    return out


def make_snapshot(nodes, bound_pods=()):
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.utils import FakeClock

    cache = Cache(clock=FakeClock())
    for n in nodes:
        cache.add_node(n)
    for p in bound_pods:
        cache.add_pod(p)
    return cache.update_snapshot()


def device_solve(snap, pods, solver, ns_labels=None):
    """One full device pass: tensorize + upload + solve + readback. Returns
    (assignment ndarray, seconds, info dict — repair-stage columns when the
    propose-and-repair solver ran, else empty)."""
    import numpy as np

    from kubernetes_tpu.models.repair import repair_solve
    from kubernetes_tpu.models.waterfill import make_groups, waterfill_solve
    from kubernetes_tpu.ops.solver import greedy_scan_solve, make_inputs
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch

    info = {}
    t0 = time.perf_counter()
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster, ns_labels=ns_labels)
    inputs, d_max = make_inputs(cluster, batch)
    if solver == "waterfill":
        a = np.asarray(waterfill_solve(inputs, make_groups(batch)))
    elif solver == "repair":
        solved = repair_solve(inputs, batch, d_max)
        assert solved is not None, "repair solver declined the problem shape"
        a, stats = solved
        a = np.asarray(a)
        s = stats.as_dict()
        info["repair"] = {k: s[k] for k in
                          ("rounds", "residual", "full_scan", "propose_calls")}
    else:
        assignment, _, _ = greedy_scan_solve(
            inputs, d_max, has_ipa=bool(batch.ipa.has_any),
            has_ct=bool(batch.ct_class.size), has_st=bool(batch.st_class.size))
        a = np.asarray(assignment)
    return a, time.perf_counter() - t0, info


def run_rung(name, snap, pods, solver, baseline, min_placed=None,
             results=None, ns_labels=None):
    """Warm-up (compile) + timed pass; records pods/s and vs_baseline. Every
    constraint rung publishes the SAME columns (solver / vs_baseline /
    repair-stage info) through this one path."""
    try:
        device_solve(snap, pods, solver, ns_labels=ns_labels)
        a, dt, info = device_solve(snap, pods, solver, ns_labels=ns_labels)
        placed = int((a >= 0).sum())
        want = len(pods) if min_placed is None else min_placed
        assert placed >= want, f"{name}: only {placed}/{want} placed"
        pods_per_sec = len(pods) / dt
        results[name] = {
            "pods_per_sec": round(pods_per_sec, 1),
            "vs_baseline": round(pods_per_sec / baseline, 2),
            "placed": placed,
            "pods": len(pods),
            "solver": solver,
            **info,
        }
        print(f"{name:>28}: {pods_per_sec:>9.0f} pods/s  "
              f"({placed}/{len(pods)} placed, {results[name]['vs_baseline']}x baseline "
              f"{baseline:.0f}, {solver})", file=sys.stderr)
    except Exception as e:  # a failed rung must not kill the whole bench
        results[name] = {"error": str(e)[:200]}
        print(f"{name:>28}: ERROR {e}", file=sys.stderr)


def rung_basic(results):
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(sz(5000)))
    pods = [MakePod(f"pod-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
            for i in range(sz(10000))]
    run_rung("SchedulingBasic", snap, pods, "waterfill", BASE_BASIC, results=results)
    run_rung("SchedulingBasic_scan", snap, pods, "scan", BASE_BASIC, results=results)


def rung_topology_spread(results):
    # TopologySpreading: every pod spreads over zones with DoNotSchedule
    # (misc/performance-config.yaml:145-186 shape)
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(sz(5000), zones=10))
    pods = [MakePod(f"sp-{i}").labels({"app": "spread"})
            .req({"cpu": "200m", "memory": "256Mi"})
            .topology_spread(1, ZONE, "DoNotSchedule", {"app": "spread"})
            .obj() for i in range(sz(5000))]
    run_rung("TopologySpreading", snap, pods, "repair", BASE_PTS, results=results)


def rung_pod_anti_affinity(results):
    # PodAntiAffinity: 50 groups x 40 pods, each group hostname-anti-affine
    # (affinity/performance-config.yaml:23-68 shape: anti-affine batches)
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(sz(5000)))
    pods = []
    for g in range(sz(50)):
        for i in range(sz(40)):
            pods.append(MakePod(f"anti-{g}-{i}").labels({"grp": f"g{g}"})
                        .pod_anti_affinity(HOST, {"grp": f"g{g}"})
                        .req({"cpu": "200m"}).obj())
    run_rung("PodAntiAffinity", snap, pods, "repair", BASE_ANTI, results=results)


def rung_pod_affinity(results):
    # PodAffinity: seed pods labeled per zone; incoming pods require
    # colocation with their seed (affinity/performance-config.yaml:85-135)
    from kubernetes_tpu.testing import MakePod

    nodes = _nodes(sz(5000), zones=sz(50))
    seeds = [MakePod(f"seed-{z}").labels({"svc": f"s{z}"})
             .node(f"node-{z}").req({"cpu": "100m"}).obj() for z in range(sz(50))]
    snap = make_snapshot(nodes, bound_pods=seeds)
    pods = [MakePod(f"aff-{i}").labels({"peer": "1"})
            .pod_affinity(ZONE, {"svc": f"s{i % sz(50)}"})
            .req({"cpu": "200m"}).obj() for i in range(sz(5000))]
    run_rung("PodAffinity", snap, pods, "repair", BASE_AFF, results=results)


def rung_anti_affinity_ns_selector(results):
    # RequiredPodAntiAffinityWithNSSelector: pods across namespaces,
    # anti-affinity scoped by namespaceSelector
    # (affinity/performance-config.yaml:480 — the reference's worst case, 24).
    # Folded into run_rung (ISSUE 8): ns_labels flow through build_pod_batch
    # via device_solve, so this rung publishes the SAME columns as every
    # other constraint rung instead of a hand-rolled result dict.
    from kubernetes_tpu.api.types import Affinity, PodAffinityTerm
    from kubernetes_tpu.api.labels import Selector
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(sz(5000)))
    ns_labels = {f"team-{t}": {"team": "x"} for t in range(10)}
    pods = []
    for g in range(sz(50)):
        term = PodAffinityTerm(
            topology_key=HOST,
            selector=Selector.from_match_labels({"grp": f"g{g}"}),
            namespace_selector=Selector.from_match_labels({"team": "x"}),
        )
        for i in range(sz(40)):
            p = MakePod(f"nsa-{g}-{i}", namespace=f"team-{(g + i) % 10}").labels(
                {"grp": f"g{g}"}).req({"cpu": "200m"}).obj()
            p.spec.affinity = Affinity(pod_anti_affinity_required=[term])
            pods.append(p)
    run_rung("AntiAffinityNSSelector", snap, pods, "repair", BASE_NSANTI,
             results=results, ns_labels=ns_labels)


def rung_mixed_churn(results):
    """End-to-end: BatchScheduler against the API store, binds enabled,
    background churn between batches (SchedulingWithMixedChurn shape —
    misc/performance-config.yaml:527-586). Wall clock covers watch ingestion,
    cache updates, tensorize, solve, and pipelined store binds."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakeNode, MakePod

    try:
        n_nodes, n_pods = sz(5000), sz(10000)
        # warm-up on a throwaway cluster at the REAL batch shapes (the round-3
        # run compiled mid-measurement because the warm batch had 1 pod)
        warm_store = APIStore()
        for n in _nodes(n_nodes):
            warm_store.create("nodes", n)
        warm = BatchScheduler(warm_store, Framework(default_plugins()),
                              batch_size=sz(2500), solver="auto")
        warm.sync()
        warm_store.create_many(
            "pods", (MakePod(f"w-{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(sz(2500))), consume=True)
        warm.run_until_idle()

        store = APIStore()
        for n in _nodes(n_nodes):
            store.create("nodes", n)
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=sz(2500), solver="auto")
        sched.sync()
        store.create("pods", MakePod("warm").req({"cpu": "100m"}).obj())
        sched.run_until_idle()

        store.create_many(
            "pods", (MakePod(f"ch-{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(n_pods)), consume=True)
        t0 = time.perf_counter()
        done = 0
        churn_i = 0
        while done < n_pods:
            handled = sched.schedule_batch(timeout=0.0)
            if handled == 0:
                sched.flush_binds()
                sched.pump_events()
                if sched.schedule_batch(timeout=0.0) == 0:
                    break
            done = sched.scheduled_count + sched.failed_count - 1  # minus warm pod
            # mixed churn: node updates + unrelated pod create/delete
            for _ in range(10):
                nm = f"node-{churn_i % n_nodes}"
                node = store.get("nodes", nm)
                node.metadata.labels["churn"] = str(churn_i)
                store.update("nodes", node, check_rv=False)
                churn_i += 1
        sched.flush_binds()
        dt = time.perf_counter() - t0
        bound = sum(1 for p in store.list("pods")[0] if p.spec.node_name)
        pps = (bound - 1) / dt
        results["MixedChurn_endtoend"] = {
            "pods_per_sec": round(pps, 1), "vs_baseline": round(pps / BASE_CHURN, 2),
            "placed": bound - 1, "pods": n_pods, "solver": "auto+store-binds"}
        print(f"{'MixedChurn_endtoend':>28}: {pps:>9.0f} pods/s  "
              f"({bound - 1}/{n_pods} bound through store, "
              f"{pps / BASE_CHURN:.1f}x baseline 265)", file=sys.stderr)
    except Exception as e:
        results["MixedChurn_endtoend"] = {"error": str(e)[:200]}
        print(f"MixedChurn_endtoend: ERROR {e}", file=sys.stderr)


def rung_preemption(results):
    """PreemptionBasic (misc/performance-config.yaml:363 shape, baseline 18):
    500 full nodes, 500 higher-priority preemptors, SERIAL victim preparation
    (the reference's non-async mode); PreemptionAsync covers the async mode."""
    _preemption_run(results, "PreemptionBasic", BASE_PREEMPT,
                    async_preparation=False)


def rung_north_star(results):
    # 100k pods / 10k nodes (BASELINE.json ladder top; constraint-free shape):
    # solver-only (tensorize + upload + solve + readback, target <1s)
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(sz(10000), cpu="16", mem="64Gi"))
    pods = [MakePod(f"ns-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
            for i in range(sz(100_000))]
    try:
        device_solve(snap, pods, "waterfill")
        a, dt, _ = device_solve(snap, pods, "waterfill")
        placed = int((a >= 0).sum())
        pps = len(pods) / dt
        results["NorthStar_100k_10k"] = {
            "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
            "vs_target": round(pps / NORTH_STAR, 2),
            "placed": placed, "pods": len(pods), "solver": "waterfill"}
        print(f"{'NorthStar_100k_10k':>28}: {pps:>9.0f} pods/s  "
              f"({placed}/{len(pods)} placed in {dt:.3f}s; target <1s)", file=sys.stderr)
    except Exception as e:
        results["NorthStar_100k_10k"] = {"error": str(e)[:200]}
        print(f"NorthStar_100k_10k: ERROR {e}", file=sys.stderr)


def rung_north_star_warm(results):
    """Steady-state variant: re-solve the SAME 100k backlog after churn on a
    few hundred nodes, through the TensorCache — tensorize work scales with
    the diff (generation-diff rows, pod-axis reuse, HBM scatter updates)
    instead of the cluster. The number the long-running scheduler sees per
    re-solve under churn."""
    import numpy as np

    from kubernetes_tpu.models.waterfill import make_groups, waterfill_solve
    from kubernetes_tpu.ops.solver import make_inputs
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.snapshot.tensorizer import TensorCache, build_pod_batch
    from kubernetes_tpu.testing import MakeNode, MakePod
    from kubernetes_tpu.utils import FakeClock

    try:
        cache = Cache(clock=FakeClock())
        for n in _nodes(sz(10000), cpu="16", mem="64Gi"):
            cache.add_node(n)
        pods = [MakePod(f"nw-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(sz(100_000))]
        tc = TensorCache()

        def solve_pass():
            t0 = time.perf_counter()
            snap = cache.update_snapshot()
            cluster, changed = tc.cluster_tensors(snap)
            batch = build_pod_batch(pods, snap, cluster, reuse=tc,
                                    changed_nodes=changed)
            inputs, _ = make_inputs(cluster, batch,
                                    device=tc.device_views(cluster))
            a = np.asarray(waterfill_solve(inputs, make_groups(batch)))
            return a, time.perf_counter() - t0

        solve_pass()  # cold: full tensorize + compile
        # warm-up the INCREMENTAL path too, at the SAME scatter width as the
        # measured pass (the .at[rows].set update compiles per row count)
        for i in range(sz(300)):
            p = MakePod(f"wchurn0-{i}").req({"cpu": "1"}).obj()
            p.spec.node_name = f"node-{i}"
            cache.add_pod(p)
        solve_pass()
        # churn: bind pods to 300 different nodes, then re-solve warm
        for i in range(sz(300)):
            p = MakePod(f"wchurn-{i}").req({"cpu": "1"}).obj()
            p.spec.node_name = f"node-{sz(300) + i}"
            cache.add_pod(p)
        a, dt = solve_pass()
        placed = int((a >= 0).sum())
        pps = len(pods) / dt
        results["NorthStar_100k_10k_warm"] = {
            "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
            "vs_target": round(pps / NORTH_STAR, 2),
            "placed": placed, "pods": len(pods),
            "solver": "waterfill+tensorcache"}
        print(f"{'NorthStar_100k_10k_warm':>28}: {pps:>9.0f} pods/s  "
              f"({placed}/{len(pods)} placed in {dt:.3f}s warm re-solve)",
              file=sys.stderr)
    except Exception as e:
        results["NorthStar_100k_10k_warm"] = {"error": str(e)[:200]}
        print(f"NorthStar_100k_10k_warm: ERROR {e}", file=sys.stderr)


def rung_north_star_endtoend(results):
    """The honest variant BASELINE.json actually defines: BIND 100k pending
    pods onto 10k nodes end-to-end — store watch ingestion (coalesced), bulk
    queue admission, cache, tensorize, device solve, batched Binding writes,
    and the self-bind confirm re-ingest all inside the timed window.

    The timed window runs with the collector frozen+disabled (restored
    after): CPython gen2 sweeps over the ~10M-object store/cache heap
    otherwise add 2x wall that measures the collector, not the pipeline —
    the standard long-lived-heap service configuration."""
    import gc

    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    try:
        n_nodes, n_pods = sz(10_000), sz(100_000)
        # warm-up on a THROWAWAY cluster at the real batch shape: the
        # 100k-pod waterfill compiles per pod-axis shape, and a 1-pod warm
        # batch left the full-shape compile inside the timed window
        warm_store = APIStore()
        for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
            warm_store.create("nodes", n)
        # warm-up runs with the flight recorder DISABLED — exercising the
        # recorder-off hot path every bench run (parity with recorder-on is
        # pinned by tests/test_flightrec.py). The pod TRACER stays on so its
        # first-call costs (numpy ufunc warmup, lazy imports, histogram
        # construction) land here, not inside the timed window
        warm = BatchScheduler(warm_store, Framework(default_plugins()),
                              batch_size=n_pods, solver="fast",
                              flight_recorder=False, pod_trace=True)
        warm.sync()
        warm_store.create_many(
            "pods", (MakePod(f"w-{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(n_pods)), consume=True)
        warm.run_until_idle()
        # the warm cluster must not sit in memory during the timed run
        # (stop() releases the bind worker, which would otherwise pin the
        # whole warm object graph from its parked q.get())
        warm.stop()
        del warm, warm_store

        store = APIStore()
        for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
            store.create("nodes", n)
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=n_pods, solver="fast")
        sched.sync()
        # bulk write API: one store lock + one coalesced ADDED event per
        # chunk; consume=True transfers ownership (no isolation deepcopy)
        CH = 10_000
        pending = [MakePod(f"e2e-{i}").req(
            {"cpu": "500m", "memory": "1Gi"}).obj() for i in range(n_pods)]
        for lo in range(0, n_pods, CH):
            store.create_many("pods", pending[lo:lo + CH], consume=True)
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            sched.flightrec.clear()  # stage table covers EXACTLY the window
            sched.podtrace.clear()  # latency histogram + spans likewise
            # jit-cache watermark (ISSUE 5 retrace guard): the warm-up
            # compiled every shape the timed run uses, so a nonzero delta
            # below IS a mid-run retrace — the regression class JT001
            # guards statically
            compiles0 = _solver_jit_cache()

            # the zero-alloc acceptance gauge (ISSUE 16): pod-object
            # materializations across the store + scheduler-cache columnar
            # tables during the timed window — 0 when the end-to-end
            # columnar pipeline (rows + column assume + clone-free
            # dispatch) never builds a per-pod Python object
            def _pod_obj_allocs():
                st = store.columnar_stats() or {}
                return (st.get("materialized_total", 0)
                        + sched.cache.columnar_materialized())

            allocs0 = _pod_obj_allocs()
            t0 = time.perf_counter()
            sched.run_until_idle()
            dt = time.perf_counter() - t0
            pod_obj_allocs = _pod_obj_allocs() - allocs0
        finally:
            # a mid-run failure must not leave the collector off for every
            # later rung (this rung records the error and the ladder
            # continues)
            gc.enable()
            gc.unfreeze()
        jit_cache = _solver_jit_cache()
        compiles_during = {k: v - compiles0.get(k, 0)
                          for k, v in jit_cache.items() if v >= 0}
        bound = sched.scheduled_count
        pps = bound / dt
        # machine-generated stage breakdown (scheduler/flightrec.py): the
        # source of ROADMAP's stage table. Serial rows sum to ~wall; "bind"
        # is the worker's wall, overlapped with the solve. instrumentation_s
        # is the recorder's measured self-time (record building, histogram
        # observation, timing taps) — the only unmeasured cost is the ~10
        # StageClock perf_counter reads per batch. Divided by wall it bounds
        # the overhead budget without differencing two noisy runs.
        table = sched.flightrec.stage_table()
        stages = {k: round(v["total_ms"] / 1000, 4) for k, v in table.items()}
        serial_sum = round(sum(v["total_ms"] for v in table.values()
                               if not v["overlapped"]) / 1000, 4)
        # pod-latency observability (ISSUE 7): per-stage p50/p99 columns,
        # the all-pods submit->bound distribution, sampled-span health, and
        # the declarative SLO gate (scheduler/slo.py) — the BENCH_r* series
        # tracks tails from this run on, not just pods/s
        from kubernetes_tpu.scheduler.slo import NORTH_STAR_SLO, evaluate_slo

        latency = sched.podtrace.latency_stats()
        tsnap = sched.podtrace.snapshot()
        # control-plane observability columns (ISSUE 9): the scheduler's own
        # coalesced subscriber gives the commit->dequeue propagation of the
        # whole ingest path; controller columns are empty here (no
        # controllers in this rung) but published so the schema is uniform
        from kubernetes_tpu.obs.reconcile import reconcile_rollup

        wtel = store.watch_telemetry()
        prop = wtel["propagation"]
        watch_col = {
            "propagation_count": prop["count"],
            "propagation_p50_s": prop["p50_s"],
            "propagation_p99_s": prop["p99_s"],
            "settle_s": prop["settle_seconds"],
            "subscribers": len(wtel["subscribers"]),
            "max_rv_lag": max((s["rv_lag"] for s in wtel["subscribers"]),
                              default=0),
        }
        compiles = sum(compiles_during.values())
        # the <2% budget now covers the new recorders too: inline watch-tap
        # settlement already bills flightrec via the Watch stat_sink. The
        # budget is a FRACTION with a 2ms ABSOLUTE floor: the smoke-shrunk
        # rung's wall is ~45ms (and shrank further with the native commit
        # engine) while the recorder's per-run cost is fixed sub-1ms — a
        # fixed cost that doesn't scale with the run must not read as a
        # budget violation on a run 2000x smaller than production
        instr_s = sched.flightrec.self_seconds
        instr_frac = (instr_s / max(dt, 1e-9)) if instr_s > 0.002 else 0.0
        slo = evaluate_slo(
            {"stages": table, "latency": latency}, NORTH_STAR_SLO,
            extra={"solver_compiles": compiles,
                   "instrumentation_frac": round(instr_frac, 5)})
        results["NorthStar_100k_10k_endtoend"] = {
            "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
            "vs_target": round(pps / NORTH_STAR, 2),
            "placed": bound, "pods": n_pods, "solver": "fast+store-binds",
            "stages": stages,
            "stages_p50_ms": {k: v.get("p50_ms") for k, v in table.items()},
            "stages_p99_ms": {k: v.get("p99_ms") for k, v in table.items()},
            "stages_serial_sum_s": serial_sum,
            "latency": latency,
            "trace": {"spans": len(tsnap["spans"]),
                      "complete": sum(1 for s in tsnap["spans"]
                                      if s["complete"]),
                      "evicted_incomplete": tsnap["evicted_incomplete"],
                      "flush_s": tsnap["flush_seconds"]},
            "watch": watch_col,
            "reconcile": reconcile_rollup(),
            "slo": slo,
            "instrumentation_s": round(sched.flightrec.self_seconds, 6),
            "jit_cache": jit_cache,
            # ISSUE 16 acceptance: zero pod-object materializations in the
            # timed window, with the row path demonstrably engaged
            "pod_obj_allocs": pod_obj_allocs,
            "cache_rows": sched.cache.columnar_rows(),
            "solver_compiles_during_run": compiles}
        print(f"{'NorthStar_100k_10k_endtoend':>28}: {pps:>9.0f} pods/s  "
              f"({bound}/{n_pods} BOUND through the store in {dt:.3f}s)",
              file=sys.stderr)
        print("    stages: " + "  ".join(
            f"{k}={v:.3f}s" for k, v in sorted(
                stages.items(), key=lambda kv: -kv[1])), file=sys.stderr)
        print(f"    submit->bound: p50={latency['p50_s']}s "
              f"p99={latency['p99_s']}s over {latency['count']} pods; "
              f"SLO {'PASS' if slo['pass'] else 'FAIL ' + str(slo['failed'])}",
              file=sys.stderr)
        # --- partitioned A/B (ISSUE 12): the SAME workload, same box,
        # through 2 partitioned pipelines — disjoint node shards,
        # hash-routed pods, each partition's GIL-held host stages
        # overlapping the other's GIL-free XLA solve. The 1p run above is
        # the A; this is the B. The 1p heap is released first (the A/B must
        # not measure the winner under the loser's memory pressure), and a
        # warm run compiles the partition-shaped kernels (half-size pod
        # bucket, shard-size node axis — fresh jit shapes).
        share_1p = round(stages.get("bind_wait", 0.0) / max(dt, 1e-9), 4)
        sched.stop()  # release the bind worker so the del really frees
        del sched, store, pending
        try:
            _w = _partitioned_e2e(n_pods, n_nodes, 2, "e2ew")[0]
            _w.stop()
            del _w
            compiles2_0 = _solver_jit_cache()
            # interleaved best-of-2 per mode (the BindCommit discipline):
            # harness co-scheduling drifts minute-to-minute on this rig
            # (same-code 1p walls vary +-30%), and alternating the modes
            # keeps the drift from landing entirely on one column. The main
            # 1p run above stays the official 1p number; its wall joins the
            # 1p sample set here.
            from kubernetes_tpu.obs import ResourceSampler
            judge = len(os.sched_getaffinity(0)) >= 2
            best = None
            walls_1p, walls_2p = [dt], []
            for i in range(2):
                samp = ResourceSampler(interval_s=0.05) if judge else None
                c, st2c, d2, b2 = _partitioned_e2e(
                    n_pods, n_nodes, 2, f"e2eb{i}", sampler=samp)
                walls_2p.append(d2)
                osum = samp.summary() if samp is not None else None
                if best is None or d2 < best[2]:
                    if best is not None:
                        best[0].stop()
                    best = (c, st2c, d2, b2, osum)  # rebind drops old best
                else:
                    c.stop()
                    del c, st2c
                _s1, _st1, d1, _b1 = _partitioned_e2e(
                    n_pods, n_nodes, 1, f"e2ea{i}")
                _s1.stop()
                del _s1, _st1
                walls_1p.append(d1)
            coord, store2, dt2, bound2, osum2 = best
            compiles_2p = sum(
                v - compiles2_0.get(k, 0)
                for k, v in _solver_jit_cache().items() if v >= 0)
            dt1_best = min(walls_1p)
            pps1b = n_pods / dt1_best  # best-of 1p for the A/B columns
            dt2 = min(walls_2p)
            pps2 = bound2 / dt2
            # bind_wait share of wall: mean over pipelines of that
            # pipeline's scheduling-thread stall — the acceptance lever
            # (partitioning exists to give the stall something to overlap
            # with)
            waits = [(p.flightrec.stage_table().get("bind_wait", {})
                      .get("total_ms", 0.0) or 0.0) / 1000.0
                     for p in coord.pipelines]
            share_2p = round((sum(waits) / max(len(waits), 1))
                             / max(dt2, 1e-9), 4)
            cores = len(os.sched_getaffinity(0))
            results["NorthStar_100k_10k_endtoend"]["partitioned"] = {
                "partitions": 2,
                "pods_per_sec_2p": round(pps2, 1),
                "wall_s_2p": round(dt2, 3),
                "placed_2p": bound2,
                "pods_per_sec_1p_best": round(pps1b, 1),
                "speedup_vs_1p": round(pps2 / max(pps1b, 1e-9), 3),
                "walls_1p": [round(w, 3) for w in walls_1p],
                "walls_2p": [round(w, 3) for w in walls_2p],
                "cores": cores,
                "ab_comparable": cores >= 2,
                # measured concurrency (ISSUE 19 satellite): overlap_cpu_s
                # sampled inside the winning 2p window; None = 1-core rig
                "overlap_cpu_s": (osum2["overlap_cpu_s"] if osum2
                                  else None),
                "concurrency_verdict": _overlap_verdict(
                    osum2["overlap_cpu_s"] if osum2 else None, dt2),
                "concurrent_drive": coord.concurrent_drive,
                "bind_wait_share_1p": share_1p,
                "bind_wait_share_2p": share_2p,
                "conflicts": coord.conflicts_total,
                "reroutes": coord.reroutes_total,
                "solver_compiles_during_run": compiles_2p,
                "per_partition": [
                    {"index": r["index"], "nodes": r["nodes"],
                     "scheduled": r["scheduled"]}
                    for r in coord.sched_stats()["rows"]],
            }
            print(f"    partitioned A/B (best-of-interleaved): "
                  f"1p {pps1b:.0f} vs 2p {pps2:.0f} pods/s "
                  f"(speedup {pps2 / max(pps1b, 1e-9):.2f}x; bind_wait "
                  f"share {share_1p:.3f} -> {share_2p:.3f}; "
                  f"compiles_2p={compiles_2p})", file=sys.stderr)
            coord.stop()  # release bind workers before later rungs
        except Exception as e:  # the A/B must not void the 1p result
            results["NorthStar_100k_10k_endtoend"]["partitioned"] = {
                "error": str(e)[:200]}
            print(f"    partitioned A/B: ERROR {e}", file=sys.stderr)
    except Exception as e:
        results["NorthStar_100k_10k_endtoend"] = {"error": str(e)[:200]}
        print(f"NorthStar_100k_10k_endtoend: ERROR {e}", file=sys.stderr)


def _partitioned_e2e(n_pods, n_nodes, partitions, prefix, batch_size=None,
                     sampler=None):
    """One end-to-end bind run (fresh store, GC-frozen timed window) through
    a 1-partition BatchScheduler or an N-partition PartitionedScheduler —
    the shared body of the Partitioned_2x rung and the NorthStar A/B column
    (ISSUE 12). Returns (sched, store, dt, bound). sampler: an
    obs/resource.py ResourceSampler started around the timed window only —
    the >=2-core A/B re-judge (ISSUE 19 satellite) reads its overlap_cpu_s
    to judge the speedup column from MEASURED parallelism, not wall ratios.
    """
    import gc

    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.partition import PartitionedScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    bs = batch_size or n_pods
    store = APIStore()
    for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
        store.create("nodes", n)
    if partitions == 1:
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=bs, solver="fast")
    else:
        sched = PartitionedScheduler(
            store, lambda: Framework(default_plugins()),
            partitions=partitions, batch_size=bs, solver="fast")
    sched.sync()
    CH = 10_000
    pending = [MakePod(f"{prefix}-{i}").req(
        {"cpu": "500m", "memory": "1Gi"}).obj() for i in range(n_pods)]
    for lo in range(0, n_pods, CH):
        store.create_many("pods", pending[lo:lo + CH], consume=True)
    gc.collect()
    gc.freeze()
    gc.disable()
    if sampler is not None:
        sched.attach_resource_sampler(sampler)
        sampler.start()
    try:
        t0 = time.perf_counter()
        sched.run_until_idle()
        dt = time.perf_counter() - t0
    finally:
        if sampler is not None:
            sampler.stop()
        gc.enable()
        gc.unfreeze()
    sched.flush_binds()
    return sched, store, dt, sched.scheduled_count


def rung_partitioned(results):
    """Partitioned_2x (ISSUE 12): the SAME constraint-free bind workload
    through ONE pipeline and through TWO partitioned pipelines on the same
    box — disjoint node shards, hash-routed pods, each partition's
    tensorize/assume/bind overlapping the other's GIL-free XLA solve. The
    quick-tier sibling of the NorthStar A/B column; publishes speedup,
    absorbed conflicts/reroutes, per-partition rows, and the conservation
    verdict (tests/test_bench_quick.py asserts correctness columns; the
    speedup itself is recorded, not tier-1-gated — a co-scheduled 2-core CI
    box is not the bench rig)."""
    from kubernetes_tpu.testing import pod_conservation_report

    try:
        n_pods = sz(20_000, floor=2000)
        n_nodes = sz(1000, floor=64)
        # warm-up BOTH configurations on throwaway clusters: the partitioned
        # run solves shard-sized batches on shard-sized node sets — fresh
        # jit shapes that must compile before the timed windows
        for parts in (1, 2):
            _w = _partitioned_e2e(n_pods, n_nodes, parts, f"pw{parts}")[0]
            _w.stop()
            del _w
        compiles0 = _solver_jit_cache()
        # >=2-core re-judge (ISSUE 19 satellite): a per-thread CPU sampler
        # rides every 2p timed window so the speedup column is judged from
        # measured overlap_cpu_s, never inferred from wall ratios
        from kubernetes_tpu.obs import ResourceSampler
        judge = len(os.sched_getaffinity(0)) >= 2
        # interleaved best-of-2 per mode (the BindCommit discipline): the
        # co-scheduled rig drifts, alternating keeps the drift off one column
        runs_1p = []  # (wall, bound) pairs — picked together, never mixed
        walls_2p = []
        best2 = None
        for i in range(2):
            _s1, _st1, d1, b1i = _partitioned_e2e(
                n_pods, n_nodes, 1, f"pa{i}")
            _s1.stop()
            del _s1, _st1
            runs_1p.append((d1, b1i))
            samp = ResourceSampler(interval_s=0.05) if judge else None
            c2, stc2, d2, b2 = _partitioned_e2e(
                n_pods, n_nodes, 2, f"pb{i}", sampler=samp)
            walls_2p.append(d2)
            osum = samp.summary() if samp is not None else None
            if best2 is None or d2 < best2[2]:
                if best2 is not None:
                    best2[0].stop()
                best2 = (c2, stc2, d2, b2, f"pb{i}", osum)
            else:
                c2.stop()
                del c2, stc2
        s2, st2, _d2, b2, pfx2, osum2 = best2
        dt1, b1 = min(runs_1p)
        walls_1p = [w for w, _b in runs_1p]
        dt2 = min(walls_2p)
        compiles = sum(v - compiles0.get(k, 0)
                       for k, v in _solver_jit_cache().items() if v >= 0)
        pps1, pps2 = b1 / dt1, b2 / dt2
        rep = pod_conservation_report(
            st2, s2, [f"default/{pfx2}-{i}" for i in range(n_pods)])
        rows = s2.sched_stats()["rows"]
        cores = len(os.sched_getaffinity(0))
        results["Partitioned_2x"] = {
            "pods_per_sec": round(pps2, 1), "wall_s": round(dt2, 3),
            "pods": n_pods, "nodes": n_nodes, "placed": b2,
            "pods_per_sec_1p": round(pps1, 1), "wall_s_1p": round(dt1, 3),
            "speedup_vs_1p": round(pps2 / pps1, 3),
            "walls_1p": [round(w, 3) for w in walls_1p],
            "walls_2p": [round(w, 3) for w in walls_2p],
            # the A/B is a CONCURRENCY claim: on a 1-core box the pipelines
            # time-slice and the speedup column measures overhead+noise,
            # not overlap — publish the cores so the number is interpretable
            # (ROADMAP direction 3 judges scaling on a >=2-core rig)
            "cores": cores,
            "ab_comparable": cores >= 2,
            # measured concurrency (ISSUE 19 satellite): cpu beyond wall
            # inside the winning 2p window; None = 1-core rig, not judged
            "overlap_cpu_s": (osum2["overlap_cpu_s"] if osum2 else None),
            "concurrency_verdict": _overlap_verdict(
                osum2["overlap_cpu_s"] if osum2 else None, dt2),
            "concurrent_drive": s2.concurrent_drive,
            "conflicts": s2.conflicts_total,
            "reroutes": s2.reroutes_total,
            "residual_passes": s2.residual_passes,
            "conservation": rep["counts"],
            "conservation_ok": (rep["counts"]["lost"] == 0
                                and rep["counts"]["double_bound"] == 0
                                and rep["counts"]["bound"] == n_pods),
            "solver_compiles_during_run": compiles,
            "per_partition": [{"index": r["index"], "nodes": r["nodes"],
                               "scheduled": r["scheduled"],
                               "conflicts": r["conflicts"],
                               "reroutes": r["reroutes"],
                               "breaker": r["breaker"]} for r in rows],
            "solver": "fast+partitioned"}
        s2.stop()  # release bind workers before later rungs
        print(f"{'Partitioned_2x':>28}: {pps2:>9.0f} pods/s  "
              f"({b2}/{n_pods} bound; 1p {pps1:.0f} pods/s, "
              f"speedup {pps2 / pps1:.2f}x, "
              f"conflicts={s2.conflicts_total} "
              f"reroutes={s2.reroutes_total})", file=sys.stderr)
    except Exception as e:
        results["Partitioned_2x"] = {"error": str(e)[:200]}
        print(f"Partitioned_2x: ERROR {e}", file=sys.stderr)


def _solver_jit_cache():
    """Per-solver compiled-variant counts (jax's per-function jit cache).
    Stable counts across same-bucket batches = the cache is hot; a growing
    count is retrace churn (tens of seconds per compile at TPU scale).
    -1 when the introspection API is unavailable."""
    from kubernetes_tpu.models.defrag import defrag_assign
    from kubernetes_tpu.models.gangcover import cover_curve, rank_align_kernel
    from kubernetes_tpu.models.repair import repair_check
    from kubernetes_tpu.models.transport import _auction_phase, _sinkhorn_iters
    from kubernetes_tpu.models.waterfill import waterfill_group
    from kubernetes_tpu.ops.solver import greedy_scan_solve

    out = {}
    for name, fn in (("waterfill_group", waterfill_group),
                     ("greedy_scan_solve", greedy_scan_solve),
                     ("repair_check", repair_check),
                     ("auction_phase", _auction_phase),
                     ("sinkhorn_iters", _sinkhorn_iters),
                     ("cover_curve", cover_curve),
                     ("rank_align_kernel", rank_align_kernel),
                     ("defrag_assign", defrag_assign)):
        try:
            out[name] = int(fn._cache_size())
        except Exception:
            out[name] = -1
    return out


def _rig_info():
    """Honesty columns every rung carries (ISSUE 13 satellite): this series
    has crossed containers with 2 -> 1 cores (BENCH_r07..r11) and cross-run
    comparisons kept tripping on it — the rig's core count and cgroup cpu
    quota are now part of every workload's JSON, not just the A/B columns."""
    try:
        cores = len(os.sched_getaffinity(0))
    except Exception:
        cores = os.cpu_count() or 0
    quota = None
    try:  # cgroup v2
        raw = open("/sys/fs/cgroup/cpu.max").read().split()
        if raw and raw[0] != "max":
            quota = round(int(raw[0]) / int(raw[1]), 2)
    except Exception:
        try:  # cgroup v1
            q = int(open("/sys/fs/cgroup/cpu/cpu.cfs_quota_us").read())
            p = int(open("/sys/fs/cgroup/cpu/cpu.cfs_period_us").read())
            if q > 0:
                quota = round(q / p, 2)
        except Exception:
            pass
    return {"cores": cores, "cpu_quota": quota}


def _overlap_verdict(overlap_cpu_s, wall_s):
    """The >=2-core A/B judge (ISSUE 19 satellite): a speedup column is
    believable only when MEASURED cpu-beyond-wall says the pipelines truly
    ran in parallel — wall-clock ratios on a co-scheduled rig can say
    anything. None = not judged (no sampler / 1-core rig)."""
    if overlap_cpu_s is None or wall_s <= 0:
        return None
    return "parallel" if overlap_cpu_s >= 0.05 * wall_s else "serialized"


def rung_north_star_soak(results):
    """NorthStar_1M (ISSUE 13): the soak rung — the control plane the paper
    describes runs FOREVER, so this rung measures steady state, not a
    single drain: a fixed pod population under sustained create/bind/delete
    churn, with the windowed time-series (obs/timeseries.py) and resource
    sampler (obs/resource.py) watching every window and the trend/leak SLO
    keys (scheduler/slo.py SOAK_SLO) gating the run's SHAPE — per-window
    stage p99 ceilings, RSS + live-object slope, p99 drift — plus zero
    post-warmup solver recompiles. Warmup (initial fill + churn cycles at
    the real shapes) is excluded via the clear()/reset() idiom. The quick
    variant is time-compressed (small windows, seconds of churn); the full
    variant churns ~1M pods through the same steady-state loop."""
    import gc

    from kubernetes_tpu.obs.resource import ResourceSampler
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.scheduler.slo import SOAK_SLO, evaluate_slo
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    try:
        if SMOKE:
            # time-compressed: small windows, the clock (not a pod count)
            # ends the run — the gate needs enough windows for real trend
            # verdicts, not a churn total
            n_nodes, steady, wave = 256, 2000, 500
            window_s, sample_s, soak_s = 0.5, 0.1, 10.0
            target_churn = None
        else:
            n_nodes, steady, wave = 10_000, 100_000, 25_000
            window_s, sample_s, soak_s = 5.0, 1.0, 300.0
            target_churn = 1_000_000
        soak_s = min(soak_s, max(6.0, budget_left() - 45.0))
        min_windows = 8  # trends over fewer windows are opinions

        # steady-state history bound: the watch-replay log pins one object
        # clone per retained event, so at churn rate it IS the store's
        # resident memory — size it to a few waves of events (~3 events per
        # pod life: create/bind/delete) so memory plateaus during warmup
        # and the rss/alloc slope gates measure the SCHEDULER's behavior,
        # not the log filling up. The soak rung found this: with the
        # 200k-event default the quick run grew ~40MB/s of nothing but
        # history.
        store = APIStore(history_limit=9 * wave if SMOKE else 200_000)
        for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
            store.create("nodes", n)
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=max(steady, wave), solver="fast",
                               ts_window_s=window_s)
        sampler = ResourceSampler(interval_s=sample_s)
        sched.attach_resource_sampler(sampler)
        sampler.register_thread("sched")  # this (driving) thread
        sampler.start()
        sched.sync()

        seq = 0
        live: list = []  # pod names in creation order (delete oldest first)

        def create_wave(n):
            nonlocal seq
            names = [f"soak-{seq + i}" for i in range(n)]
            seq += n
            store.create_many(
                "pods", (MakePod(nm).req({"cpu": "500m", "memory": "1Gi"})
                         .obj() for nm in names), consume=True)
            live.extend(names)

        def drain_wave(n):
            victims = live[:n]
            del live[:n]
            # chunked like the bind path: delete_pods holds one critical
            # section per call, and a 25k-victim wave must not starve every
            # other store consumer behind one lock hold
            for lo in range(0, len(victims), 4096):
                store.delete_pods([f"default/{nm}"
                                   for nm in victims[lo:lo + 4096]])
            return len(victims)

        # -- warmup: initial fill + churn cycles at the REAL shapes (both
        # pod-axis buckets: the steady fill and the wave) so the measured
        # soak compiles nothing
        create_wave(steady)
        sched.run_until_idle()
        # churn until the ALLOCATOR plateaus, not a fixed cycle count: the
        # first churn cycles keep growing RSS (fresh obmalloc arenas for the
        # transient wave peaks) and a measured window that inherits that
        # warm-up growth fails the slope gate on allocator behavior instead
        # of a leak. Two consecutive stable reads = steady state.
        stable, prev_rss = 0, sampler.rss_mb()
        for _ in range(32):
            drain_wave(wave)
            create_wave(wave)
            sched.run_until_idle()
            cur = sampler.rss_mb()
            stable = stable + 1 if cur - prev_rss < 0.75 else 0
            prev_rss = cur
            if stable >= 2:
                break
        sched.flush_binds()

        # -- measured soak starts here (warmup excluded, the clear idiom).
        # GC stays ENABLED (a forever-running service collects its churn
        # garbage and the sampler measures the pauses) but the steady-state
        # heap is FROZEN: without freeze(), every gen2 pass re-scans the
        # ~stable store/cache graph and lands a 100-300ms pause in whatever
        # stage is running — honest for an untuned process, but the
        # documented long-lived-heap configuration (the NorthStar rung's
        # freeze+disable, minus the disable) is what a production soak runs.
        gc.collect()
        gc.freeze()
        # every post-freeze step runs under the unfreeze finally: an
        # exception escaping with the process frozen would corrupt every
        # later rung's memory/GC behavior
        try:
            # the collect above RETURNS arenas to the OS — a window opened
            # at that trough measures the first seconds re-acquiring the
            # working high-water as "growth" (~100MB/2s observed).
            # Re-churn until RSS is stable again so the measured series
            # starts AT steady state.
            stable, prev_rss = 0, sampler.rss_mb()
            for _ in range(24):
                drain_wave(wave)
                create_wave(wave)
                sched.run_until_idle()
                cur = sampler.rss_mb()
                stable = stable + 1 if abs(cur - prev_rss) < 0.75 else 0
                prev_rss = cur
                if stable >= 2:
                    break
            sched.flightrec.clear()
            sched.podtrace.clear()
            sched.timeseries.clear()
            sampler.reset()
            compiles0 = _solver_jit_cache()
            churned = 0
            t0 = time.perf_counter()
            deadline = t0 + soak_s
            while time.perf_counter() < deadline:
                if (target_churn is not None and churned >= target_churn
                        and sched.timeseries.windows_closed >= min_windows):
                    break  # full-size: 1M churned and a real trend axis
                drain_wave(wave)
                create_wave(wave)
                sched.run_until_idle()
                churned += wave
        finally:
            gc.unfreeze()
        dt = time.perf_counter() - t0
        sampler.stop()
        windows = sched.timeseries.windows()
        compiles = sum(v - compiles0.get(k, 0)
                       for k, v in _solver_jit_cache().items() if v >= 0)

        spec = dict(SOAK_SLO)
        if SMOKE:
            # time compression divides the same absolute allocator noise by
            # a baseline ~30x shorter: one ~25MB obmalloc arena step
            # anywhere in a 10s axis reads ~150MB/min, and such steps DO
            # happen at steady state (measured run to run). Size the quick
            # ceiling above the step noise — a real pin (one leaked
            # scheduler graph per window) reads thousands of MB/min, still
            # an order of magnitude past this — and let the alloc-blocks
            # gate keep the deterministic live-object precision
            spec["rss_slope_mb_per_min"] = 300.0
            spec["alloc_block_slope_per_s"] = 500_000.0
        # the NEW layers' measured overhead gates the <2% budget (ISSUE 13
        # acceptance): timeseries taps + sampler ticks — deterministic
        # costs. The flight recorder's own self-time is published beside it
        # but gated by the NorthStar rung, where production batch sizes
        # amortize it: at smoke's 500-pod batches its tiny wall-clock
        # windows mostly measure 1-core co-scheduling preemption noise.
        instr_s = sched.timeseries.self_seconds + sampler.self_seconds
        instr_frac = (instr_s / max(dt, 1e-9)) if instr_s > 0.002 else 0.0
        spec["instrumentation_frac"] = 0.02
        slo = evaluate_slo(
            {"windows": windows}, spec,
            extra={"solver_compiles": compiles,
                   "instrumentation_frac": round(instr_frac, 5)})
        # the gate the tier asserts: windowed SLOs PASS with the trend
        # checks REAL (enough windows to fit a slope), zero recompiles
        trend_real = not any(c.startswith(("rss_slope", "alloc_block",
                                           "p99_drift"))
                             for c in slo["skipped"])
        res = sampler.summary()
        # the per-window zero-alloc gauge (ISSUE 16): under full churn the
        # DELETED-event contract materializes every drained victim (honest
        # column — the scheduling path itself allocates nothing), so the
        # soak publishes the distribution rather than gating on zero
        alloc_vals = [a for a in
                      ((w.get("alloc") or {}).get("pod_obj_allocs")
                       for w in windows) if a is not None]
        results["NorthStar_1M"] = {
            "pods_per_sec": round(churned / dt, 1), "wall_s": round(dt, 3),
            "pods": churned, "steady_pods": steady, "wave": wave,
            "nodes": n_nodes, "placed": churned,
            "windows": len(windows),
            "window_s": window_s,
            "windows_sample": windows[-3:],
            "pod_obj_allocs": {
                "windows_counted": len(alloc_vals),
                "zero_windows": sum(1 for a in alloc_vals if a == 0),
                "max_per_window": max(alloc_vals) if alloc_vals else None,
                "total": sum(alloc_vals) if alloc_vals else None,
            },
            "resource": res,
            "slo": slo, "soak_ok": bool(slo["pass"] and trend_real
                                        and compiles == 0),
            "solver_compiles_during_run": compiles,
            "instrumentation_s": round(instr_s, 6),
            "instrumentation_frac": round(instr_frac, 5),
            "flightrec_self_s": round(sched.flightrec.self_seconds, 6),
            "sampler_overhead_frac": res["overhead_frac"],
            "clock_source": res["clock_source"],
            "clock_resolution_s": res["clock_resolution_s"],
            "solver": "fast+store-binds+churn"}
        sched.stop()
        print(f"{'NorthStar_1M':>28}: {churned / dt:>9.0f} pods/s sustained "
              f"({churned} churned over {len(windows)} windows in {dt:.1f}s; "
              f"rss {res['rss_mb']}MB (+{res['rss_growth_mb']}), "
              f"SLO {'PASS' if slo['pass'] else 'FAIL ' + str(slo['failed'])}"
              f", compiles={compiles})", file=sys.stderr)
    except Exception as e:
        # a failed rung must not leave ITS threads churning (or its
        # sampler ticking) through every later rung's timed window
        for owner in (locals().get("sampler"), locals().get("sched")):
            try:
                if owner is not None:
                    owner.stop()
            except Exception:
                pass
        results["NorthStar_1M"] = {"error": str(e)[:200]}
        print(f"NorthStar_1M: ERROR {e}", file=sys.stderr)


def rung_schedlint(results):
    """SchedLint_tree: the static-analysis gate's whole-tree self-time. The
    analyzer runs inside tier-1 (tests/test_schedlint.py), so its wall time
    is a budget like the flight recorder's: tests/test_bench_quick.py
    asserts it stays cheap AND clean (0 findings) so the gate can't quietly
    become the slowest — or a red — part of tier-1."""
    from kubernetes_tpu.analysis.schedlint import package_root, run_paths

    try:
        t0 = time.perf_counter()
        findings, stats = run_paths([package_root()])
        dt = time.perf_counter() - t0
        results["SchedLint_tree"] = {
            "wall_s": round(dt, 3), "findings": len(findings),
            "suppressed": stats["suppressed"], "files": stats["files"],
            # interprocedural closure shape (ISSUE 20): edge count and the
            # deepest chain any rule actually walked, so a regression in
            # resolution (edges collapsing to ~0) or a blow-up (depth
            # hitting the cap) is visible in BENCH history
            "callgraph_edges": stats["callgraph_edges"],
            "resolve_depth": stats["resolve_depth"],
            # the published hard budget tests/test_bench_quick.py asserts
            "budget_s": 15.0}
        print(f"{'SchedLint_tree':>28}: {stats['files']} files, "
              f"{len(findings)} findings, {stats['suppressed']} suppressed, "
              f"{stats['callgraph_edges']} call edges (depth "
              f"{stats['resolve_depth']}) in {dt:.2f}s", file=sys.stderr)
    except Exception as e:
        results["SchedLint_tree"] = {"error": str(e)[:200]}
        print(f"SchedLint_tree: ERROR {e}", file=sys.stderr)


def rung_bind_commit(results):
    """BindCommit_20k: store.bind_many throughput in ISOLATION (the PR 4
    clone-free commit path) — 20k pending pods bound in bind-worker-sized
    chunks with only a coalescing watcher subscribed (the scheduler steady
    state: lazy shared events, no per-object clones, sharded lock), no
    scheduler and no flight recorder involved. Fixed-size like the gang
    rung: 20k binds run in a fraction of a second, so the rung doubles as
    the quick-tier smoke for the store commit hot path."""
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    try:
        import gc

        from kubernetes_tpu.native import hostcommit

        n, chunk = 20_000, 4096

        def run_once(native, columnar=False):
            # columnar=False pins the DICT commit path for the legacy
            # python-vs-native columns; the columnar legs (ISSUE 15) run
            # the same workload through the column-write commit
            store = APIStore(native_commit=native, columnar=columnar)
            w = store.watch(kind=("pods",), coalesce=True)
            store.create_many(
                "pods", (MakePod(f"bc-{i}").req({"cpu": "100m"}).obj()
                         for i in range(n)), consume=True)
            w.drain()
            triples = [("default", f"bc-{i}", f"node-{i % 512}")
                       for i in range(n)]
            # timed window with the collector frozen+disabled, like the
            # NorthStar rung: gen2 sweeps over the 20k-pod heap otherwise
            # dominate (and randomize) the ~µs/pod commit numbers the
            # python-vs-native columns exist to compare. try/finally: an
            # assert/bind failure must not leave GC off for every later rung
            gc.collect()
            gc.freeze()
            gc.disable()
            try:
                t0 = time.perf_counter()
                bound = 0
                for lo in range(0, n, chunk):
                    b, errs = store.bind_many(triples[lo:lo + chunk],
                                              origin="bench")
                    bound += b
                    assert not errs, errs[:3]
                dt = time.perf_counter() - t0
            finally:
                gc.enable()
                gc.unfreeze()
            return bound, dt

        # python-vs-native columns (ISSUE 11): the SAME workload through the
        # Python oracle and the C-API commit engine — the before/after pair
        # for the native commit-loop port, asserted by test_bench_quick.py.
        # Interleaved best-of-2 per mode (P,N,P,N): harness co-scheduling
        # drifts on a 2-core rig, and alternating the modes keeps the drift
        # from landing entirely on one column.
        from kubernetes_tpu.store import columnar as _columnar_mod

        native_ok = hostcommit.available()
        columnar_ok = (_columnar_mod.numpy_available()
                       and _columnar_mod.env_enabled())
        bound, _warm = run_once(native_ok)  # warm-up (faults obmalloc arenas)
        # interleaved best-of-2 per mode (the BindCommit discipline), the
        # columnar A/B leg riding the same rounds: dict-python, dict-native,
        # columnar — the µs/pod dict-vs-columnar pair is a SAME-BOX
        # interleaved A/B by construction (BENCH_r12 discipline: rig core
        # counts vary across the series, so only same-box pairs compare)
        py_runs, nat_runs, col_runs = [], [], []
        for _ in range(2):
            py_runs.append(run_once(False)[1])
            if native_ok:
                nat_runs.append(run_once(True)[1])
            if columnar_ok:
                col_runs.append(run_once(native_ok, columnar=True)[1])
        dt_py = min(py_runs)
        dt = min(nat_runs) if native_ok else dt_py
        dt_col = min(col_runs) if columnar_ok else None
        us_dict = dt / n * 1e6
        pps = n / (dt_col if dt_col is not None else dt)
        results["BindCommit_20k"] = {
            "pods_per_sec": round(pps, 1),
            "wall_s": round(dt_col if dt_col is not None else dt, 4),
            "placed": bound, "pods": n,
            "us_per_pod": round((dt_col if dt_col is not None else dt)
                                / n * 1e6, 2),
            "native": {
                "available": native_ok,
                "us_per_pod_python": round(dt_py / n * 1e6, 2),
                "us_per_pod_native": (round(dt / n * 1e6, 2)
                                      if native_ok else None),
            },
            # columnar pod-row store (ISSUE 15): dict vs columnar on the
            # SAME box, interleaved; honesty flags per the r12 discipline
            "columnar": dict({
                "available": columnar_ok,
                "us_per_pod_dict": round(us_dict, 2),
                "us_per_pod_columnar": (round(dt_col / n * 1e6, 2)
                                        if dt_col is not None else None),
                "speedup": (round(dt / dt_col, 2)
                            if dt_col is not None else None),
                "ab_comparable": True,  # interleaved same-box by design
            }, **_rig_info()),
            "solver": ("bind_many-columnar" if columnar_ok
                       else "bind_many-native" if native_ok
                       else "bind_many-python")}
        print(f"{'BindCommit_20k':>28}: {pps:>9.0f} pods/s  "
              f"({bound}/{n} bound, python {dt_py / n * 1e6:.1f}us/pod"
              + (f", native {us_dict:.1f}us/pod" if native_ok
                 else ", native unavailable")
              + (f", columnar {dt_col / n * 1e6:.2f}us/pod"
                 if dt_col is not None else ", columnar unavailable")
              + ")", file=sys.stderr)
    except Exception as e:
        results["BindCommit_20k"] = {"error": str(e)[:200]}
        print(f"BindCommit_20k: ERROR {e}", file=sys.stderr)


def rung_sched_stages(results):
    """SchedStages_8k (ISSUE 16): per-stage same-box A/B columns for the
    four steady-state stages the end-to-end columnar pipeline rewrote, each
    measured columnar-vs-object under the BindCommit discipline (interleaved
    best-of-2, GC frozen, rig honesty flags):

      build_pod_batch  store sig-column memo re-seed vs object signature walk
      assume           column insert (assume_pods_columnar) vs per-pod
                       structural PodInfo appends (both phase-1-only; phase 2
                       is the shared scatter either way)
      tensorize        dirty-name diff (changed_names) vs identity walk over
                       every node, at the steady-state delta shape (a few
                       dirty nodes out of the fleet)
      dispatch         clone-free handoff vs pod_bind_clone per pod
    """
    import gc

    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.cache import Cache
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.snapshot.tensorizer import (TensorCache,
                                                    build_cluster_tensors,
                                                    build_pod_batch)
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.store.store import pod_bind_clone
    from kubernetes_tpu.testing import MakePod

    try:
        n_pods, n_nodes = sz(8000, floor=128), sz(256, floor=16)
        store = APIStore()
        nodes = _nodes(n_nodes, cpu="64", mem="256Gi")
        for nd in nodes:
            store.create("nodes", nd)
        node_names = [nd.metadata.name for nd in nodes]
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=n_pods, solver="exact",
                               columnar=True)
        sched.sync()
        store.create_many(
            "pods", (MakePod(f"ss-{i}").req({"cpu": "100m", "memory": "64Mi"})
                     .obj() for i in range(n_pods)), consume=True)
        sched.pump_events()
        snap = sched.cache.update_snapshot()
        cluster = build_cluster_tensors(snap)
        pods = sorted(store.list("pods")[0], key=lambda p: p.key)
        getcols = getattr(store, "pod_columns", None)
        store_cols = getcols() if getcols else None

        def strip_memos():
            for p in pods:
                p.__dict__.pop("_class_sig", None)
                p.__dict__.pop("_req_sig", None)

        def t_build(cols):
            strip_memos()
            t0 = time.perf_counter()
            build_pod_batch(pods, snap, cluster, store_cols=cols)
            return time.perf_counter() - t0

        assume_pairs = [(p, node_names[i % n_nodes])
                        for i, p in enumerate(pods)]

        def t_assume(columnar):
            cache = Cache()
            for nd in nodes:
                cache.add_node(nd)
            t0 = time.perf_counter()
            if columnar:
                bad = cache.assume_pods_columnar(assume_pairs)
            else:
                bad = cache.assume_pods_structural(assume_pairs)
            dt = time.perf_counter() - t0
            assert not bad, bad[:3]
            return dt

        # steady-state delta shape: a handful of dirty nodes out of the fleet
        k_dirty = max(1, n_nodes // 32)
        extra = [MakePod(f"ssx-{i}").req({"cpu": "50m"}).obj()
                 for i in range(k_dirty)]
        sched.cache.assume_pods(
            [(p, node_names[i]) for i, p in enumerate(extra)])
        snap2 = sched.cache.update_snapshot()

        def t_tensorize(incremental):
            tc = TensorCache()
            tc.cluster_tensors(snap)  # re-base off the pre-delta snapshot
            saved = snap2.changed_names
            if not incremental:
                snap2.changed_names = None  # force the identity-walk oracle
            try:
                t0 = time.perf_counter()
                tc.cluster_tensors(snap2)
                return time.perf_counter() - t0
            finally:
                snap2.changed_names = saved

        def t_dispatch(clone):
            t0 = time.perf_counter()
            if clone:
                out = [pod_bind_clone(p) for p in pods]
            else:
                out = list(pods)
            dt = time.perf_counter() - t0
            assert len(out) == n_pods
            return dt

        stages = {"build_pod_batch": (t_build, store_cols, None),
                  "assume": (t_assume, True, False),
                  "tensorize": (t_tensorize, True, False),
                  "dispatch": (t_dispatch, False, True)}
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            cols_out = {}
            for name, (fn, col_arg, obj_arg) in stages.items():
                fn(col_arg)  # warm-up
                col_runs, obj_runs = [], []
                for _ in range(2):  # interleaved best-of-2 per mode
                    col_runs.append(fn(col_arg))
                    obj_runs.append(fn(obj_arg))
                dt_c, dt_o = min(col_runs), min(obj_runs)
                per = n_pods if name != "tensorize" else 1
                unit = "us_per_pod" if name != "tensorize" else "us_per_diff"
                cols_out[name] = {
                    f"{unit}_columnar": round(dt_c / per * 1e6, 3),
                    f"{unit}_object": round(dt_o / per * 1e6, 3),
                    "speedup": round(dt_o / dt_c, 2) if dt_c > 0 else None,
                }
        finally:
            gc.enable()
            gc.unfreeze()
        results["SchedStages_8k"] = dict({
            "pods": n_pods, "nodes": n_nodes, "dirty_nodes": k_dirty,
            "store_cols": store_cols is not None,
            "stages": cols_out,
            "ab_comparable": True,  # interleaved same-box by design
        }, **_rig_info())
        print(f"{'SchedStages_8k':>28}: "
              + "  ".join(f"{k} x{v['speedup']}"
                          for k, v in cols_out.items()), file=sys.stderr)
    except Exception as e:
        results["SchedStages_8k"] = {"error": str(e)[:200]}
        print(f"SchedStages_8k: ERROR {e}", file=sys.stderr)


def _gang_adjacency(store, sched):
    """Placement-quality column (ISSUE 14): mean intra-gang neighbor ring
    distance of the BOUND members, measured from the STORE (labels + node
    topology), independent of the scheduler's own stats."""
    from kubernetes_tpu.api.podgroup import pod_gang_rank, pod_group_key
    from kubernetes_tpu.models.gangcover import mean_neighbor_distance
    from kubernetes_tpu.scheduler.gang import node_slice_positions, \
        ring_lengths
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors

    cl = build_cluster_tensors(sched.cache.update_snapshot())
    slice_ids, pos = node_slice_positions(cl)
    if slice_ids is None:
        return None
    node_idx = {n: i for i, n in enumerate(cl.node_names)}
    gids, groups, ranks, slices, poss = {}, [], [], [], []
    for p in store.list("pods")[0]:
        g = pod_group_key(p)
        if not g or not p.spec.node_name:
            continue
        ni = node_idx[p.spec.node_name]
        gids.setdefault(g, len(gids))
        groups.append(gids[g])
        ranks.append(pod_gang_rank(p))
        slices.append(int(slice_ids[ni]))
        poss.append(int(pos[ni]))
    return mean_neighbor_distance(groups, ranks, slices, poss,
                                  ring_lengths(slice_ids, pos))


def rung_gang(results):
    """GangScheduling_2k_250: 8 PodGroups x 250 RANKED members bound
    end-to-end — store ingest, queue gang staging, the all-or-nothing veto,
    slice-packing score, rank alignment, and batched binds all inside the
    timed window. Publishes the adjacency placement-quality column (ISSUE
    14): mean intra-gang neighbor ring distance, rank-aligned vs the
    rank-blind baseline (same workload, rank_align=False). Fixed-size (no
    SMOKE shrink): the rung IS the quick-tier gang smoke and 2k pods solves
    in a few seconds on the CPU rig."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakeNode, MakePod, make_pod_group

    try:
        n_gangs, members, n_nodes, n_slices = 8, 250, 256, 4

        def gang_nodes():
            return [MakeNode(f"node-{i}")
                    .tpu_slice(i % n_slices, index=i // n_slices)
                    .capacity({"cpu": "16", "memory": "64Gi",
                               "pods": "110"}).obj() for i in range(n_nodes)]

        def gang_pods():
            return [MakePod(f"gp-{g}-{i}").gang(f"train-{g}", rank=i)
                    .req({"cpu": "500m", "memory": "1Gi"}).obj()
                    for g in range(n_gangs) for i in range(members)]

        def run_once(rank_align=True):
            store = APIStore()
            for n in gang_nodes():
                store.create("nodes", n)
            sched = BatchScheduler(store, Framework(default_plugins()),
                                   batch_size=4096, solver="fast",
                                   rank_align=rank_align)
            sched.sync()
            for g in range(n_gangs):
                store.create("podgroups", make_pod_group(f"train-{g}", members))
            store.create_many("pods", gang_pods(), consume=True)
            t0 = time.perf_counter()
            sched.run_until_idle()
            dt = time.perf_counter() - t0
            return sched, store, dt

        wsched, _wstore, _wdt = run_once()  # warm-up: compile at real shapes
        wsched.stop()  # release the bind worker (PR 11 discard hygiene)
        sched, store, dt = run_once()
        adjacency = _gang_adjacency(store, sched)
        # rank-blind baseline: the SAME workload with the alignment pass off
        # — what greedy water-filling alone gives consecutive ranks
        bsched, bstore, _bdt = run_once(rank_align=False)
        adjacency_blind = _gang_adjacency(bstore, bsched)
        bsched.stop()
        sched.stop()
        n_pods = n_gangs * members
        bound = sched.scheduled_count
        pps = bound / dt if dt > 0 else 0.0
        results["GangScheduling_2k_250"] = {
            "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
            "placed": bound, "pods": n_pods, "gangs": n_gangs,
            "gang_vetoes": sched.gang_vetoes,
            "adjacency": {
                "mean_neighbor_distance": (round(adjacency, 3)
                                           if adjacency is not None
                                           else None),
                "mean_neighbor_distance_rank_blind": (
                    round(adjacency_blind, 3)
                    if adjacency_blind is not None else None),
                "placed_rank_blind": bsched.scheduled_count,
            },
            "solver": "fast+gang+rank-align+store-binds"}
        print(f"{'GangScheduling_2k_250':>28}: {pps:>9.0f} pods/s  "
              f"({bound}/{n_pods} bound in {n_gangs} gangs, "
              f"{sched.gang_vetoes} vetoes, adjacency "
              f"{adjacency if adjacency is None else round(adjacency, 3)} vs "
              f"rank-blind "
              f"{adjacency_blind if adjacency_blind is None else round(adjacency_blind, 3)}, "
              f"{dt:.3f}s)", file=sys.stderr)
    except Exception as e:
        results["GangScheduling_2k_250"] = {"error": str(e)[:200]}
        print(f"GangScheduling_2k_250: ERROR {e}", file=sys.stderr)


def rung_gang_preempt(results):
    """GangPreemption (ISSUE 14): the victim-cover rung, quick tier. A
    2-slice cluster full of low-priority fillers takes a high-priority gang
    that cannot fit anywhere: the preemptor must select the MIN-COST victim
    set whose release fits the entire quorum on one slice (6 of 8 fillers,
    not all 8), delete it through the batched store path, park the gang,
    and place it WHOLE on release — inside a bounded wall with zero mid-run
    solver compiles. A second, larger gang has only PARTIAL room on every
    slice: it must be vetoed with a narrated event and ZERO further
    evictions. Pod conservation asserted over both gangs."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import (MakeNode, MakePod, make_pod_group,
                                        pod_conservation_report)

    try:
        n_slices, per_slice = 2, 8
        gang_n, big_n = 12, 40  # 12 fits one slice after 6 evictions; 40 never

        def build():
            store = APIStore()
            for s in range(n_slices):
                for i in range(per_slice):
                    store.create("nodes", MakeNode(f"node-{s}-{i}")
                                 .tpu_slice(s, index=i)
                                 .capacity({"cpu": "8", "memory": "32Gi",
                                            "pods": "110"}).obj())
            for s in range(n_slices):
                for i in range(per_slice):
                    low = MakePod(f"low-{s}-{i}").priority(1).req(
                        {"cpu": "6"}).obj()
                    low.spec.node_name = f"node-{s}-{i}"
                    store.create("pods", low)
            sched = BatchScheduler(store, Framework(default_plugins()),
                                   batch_size=1024, solver="fast",
                                   pod_initial_backoff=0.05,
                                   pod_max_backoff=0.2)
            sched.sync()
            return store, sched

        def gang_pods(name, n):
            return [MakePod(f"{name}-{i}").gang(name, rank=i).priority(100)
                    .req({"cpu": "3"}).obj() for i in range(n)]

        def drive(store, sched, prefix, want, deadline_s):
            bound = 0
            deadline = time.perf_counter() + deadline_s
            while time.perf_counter() < deadline:
                sched.run_until_idle()
                sched.queue.flush_backoff_completed()
                sched.pump_events()
                bound = sum(1 for p in store.list("pods")[0]
                            if p.metadata.name.startswith(f"{prefix}-")
                            and p.spec.node_name)
                if bound >= want:
                    return bound
                time.sleep(0.02)
            return bound

        def run_once():
            store, sched = build()
            store.create("podgroups", make_pod_group("gp", gang_n))
            pods = gang_pods("gp", gang_n)
            store.create_many("pods", pods, consume=True)
            t0 = time.perf_counter()
            bound = drive(store, sched, "gp", gang_n,
                          20.0 if SMOKE else 60.0)
            dt = time.perf_counter() - t0
            return store, sched, pods, bound, dt

        # warm-up: compile the cover/alignment kernels at the run's shapes
        _wst, wsched, _wp, _wb, _wdt = run_once()
        wsched.stop()
        compiles0 = _solver_jit_cache()
        store, sched, pods, bound, dt = run_once()
        # watermark read HERE: the veto leg below runs new shapes (a
        # 40-member alignment axis) by design — the zero-compile claim is
        # about the preemption run the warm-up covered
        compiles = sum(v - compiles0.get(k, 0)
                       for k, v in _solver_jit_cache().items() if v >= 0)
        stats = sched.gangpreempt.stats()
        fillers_left = sorted(p.metadata.name for p in store.list("pods")[0]
                              if p.metadata.name.startswith("low-"))
        slices_used = {n.spec.node_name.split("-")[1]
                       for n in store.list("pods")[0]
                       if n.metadata.name.startswith("gp-")
                       and n.spec.node_name}
        adjacency = _gang_adjacency(store, sched)
        rep = pod_conservation_report(store, sched, [p.key for p in pods])

        # --- partial-room leg: a gang NO slice can host even after evicting
        # every remaining filler — vetoed, narrated, zero evictions
        pods_before = len(store.list("pods")[0])
        store.create("podgroups", make_pod_group("big", big_n))
        big = gang_pods("big", big_n)
        store.create_many("pods", big, consume=True)
        sched.run_until_idle()
        sched.pump_events()
        veto_stats = sched.gangpreempt.stats()
        big_bound = sum(1 for p in store.list("pods")[0]
                        if p.metadata.name.startswith("big-")
                        and p.spec.node_name)
        evictions_after_veto = (pods_before + big_n
                                - len(store.list("pods")[0]))
        veto_events = sum(1 for e in store.list("events")[0]
                          if (e.reason or "") == "GangPreemptionVetoed")
        rep_big = pod_conservation_report(
            store, sched, [p.key for p in pods + big])
        sched.stop()
        c = rep["counts"]
        ok = (bound == gang_n and len(slices_used) == 1
              and stats["preempted"] == 1 and stats["victims"] == 6
              and len(fillers_left) == per_slice * n_slices - 6
              and c["lost"] == 0 and c["double_bound"] == 0
              and big_bound == 0 and evictions_after_veto == 0
              and veto_stats["vetoed_partial"] >= 1 and veto_events >= 1
              and rep_big["counts"]["lost"] == 0
              and rep_big["counts"]["double_bound"] == 0)
        results["GangPreemption"] = {
            "wall_s": round(dt, 3), "placed": bound, "pods": gang_n,
            "victims": stats["victims"],
            "cover_cost": stats["cover_cost"],
            "slices_ripped": stats["slices_ripped"],
            "vetoed_partial": veto_stats["vetoed_partial"],
            "veto_evictions": evictions_after_veto,
            "veto_narrated": veto_events,
            "adjacency_mean_neighbor_distance": (
                round(adjacency, 3) if adjacency is not None else None),
            "conservation": c, "conservation_ok": ok,
            "solver_compiles_during_run": compiles,
            "preempt_ok": ok,
            "solver": "fast+gang-preempt+victim-cover"}
        print(f"{'GangPreemption':>28}: {bound}/{gang_n} placed whole via "
              f"{stats['victims']}-victim cover in {dt:.3f}s "
              f"(cost {stats['cover_cost']}, compiles={compiles}; "
              f"partial-room gang vetoed: {veto_stats['vetoed_partial']} "
              f"veto(s), {evictions_after_veto} evictions)", file=sys.stderr)
    except Exception as e:
        results["GangPreemption"] = {"error": str(e)[:200]}
        print(f"GangPreemption: ERROR {e}", file=sys.stderr)


def rung_defrag(results):
    """Defrag (ISSUE 17): the rebalancer A/B, quick tier. Churn smears one
    low-priority filler onto every node of a 2-slice cluster — each node is
    half-full, no node can host a gang member, and an arriving gang's ONLY
    path is destroying work through preemption. Same box, same scheduler
    config, two legs: OFF (no rebalancer — the gang admits via the victim
    cover, evicting fillers) vs ON (the background rebalancer consolidates
    the fillers into one slice between workloads, inside the hard per-cycle
    migration budget, so the SAME gang admits with ZERO preemptions). Gates:
    preemption rate AND admission latency improve ON vs OFF, the migration
    budget is never exceeded (checked per cycle, not just in aggregate),
    pod conservation holds through the migration chain, the windowed SLO
    verdict passes on both legs, and the timed window compiles nothing
    (the warm-up leg covers the defrag kernel's pow2 buckets)."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.scheduler.slo import evaluate_slo
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import (MakeNode, MakePod, make_pod_group,
                                        pod_conservation_report)

    DEFRAG_SLO = {"submit_to_bound_p99_s": 30.0}

    try:
        n_slices, per_slice, gang_n = 2, 4, 4
        budget_wave, budget_cycle = 2, 8

        def build():
            store = APIStore()
            for s in range(n_slices):
                for i in range(per_slice):
                    store.create("nodes", MakeNode(f"node-{s}-{i}")
                                 .tpu_slice(s, index=i)
                                 .capacity({"cpu": "8", "memory": "32Gi",
                                            "pods": "110"}).obj())
            fillers = []
            for s in range(n_slices):
                for i in range(per_slice):
                    low = MakePod(f"low-{s}-{i}").priority(1).req(
                        {"cpu": "3"}).obj()
                    low.spec.node_name = f"node-{s}-{i}"
                    store.create("pods", low)
                    fillers.append(low)
            sched = BatchScheduler(store, Framework(default_plugins()),
                                   batch_size=1024, solver="fast",
                                   pod_initial_backoff=0.05,
                                   pod_max_backoff=0.2)
            sched.sync()
            return store, sched, fillers

        def drive(store, sched, want, deadline_s):
            bound = 0
            deadline = time.perf_counter() + deadline_s
            while time.perf_counter() < deadline:
                sched.run_until_idle()
                sched.queue.flush_backoff_completed()
                sched.pump_events()
                bound = sum(1 for p in store.list("pods")[0]
                            if p.metadata.name.startswith("gang-")
                            and p.spec.node_name)
                if bound >= want:
                    break
                time.sleep(0.02)
            return bound

        def run_leg(rebalance):
            store, sched, fillers = build()
            rb = None
            budget_ok = True
            frag_before = frag_after = 0.0
            if rebalance:
                def probe():
                    # the mid-plan abort hook, wired to the REAL windowed
                    # SLO verdict (skipped checks pass; a degraded tail
                    # stops the remaining waves)
                    return evaluate_slo(sched.sched_stats(),
                                        DEFRAG_SLO)["pass"]

                rb = sched.enable_rebalancer(
                    frag_threshold=0.25, budget_per_wave=budget_wave,
                    budget_per_cycle=budget_cycle, priority_ceiling=50,
                    slo_probe=probe)
                # background consolidation between workloads: cycle to the
                # no-op steady state, auditing the budget on EVERY cycle
                for ci in range(8):
                    r = rb.cycle()
                    budget_ok &= (r.get("migrations", 0) <= budget_cycle)
                    if ci == 0:
                        frag_before = r.get("frag", 0.0)
                    sched.pump_events()
                    if not r.get("migrations"):
                        frag_after = r.get("frag", frag_before)
                        break
            store.create("podgroups", make_pod_group("gang", gang_n))
            gang = [MakePod(f"gang-{i}").gang("gang", rank=i).priority(100)
                    .req({"cpu": "6"}).obj() for i in range(gang_n)]
            t0 = time.perf_counter()
            store.create_many("pods", gang, consume=True)
            bound = drive(store, sched, gang_n, 20.0 if SMOKE else 60.0)
            dt = time.perf_counter() - t0
            victims = sched.gangpreempt.stats()["victims"]
            # conservation through the migration chain: ON leg fillers may
            # have been re-placed under -mgN names (resolve_keys follows
            # the victim->replacement chain); OFF leg fillers are LEGALLY
            # destroyed by preemption, so only the gang is gated there
            keys = [p.key for p in gang]
            if rb is not None:
                keys += rb.resolve_keys([p.key for p in fillers])
            rep = pod_conservation_report(store, sched, keys)
            slo = evaluate_slo(sched.sched_stats(), DEFRAG_SLO)
            stats = rb.stats() if rb is not None else {}
            sched.stop()
            if rb is not None:
                rb.release()
            return {"bound": bound, "wall_s": dt, "victims": victims,
                    "conservation": rep["counts"], "slo": slo,
                    "budget_ok": budget_ok, "frag_before": frag_before,
                    "frag_after": frag_after, "rebalance": stats}

        # warm-up: both legs compile their kernels at the run's shapes (the
        # defrag scan's pow2 buckets AND the victim-cover shapes)
        run_leg(True)
        run_leg(False)
        compiles0 = _solver_jit_cache()
        on = run_leg(True)
        off = run_leg(False)
        compiles = sum(v - compiles0.get(k, 0)
                       for k, v in _solver_jit_cache().items() if v >= 0)
        conserved = all(
            leg["conservation"]["lost"] == 0
            and leg["conservation"]["double_bound"] == 0
            for leg in (on, off))
        latency_improved = on["wall_s"] < off["wall_s"]
        preempt_improved = (on["victims"] == 0 and off["victims"] > 0)
        ok = (on["bound"] == gang_n and off["bound"] == gang_n
              and preempt_improved and latency_improved
              and on["budget_ok"] and conserved
              and on["rebalance"].get("migrations", 0) > 0
              and on["frag_after"] < 0.25 <= on["frag_before"]
              and on["slo"]["pass"] and off["slo"]["pass"]
              and compiles == 0)
        results["Defrag"] = {
            "admission_s_on": round(on["wall_s"], 3),
            "admission_s_off": round(off["wall_s"], 3),
            "preemptions_on": on["victims"],
            "preemptions_off": off["victims"],
            "migrations": on["rebalance"].get("migrations", 0),
            "waves": on["rebalance"].get("waves", 0),
            "frag_before": round(on["frag_before"], 3),
            "frag_after": round(on["frag_after"], 3),
            "budget_per_cycle": budget_cycle,
            "budget_ok": on["budget_ok"],
            "latency_improved": latency_improved,
            "preempt_improved": preempt_improved,
            "conservation_on": on["conservation"],
            "conservation_off": off["conservation"],
            "conservation_ok": conserved,
            "slo_pass_on": on["slo"]["pass"],
            "slo_pass_off": off["slo"]["pass"],
            "solver_compiles_during_run": compiles,
            "ab_comparable": True,  # same box, same process, interleaved
            "defrag_ok": ok,
            "solver": "fast+rebalance+defrag-scan"}
        print(f"{'Defrag':>28}: gang admitted in {on['wall_s']:.3f}s/"
              f"{on['victims']} evictions (rebalancer ON, "
              f"{on['rebalance'].get('migrations', 0)} migrations, frag "
              f"{on['frag_before']:.2f}->{on['frag_after']:.2f}) vs "
              f"{off['wall_s']:.3f}s/{off['victims']} evictions OFF "
              f"(compiles={compiles}, ok={ok})", file=sys.stderr)
    except Exception as e:
        results["Defrag"] = {"error": str(e)[:200]}
        print(f"Defrag: ERROR {e}", file=sys.stderr)


def rung_chaos_churn(results):
    """ChaosChurn_20k: the failure-domain rung (ISSUE 6) — bind 20k pods
    end-to-end WHILE the fault injector fails the first solves (tripping the
    solver circuit breaker to the exact scan oracle), fails store.bind_many
    transiently at a seeded rate (exercising the bind retry/backoff), and
    hard-kills the bind worker once mid-run (exercising the dead-worker
    liveness recovery); a crash resync_from_store runs at the halfway mark.
    Asserts the pod-conservation invariant — every submitted pod bound, 0
    lost, 0 double-bound — and that the breaker tripped AND recovered to the
    fast solver within the run. Also publishes the measured cost of the
    DISABLED injector guard so tests can bound its NorthStar overhead <1%
    from a measurement instead of differencing two noisy runs."""
    from kubernetes_tpu.chaos import faultinject as fi
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod, pod_conservation_report

    try:
        n_pods = sz(20_000, floor=2000)
        n_nodes = sz(1000, floor=128)
        batch = 2048
        waves = 4

        def build():
            store = APIStore()
            for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
                store.create("nodes", n)
            sched = BatchScheduler(
                store, Framework(default_plugins()), batch_size=batch,
                solver="fast", breaker_threshold=3, breaker_cooldown_s=0.5,
                bind_retry_base_s=0.01,
                pod_initial_backoff=0.05, pod_max_backoff=0.2)
            # small commit chunks: the chaos plans need MANY bind_many calls
            # and worker cycles to bite (one merged 20k-pod cycle would see
            # the rate fault twice)
            sched.bind_chunk = 256
            sched.sync()
            return store, sched

        def mk(prefix, n):
            return [MakePod(f"{prefix}-{i}").req(
                {"cpu": "500m", "memory": "1Gi"}).obj() for i in range(n)]

        # warm-up: compile BOTH solvers at the run's shapes — the breaker
        # drives the scan oracle mid-run, and a cold compile inside the
        # chaos window would be measured as recovery latency
        wstore, wsched = build()
        wstore.create_many("pods", mk("w", min(n_pods, 2 * batch)),
                           consume=True)
        wsched.run_until_idle()
        wsched.solver = "exact"
        wstore.create_many("pods", mk("wx", batch), consume=True)
        wsched.run_until_idle()
        wsched.flush_binds()
        wsched.stop()
        del wstore, wsched

        store, sched = build()
        keys = [f"default/cc-{i}" for i in range(n_pods)]
        pending = mk("cc", n_pods)
        from kubernetes_tpu.native import hostcommit

        plans = [
            fi.FaultPlan("solver.solve", "fail", count=3),
            fi.FaultPlan("store.bind_many", "rate", rate=0.3, seed=1234),
            fi.FaultPlan("bind.worker", "kill", after=1),
        ]
        native_leg = hostcommit.available()
        if native_leg:
            # ISSUE 11 satellite: mid-chunk NATIVE commit failure (fires in
            # bind_many's phase gap — clones made, nothing committed) must
            # ride the same supervised-worker requeue and conserve every pod
            plans.append(fi.FaultPlan("native.commit", "fail", count=3,
                                      after=2))
        fi.arm(plans)
        t0 = time.perf_counter()
        deadline = t0 + (40.0 if SMOKE else 240.0)
        resynced = False
        bound = 0
        per_wave = (n_pods + waves - 1) // waves
        next_wave = 0
        injected = {}
        try:
            while time.perf_counter() < deadline:
                if next_wave < n_pods:
                    store.create_many(
                        "pods", pending[next_wave:next_wave + per_wave],
                        consume=True)
                    next_wave += per_wave
                sched.run_until_idle()
                sched.queue.flush_backoff_completed()
                sched.queue.move_all_to_active_or_backoff()
                bound = sum(1 for p in store.list("pods")[0]
                            if p.metadata.name.startswith("cc-")
                            and p.spec.node_name)
                if not resynced and bound >= n_pods // 2:
                    # settle the pre-crash subscriber's propagation ops into
                    # the store histograms BEFORE the resync discards the
                    # subscription (a mid-run /metrics scrape would do the
                    # same): the post-crash watch starts a fresh baseline,
                    # and with the native commit path the whole backlog can
                    # bind pre-resync — without this read the rung's
                    # propagation column could legitimately read 0
                    store.watch_telemetry()
                    sched.resync_from_store()  # simulated crash restart
                    resynced = True
                if bound >= n_pods and next_wave >= n_pods:
                    if sched.breaker.state == "closed":
                        break
                    # all work drained while the breaker was still open: the
                    # half-open probe needs a REAL batch — submit a few
                    # probe pods (tracked by the conservation check too)
                    extra = mk(f"probe{len(keys)}", 8)
                    keys.extend(p.key for p in extra)
                    store.create_many("pods", extra, consume=True)
                    time.sleep(sched.breaker.cooldown_s / 2)
                time.sleep(0.02)
            injected = (fi.ACTIVE.stats() if fi.ACTIVE is not None else {})
        finally:
            fi.disarm()
        # settle: with the injector gone, drain every tier to quiescence so
        # the conservation check reads a stable partition
        for _ in range(40):
            sched.flush_binds()
            sched.queue.flush_backoff_completed()
            sched.queue.move_all_to_active_or_backoff()
            sched.run_until_idle()
            if all(p.spec.node_name for p in store.list("pods")[0]
                   if not p.metadata.name.startswith(("w-", "wx-"))):
                break
            time.sleep(0.05)
        dt = time.perf_counter() - t0
        rep = pod_conservation_report(store, sched, keys)
        c = rep["counts"]
        brk = sched.breaker
        ok = (c["lost"] == 0 and c["double_bound"] == 0
              and c["bound"] == len(keys) and brk.trips >= 1
              and brk.recoveries >= 1 and brk.state == "closed"
              and injected.get("bind.worker", {}).get("injected", 0) >= 1
              and sched.bind_worker_restarts >= 1)
        # ISSUE 7: the breaker trip must SHOW UP as a latency excursion in
        # the trace without breaking the tracer — at quiescence every pod is
        # bound, so every surviving sampled span must be complete, the
        # submit->bound p99 must sit above the median (the faulted/backoff
        # pods ARE the tail) yet inside the chaos SLO ceiling
        from kubernetes_tpu.scheduler.slo import CHAOS_SLO, evaluate_slo

        latency = sched.podtrace.latency_stats()
        tsnap = sched.podtrace.snapshot()
        n_spans = len(tsnap["spans"])
        n_complete = sum(1 for s in tsnap["spans"] if s["complete"])
        # watch-propagation column (ISSUE 9): chaos drops and the breaker
        # excursion show up as commit->dequeue tail + counted drops
        wtel = store.watch_telemetry()
        prop = wtel["propagation"]
        watch_col = {
            "propagation_count": prop["count"],
            "propagation_p50_s": prop["p50_s"],
            "propagation_p99_s": prop["p99_s"],
            "subscribers": len(wtel["subscribers"]),
            "dropped": wtel["dropped"],
        }
        slo = evaluate_slo({"latency": latency}, CHAOS_SLO)
        trace_ok = (n_spans > 0 and n_complete == n_spans
                    and latency["count"] > 0
                    and latency["p99_s"] >= latency["p50_s"]
                    and slo["pass"])
        # --- partition hard-kill leg (ISSUE 12 satellite): the same churn
        # through a 2-partition scheduler, with partition 1 HARD-KILLED
        # mid-run by the partition.dispatch chaos site. The survivor must
        # absorb the dead shard — router remap + resync_from_store — and
        # every pod must still be conserved (the dead pipeline's in-flight
        # binds reconcile through the conflict machinery).
        from kubernetes_tpu.scheduler.partition import PartitionedScheduler
        pk = {}
        try:
            pstore = APIStore()
            for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
                pstore.create("nodes", n)
            coord = PartitionedScheduler(
                pstore, lambda: Framework(default_plugins()), partitions=2,
                batch_size=batch, solver="fast",
                pod_initial_backoff=0.05, pod_max_backoff=0.2)
            coord.sync()
            pkeys = [f"default/pk-{i}" for i in range(n_pods)]
            ppods = mk("pk", n_pods)
            fi.arm([fi.FaultPlan("partition.dispatch", "kill",
                                 match="partition-1", after=1)])
            t0p = time.perf_counter()
            deadline_p = t0p + (30.0 if SMOKE else 120.0)
            try:
                sent = 0
                pbound = 0
                while time.perf_counter() < deadline_p:
                    if sent < n_pods:
                        pstore.create_many(
                            "pods", ppods[sent:sent + per_wave],
                            consume=True)
                        sent += per_wave
                    coord.run_until_idle()
                    coord.flush_queues()
                    pbound = sum(1 for p in pstore.list("pods")[0]
                                 if p.metadata.name.startswith("pk-")
                                 and p.spec.node_name)
                    if pbound >= n_pods and sent >= n_pods:
                        break
                    time.sleep(0.02)
            finally:
                fi.disarm()
            coord.run_until_idle()
            coord.flush_binds()
            prep = pod_conservation_report(pstore, coord, pkeys)
            pc = prep["counts"]
            pk = {"pods": n_pods, "bound": pc["bound"], "lost": pc["lost"],
                  "double_bound": pc["double_bound"],
                  "partitions_absorbed": coord.partitions_absorbed,
                  "conflicts": coord.conflicts_total,
                  "reroutes": coord.reroutes_total,
                  "wall_s": round(time.perf_counter() - t0p, 3),
                  "ok": (pc["bound"] == len(pkeys) and pc["lost"] == 0
                         and pc["double_bound"] == 0
                         and coord.partitions_absorbed == 1)}
            coord.stop()
        except Exception as e:  # the leg must not void the main chaos run
            fi.disarm()
            pk = {"error": str(e)[:200]}
        # --- gang-preemption leg (ISSUE 14 satellite): a victim cover under
        # injected bind + native-commit faults AND a mid-run bind-worker
        # kill. The invariants: pod conservation clean over gang AND
        # surviving fillers, the gang is never half-bound (0 or all), and a
        # cover never half-fires without the gang eventually landing whole.
        gp = {}
        try:
            from kubernetes_tpu.testing import MakeNode, make_pod_group

            gstore = APIStore()
            for s in range(2):
                for i in range(8):
                    gstore.create("nodes", MakeNode(f"node-{s}-{i}")
                                  .tpu_slice(s, index=i)
                                  .capacity({"cpu": "8", "memory": "32Gi",
                                             "pods": "110"}).obj())
            filler_keys = []
            for s in range(2):
                for i in range(8):
                    low = MakePod(f"low-{s}-{i}").priority(1).req(
                        {"cpu": "6"}).obj()
                    low.spec.node_name = f"node-{s}-{i}"
                    gstore.create("pods", low)
                    filler_keys.append(low.key)
            gsched = BatchScheduler(
                gstore, Framework(default_plugins()), batch_size=1024,
                solver="fast", breaker_threshold=3, breaker_cooldown_s=0.5,
                bind_retry_base_s=0.01,
                pod_initial_backoff=0.05, pod_max_backoff=0.2)
            gsched.bind_chunk = 4
            gsched.sync()
            gstore.create("podgroups", make_pod_group("cg", 12))
            gpods = [MakePod(f"cg-{i}").gang("cg", rank=i).priority(100)
                     .req({"cpu": "3"}).obj() for i in range(12)]
            gplans = [fi.FaultPlan("store.bind_many", "rate", rate=0.25,
                                   seed=77),
                      fi.FaultPlan("bind.worker", "kill", after=1)]
            if native_leg:
                gplans.append(fi.FaultPlan("native.commit", "fail", count=2))
            fi.arm(gplans)
            t0g = time.perf_counter()
            deadline_g = t0g + (25.0 if SMOKE else 90.0)
            gbound = 0
            try:
                gstore.create_many("pods", gpods, consume=True)
                while time.perf_counter() < deadline_g:
                    gsched.run_until_idle()
                    gsched.queue.flush_backoff_completed()
                    gsched.pump_events()
                    gbound = sum(1 for p in gstore.list("pods")[0]
                                 if p.metadata.name.startswith("cg-")
                                 and p.spec.node_name)
                    if gbound >= 12:
                        break
                    time.sleep(0.02)
            finally:
                fi.disarm()
            # settle to quiescence with the injector gone
            for _ in range(40):
                gsched.run_until_idle()
                gsched.queue.flush_backoff_completed()
                gsched.pump_events()
                gbound = sum(1 for p in gstore.list("pods")[0]
                             if p.metadata.name.startswith("cg-")
                             and p.spec.node_name)
                if gbound >= 12:
                    break
                time.sleep(0.05)
            gstats = gsched.gangpreempt.stats()
            # conservation over the gang + every filler the cover did NOT
            # delete (a deleted victim is the cover's documented outcome)
            live_fillers = [k for k in filler_keys
                            if any(p.key == k
                                   for p in gstore.list("pods")[0])]
            grep_ = pod_conservation_report(
                gstore, gsched, [p.key for p in gpods] + live_fillers)
            gc_ = grep_["counts"]
            gsched.stop()
            gp = {"pods": 12, "bound": gbound,
                  "lost": gc_["lost"], "double_bound": gc_["double_bound"],
                  "preempted": gstats["preempted"],
                  "victims": gstats["victims"],
                  "expired_covers": gstats["expired"],
                  "wall_s": round(time.perf_counter() - t0g, 3),
                  "ok": (gbound == 12 and gc_["lost"] == 0
                         and gc_["double_bound"] == 0
                         and gstats["preempted"] >= 1)}
        except Exception as e:  # the leg must not void the main chaos run
            fi.disarm()
            gp = {"error": str(e)[:200]}
        # --- mp worker-kill leg (ISSUE 19 satellite): the same churn
        # through a 2-process MPScheduler with worker 1 HARD-KILLED
        # (SIGKILL — a process failure domain, not an exception) mid-run by
        # the process.worker chaos site. The supervisor must detect the
        # death, respawn the slot, resync the estate, and conserve every
        # pod; the dead worker's in-flight intents die with its queue and
        # the rv re-validation absorbs anything already submitted.
        mpk = {}
        try:
            from kubernetes_tpu.scheduler.mpsched import MPScheduler
            from kubernetes_tpu.store import shm as _shm_mod

            if not _shm_mod.available():
                mpk = {"skipped": "shared memory unavailable"}
            else:
                mstore = APIStore()
                for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
                    mstore.create("nodes", n)
                msched = MPScheduler(mstore, processes=2)
                mkeys = [f"default/mpk-{i}" for i in range(n_pods)]
                mpods = mk("mpk", n_pods)
                fi.arm([fi.FaultPlan("process.worker", "kill",
                                     match="worker-1", after=1)])
                t0m = time.perf_counter()
                deadline_m = t0m + (30.0 if SMOKE else 120.0)
                try:
                    sent = 0
                    mbound = 0
                    while time.perf_counter() < deadline_m:
                        if sent < n_pods:
                            mstore.create_many(
                                "pods", mpods[sent:sent + per_wave],
                                consume=True)
                            sent += per_wave
                        msched.run_until_idle()
                        mbound = sum(1 for pd in mstore.list("pods")[0]
                                     if pd.metadata.name.startswith("mpk-")
                                     and pd.spec.node_name)
                        if mbound >= n_pods and sent >= n_pods:
                            break
                        time.sleep(0.02)
                finally:
                    fi.disarm()
                msched.run_until_idle()
                msched.flush_binds()
                mrep = pod_conservation_report(mstore, msched, mkeys)
                mc = mrep["counts"]
                mst = msched.sched_stats()["processes"]
                mpk = {"pods": n_pods, "bound": mc["bound"],
                       "lost": mc["lost"],
                       "double_bound": mc["double_bound"],
                       "worker_restarts": mst["worker_restarts"],
                       "stale_intents": mst["stale_intents"],
                       "bind_conflicts": mst["bind_conflicts"],
                       "rounds": mst["rounds"],
                       "wall_s": round(time.perf_counter() - t0m, 3),
                       "ok": (mc["bound"] == len(mkeys) and mc["lost"] == 0
                              and mc["double_bound"] == 0
                              and mst["worker_restarts"] >= 1)}
                msched.stop()
        except Exception as e:  # the leg must not void the main chaos run
            fi.disarm()
            mpk = {"error": str(e)[:200]}
        results["ChaosChurn_20k"] = {
            "pods_per_sec": round(n_pods / dt, 1), "wall_s": round(dt, 3),
            "placed": c["bound"], "pods": len(keys),
            "conservation": c, "conservation_ok": ok,
            "breaker_trips": brk.trips, "breaker_recoveries": brk.recoveries,
            "breaker_state": brk.state,
            "bind_worker_restarts": sched.bind_worker_restarts,
            "resynced": resynced, "injected": injected,
            "latency": latency,
            "trace": {"spans": n_spans, "complete": n_complete,
                      "evicted_incomplete": tsnap["evicted_incomplete"]},
            "watch": watch_col,
            "trace_ok": trace_ok, "slo": slo,
            "disabled_check_ns": round(fi.disabled_check_cost_ns(), 2),
            "native_commit_faults": injected.get("native.commit",
                                                 {}).get("injected", 0),
            "native_commit": native_leg,
            "partition_kill": pk,
            "mp_worker_kill": mpk,
            "gang_preemption": gp,
            "solver": "fast+breaker+chaos"}
        print(f"{'ChaosChurn_20k':>28}: {n_pods / dt:>9.0f} pods/s  "
              f"({c['bound']}/{n_pods} bound under chaos, "
              f"{c['lost']} lost, {c['double_bound']} double-bound, "
              f"breaker trips={brk.trips} recoveries={brk.recoveries}, "
              f"worker restarts={sched.bind_worker_restarts}, {dt:.1f}s; "
              f"p50={latency['p50_s']}s p99={latency['p99_s']}s, "
              f"{n_complete}/{n_spans} spans complete)",
              file=sys.stderr)
        if "error" in pk:
            print(f"    partition-kill leg: ERROR {pk['error']}",
                  file=sys.stderr)
        else:
            print(f"    partition-kill leg: {pk['bound']}/{pk['pods']} "
                  f"conserved after absorbing partition 1 "
                  f"(absorbed={pk['partitions_absorbed']}, "
                  f"conflicts={pk['conflicts']}, "
                  f"reroutes={pk['reroutes']}, {pk['wall_s']}s)",
                  file=sys.stderr)
        if "error" in mpk:
            print(f"    mp worker-kill leg: ERROR {mpk['error']}",
                  file=sys.stderr)
        elif "skipped" in mpk:
            print(f"    mp worker-kill leg: SKIPPED {mpk['skipped']}",
                  file=sys.stderr)
        else:
            print(f"    mp worker-kill leg: {mpk['bound']}/{mpk['pods']} "
                  f"conserved after SIGKILLing worker 1 "
                  f"(restarts={mpk['worker_restarts']}, "
                  f"stale_intents={mpk['stale_intents']}, "
                  f"rounds={mpk['rounds']}, {mpk['wall_s']}s)",
                  file=sys.stderr)
        if "error" in gp:
            print(f"    gang-preemption leg: ERROR {gp['error']}",
                  file=sys.stderr)
        else:
            print(f"    gang-preemption leg: {gp['bound']}/{gp['pods']} "
                  f"placed whole under faults "
                  f"(covers={gp['preempted']}, victims={gp['victims']}, "
                  f"expired={gp['expired_covers']}, {gp['lost']} lost, "
                  f"{gp['wall_s']}s)", file=sys.stderr)
    except Exception as e:
        from kubernetes_tpu.chaos import faultinject as fi

        fi.disarm()  # never leak an armed injector into later rungs
        results["ChaosChurn_20k"] = {"error": str(e)[:200]}
        print(f"ChaosChurn_20k: ERROR {e}", file=sys.stderr)


def rung_control_plane(results):
    """ControlPlane_churn (ISSUE 9): the WHOLE "watch, reconcile, write
    status" loop — deployment rollout + node drain + eviction/replace driven
    through the controllers, hollow kubelets, and the batch scheduler, with
    the control-plane flight recorder measuring it all: per-controller
    reconcile-loop p99s (obs/reconcile.py), store watch-propagation
    commit->dequeue latency + delivered-RV lag, and submit->running spans
    with evict->replace causal links. Gated by CONTROL_PLANE_SLO
    (watch_propagation_p99_s / reconcile_p99_ms), asserted PASS by
    tests/test_bench_quick.py. Fixed-size like the gang rung: the rung IS
    the quick-tier control-plane smoke and runs in seconds."""
    from kubernetes_tpu.agent import HollowKubelet
    from kubernetes_tpu.api.types import Taint, TAINT_NO_EXECUTE
    from kubernetes_tpu.api.workloads import Deployment
    from kubernetes_tpu.controllers import (DeploymentController,
                                            ReplicaSetController,
                                            TaintEvictionController)
    from kubernetes_tpu.obs.reconcile import (controlstats_snapshot,
                                              reconcile_rollup)
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.scheduler.slo import CONTROL_PLANE_SLO, evaluate_slo
    from kubernetes_tpu.store import APIStore

    try:
        n_nodes, replicas = 24, 192
        store = APIStore()
        kubelets = [HollowKubelet(store, f"hollow-{i}",
                                  capacity={"cpu": "16", "memory": "64Gi",
                                            "pods": "110"})
                    for i in range(n_nodes)]
        for k in kubelets:
            k.register()
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=1024, solver="exact",
                               trace_sample_k=256)  # sample every pod: the
        # evict->replace chain assertions need both ends of every link
        sched.sync()
        dc = DeploymentController(store)
        rsc = ReplicaSetController(store)
        te = TaintEvictionController(store)
        for c in (dc, rsc, te):
            c.sync_all()
        controllers = (dc, rsc, te)

        def drive(rounds, done):
            for _ in range(rounds):
                for c in controllers:
                    c.reconcile_once()
                te.tick()  # fire due timed evictions
                sched.run_until_idle()
                for k in kubelets:
                    k.pump()
                if done():
                    return True
            return done()

        def pods_running():
            pods, _ = store.list("pods")
            return bool(pods) and all(
                p.spec.node_name and p.status.phase == "Running"
                for p in pods)

        store.create("deployments", Deployment.from_dict({
            "metadata": {"name": "cp-web"},
            "spec": {
                "replicas": replicas,
                # wide surge budget: the rung measures the control plane
                # under bulk churn, not the default one-pod-per-round crawl
                "strategy": {"type": "RollingUpdate",
                             "rollingUpdate": {"maxSurge": 64,
                                               "maxUnavailable": 64}},
                "selector": {"matchLabels": {"app": "cp-web"}},
                "template": {
                    "metadata": {"labels": {"app": "cp-web"}},
                    "spec": {"containers": [{"name": "c", "image": "v1",
                                             "resources": {"requests": {
                                                 "cpu": "100m"}}}]}},
            },
        }))
        # warm phase: initial rollout to Running (includes the solver's one
        # jit compile) — NOT measured; the churn window below is
        assert drive(30, pods_running), "initial rollout"
        # measured window starts here (the flightrec.clear() idiom)
        store.clear_watch_propagation()
        for c in controllers:
            c.recorder.clear()
        t0 = time.perf_counter()

        # (1) rolling update: new template -> new RS -> replace all pods
        def set_image(d):
            d.spec.template.spec.containers[0].image = "v2"
            return d

        store.guaranteed_update("deployments", "default/cp-web", set_image)

        def rolled():
            pods, _ = store.list("pods")
            new = [p for p in pods if any(
                c.image == "v2" for c in p.spec.containers)]
            return (len(new) >= replicas and all(
                p.spec.node_name and p.status.phase == "Running"
                for p in new))

        assert drive(60, rolled), "rolling update did not converge"

        # (2) node drain: NoExecute taint -> tainteviction evicts ->
        # ReplicaSet replaces -> scheduler re-places off the drained node
        drained = "hollow-0"
        node = store.get("nodes", drained)
        victims = sum(1 for p in store.list("pods")[0]
                      if p.spec.node_name == drained)
        node.spec.taints = list(node.spec.taints) + [
            Taint(key="bench/drain", effect=TAINT_NO_EXECUTE)]
        store.update("nodes", node, check_rv=False)

        def drained_done():
            pods, _ = store.list("pods")
            on_node = [p for p in pods if p.spec.node_name == drained]
            return (not on_node and len(pods) >= replicas
                    and pods_running())

        assert drive(60, drained_done), "drain/replace did not converge"
        dt = time.perf_counter() - t0

        # collect: controller reconcile rollup + watch propagation + spans
        snap = controlstats_snapshot()
        snap = {k: v for k, v in snap.items()
                if k in ("DeploymentController", "ReplicaSetController",
                         "TaintEvictionController")}
        roll = reconcile_rollup(snap)
        tel = store.watch_telemetry()
        prop = tel["propagation"]
        max_lag = max((s["rv_lag"] for s in tel["subscribers"]), default=0)
        tsnap = sched.podtrace.snapshot()
        chains = sum(1 for s in tsnap["spans"] if s.get("replaces"))
        chain_complete = sum(1 for s in tsnap["spans"]
                             if s.get("replaces") and s["complete"])
        running_spans = sum(1 for s in tsnap["spans"]
                            if s.get("submit_to_running_ms") is not None)
        slo = evaluate_slo({"watch": {"propagation": prop},
                            "reconcile": roll}, CONTROL_PLANE_SLO)
        ok = (slo["pass"] and not slo["skipped"] and victims > 0
              and chains >= 1 and chain_complete == chains
              and running_spans > 0)
        results["ControlPlane_churn"] = {
            "pods_per_sec": round(replicas / dt, 1), "wall_s": round(dt, 3),
            "pods": replicas, "nodes": n_nodes, "evicted_from_drain": victims,
            "watch": {"propagation_count": prop["count"],
                      "propagation_p50_s": prop["p50_s"],
                      "propagation_p99_s": prop["p99_s"],
                      "subscribers": len(tel["subscribers"]),
                      "max_rv_lag": max_lag,
                      "settle_s": prop["settle_seconds"]},
            "reconcile": roll,
            "controllers": {name: {"loops": st.get("loops"),
                                   "keys": st.get("keys"),
                                   "errors": st.get("errors"),
                                   "p99_ms": st.get("reconcile_p99_ms")}
                            for name, st in snap.items()},
            "trace": {"spans": len(tsnap["spans"]),
                      "evict_replace_chains": chains,
                      "chains_complete": chain_complete,
                      "running_spans": running_spans},
            "slo": slo, "controlplane_ok": ok,
            "solver": "exact+controllers+kubelets"}
        print(f"{'ControlPlane_churn':>28}: rollout+drain of {replicas} pods "
              f"in {dt:.2f}s  (propagation p99={prop['p99_s']}s over "
              f"{prop['count']} deliveries, worst reconcile p99="
              f"{roll['p99_ms']}ms [{roll['worst_controller']}], "
              f"{chains} evict->replace chains, SLO "
              f"{'PASS' if slo['pass'] else 'FAIL ' + str(slo['failed'])})",
              file=sys.stderr)
    except Exception as e:
        results["ControlPlane_churn"] = {"error": str(e)[:200]}
        print(f"ControlPlane_churn: ERROR {e}", file=sys.stderr)


def rung_transport(results):
    """Auction + Sinkhorn global solvers at 50k pods / 5k nodes (BASELINE.json
    ladder steps 3-4): throughput, placements, and mean assignment score vs
    the waterfill fast path on the identical problem."""
    import numpy as np

    from kubernetes_tpu.models.transport import transport_solve
    from kubernetes_tpu.models.waterfill import make_groups, waterfill_solve
    from kubernetes_tpu.ops.solver import make_inputs
    from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch
    from kubernetes_tpu.testing import MakePod

    try:
        snap = make_snapshot(_nodes(sz(5000), cpu="16", mem="64Gi"))
        pods = [MakePod(f"tr-{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(sz(50_000))]
        cluster = build_cluster_tensors(snap)
        batch = build_pod_batch(pods, snap, cluster)
        inputs, _ = make_inputs(cluster, batch)
        groups = make_groups(batch)

        def timed(fn):
            fn()  # warm-up/compile
            t0 = time.perf_counter()
            out = fn()
            return out, time.perf_counter() - t0

        base, dt_wf = timed(lambda: np.asarray(waterfill_solve(inputs, groups)))

        # BASELINE ladder #4: node-sharded Sinkhorn at 100k pods / 10k nodes
        # through the mesh path (all available devices; on the 1-chip bench
        # rig the sharding machinery still runs with a 1-wide mesh)
        try:
            from kubernetes_tpu.parallel.sharded import make_mesh

            big_snap = make_snapshot(_nodes(sz(10_000), cpu="16", mem="64Gi"))
            big_pods = [MakePod(f"ts-{i}").req(
                {"cpu": "500m" if i % 2 else "250m",
                 "memory": "1Gi"}).obj() for i in range(sz(100_000))]
            big_cluster = build_cluster_tensors(big_snap)
            big_batch = build_pod_batch(big_pods, big_snap, big_cluster)
            big_inputs, _ = make_inputs(big_cluster, big_batch)
            big_groups = make_groups(big_batch)
            mesh = make_mesh()
            wf_big, dt_wf_big = timed(
                lambda: np.asarray(waterfill_solve(big_inputs, big_groups)))
            solved, dt = timed(lambda: transport_solve(
                big_inputs, big_groups, method="sinkhorn",
                node_names=big_cluster.node_names, mesh=mesh))
            a = np.asarray(solved[0])
            placed = int((a >= 0).sum())
            pps = len(big_pods) / dt
            wf_pps = len(big_pods) / dt_wf_big
            results["Transport_sinkhorn_sharded_100k"] = {
                "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
                "placed": placed, "pods": len(big_pods),
                "waterfill_placed": int((wf_big >= 0).sum()),
                "mesh_devices": int(np.prod(list(mesh.shape.values()))),
                "waterfill_pods_per_sec": round(wf_pps, 1),
                "vs_waterfill": round(pps / wf_pps, 2)}
            print(f"{'Transport_sinkhorn_sharded_100k':>28}: {pps:>9.0f} "
                  f"pods/s  ({placed}/{len(big_pods)} placed; "
                  f"{pps / wf_pps:.2f}x waterfill)", file=sys.stderr)
        except Exception as e:
            results["Transport_sinkhorn_sharded_100k"] = {"error": str(e)[:200]}
            print(f"Transport_sinkhorn_sharded_100k: ERROR {e}",
                  file=sys.stderr)

        for method in ("auction", "sinkhorn"):
            try:
                solved, dt = timed(lambda m=method: transport_solve(
                    inputs, groups, method=m, node_names=cluster.node_names))
                if solved is None:
                    results[f"Transport_{method}_50k"] = {"error": "solver declined problem"}
                    continue
                a = np.asarray(solved[0])
                placed = int((a >= 0).sum())
                pps = len(pods) / dt
                results[f"Transport_{method}_50k"] = {
                    "pods_per_sec": round(pps, 1), "wall_s": round(dt, 3),
                    "placed": placed, "pods": len(pods),
                    "waterfill_pods_per_sec": round(len(pods) / dt_wf, 1),
                    "waterfill_placed": int((base >= 0).sum())}
                print(f"{'Transport_' + method + '_50k':>28}: {pps:>9.0f} pods/s  "
                      f"({placed}/{len(pods)} placed; waterfill "
                      f"{len(pods) / dt_wf:.0f} pods/s)", file=sys.stderr)
            except Exception as e:
                results[f"Transport_{method}_50k"] = {"error": str(e)[:200]}
                print(f"Transport_{method}_50k: ERROR {e}", file=sys.stderr)
    except Exception as e:
        results["Transport_50k"] = {"error": str(e)[:200]}
        print(f"Transport_50k: ERROR {e}", file=sys.stderr)


def rung_node_affinity(results):
    # NodeAffinity (affinity/performance-config.yaml:323 shape, baseline 220):
    # half the nodes carry the wanted label; every pod requires it
    from kubernetes_tpu.testing import MakePod

    nodes = _nodes(sz(5000))
    for i, n in enumerate(nodes):
        n.metadata.labels["disk"] = "ssd" if i % 2 == 0 else "hdd"
    snap = make_snapshot(nodes)
    pods = [MakePod(f"na-{i}").node_affinity_in("disk", ["ssd"])
            .req({"cpu": "200m", "memory": "256Mi"}).obj()
            for i in range(sz(10000))]
    run_rung("NodeAffinity", snap, pods, "scan", 220, results=results)


def rung_preferred_topology_spread(results):
    # PreferredTopologySpreading (misc/performance-config.yaml:249 shape,
    # baseline 125): ScheduleAnyway constraints score instead of filter
    from kubernetes_tpu.testing import MakePod

    snap = make_snapshot(_nodes(sz(5000), zones=10))
    pods = [MakePod(f"pts-{i}").labels({"app": "soft"})
            .req({"cpu": "200m", "memory": "256Mi"})
            .topology_spread(1, ZONE, "ScheduleAnyway", {"app": "soft"})
            .obj() for i in range(sz(5000))]
    run_rung("PreferredTopologySpreading", snap, pods, "repair", 125,
             results=results)


def rung_affinity_quality(results):
    """AffinityQuality (ISSUE 17 satellite, ROADMAP carryover): the soft-term
    placement-QUALITY yardstick, not a throughput rung. Pods carry preferred
    pod-affinity terms toward per-zone seeds with deliberate capacity
    pressure (each zone can host ~80% of the pods that prefer it), so the
    scorer decides how much preference-weight each solver path realizes.
    The same workload solves twice — the propose-and-repair fast path (the
    penalty fold) vs the exact scan oracle — and the rung publishes the
    achieved soft score of each plus their ratio: the parity claim the
    defrag kernel's placement-quality numbers lean on."""
    import numpy as np

    from kubernetes_tpu.testing import MakePod

    try:
        n_z, nodes_per_zone, pref_z, n_pods, weight = 10, 3, 7, 140, 10
        n_nodes = n_z * nodes_per_zone
        # node-i sits in zone-(i % n_z): zone capacity = 3 nodes x 8 cpu
        nodes = _nodes(n_nodes, zones=n_z)
        # one seed per PREFERRED zone on node-z (zone-z for z < pref_z)
        seeds = [MakePod(f"seed-{z}").labels({"svc": f"s{z}"})
                 .node(f"node-{z}").req({"cpu": "100m"}).obj()
                 for z in range(pref_z)]
        snap = make_snapshot(nodes, bound_pods=seeds)
        # 20 pods prefer each seeded zone at 1.5 cpu = 30 cpu wanted vs
        # ~23.9 free — only ~15 of 20 can land preferred, the rest spill to
        # the 3 seedless zones (global headroom: every pod still places).
        # The score separates a real soft-term fold from a scorer that
        # ignores the preference
        pods = [MakePod(f"aq-{i}").labels({"peer": "1"})
                .preferred_pod_affinity(weight, ZONE,
                                        {"svc": f"s{i % pref_z}"})
                .req({"cpu": "1500m"}).obj() for i in range(n_pods)]

        from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors

        node_zone = [int(n.split("-")[1]) % n_z
                     for n in build_cluster_tensors(snap).node_names]

        def soft_score(a):
            # realized preference-weight: pod i's term is satisfied iff its
            # node's zone holds seed s{i % pref_z} (zone i % pref_z)
            return sum(weight for i in range(len(pods))
                       if a[i] >= 0 and node_zone[int(a[i])] == i % pref_z)

        def solve(solver):
            device_solve(snap, pods, solver)  # warm-up: compile
            a, dt, _info = device_solve(snap, pods, solver)
            return np.asarray(a), dt

        a_rep, dt_rep = solve("repair")
        a_scan, dt_scan = solve("scan")
        s_rep, s_scan = soft_score(a_rep), soft_score(a_scan)
        placed_rep = int((a_rep >= 0).sum())
        placed_scan = int((a_scan >= 0).sum())
        max_score = n_pods * weight
        parity = (s_rep / s_scan) if s_scan else (1.0 if not s_rep else 0.0)
        # the repair fold is approximate BY DESIGN (soft scores steer, hard
        # masks decide — a 0..200 preference row vs a 0..800 packing score):
        # measured parity on this shape is ~0.82, and the floor catches a
        # fold regression (sign flip, dropped term), not design headroom
        ok = (placed_rep == placed_scan == n_pods
              and s_scan > 0 and parity >= 0.7)
        results["AffinityQuality"] = {
            "pods": n_pods, "placed_repair": placed_rep,
            "placed_scan": placed_scan,
            "soft_score_repair": s_rep, "soft_score_scan": s_scan,
            "soft_score_max": max_score,
            "soft_score_parity": round(parity, 3),
            "pods_per_sec_repair": round(n_pods / dt_rep, 1) if dt_rep else 0,
            "pods_per_sec_scan": round(n_pods / dt_scan, 1) if dt_scan else 0,
            "ab_comparable": True,  # same box, same process, interleaved
            "quality_ok": ok,
            "solver": "repair-vs-scan"}
        print(f"{'AffinityQuality':>28}: soft score {s_rep}/{max_score} "
              f"(repair) vs {s_scan}/{max_score} (scan oracle), parity "
              f"{parity:.3f}, ok={ok}", file=sys.stderr)
    except Exception as e:
        results["AffinityQuality"] = {"error": str(e)[:200]}
        print(f"AffinityQuality: ERROR {e}", file=sys.stderr)


def _preemption_run(results, name, baseline, async_preparation):
    """Shared preemption harness; async_preparation picks the reference's
    PreemptionBasic (serial victim prep, baseline 18) vs PreemptionAsync
    (prepareCandidateAsync, baseline 160) modes."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.scheduler.plugins.default_preemption import (
        DefaultPreemption,
    )
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    def make_framework():
        plugins = default_plugins()
        for i, p in enumerate(plugins):
            if isinstance(p, DefaultPreemption):
                plugins[i] = DefaultPreemption(
                    async_preparation=async_preparation)
        return Framework(plugins)

    try:
        n_nodes = sz(500, floor=16)
        store = APIStore()
        for n in _nodes(n_nodes, cpu="4"):
            store.create("nodes", n)
        for i in range(n_nodes):
            low = MakePod(f"low-{i}").priority(1).req({"cpu": "3"}).obj()
            low.spec.node_name = f"node-{i}"
            store.create("pods", low)
        warm_store = APIStore()
        for n in _nodes(n_nodes, cpu="4"):
            warm_store.create("nodes", n)
        warm = BatchScheduler(warm_store, make_framework(), solver="auto")
        warm.sync()
        for i in range(n_nodes):
            warm_store.create("pods", MakePod(f"w-{i}").priority(100).req(
                {"cpu": "2"}).obj())
        warm.run_until_idle()

        sched = BatchScheduler(store, make_framework(), solver="auto")
        sched.sync()
        sched.run_until_idle()
        for i in range(n_nodes):
            store.create("pods", MakePod(f"high-{i}").priority(100).req(
                {"cpu": "2"}).obj())
        t0 = time.perf_counter()
        deadline = t0 + 120
        bound = 0
        while time.perf_counter() < deadline:
            sched.run_until_idle()
            bound = sum(1 for p in store.list("pods")[0]
                        if p.metadata.name.startswith("high") and p.spec.node_name)
            if bound >= n_nodes:
                break
            sched.queue.flush_backoff_completed()
            sched.queue.flush_unschedulable_left_over()
            time.sleep(0.05)
        dt = time.perf_counter() - t0
        pps = bound / dt
        results[name] = {
            "pods_per_sec": round(pps, 1),
            "vs_baseline": round(pps / baseline, 2),
            "placed": bound, "pods": n_nodes,
            "solver": ("async" if async_preparation else "serial")
            + "-preempt+batch"}
        print(f"{name:>28}: {pps:>9.0f} pods/s  "
              f"({bound}/{n_nodes} preempted+bound, "
              f"{pps / baseline:.1f}x baseline {baseline})", file=sys.stderr)
    except Exception as e:
        results[name] = {"error": str(e)[:200]}
        print(f"{name}: ERROR {e}", file=sys.stderr)


def rung_preemption_async(results):
    _preemption_run(results, "PreemptionAsync", 160, async_preparation=True)


def rung_watch_fanout(results):
    """Apiserver watch fan-out at kubemark scale: 5k streaming watchers
    through the select-based mux, measuring deliveries/s (VERDICT r4 #8;
    reference: cacher fan-out, storage/cacher/cacher.go:261)."""
    from kubernetes_tpu.perf.watch_scale import run as watch_run

    try:
        out = watch_run(n_watchers=sz(5000, floor=64),
                        n_events=sz(100, floor=8))
        results["ApiserverWatchFanout_5k"] = out
        if "error" in out:
            print(f"ApiserverWatchFanout_5k: ERROR {out['error']}",
                  file=sys.stderr)
        else:
            print(f"{'ApiserverWatchFanout_5k':>28}: "
                  f"{out['deliveries_per_s']:>9.0f} deliveries/s  "
                  f"({out['streams_established']} streams, "
                  f"{out['deliveries']} delivered in {out['fanout_s']}s)",
                  file=sys.stderr)
    except Exception as e:
        results["ApiserverWatchFanout_5k"] = {"error": str(e)[:200]}
        print(f"ApiserverWatchFanout_5k: ERROR {e}", file=sys.stderr)


def rung_trace_timeline(results):
    """TraceTimeline (ISSUE 18): the NorthStar smoke window captured with
    the trace buffer ARMED through TWO partitioned pipelines — the export
    must validate as Chrome trace-event JSON (B/E balanced, monotonic per
    tid, the partition pipelines on DISTINCT tracks so ≥2-core overlap is
    visible, ≥1 evict→replace flow arrow), the critical-path components
    must sum to the measured submit→bound latency, and the armed overhead
    is asserted from a MEASUREMENT (the buffer's accumulated tap self-time
    vs the timed wall, <1% with the 2ms absolute floor) published beside
    `disabled_check_ns` (tests/test_bench_quick.py)."""
    from kubernetes_tpu.obs import critpath, tracebuf
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    try:
        n_pods = sz(10_000, floor=1000)
        n_nodes = sz(500, floor=40)
        # warm-up on a throwaway cluster: shard-sized jit shapes must
        # compile before the timed window (the Partitioned rung discipline)
        _w = _partitioned_e2e(n_pods, n_nodes, 2, "ttw")[0]
        _w.stop()
        del _w
        # the disabled cost: ONE module-attribute check, measured
        dcn = tracebuf.disabled_check_cost_ns()
        buf = tracebuf.arm(capacity=200_000)
        try:
            sched, store, dt, bound = _partitioned_e2e(
                n_pods, n_nodes, 2, "tt")
            # the armed overhead measurement stops HERE: taps after the
            # timed window (the flow leg below) are not its cost
            instr_s = buf.self_seconds
            spans = []
            table = None
            for pipe in sched.pipelines:
                spans.extend(pipe.podtrace.snapshot().get("spans") or [])
                if table is None:
                    table = pipe.flightrec.stage_table()
            sched.stop()
            # evict→replace leg (separate small cluster, same armed
            # buffer): bound owner-ref'd pods deleted, then same-owner
            # replacements — the podtrace link path that export() renders
            # as Perfetto flow arrows
            fstore = APIStore()
            for n in _nodes(8, cpu="16", mem="64Gi"):
                fstore.create("nodes", n)
            fsched = BatchScheduler(fstore, Framework(default_plugins()),
                                    batch_size=1024, solver="fast")
            fsched.sync()
            owner = [{"kind": "ReplicaSet", "name": "rs-tt",
                      "uid": "u-rs-tt"}]
            firsts = []
            for i in range(8):
                p = MakePod(f"ttf-{i}").req({"cpu": "100m"}).obj()
                p.metadata.owner_references = [dict(r) for r in owner]
                firsts.append(p)
            fstore.create_many("pods", firsts, consume=True)
            fsched.run_until_idle()
            fsched.flush_binds()
            for p in firsts[:4]:
                fstore.delete("pods", p.key)
            fsched.run_until_idle()
            reps = []
            for i in range(4):
                p = MakePod(f"ttr-{i}").req({"cpu": "100m"}).obj()
                p.metadata.owner_references = [dict(r) for r in owner]
                reps.append(p)
            fstore.create_many("pods", reps, consume=True)
            fsched.run_until_idle()
            fsched.flush_binds()
            flow_spans = fsched.podtrace.snapshot().get("spans") or []
            spans.extend(flow_spans)
            fsched.stop()
            doc = buf.export(spans=spans)
            val = tracebuf.validate_export(doc)
            track_names = [ev.get("args", {}).get("name")
                           for ev in doc["traceEvents"]
                           if ev["ph"] == "M"
                           and ev["name"] == "thread_name"]
            partition_tracks = sum(
                1 for t in track_names
                if t and t.startswith("p") and t.endswith("-sched"))
            cp = critpath.analyze(spans, stage_table=table)
            overall = cp.get("overall") or {}
            st = buf.status()
        finally:
            tracebuf.disarm()
        results["TraceTimeline"] = {
            "wall_s": round(dt, 3),
            "pods": n_pods, "placed": bound,
            "pods_per_sec": round(bound / dt, 1) if dt > 0 else 0.0,
            "export_valid": val["valid"],
            "export_errors": val["errors"][:3],
            "events": st["trace_events_total"],
            "dropped": st["trace_events_dropped_total"],
            "tracks": val["tracks"],
            "partition_tracks": partition_tracks,
            "flow_arrows": val["flow_pairs"],
            "counters": val["counters"],
            # the armed budget, measured (never differenced): tap
            # self-time accumulated during the timed window
            "instrumentation_s": round(instr_s, 6),
            "overhead_frac": round(instr_s / dt, 6) if dt > 0 else 0.0,
            "disabled_check_ns": round(dcn, 2),
            "critpath": {
                "spans": cp.get("spans_analyzed", 0),
                "dominant": overall.get("dominant"),
                "dominant_share": overall.get("dominant_share"),
                "sum_p50_ms": overall.get("sum_p50_ms"),
                "total_p50_ms": overall.get("total_p50_ms"),
                "sum_p99_ms": overall.get("sum_p99_ms"),
                "total_p99_ms": overall.get("total_p99_ms"),
            },
        }
        print(f"{'TraceTimeline':>28}: {st['trace_events_total']} events "
              f"({st['trace_events_dropped_total']} dropped), "
              f"{partition_tracks} partition tracks, "
              f"{val['flow_pairs']} flow arrows, "
              f"overhead {instr_s / dt * 100 if dt > 0 else 0:.3f}% "
              f"of {dt:.2f}s, dominant={overall.get('dominant')}",
              file=sys.stderr)
    except Exception as e:
        results["TraceTimeline"] = {"error": str(e)[:200]}
        print(f"TraceTimeline: ERROR {e}", file=sys.stderr)



def rung_multiprocess(results):
    """MultiProcess_2w (ISSUE 19): the tentpole rung — the SAME
    constraint-free bind workload through an MPScheduler with TWO worker
    PROCESSES reading the store's pod columns from shared memory, solving
    locally, and submitting integer bind intents the owner arbitrates
    through bind_many + rv re-validation. Publishes conservation, the
    measured overlap (owner cpu + worker-reported cpu beyond wall — on a
    1-core rig that is ~0 and ab_comparable says so), 0 mid-run solver
    compiles (plain pods never touch the jit solvers), and the shm
    unlink-clean check (no named segment outlives stop())."""
    from kubernetes_tpu.scheduler.mpsched import MPScheduler
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.store import shm
    from kubernetes_tpu.testing import MakePod, pod_conservation_report

    try:
        if not shm.available():
            results["MultiProcess_2w"] = {
                "skipped": "shared memory unavailable"}
            print("MultiProcess_2w: SKIPPED (no shared memory)",
                  file=sys.stderr)
            return
        n_pods = sz(20_000, floor=2000)
        n_nodes = sz(1000, floor=64)
        leaked_before = set(shm.leaked_segments())
        store = APIStore()
        for n in _nodes(n_nodes, cpu="16", mem="64Gi"):
            store.create("nodes", n)
        sched = MPScheduler(store, processes=2)
        CH = 10_000
        pending = [MakePod(f"mpb-{i}").req(
            {"cpu": "500m", "memory": "1Gi"}).obj() for i in range(n_pods)]
        keys = [pd.key for pd in pending]
        for lo in range(0, n_pods, CH):
            store.create_many("pods", pending[lo:lo + CH], consume=True)
        compiles0 = _solver_jit_cache()
        tms0 = os.times()
        t0 = time.perf_counter()
        sched.run_until_idle()
        dt = time.perf_counter() - t0
        tms1 = os.times()
        sched.flush_binds()
        compiles = sum(v - compiles0.get(k, 0)
                       for k, v in _solver_jit_cache().items() if v >= 0)
        st = sched.sched_stats()
        procs = st["processes"]
        rep = pod_conservation_report(store, sched, keys)
        c = rep["counts"]
        # overlap, measured: owner-process cpu (user+sys deltas) plus the
        # workers' self-reported process_time, minus wall — cpu beyond wall
        # can only come from processes genuinely running in parallel
        owner_cpu = ((tms1.user - tms0.user) + (tms1.system - tms0.system))
        worker_cpu = procs["worker_cpu_s"]
        overlap = round(max(0.0, owner_cpu + worker_cpu - dt), 6)
        sched.stop()
        leaked_after = [seg for seg in shm.leaked_segments()
                        if seg not in leaked_before]
        rig = _rig_info()
        cores = rig["cores"]
        ok = (c["lost"] == 0 and c["double_bound"] == 0
              and c["bound"] == n_pods)
        results["MultiProcess_2w"] = dict({
            "pods_per_sec": round(c["bound"] / dt, 1) if dt > 0 else 0.0,
            "wall_s": round(dt, 3),
            "pods": n_pods, "nodes": n_nodes, "placed": c["bound"],
            "processes": procs["configured"],
            "rounds": procs["rounds"],
            "stale_intents": procs["stale_intents"],
            "bind_conflicts": procs["bind_conflicts"],
            "worker_restarts": procs["worker_restarts"],
            "owner_cpu_s": round(owner_cpu, 4),
            "worker_cpu_s": round(worker_cpu, 4),
            "overlap_cpu_s": overlap,
            "concurrency_verdict": (_overlap_verdict(overlap, dt)
                                    if cores >= 2 else None),
            "ab_comparable": cores >= 2,
            "conservation": c,
            "conservation_ok": ok,
            "solver_compiles_during_run": compiles,
            "shm_leaked_segments": leaked_after,
            "shm_unlink_clean": not leaked_after,
            "per_worker": procs["workers"],
            "residual": procs["residual"],
            "solver": "ffd+mp2"}, **rig)
        print(f"{'MultiProcess_2w':>28}: {c['bound'] / dt:>9.0f} pods/s  "
              f"({c['bound']}/{n_pods} bound via 2 worker processes, "
              f"rounds={procs['rounds']} "
              f"stale={procs['stale_intents']} "
              f"conflicts={procs['bind_conflicts']}, "
              f"overlap {overlap:.2f}s cpu/{dt:.2f}s wall, "
              f"shm clean={not leaked_after})", file=sys.stderr)
    except Exception as e:
        results["MultiProcess_2w"] = {"error": str(e)[:200]}
        print(f"MultiProcess_2w: ERROR {e}", file=sys.stderr)


def rung_watch_fanout_store(results):
    """WatchFanout (ISSUE 19 satellite): the STORE's watch bus fanned out
    to a subscriber sweep — half lossy observability rings, half
    small-buffer cache watchers that the eviction path terminates when
    they fall behind — under create churn. Publishes the propagation-p99
    curve (commit->dequeue, settled per point) and the <=10s SLO verdict
    at EVERY point: fan-out scale must degrade the tail gracefully, never
    cliff it."""
    from kubernetes_tpu.scheduler.slo import CONTROL_PLANE_SLO
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakePod

    try:
        slo_s = CONTROL_PLANE_SLO["watch_propagation_p99_s"]
        n_events = sz(512, floor=128)
        sweep = (sz(32, floor=8), sz(256, floor=16), sz(1024, floor=32))
        curve = []
        ok_all = True
        for n_subs in sweep:
            store = APIStore()
            watches = []
            for i in range(n_subs):
                if i % 2 == 0:
                    # observability consumer: lossy ring survives overflow
                    w = store.watch(kind="pods", ring=True, maxsize=48)
                else:
                    # cache consumer: small buffer, falls behind -> evicted
                    w = store.watch(kind="pods", maxsize=48)
                watches.append(w)
            store.clear_watch_propagation()
            pods = [MakePod(f"wf{n_subs}-{i}").req({"cpu": "100m"}).obj()
                    for i in range(n_events)]
            t0 = time.perf_counter()
            CH = 64
            for lo in range(0, n_events, CH):
                store.create_many("pods", pods[lo:lo + CH], consume=True)
                # drain a rotating half each wave: mixed consumer speeds —
                # the undrained half's non-ring watchers fall behind and
                # evict, the rings drop oldest and survive
                off = (lo // CH) % 2
                for w in watches[off::2]:
                    if not w.terminated:
                        w.drain()
            for w in watches:
                if not w.terminated:
                    w.drain()
            dt = time.perf_counter() - t0
            wtel = store.watch_telemetry()
            prop = wtel["propagation"]
            evicted = sum(1 for w in watches if w.terminated)
            ring_dropped = sum(w.ring_dropped for w in watches)
            point_ok = (prop["count"] > 0
                        and (prop["p99_s"] or 0.0) <= slo_s)
            ok_all = ok_all and point_ok
            curve.append({
                "subscribers": n_subs,
                "events": n_events,
                "wall_s": round(dt, 3),
                "deliveries": prop["count"],
                "propagation_p50_s": prop["p50_s"],
                "propagation_p99_s": prop["p99_s"],
                "evicted": evicted,
                "ring_dropped": ring_dropped,
                "dropped": wtel["dropped"],
                "slo_ok": point_ok,
            })
            for w in watches:
                w.stop()
            del store, watches
        results["WatchFanout"] = dict({
            "points": curve,
            "slo_s": slo_s,
            "slo_ok": ok_all,
            "max_p99_s": max((pt["propagation_p99_s"] or 0.0)
                             for pt in curve),
            "subscribers_max": max(pt["subscribers"] for pt in curve),
        }, **_rig_info())
        print(f"{'WatchFanout':>28}: p99 curve "
              + " ".join(f"{pt['subscribers']}sub="
                         f"{(pt['propagation_p99_s'] or 0.0) * 1000:.1f}ms"
                         for pt in curve)
              + f"  (SLO<= {slo_s:.0f}s: {'PASS' if ok_all else 'FAIL'})",
              file=sys.stderr)
    except Exception as e:
        results["WatchFanout"] = {"error": str(e)[:200]}
        print(f"WatchFanout: ERROR {e}", file=sys.stderr)


RUNGS = [
    ("SchedulingBasic", rung_basic),
    ("TopologySpreading", rung_topology_spread),
    ("PodAntiAffinity", rung_pod_anti_affinity),
    ("PodAffinity", rung_pod_affinity),
    ("AntiAffinityNSSelector", rung_anti_affinity_ns_selector),
    ("MixedChurn", rung_mixed_churn),
    ("Preemption", rung_preemption),
    ("PreemptionAsync", rung_preemption_async),
    ("NodeAffinity", rung_node_affinity),
    ("PreferredTopologySpreading", rung_preferred_topology_spread),
    ("NorthStar", rung_north_star),
    ("NorthStarWarm", rung_north_star_warm),
    ("NorthStarEndToEnd", rung_north_star_endtoend),
    ("NorthStarSoak", rung_north_star_soak),
    ("BindCommit", rung_bind_commit),
    ("SchedStages", rung_sched_stages),
    ("GangScheduling", rung_gang),
    ("GangPreemption", rung_gang_preempt),
    ("Defrag", rung_defrag),
    ("AffinityQuality", rung_affinity_quality),
    ("Partitioned", rung_partitioned),
    ("ChaosChurn", rung_chaos_churn),
    ("MultiProcess", rung_multiprocess),
    ("WatchFanout", rung_watch_fanout_store),
    ("ControlPlane", rung_control_plane),
    ("SchedLint", rung_schedlint),
    ("TraceTimeline", rung_trace_timeline),
    ("Transport", rung_transport),
    ("ApiserverWatchFanout", rung_watch_fanout),
]

# --quick: the tier-1 smoke ladder — SMOKE-sized shapes, the rungs that
# exercise the host pipeline end-to-end, <=60s wall, same JSON line on
# stdout. Catches perf-path regressions (a broken coalesced ingest or bind
# path fails loudly here) without the full ladder's budget.
QUICK_RUNGS = ("SchedulingBasic", "MixedChurn", "NorthStarEndToEnd",
               "NorthStarSoak", "BindCommit", "SchedStages",
               "GangScheduling", "GangPreemption", "Defrag", "Partitioned",
               "ChaosChurn", "MultiProcess", "WatchFanout", "ControlPlane",
               "SchedLint", "TraceTimeline")
QUICK_BUDGET_S = 135.0


def cpu_fallback(reason: str) -> int:
    """The device backend is unresponsive: run the full-shape ladder on the
    host platform in a CLEAN child process (this process's jax backend init
    may be wedged mid-handshake with the dead device) and pass its output
    through. The child's JSON is labeled platform=cpu + fallback_reason so a
    CPU number can never masquerade as a TPU number."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CPU_FALLBACK"] = "1"
    env["BENCH_FALLBACK_REASON"] = reason
    # hand the child only the budget we actually have left (no grow-floor: a
    # nearly-spent budget means the child skips rungs and still emits its
    # JSON line fast, instead of wedging past an outer deadline)
    env["BENCH_BUDGET_S"] = str(max(0.0, budget_left() - 30.0))
    print(f"device backend down ({reason}); rerunning FULL ladder on cpu",
          file=sys.stderr)
    # child INHERITS stdout: its JSON streams out the moment it prints, so an
    # outer kill of this parent can't strand a fully-written result in a pipe
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)]
        + [a for a in sys.argv[1:] if a == "--quick"], env=env)
    return proc.returncode


def main():
    global SMOKE, GLOBAL_BUDGET_S, MIN_RUNG_BUDGET_S, RUNGS
    results = {}
    quick = "--quick" in sys.argv
    if quick:
        SMOKE = True
        GLOBAL_BUDGET_S = min(GLOBAL_BUDGET_S, QUICK_BUDGET_S)
        MIN_RUNG_BUDGET_S = 5.0
        RUNGS = [(n, fn) for n, fn in RUNGS if n in QUICK_RUNGS]
    in_fallback = os.environ.get("BENCH_CPU_FALLBACK", "") not in ("", "0")
    try:
        platform = ensure_device_alive(timeout_s=60.0)
        print(f"device backend alive: {platform}", file=sys.stderr)
    except RuntimeError as e:
        if not in_fallback and os.environ.get("JAX_PLATFORMS", "") != "cpu":
            sys.exit(cpu_fallback(str(e)))
        results["device"] = {"error": str(e)}
        checkpoint(results)
        out = {
            "metric": "scheduling_throughput_5000nodes_10000pods",
            "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
            "error": str(e), "platform": "none", "workloads": results,
        }
        if in_fallback:
            # total failure (TPU down AND the cpu fallback child failed too):
            # keep the original outage reason distinguishable
            out["fallback_reason"] = os.environ.get("BENCH_FALLBACK_REASON", "")
        print(json.dumps(out))
        return

    for name, rung in RUNGS:
        if budget_left() < MIN_RUNG_BUDGET_S:
            results[f"{name}_skipped"] = {
                "error": f"global budget exhausted ({GLOBAL_BUDGET_S:.0f}s)"}
            print(f"{name}: SKIPPED (budget)", file=sys.stderr)
            continue
        t0 = time.monotonic()
        rung(results)
        print(f"-- {name} took {time.monotonic() - t0:.1f}s "
              f"({budget_left():.0f}s budget left)", file=sys.stderr)
        checkpoint(results)

    # rig honesty columns (ISSUE 13 satellite): every successful rung's
    # JSON carries the core count + cgroup cpu quota it ran under, so a
    # core-starved run can never masquerade as a comparable number in the
    # BENCH_r* series (setdefault: the A/B rungs' own cores columns win)
    rig = _rig_info()
    for w in results.values():
        if isinstance(w, dict) and "error" not in w:
            w.setdefault("cores", rig["cores"])
            w.setdefault("cpu_quota", rig["cpu_quota"])

    ratios = [w["vs_baseline"] for w in results.values() if "vs_baseline" in w]
    headline = results.get("SchedulingBasic", {})
    out = {
        "metric": "scheduling_throughput_5000nodes_10000pods",
        "value": headline.get("pods_per_sec", 0.0),
        "unit": "pods/s",
        "vs_baseline": headline.get("vs_baseline", 0.0),
        "min_vs_baseline": min(ratios) if ratios else 0.0,
        "platform": platform,
        "rig": rig,
        "workloads": results,
    }
    if quick:
        out["quick"] = True
    if in_fallback:
        out["fallback_reason"] = os.environ.get("BENCH_FALLBACK_REASON", "")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
