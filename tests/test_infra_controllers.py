"""GC, namespace, resourcequota, endpointslice, tainteviction, HPA controller
tests — mirrors pkg/controller/{garbagecollector,namespace,resourcequota,
endpointslice,tainteviction,podautoscaler} unit tests in compressed form."""

from kubernetes_tpu.api.networking import EndpointSlice, Service
from kubernetes_tpu.api.policy import HorizontalPodAutoscaler, ResourceQuota
from kubernetes_tpu.api.types import Namespace, ObjectMeta, Taint, new_uid
from kubernetes_tpu.api.workloads import ReplicaSet
from kubernetes_tpu.controllers import (
    EndpointSliceController,
    GarbageCollector,
    HorizontalPodAutoscalerController,
    NamespaceController,
    ReplicaSetController,
    ResourceQuotaController,
    TaintEvictionController,
)
from kubernetes_tpu.store import APIStore, NotFoundError
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock

import pytest


def set_phase(store, key, phase):
    def mutate(p):
        p.status.phase = phase
        return p

    store.guaranteed_update("pods", key, mutate)


class TestGarbageCollector:
    def test_orphaned_pod_collected(self):
        store = APIStore()
        rs = ReplicaSet.from_dict({"metadata": {"name": "rs"}, "spec": {
            "replicas": 1, "template": {"spec": {"containers": [{"name": "c"}]}}}})
        rs.metadata.uid = new_uid()
        store.create("replicasets", rs)
        pod = MakePod("owned").obj()
        pod.metadata.owner_references = [{"kind": "ReplicaSet", "name": "rs",
                                          "uid": rs.metadata.uid, "controller": True}]
        store.create("pods", pod)
        free = MakePod("free").obj()
        store.create("pods", free)
        gc = GarbageCollector(store, clock=FakeClock())
        assert gc.sweep() == 0  # owner alive: nothing collected
        store.delete("replicasets", "default/rs")
        assert gc.sweep() == 1
        with pytest.raises(NotFoundError):
            store.get("pods", "default/owned")
        assert store.get("pods", "default/free")  # ownerless object untouched

    def test_uid_mismatch_is_orphan(self):
        store = APIStore()
        rs = ReplicaSet.from_dict({"metadata": {"name": "rs"}, "spec": {}})
        rs.metadata.uid = new_uid()
        store.create("replicasets", rs)
        pod = MakePod("stale").obj()
        pod.metadata.owner_references = [{"kind": "ReplicaSet", "name": "rs",
                                          "uid": "old-uid", "controller": True}]
        store.create("pods", pod)
        gc = GarbageCollector(store, clock=FakeClock())
        assert gc.sweep() == 1  # recreated owner does not adopt


class TestNamespaceController:
    def test_terminating_namespace_drained(self):
        store = APIStore()
        store.create("namespaces", Namespace(metadata=ObjectMeta(name="team-a")))
        store.create("pods", MakePod("p1", namespace="team-a").obj())
        store.create("pods", MakePod("p2", namespace="team-a").obj())
        store.create("pods", MakePod("other", namespace="default").obj())
        ctl = NamespaceController(store, clock=FakeClock())
        ctl.sync_all()
        ctl.mark_terminating("team-a")
        ctl.process()
        ctl.process()  # second pass observes emptiness and removes the ns
        assert not store.list("pods", lambda p: p.metadata.namespace == "team-a")[0]
        with pytest.raises(NotFoundError):
            store.get("namespaces", "team-a")
        assert store.get("pods", "default/other")


class TestResourceQuota:
    def test_usage_recalculated(self):
        store = APIStore()
        quota = ResourceQuota.from_dict({
            "metadata": {"name": "q", "namespace": "default"},
            "spec": {"hard": {"requests.cpu": "4", "pods": "10",
                              "count/replicasets": "5"}},
        })
        store.create("resourcequotas", quota)
        store.create("pods", MakePod("a").req({"cpu": "500m"}).obj())
        store.create("pods", MakePod("b").req({"cpu": "250m"}).obj())
        rs = ReplicaSet.from_dict({"metadata": {"name": "rs"}, "spec": {}})
        store.create("replicasets", rs)
        ctl = ResourceQuotaController(store, clock=FakeClock())
        ctl.sync_all()
        ctl.process()
        q = store.get("resourcequotas", "default/q")
        assert q.used["requests.cpu"] == "750m"
        assert q.used["pods"] == "2"
        assert q.used["count/replicasets"] == "1"

    def test_pod_deletion_updates_usage(self):
        store = APIStore()
        store.create("resourcequotas", ResourceQuota.from_dict({
            "metadata": {"name": "q"}, "spec": {"hard": {"pods": "10"}}}))
        store.create("pods", MakePod("a").obj())
        ctl = ResourceQuotaController(store, clock=FakeClock())
        ctl.sync_all()
        ctl.process()
        store.delete("pods", "default/a")
        ctl.reconcile_once()
        assert store.get("resourcequotas", "default/q").used["pods"] == "0"


class TestEndpointSlice:
    def _setup(self):
        store = APIStore()
        svc = Service.from_dict({
            "metadata": {"name": "web"},
            "spec": {"selector": {"app": "web"},
                     "ports": [{"name": "http", "port": 80, "targetPort": 8080}]},
        })
        svc.metadata.uid = new_uid()
        store.create("services", svc)
        ctl = EndpointSliceController(store, clock=FakeClock())
        ctl.sync_all()
        return store, ctl

    def test_slice_tracks_ready_pods(self):
        store, ctl = self._setup()
        for i in range(3):
            pod = MakePod(f"w{i}").labels({"app": "web"}).node(f"n{i}").obj()
            store.create("pods", pod)
        set_phase(store, "default/w0", "Running")
        set_phase(store, "default/w1", "Running")
        ctl.reconcile_once()
        es = store.get("endpointslices", "default/web-0")
        assert len(es.endpoints) == 3
        ready = {e.target_ref: e.ready for e in es.endpoints}
        assert ready == {"default/w0": True, "default/w1": True, "default/w2": False}
        assert es.ports[0].port == 80
        assert all(e.addresses[0].startswith("10.") for e in es.endpoints)

    def test_non_matching_and_unscheduled_excluded(self):
        store, ctl = self._setup()
        store.create("pods", MakePod("other").labels({"app": "db"}).node("n1").obj())
        store.create("pods", MakePod("pending").labels({"app": "web"}).obj())
        ctl.reconcile_once()
        es = store.get("endpointslices", "default/web-0")
        assert es.endpoints == []

    def test_service_deletion_removes_slices(self):
        store, ctl = self._setup()
        ctl.reconcile_once()
        assert store.get("endpointslices", "default/web-0")
        store.delete("services", "default/web")
        ctl.reconcile_once()
        with pytest.raises(NotFoundError):
            store.get("endpointslices", "default/web-0")

    def test_slices_capped_and_chunked(self):
        store, ctl = self._setup()
        ctl.max_endpoints = 2
        for i in range(5):
            store.create("pods",
                         MakePod(f"w{i}").labels({"app": "web"}).node("n").obj())
        ctl.reconcile_once()
        slices, _ = store.list("endpointslices")
        assert sorted(s.metadata.name for s in slices) == ["web-0", "web-1", "web-2"]
        assert sum(len(s.endpoints) for s in slices) == 5

    def test_many_slices_scale_down_keeps_low_ordinals(self):
        """11 slices shrunk to 2: lexicographic ordering (web-10 < web-2) must
        not confuse the reconciler into deleting live slices."""
        store, ctl = self._setup()
        ctl.max_endpoints = 1
        for i in range(11):
            store.create("pods",
                         MakePod(f"w{i:02d}").labels({"app": "web"}).node("n").obj())
        ctl.reconcile_once()
        assert len(store.list("endpointslices")[0]) == 11
        for i in range(2, 11):
            store.delete("pods", f"default/w{i:02d}")
        ctl.reconcile_once()
        slices, _ = store.list("endpointslices")
        assert sorted(s.metadata.name for s in slices) == ["web-0", "web-1"]
        assert sum(len(s.endpoints) for s in slices) == 2


class TestDisruptionController:
    """Mirrors pkg/controller/disruption trySync: disruptionsAllowed =
    max(0, currentHealthy - desiredHealthy)."""

    def _setup(self):
        from kubernetes_tpu.controllers import DisruptionController

        store = APIStore()
        ctl = DisruptionController(store, clock=FakeClock())
        ctl.sync_all()
        return store, ctl

    def _pdb(self, store, name="pdb", min_available=None, max_unavailable=None,
             labels=None):
        from kubernetes_tpu.api.policy import PodDisruptionBudget
        from kubernetes_tpu.api.labels import Selector
        from kubernetes_tpu.api.types import ObjectMeta

        store.create("poddisruptionbudgets", PodDisruptionBudget(
            metadata=ObjectMeta(name=name, namespace="default"),
            selector=Selector.from_match_labels(labels or {"app": "web"}),
            min_available=min_available, max_unavailable=max_unavailable))

    def test_min_available_absolute(self):
        store, ctl = self._setup()
        for i in range(5):
            store.create("pods", MakePod(f"w{i}").labels({"app": "web"})
                         .node("n1").obj())
        self._pdb(store, min_available=3)
        ctl.reconcile_once()
        pdb = store.get("poddisruptionbudgets", "default/pdb")
        assert pdb.disruptions_allowed == 2

    def test_max_unavailable_percent(self):
        store, ctl = self._setup()
        for i in range(10):
            store.create("pods", MakePod(f"w{i}").labels({"app": "web"})
                         .node("n1").obj())
        self._pdb(store, max_unavailable="20%")
        ctl.reconcile_once()
        # desired = 10 - ceil(20% of 10) = 8 -> allowed 2
        assert store.get("poddisruptionbudgets", "default/pdb").disruptions_allowed == 2

    def test_unbound_pods_not_healthy(self):
        store, ctl = self._setup()
        for i in range(3):
            store.create("pods", MakePod(f"w{i}").labels({"app": "web"}).obj())
        self._pdb(store, min_available=1)
        ctl.reconcile_once()
        # 0 healthy (none bound): allowed stays 0
        assert store.get("poddisruptionbudgets", "default/pdb").disruptions_allowed == 0

    def test_pod_events_retrigger(self):
        store, ctl = self._setup()
        self._pdb(store, min_available=1)
        ctl.reconcile_once()
        assert store.get("poddisruptionbudgets", "default/pdb").disruptions_allowed == 0
        for i in range(2):
            store.create("pods", MakePod(f"w{i}").labels({"app": "web"})
                         .node("n1").obj())
        ctl.reconcile_once()
        assert store.get("poddisruptionbudgets", "default/pdb").disruptions_allowed == 1


class TestTaintEviction:
    def _setup(self):
        store = APIStore()
        clock = FakeClock(start=100.0)
        store.create("nodes", MakeNode("n1").obj())
        ctl = TaintEvictionController(store, clock=clock)
        ctl.sync_all()
        return store, clock, ctl

    def _taint_node(self, store):
        def mutate(n):
            n.spec.taints.append(Taint(key="node.kubernetes.io/unreachable",
                                       effect="NoExecute"))
            return n

        store.guaranteed_update("nodes", "n1", mutate)

    def test_untolerated_pod_evicted_immediately(self):
        store, clock, ctl = self._setup()
        store.create("pods", MakePod("p").node("n1").obj())
        self._taint_node(store)
        ctl.reconcile_once()
        with pytest.raises(NotFoundError):
            store.get("pods", "default/p")

    def test_toleration_seconds_delays_eviction(self):
        store, clock, ctl = self._setup()
        pod = MakePod("p").node("n1").toleration(
            "node.kubernetes.io/unreachable", operator="Exists",
            effect="NoExecute").obj()
        pod.spec.tolerations[0] = type(pod.spec.tolerations[0])(
            key="node.kubernetes.io/unreachable", operator="Exists",
            effect="NoExecute", toleration_seconds=30)
        store.create("pods", pod)
        self._taint_node(store)
        ctl.reconcile_once()
        assert store.get("pods", "default/p")  # still tolerated
        clock.step(31)
        ctl.tick()
        with pytest.raises(NotFoundError):
            store.get("pods", "default/p")

    def test_forever_toleration_never_evicts(self):
        store, clock, ctl = self._setup()
        pod = MakePod("p").node("n1").toleration(
            "node.kubernetes.io/unreachable", operator="Exists",
            effect="NoExecute").obj()
        store.create("pods", pod)
        self._taint_node(store)
        ctl.reconcile_once()
        clock.step(10_000)
        ctl.tick()
        assert store.get("pods", "default/p")

    def test_additional_taint_tightens_deadline(self):
        # a second NoExecute taint with smaller tolerationSeconds must replace
        # the stale longer deadline (tainteviction timed-worker semantics)
        store, clock, ctl = self._setup()
        pod = MakePod("p").node("n1").obj()
        T = Taint
        from kubernetes_tpu.api.types import Toleration

        pod.spec.tolerations = [
            Toleration(key="node.kubernetes.io/unreachable", operator="Exists",
                       effect="NoExecute", toleration_seconds=600),
            Toleration(key="node.kubernetes.io/memory-pressure", operator="Exists",
                       effect="NoExecute", toleration_seconds=5),
        ]
        store.create("pods", pod)
        self._taint_node(store)  # unreachable: 600s countdown
        ctl.reconcile_once()
        assert store.get("pods", "default/p")

        def add_second(n):
            n.spec.taints.append(T(key="node.kubernetes.io/memory-pressure",
                                   effect="NoExecute"))
            return n

        store.guaranteed_update("nodes", "n1", add_second)
        ctl.reconcile_once()
        clock.step(6)  # past the tightened 5s deadline, far before 600s
        ctl.tick()
        with pytest.raises(NotFoundError):
            store.get("pods", "default/p")

    def test_removing_tight_taint_restores_longer_deadline(self):
        # inverse of the tighten case: dropping the 5s taint while the 600s
        # taint remains must reschedule on the longer deadline
        store, clock, ctl = self._setup()
        pod = MakePod("p").node("n1").obj()
        from kubernetes_tpu.api.types import Toleration

        pod.spec.tolerations = [
            Toleration(key="node.kubernetes.io/unreachable", operator="Exists",
                       effect="NoExecute", toleration_seconds=600),
            Toleration(key="node.kubernetes.io/memory-pressure", operator="Exists",
                       effect="NoExecute", toleration_seconds=5),
        ]
        store.create("pods", pod)

        def add_both(n):
            n.spec.taints = [
                Taint(key="node.kubernetes.io/unreachable", effect="NoExecute"),
                Taint(key="node.kubernetes.io/memory-pressure", effect="NoExecute"),
            ]
            return n

        store.guaranteed_update("nodes", "n1", add_both)
        ctl.reconcile_once()

        def drop_tight(n):
            n.spec.taints = [t for t in n.spec.taints
                             if t.key == "node.kubernetes.io/unreachable"]
            return n

        store.guaranteed_update("nodes", "n1", drop_tight)
        ctl.reconcile_once()
        clock.step(10)  # past the stale 5s deadline
        ctl.tick()
        assert store.get("pods", "default/p")  # survives on the 600s countdown
        clock.step(600)
        ctl.tick()
        with pytest.raises(NotFoundError):
            store.get("pods", "default/p")

    def test_taint_removed_cancels_pending_eviction(self):
        store, clock, ctl = self._setup()
        pod = MakePod("p").node("n1").obj()
        pod.spec.tolerations = [type(pod.spec.tolerations[0] if pod.spec.tolerations
                                     else __import__("kubernetes_tpu.api.types",
                                                     fromlist=["Toleration"]).Toleration())(
            key="node.kubernetes.io/unreachable", operator="Exists",
            effect="NoExecute", toleration_seconds=60)]
        store.create("pods", pod)
        self._taint_node(store)
        ctl.reconcile_once()

        def clear(n):
            n.spec.taints = []
            return n

        store.guaranteed_update("nodes", "n1", clear)
        ctl.reconcile_once()
        clock.step(120)
        ctl.tick()
        assert store.get("pods", "default/p")


class TestHPA:
    def _setup(self, target=50, minr=1, maxr=10):
        store = APIStore()
        clock = FakeClock(start=1000.0)
        rs = ReplicaSet.from_dict({
            "metadata": {"name": "web"},
            "spec": {"replicas": 2,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}},
        })
        rs.metadata.uid = new_uid()
        store.create("replicasets", rs)
        hpa = HorizontalPodAutoscaler.from_dict({
            "metadata": {"name": "web"},
            "spec": {"scaleTargetRef": {"kind": "ReplicaSet", "name": "web"},
                     "minReplicas": minr, "maxReplicas": maxr,
                     "targetCPUUtilizationPercentage": target},
        })
        store.create("horizontalpodautoscalers", hpa)
        ctl = HorizontalPodAutoscalerController(store, clock=clock,
                                                downscale_stabilization=300)
        ctl.sync_all()
        return store, clock, ctl

    def _add_pod(self, store, name, request="1", usage_milli=500):
        pod = (MakePod(name).labels({"app": "web"}).req({"cpu": request})
               .node("n1").phase("Running").obj())
        pod.metadata.annotations["metrics.k8s.io/cpu-usage"] = f"{usage_milli}m"
        store.create("pods", pod)

    def test_scale_up_on_high_utilization(self):
        store, clock, ctl = self._setup(target=50)
        self._add_pod(store, "w0", usage_milli=900)  # 90% of 1 cpu, target 50%
        self._add_pod(store, "w1", usage_milli=900)
        ctl.resync()
        rs = store.get("replicasets", "default/web")
        assert rs.spec.replicas == 4  # ceil(2 * 0.9/0.5)
        hpa = store.get("horizontalpodautoscalers", "default/web")
        assert hpa.desired_replicas == 4

    def test_within_tolerance_no_change(self):
        store, clock, ctl = self._setup(target=50)
        self._add_pod(store, "w0", usage_milli=520)
        self._add_pod(store, "w1", usage_milli=480)
        ctl.resync()
        assert store.get("replicasets", "default/web").spec.replicas == 2

    def test_scale_down_stabilization(self):
        store, clock, ctl = self._setup(target=50)
        self._add_pod(store, "w0", usage_milli=100)
        self._add_pod(store, "w1", usage_milli=100)

        def stamp(h):
            h.last_scale_time = clock.now()
            return h

        store.guaranteed_update("horizontalpodautoscalers", "default/web", stamp)
        ctl.resync()
        assert store.get("replicasets", "default/web").spec.replicas == 2  # held
        clock.step(301)
        ctl.resync()
        assert store.get("replicasets", "default/web").spec.replicas == 1

    def test_bounded_by_max(self):
        store, clock, ctl = self._setup(target=10, maxr=3)
        self._add_pod(store, "w0", usage_milli=1000)
        self._add_pod(store, "w1", usage_milli=1000)
        ctl.resync()
        assert store.get("replicasets", "default/web").spec.replicas == 3


class TestHPAEndToEnd:
    def test_hpa_drives_replicaset_controller(self):
        """HPA scales the ReplicaSet spec; the RS controller materializes pods."""
        store = APIStore()
        clock = FakeClock(start=0.0)
        rs = ReplicaSet.from_dict({
            "metadata": {"name": "web"},
            "spec": {"replicas": 1,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}},
        })
        rs.metadata.uid = new_uid()
        store.create("replicasets", rs)
        store.create("horizontalpodautoscalers", HorizontalPodAutoscaler.from_dict({
            "metadata": {"name": "web"},
            "spec": {"scaleTargetRef": {"kind": "ReplicaSet", "name": "web"},
                     "maxReplicas": 5, "targetCPUUtilizationPercentage": 50},
        }))
        rs_ctl = ReplicaSetController(store, clock=clock)
        hpa_ctl = HorizontalPodAutoscalerController(store, clock=clock)
        rs_ctl.sync_all()
        hpa_ctl.sync_all()
        rs_ctl.process()
        pods, _ = store.list("pods")
        assert len(pods) == 1

        def hot(p):
            p.metadata.annotations["metrics.k8s.io/cpu-usage"] = "1000m"
            p.spec.containers[0].resources = {"requests": {"cpu": "1"}}
            p.status.phase = "Running"
            return p

        store.guaranteed_update("pods", pods[0].key, hot)
        hpa_ctl.resync()
        rs_ctl.reconcile_once()
        assert len(store.list("pods")[0]) == 2  # ceil(1 * 100%/50%) = 2
