"""bench.py --quick: the tier-1 perf smoke — runs in <=60s on the CPU
backend and emits one parseable JSON line on stdout, so a regression in the
batched host pipeline (coalesced ingest, bulk admission, bind path) is
caught without the full ladder."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_quick_runs_and_emits_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the conftest autouse fixture arms STORE_LOCK_ORDER_CHECK for every
    # in-process test store; the bench subprocess must measure the
    # PRODUCTION lock configuration, not the debug wrapper
    env.pop("STORE_LOCK_ORDER_CHECK", None)
    env.pop("CACHE_MUTATION_DETECTOR", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    # stdout is exactly one JSON object (the last non-empty line)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert lines, proc.stderr[-2000:]
    out = json.loads(lines[-1])
    assert out.get("quick") is True
    assert out["unit"] == "pods/s"
    workloads = out["workloads"]
    # the quick ladder covers the host pipeline end-to-end
    assert "NorthStar_100k_10k_endtoend" in workloads
    ns = workloads["NorthStar_100k_10k_endtoend"]
    assert "error" not in ns, ns
    assert ns["placed"] == ns["pods"] > 0
    assert ns["pods_per_sec"] > 0
    # the flight-recorder stage breakdown (ISSUE 3): generated, present, and
    # consistent — the serial (non-overlapped) stages must approximately
    # explain the reported wall time (generous band: the harness co-schedules
    # other work on a 2-core rig), and the recorder's measured self-time must
    # stay under the 2% instrumentation budget
    stages = ns["stages"]
    assert stages and all(v >= 0 for v in stages.values()), stages
    assert "solve" in stages and "ingest" in stages
    wall = ns["wall_s"]
    serial_sum = ns["stages_serial_sum_s"]
    assert 0.3 * wall <= serial_sum <= 1.2 * wall, (serial_sum, wall, stages)
    # 2% of wall with a 2ms ABSOLUTE floor: the quick rung's wall shrank
    # with the native commit engine (ISSUE 11) to the point where the
    # recorder's fixed sub-1ms per-run cost was ~1.6% of wall — one
    # co-scheduling hiccup away from failing on cost that doesn't scale
    # with the run (production-size walls never hit the floor)
    assert ns["instrumentation_s"] <= max(0.02 * wall, 0.002), (
        ns["instrumentation_s"], wall)
    # pod-latency observability (ISSUE 7): the rung emits per-stage p50/p99
    # and an all-pods submit->bound distribution, and the declarative SLO
    # gate (scheduler/slo.py NORTH_STAR_SLO) passes — tails are now gated,
    # not just throughput
    assert ns["stages_p99_ms"].get("solve", 0) > 0, ns["stages_p99_ms"]
    p50 = ns["stages_p50_ms"].get("solve")
    p99 = ns["stages_p99_ms"].get("solve")
    assert p50 is not None and p99 >= p50, (p50, p99)
    lat = ns["latency"]
    # every bound pod is observed exactly once (batch-boundary timestamps)
    assert lat["count"] == ns["pods"], lat
    assert lat["p50_s"] > 0 and lat["p99_s"] >= lat["p50_s"], lat
    slo = ns["slo"]
    assert slo["pass"] is True, slo
    # the out-of-band checks really ran (not silently skipped)
    assert "solver_compiles" not in slo["skipped"], slo
    assert "instrumentation_frac" not in slo["skipped"], slo
    # watch-propagation columns (ISSUE 9): the rung publishes the scheduler
    # subscriber's commit->dequeue distribution — the coalesced fast path
    # must be counted (the NorthStar ingest IS that path), and the
    # instrumentation budget asserted above now includes the watch-tap
    # settlement billed through the Watch stat_sink
    wcol = ns["watch"]
    assert wcol["propagation_count"] >= ns["pods"], wcol
    assert wcol["propagation_p99_s"] is not None, wcol
    assert wcol["propagation_p99_s"] >= (wcol["propagation_p50_s"] or 0), wcol
    assert wcol["subscribers"] >= 1, wcol
    # controller-reconcile column: uniform schema (no controllers run here)
    assert "reconcile" in ns, ns.keys()
    # sampled lifecycle spans: the tracer sampled pods and completed every
    # span it kept (all pods bound in this rung)
    tr = ns["trace"]
    assert tr["spans"] > 0 and tr["complete"] == tr["spans"], tr
    # the NorthStar_1M soak rung (ISSUE 13): steady-state churn gated by
    # the WINDOWED SLOs — per-window stage p99 ceilings, RSS + live-object
    # slope, p99 drift — with zero post-warmup recompiles and the trend
    # checks REAL (enough windows for a slope), not skipped
    soak = workloads["NorthStar_1M"]
    assert "error" not in soak, soak
    assert soak["soak_ok"] is True, soak["slo"]
    assert soak["slo"]["pass"] is True, soak["slo"]
    assert soak["windows"] >= 8, soak
    assert soak["pods"] > 0 and soak["pods_per_sec"] > 0
    assert soak["solver_compiles_during_run"] == 0, soak
    checked = {c["name"] for c in soak["slo"]["checks"] if c["ok"] is True}
    assert {"rss_slope_mb_per_min", "alloc_block_slope_per_s",
            "p99_drift_ratio"} <= checked, soak["slo"]
    # sampler + time-series overhead inside the <2% budget, from a
    # MEASUREMENT (the instrumentation_frac check really ran)
    assert "instrumentation_frac" not in soak["slo"]["skipped"], soak["slo"]
    assert soak["instrumentation_frac"] <= 0.02, soak
    assert soak["sampler_overhead_frac"] <= 0.02, soak
    # the honesty flags: the per-thread clock source + its MEASURED tick
    # are published beside the attribution columns
    assert soak["clock_source"] in ("clockid", "schedstat",
                                    "unavailable"), soak
    res = soak["resource"]
    assert res["rss_mb"] > 0 and res["samples"] > 0, res
    assert "thread_cpu_s" in res and "overlap_cpu_s" in res, res
    # rig honesty columns (ISSUE 13 satellite): EVERY successful rung
    # carries the cores + cgroup quota it ran under
    rig = out["rig"]
    assert rig["cores"] >= 1, rig
    for name, w in workloads.items():
        if isinstance(w, dict) and "error" not in w:
            assert "cores" in w and "cpu_quota" in w, (name, w.keys())
            assert w["cores"] == rig["cores"], (name, w["cores"])
    basic = workloads.get("SchedulingBasic", {})
    assert "error" not in basic, basic
    # the bind-commit micro-rung (ISSUE 4): pods/s through store.bind_many
    # alone — a regression in the clone-free lazy-event commit path (or the
    # sharded lock) fails loudly here without the full ladder
    bc = workloads["BindCommit_20k"]
    assert "error" not in bc, bc
    assert bc["placed"] == bc["pods"] > 0
    assert bc["pods_per_sec"] > 0
    # the native commit engine column (ISSUE 11): python-vs-native us/pod
    # published side by side. On a rig with g++ the native engine must have
    # actually loaded and run (us_per_pod_native real); without one the
    # python column still publishes and `available` says why
    nat = bc["native"]
    assert nat["us_per_pod_python"] > 0, bc
    if nat["available"]:
        assert nat["us_per_pod_native"] > 0, bc
    else:
        assert nat["us_per_pod_native"] is None, bc
    # the columnar pod-row store column (ISSUE 15): dict-vs-columnar µs/pod
    # as a SAME-BOX interleaved A/B with the r12 honesty flags (cores/
    # cpu_quota/ab_comparable published IN the column — rig core counts
    # vary across the BENCH series, so only same-box pairs may be compared)
    col = bc["columnar"]
    assert {"available", "us_per_pod_dict", "us_per_pod_columnar",
            "speedup", "cores", "cpu_quota", "ab_comparable"} <= set(col), bc
    assert col["us_per_pod_dict"] > 0, bc
    if col["available"]:
        assert col["us_per_pod_columnar"] > 0 and col["speedup"] > 0, bc
        assert col["ab_comparable"] is True, bc
    else:
        assert col["us_per_pod_columnar"] is None, bc
    # the per-stage columnar A/B rung (ISSUE 16): all four rewritten stages
    # publish same-box interleaved columnar-vs-object columns with the rig
    # honesty flags; each stage's pair must be real (measured, not None)
    ss = workloads["SchedStages_8k"]
    assert "error" not in ss, ss
    assert ss["ab_comparable"] is True and "cores" in ss, ss
    assert set(ss["stages"]) == {"build_pod_batch", "assume", "tensorize",
                                 "dispatch"}, ss
    for stage, row in ss["stages"].items():
        vals = [v for k, v in row.items() if k != "speedup"]
        assert all(v is not None and v >= 0 for v in vals), (stage, row)
        assert row["speedup"] is not None and row["speedup"] > 0, (stage, row)
    # the ISSUE 16 acceptance gauge: the timed end-to-end window builds
    # ZERO per-pod Python objects — placements live as cache rows (the row
    # path demonstrably engaged) and neither columnar table materialized
    assert ns["pod_obj_allocs"] == 0, ns
    assert ns["cache_rows"] > 0, ns
    # the soak publishes the per-window gauge distribution (full churn
    # materializes drained victims by the DELETED-event contract, so the
    # column is informational there, never gated on zero)
    assert "pod_obj_allocs" in workloads["NorthStar_1M"], \
        workloads["NorthStar_1M"].keys()
    # the gang rung (ISSUE 2): every member of every gang binds, all-or-
    # nothing never fires on the happy path
    gang = workloads["GangScheduling_2k_250"]
    assert "error" not in gang, gang
    assert gang["placed"] == gang["pods"] > 0
    assert gang["gangs"] == 8
    assert gang["pods_per_sec"] > 0
    # ISSUE 14: the adjacency placement-quality column — rank-aligned gang
    # members are measurably MORE adjacent (smaller mean neighbor ring
    # distance) than the rank-blind baseline on the same workload
    adj = gang["adjacency"]
    assert adj["placed_rank_blind"] == gang["pods"], adj
    assert adj["mean_neighbor_distance"] is not None, adj
    assert adj["mean_neighbor_distance_rank_blind"] is not None, adj
    assert (adj["mean_neighbor_distance"]
            < adj["mean_neighbor_distance_rank_blind"]), adj
    # the gang-preemption rung (ISSUE 14): a parked gang with feasible
    # lower-priority victims is placed WHOLE via a min-cost victim cover
    # (bounded wall, conservation clean, zero mid-run compiles), and a gang
    # with only partial room is vetoed with a narrated event and ZERO
    # evictions
    gpre = workloads["GangPreemption"]
    assert "error" not in gpre, gpre
    assert gpre["preempt_ok"] is True, gpre
    assert gpre["placed"] == gpre["pods"] > 0, gpre
    assert 1 <= gpre["victims"] < 16, gpre
    assert gpre["slices_ripped"] == 1, gpre
    assert gpre["conservation_ok"] is True, gpre
    assert gpre["solver_compiles_during_run"] == 0, gpre
    assert gpre["vetoed_partial"] >= 1, gpre
    assert gpre["veto_evictions"] == 0, gpre
    assert gpre["veto_narrated"] >= 1, gpre
    assert gpre["adjacency_mean_neighbor_distance"] is not None, gpre
    # the partitioned scheduler (ISSUE 12): the quick A/B rung's CORRECTNESS
    # columns are tier-1-gated — conservation, zero mid-run compiles, per-
    # partition rows, dispatch-layer counters. The SPEEDUP column is
    # published, never gated here: the A/B is a concurrency claim and a
    # co-scheduled (possibly 1-core) CI box measures overhead, not overlap
    # (the `cores`/`ab_comparable` columns say which one you got)
    px = workloads["Partitioned_2x"]
    assert "error" not in px, px
    assert px["conservation_ok"] is True, px
    assert px["conservation"]["lost"] == 0, px
    assert px["conservation"]["double_bound"] == 0, px
    assert px["placed"] == px["pods"] > 0
    assert px["solver_compiles_during_run"] == 0, px
    assert len(px["per_partition"]) == 2, px
    assert sum(r["nodes"] for r in px["per_partition"]) == px["nodes"], px
    assert px["speedup_vs_1p"] > 0 and px["pods_per_sec_1p"] > 0, px
    assert isinstance(px["ab_comparable"], bool), px
    # ISSUE 19: the >=2-core re-judge — on a judged rig the concurrency
    # verdict comes from MEASURED overlap_cpu_s, not wall ratios; on a
    # 1-core rig both columns honestly say "not judged"
    assert "overlap_cpu_s" in px and "concurrency_verdict" in px, px
    if px["ab_comparable"]:
        assert px["overlap_cpu_s"] is not None, px
        assert px["concurrency_verdict"] in ("parallel", "serialized"), px
    else:
        assert px["concurrency_verdict"] is None, px
    # the NorthStar A/B column: same-box 1p-vs-2p, zero mid-run compiles
    # per partition, every pod bound through the partitioned path too
    nsp = ns["partitioned"]
    assert "error" not in nsp, nsp
    assert nsp["placed_2p"] == ns["pods"], nsp
    assert nsp["solver_compiles_during_run"] == 0, nsp
    assert len(nsp["per_partition"]) == 2, nsp
    # ISSUE 19: the measured-overlap columns ride the NorthStar A/B too
    assert "overlap_cpu_s" in nsp and "concurrency_verdict" in nsp, nsp
    # the jit-retrace guard (ISSUE 5): the end-to-end rung's timed window
    # must compile NOTHING — the warm-up covered every bucket, so a nonzero
    # count here is retrace churn (the JT001 bug class, tens of seconds per
    # compile at TPU scale)
    assert ns["solver_compiles_during_run"] == 0, ns["jit_cache"]
    assert ns["jit_cache"].get("waterfill_group", 0) >= 1, ns["jit_cache"]
    # the chaos-churn rung (ISSUE 6): pod conservation under injected solver
    # faults, transient bind faults, and a mid-run bind-worker kill — every
    # submitted pod bound, 0 lost, 0 double-bound; the solver circuit
    # breaker demonstrably TRIPPED to the scan oracle and RECOVERED to the
    # fast solver; the killed worker was detected and restarted
    cc = workloads["ChaosChurn_20k"]
    assert "error" not in cc, cc
    assert cc["conservation_ok"] is True, cc
    assert cc["conservation"]["lost"] == 0, cc
    assert cc["conservation"]["double_bound"] == 0, cc
    assert cc["placed"] == cc["pods"] > 0
    assert cc["breaker_trips"] >= 1 and cc["breaker_recoveries"] >= 1, cc
    assert cc["breaker_state"] == "closed", cc
    assert cc["bind_worker_restarts"] >= 1, cc
    assert cc["resynced"] is True, cc
    # ISSUE 11: on a native-capable rig the chaos run must have injected
    # mid-chunk NATIVE commit faults (the native.commit site) and still
    # conserved every pod — the assertion above (lost == 0) covers both legs
    if cc["native_commit"]:
        assert cc["native_commit_faults"] >= 1, cc
    # ISSUE 12: the partition hard-kill leg — one of two partitions killed
    # mid-run by the partition.dispatch chaos site; the survivor absorbed
    # the dead shard (router remap + resync) and every pod is conserved
    pk = cc["partition_kill"]
    assert "error" not in pk, pk
    assert pk["ok"] is True, pk
    assert pk["bound"] == pk["pods"] > 0, pk
    assert pk["lost"] == 0 and pk["double_bound"] == 0, pk
    assert pk["partitions_absorbed"] == 1, pk
    # ISSUE 14: the gang-preemption chaos leg — a victim cover under
    # injected bind/native.commit faults + a mid-run worker kill; the gang
    # lands WHOLE (never half-evicted or half-bound), conservation clean
    gcc = cc["gang_preemption"]
    assert "error" not in gcc, gcc
    assert gcc["ok"] is True, gcc
    assert gcc["bound"] == gcc["pods"] > 0, gcc
    assert gcc["lost"] == 0 and gcc["double_bound"] == 0, gcc
    assert gcc["preempted"] >= 1, gcc
    # ISSUE 19: the mp worker-kill leg — worker process 1 SIGKILLed by the
    # process.worker chaos site mid-run; the supervisor detected the death,
    # respawned the slot, resynced the estate, and every pod is conserved
    # (the dead worker's in-flight intents died with its queue, anything
    # already submitted fell to rv re-validation / bind-conflict absorption)
    mpk = cc["mp_worker_kill"]
    if "skipped" not in mpk:
        assert "error" not in mpk, mpk
        assert mpk["ok"] is True, mpk
        assert mpk["bound"] == mpk["pods"] > 0, mpk
        assert mpk["lost"] == 0 and mpk["double_bound"] == 0, mpk
        assert mpk["worker_restarts"] >= 1, mpk
    # ISSUE 7: the breaker trip shows as a BOUNDED p99 excursion in the
    # trace (the faulted/backoff pods are the tail, under the chaos SLO
    # ceiling) while every sampled span still completed — chaos must be
    # visible in the latency distribution, never break the tracer
    assert cc["trace_ok"] is True, cc
    assert cc["trace"]["spans"] > 0, cc
    assert cc["trace"]["complete"] == cc["trace"]["spans"], cc
    # ISSUE 19 tentpole rung: two worker PROCESSES solving over shm column
    # shards, bind intents arbitrated by the owner — every pod conserved,
    # zero mid-run compiles in the owner, and every named /dev/shm segment
    # unlinked on stop (the MP002 contract, enforced at runtime)
    mp = workloads["MultiProcess_2w"]
    if "skipped" not in mp:
        assert "error" not in mp, mp
        assert mp["conservation_ok"] is True, mp
        assert mp["placed"] == mp["pods"] > 0, mp
        assert mp["processes"] == 2, mp
        assert mp["rounds"] >= 1, mp
        assert mp["solver_compiles_during_run"] == 0, mp
        assert mp["shm_unlink_clean"] is True, mp
        assert mp["shm_leaked_segments"] == [], mp
        assert len(mp["per_worker"]) == 2, mp
        assert sum(w["binds"] for w in mp["per_worker"]) > 0, mp
        # the verdict column is honest: judged only on a >=2-core rig
        assert "concurrency_verdict" in mp, mp
        if mp["cores"] < 2:
            assert mp["concurrency_verdict"] is None, mp
    # ISSUE 19 satellite: watch fan-out at scale — the subscriber sweep
    # must hold the propagation-p99 SLO at every point, with the ring
    # eviction path genuinely exercised (slow ring consumers evict, never
    # stall the store's mutation path)
    wf = workloads["WatchFanout"]
    if "skipped" not in wf:
        assert "error" not in wf, wf
        assert wf["slo_ok"] is True, wf
        assert wf["max_p99_s"] <= wf["slo_s"], wf
        assert len(wf["points"]) == 3, wf
        for pt in wf["points"]:
            assert pt["slo_ok"] is True, pt
            assert pt["deliveries"] > 0, pt
        assert any(pt["ring_dropped"] > 0 or pt["evicted"] > 0
                   for pt in wf["points"]), wf["points"]
    assert cc["latency"]["count"] > 0, cc
    assert cc["latency"]["p99_s"] >= cc["latency"]["p50_s"] > 0, cc
    assert cc["slo"]["pass"] is True, cc
    # the chaos rung publishes watch-propagation columns too (ISSUE 9):
    # injected watch.deliver drops are counted, delivered events measured
    assert cc["watch"]["propagation_count"] > 0, cc["watch"]
    # the control-plane flight recorder rung (ISSUE 9): deployment rollout
    # + node drain + eviction/replace driven through the controllers and
    # hollow kubelets, gated by the new SLO keys — BOTH must be real PASS
    # verdicts (present, not skipped), the drain must actually evict, the
    # evict->replace span chains must link and complete, and submit->running
    # spans must cover the kubelet tail
    cp = workloads["ControlPlane_churn"]
    assert "error" not in cp, cp
    assert cp["controlplane_ok"] is True, cp
    assert cp["slo"]["pass"] is True, cp
    assert cp["slo"]["skipped"] == [], cp["slo"]
    checked = {c["name"] for c in cp["slo"]["checks"] if c["ok"] is True}
    assert {"watch_propagation_p99_s", "reconcile_p99_ms"} <= checked, cp["slo"]
    assert cp["evicted_from_drain"] > 0, cp
    assert cp["trace"]["evict_replace_chains"] >= 1, cp["trace"]
    assert cp["trace"]["chains_complete"] == \
        cp["trace"]["evict_replace_chains"], cp["trace"]
    assert cp["trace"]["running_spans"] > 0, cp["trace"]
    assert cp["watch"]["propagation_count"] > 0, cp["watch"]
    assert cp["reconcile"]["p99_ms"] is not None, cp["reconcile"]
    assert cp["reconcile"]["errors"] == 0, cp["reconcile"]
    assert len(cp["controllers"]) == 3, cp["controllers"]
    # injector-DISABLED overhead budget (<1% on the NorthStar rung): the
    # rung measures the per-check cost of the disabled guard directly; the
    # NorthStar path runs a handful of checks per BATCH/chunk/delivery,
    # bounded far above reality at 4 per pod — even that must cost <1% of
    # the measured per-pod budget
    per_pod_s = ns["wall_s"] / ns["pods"]
    assert cc["disabled_check_ns"] * 4 * 1e-9 < 0.01 * per_pod_s, (
        cc["disabled_check_ns"], per_pod_s)
    # the schedlint rung (ISSUE 5): the static-analysis gate stays CLEAN
    # (zero unsuppressed findings over the shipped tree) and CHEAP — the
    # self-time budget keeps the tier-1 gate from quietly becoming the
    # slowest test in the tier
    sl = workloads["SchedLint_tree"]
    assert "error" not in sl, sl
    assert sl["findings"] == 0, sl
    assert sl["files"] > 100
    # ISSUE 20: the rung publishes its own hard budget and the
    # interprocedural closure shape — wall time must fit the published
    # budget, the resolved call graph must be substantial (a resolver
    # regression collapsing it to ~nothing would silently blind LK002/
    # HP001/MP001/AL001's via-chain forms), and some rule must actually
    # have walked a multi-level chain
    assert sl["budget_s"] == 15.0, sl
    assert sl["wall_s"] <= sl["budget_s"], sl
    assert sl["callgraph_edges"] > 500, sl
    assert sl["resolve_depth"] >= 2, sl
    # the defrag rung (ISSUE 17): the rebalancer A/B — on the churn-smeared
    # cluster the SAME gang admits with ZERO preemptions and lower latency
    # once the background rebalancer has consolidated the fillers, the
    # migration budget is audited per cycle, conservation holds through the
    # victim->replacement migration chain, the windowed SLO verdict passes
    # on BOTH legs, and the timed window compiles nothing (the defrag
    # kernel's pow2 buckets were covered by the warm-up leg)
    df = workloads["Defrag"]
    assert "error" not in df, df
    assert df["defrag_ok"] is True, df
    assert df["preemptions_on"] == 0 < df["preemptions_off"], df
    assert df["latency_improved"] is True, df
    assert df["migrations"] > 0, df
    assert df["migrations"] <= df["budget_per_cycle"] * max(df["waves"], 1), df
    assert df["budget_ok"] is True, df
    assert df["frag_after"] < 0.25 <= df["frag_before"], df
    assert df["conservation_ok"] is True, df
    assert df["conservation_on"]["lost"] == 0, df
    assert df["conservation_on"]["double_bound"] == 0, df
    assert df["slo_pass_on"] is True and df["slo_pass_off"] is True, df
    assert df["solver_compiles_during_run"] == 0, df
    assert df["ab_comparable"] is True, df
    # the trace-timeline rung (ISSUE 18): the smoke window captured with the
    # trace buffer ARMED exports a valid Chrome trace (B/E balanced,
    # monotonic per tid — validate_export's contract), the two partitioned
    # pipelines land on DISTINCT tracks, the evict->replace leg yields real
    # flow arrows, and the critical-path decomposition names a dominant
    # component whose p50/p99 sums sit within the 10% acceptance band of
    # the measured submit->bound quantiles
    tt = workloads["TraceTimeline"]
    assert "error" not in tt, tt
    assert tt["export_valid"] is True, tt["export_errors"]
    assert tt["events"] > 0 and tt["dropped"] == 0, tt
    assert tt["partition_tracks"] >= 2, tt
    assert tt["flow_arrows"] >= 1, tt
    assert tt["placed"] == tt["pods"] > 0, tt
    ttc = tt["critpath"]
    assert ttc["spans"] > 0, tt
    assert ttc["dominant"] in ("queue_wait", "build", "solve", "assume",
                               "dispatch", "bind"), ttc
    assert ttc["sum_p50_ms"] <= ttc["total_p50_ms"] * 1.10 + 0.5, ttc
    assert ttc["sum_p50_ms"] >= ttc["total_p50_ms"] * 0.90 - 0.5, ttc
    assert ttc["sum_p99_ms"] <= ttc["total_p99_ms"] * 1.10 + 0.5, ttc
    assert ttc["sum_p99_ms"] >= ttc["total_p99_ms"] * 0.90 - 0.5, ttc
    # the ARMED overhead budget (<1% of wall, 2ms absolute floor — same
    # floor discipline as the recorder assertion above), from a MEASUREMENT:
    # the buffer's accumulated tap self-time over the timed window, beside
    # the measured disabled-guard cost (one module-attribute check)
    assert tt["instrumentation_s"] <= max(0.01 * tt["wall_s"], 0.002), tt
    assert 0 < tt["disabled_check_ns"] < 10_000, tt
