"""Leader-elected control plane: failover, single-writer, live PDB status.

reference: cmd/kube-scheduler/app/server.go:281 (only the elected instance
runs the scheduling loop), kube-controller-manager election, and
pkg/controller/disruption (PDB status reconciliation).
"""

import time

from kubernetes_tpu.server.controlplane import ControlPlane
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod


def _wait(pred, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _mk_cp(store, ident):
    return ControlPlane(
        store, identity=ident, use_batch_scheduler=False,
        controllers=("replicaset", "deployment", "disruption"),
        lease_duration=0.6, renew_deadline=0.4, retry_period=0.05)


class TestControlPlane:
    def test_single_leader_schedules(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "50"}).obj())
        cp1 = _mk_cp(store, "cp-1").start()
        assert _wait(lambda: cp1.is_leader, 5)
        cp2 = _mk_cp(store, "cp-2").start()
        time.sleep(0.3)
        assert not cp2.is_leader
        assert cp2.scheduler is None  # standby runs nothing

        store.create("pods", MakePod("p0").req({"cpu": "1"}).obj())
        assert _wait(lambda: store.get("pods", "default/p0").spec.node_name != "")
        cp1.stop()
        cp2.stop()

    def test_failover_takes_over_and_no_double_binds(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "16", "memory": "32Gi", "pods": "100"}).obj())
        cp1 = _mk_cp(store, "cp-1").start()
        assert _wait(lambda: cp1.is_leader, 5)
        cp2 = _mk_cp(store, "cp-2").start()

        for i in range(5):
            store.create("pods", MakePod(f"pre-{i}").req({"cpu": "100m"}).obj())
        assert _wait(lambda: all(
            store.get("pods", f"default/pre-{i}").spec.node_name != ""
            for i in range(5)))

        # crash the leader: renewals stop and its components die mid-flight
        cp1.elector.try_acquire_or_renew = lambda: False
        cp1._stop_components()
        assert _wait(lambda: cp2.is_leader, 5), "standby did not take over"

        for i in range(5):
            store.create("pods", MakePod(f"post-{i}").req({"cpu": "100m"}).obj())
        assert _wait(lambda: all(
            store.get("pods", f"default/post-{i}").spec.node_name != ""
            for i in range(5))), "new leader is not scheduling"
        # every pod bound exactly once (store.bind would have raised on a
        # second write; verify all have a node and phase is consistent)
        pods, _ = store.list("pods")
        assert all(p.spec.node_name == "n0" for p in pods)
        cp1.stop()
        cp2.stop()

    def test_disruption_controller_updates_pdb_status(self):
        from kubernetes_tpu.api.policy import PodDisruptionBudget
        from kubernetes_tpu.api.types import ObjectMeta
        from kubernetes_tpu.api.labels import Selector

        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "50"}).obj())
        store.create("poddisruptionbudgets", PodDisruptionBudget(
            metadata=ObjectMeta(name="web-pdb", namespace="default"),
            selector=Selector.from_match_labels({"app": "web"}),
            min_available=1))
        cp = _mk_cp(store, "cp-1").start()
        assert _wait(lambda: cp.is_leader, 5)
        for i in range(3):
            store.create("pods", MakePod(f"w{i}").labels(
                {"app": "web"}).req({"cpu": "100m"}).obj())
        # live DisruptionController: 3 healthy pods, minAvailable 1 -> 2 allowed
        assert _wait(lambda: store.get(
            "poddisruptionbudgets", "default/web-pdb").disruptions_allowed == 2)
        cp.stop()


class TestRestartRecovery:
    """Chaos/restart tier (SURVEY.md §5): the scheduler is stateless — killed
    mid-workload, a fresh instance rebuilds cache+queue from LIST+WATCH and
    finishes the backlog without double-binding (reference: scheduler
    restart semantics, eventhandlers.go:364 + assumed-pod expiry)."""

    def test_scheduler_restart_mid_backlog(self):
        from kubernetes_tpu.scheduler import Framework
        from kubernetes_tpu.scheduler.batch import BatchScheduler
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.testing import MakePod

        store = APIStore()
        for i in range(4):
            store.create("nodes", MakeNode(f"n{i}").capacity(
                {"cpu": "16", "memory": "32Gi", "pods": "100"}).obj())
        s1 = BatchScheduler(store, Framework(default_plugins()),
                            batch_size=10, solver="exact")
        s1.sync()
        for i in range(60):
            store.create("pods", MakePod(f"r-{i}").req({"cpu": "100m"}).obj())
        # schedule part of the backlog, then "crash"
        s1.schedule_batch(timeout=0.0)
        s1.flush_binds()
        bound_before = sum(1 for p in store.list("pods")[0] if p.spec.node_name)
        assert 0 < bound_before < 60
        s1.stop()
        del s1

        s2 = BatchScheduler(store, Framework(default_plugins()),
                            batch_size=64, solver="exact")
        s2.sync()  # fresh LIST: bound pods -> cache, pending -> queue
        s2.run_until_idle()
        pods, _ = store.list("pods")
        assert sum(1 for p in pods if p.spec.node_name) == 60
        # no double binds: every bind after restart succeeded exactly once
        assert s2.scheduled_count == 60 - bound_before

    def test_hollow_node_restart_readopts(self):
        from kubernetes_tpu.agent import HollowCluster
        from kubernetes_tpu.scheduler import Framework
        from kubernetes_tpu.scheduler.serial import Scheduler
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.testing import MakePod

        store = APIStore()
        cluster = HollowCluster(store, n_nodes=2)
        cluster.register_all()
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        store.create("pods", MakePod("w").req({"cpu": "100m"}).obj())
        sched.run_until_idle()
        pod = store.get("pods", "default/w")
        assert pod.spec.node_name != ""
        # node agent restarts: a fresh kubelet adopts the bound pod
        from kubernetes_tpu.agent.hollow import HollowKubelet

        hk = HollowKubelet(store, pod.spec.node_name)
        hk.register()
        hk.pump()
        assert pod.key in hk.running_pods


class TestFailoverMidIndexedJob:
    def test_indexed_job_survives_failover_without_duplicate_indexes(self):
        """Leader dies while an Indexed Job is mid-flight: the standby's job
        controller must finish the remaining indexes WITHOUT double-creating
        pods for indexes that already succeeded or are active."""
        from kubernetes_tpu.api.workloads import Job
        from kubernetes_tpu.api.types import new_uid
        from kubernetes_tpu.controllers.job import pod_completion_index

        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "32", "memory": "64Gi", "pods": "100"}).obj())

        def mk(ident):
            return ControlPlane(
                store, identity=ident, use_batch_scheduler=False,
                controllers=("job",),
                lease_duration=0.6, renew_deadline=0.4, retry_period=0.05)

        cp1 = mk("cp-1").start()
        cp2 = None
        try:
            assert _wait(lambda: cp1.is_leader, 5)
            cp2 = mk("cp-2").start()
            self._run(store, cp1, cp2)
        finally:
            cp1.stop()
            if cp2 is not None:
                cp2.stop()

    def _run(self, store, cp1, cp2):
        from kubernetes_tpu.api.workloads import Job
        from kubernetes_tpu.api.types import new_uid
        from kubernetes_tpu.controllers.job import pod_completion_index

        job = Job.from_dict({
            "metadata": {"name": "train"},
            "spec": {"parallelism": 6, "completions": 6,
                     "completionMode": "Indexed",
                     "template": {"spec": {"containers": [
                         {"name": "w", "resources": {
                             "requests": {"cpu": "100m"}}}]}}}})
        job.metadata.uid = new_uid()
        store.create("jobs", job)
        assert _wait(lambda: len(store.list("pods")[0]) == 6, 10)

        # half the indexes succeed under the first leader
        for p in store.list("pods")[0]:
            if pod_completion_index(p) < 3:
                def done(x):
                    x.status.phase = "Succeeded"
                    return x

                store.guaranteed_update("pods", p.key, done)
        assert _wait(lambda: store.get(
            "jobs", "default/train").status.succeeded == 3, 10)

        # crash the leader mid-job
        cp1.elector.try_acquire_or_renew = lambda: False
        cp1._stop_components()
        assert _wait(lambda: cp2.is_leader, 5), "standby did not take over"

        # finish the rest under the new leader
        def finish_remaining():
            for p in store.list("pods")[0]:
                if not p.is_terminal():
                    def done(x):
                        x.status.phase = "Succeeded"
                        return x

                    store.guaranteed_update("pods", p.key, done)
            j = store.get("jobs", "default/train")
            return j.is_finished()

        assert _wait(finish_remaining, 10), "job did not complete after failover"
        j = store.get("jobs", "default/train")
        assert j.status.completed_indexes == "0-5"
        # no index ever had two simultaneously-active pods: every index's
        # pods are terminal now and each index appears exactly once among
        # the succeeded set per sync accounting
        by_index = {}
        for p in store.list("pods")[0]:
            by_index.setdefault(pod_completion_index(p), []).append(p)
        assert sorted(by_index) == [0, 1, 2, 3, 4, 5]
        for idx, pods in by_index.items():
            succ = [p for p in pods if p.status.phase == "Succeeded"]
            assert len(succ) >= 1
            # duplicates would mean the standby recreated an index that was
            # already done/active
            assert len(pods) == 1, (idx, [p.metadata.name for p in pods])
