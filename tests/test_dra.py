"""Dynamic Resource Allocation tests.

Mirrors the reference's dynamicresources plugin + structured allocator
behavior (pkg/scheduler/framework/plugins/dynamicresources,
staging/src/k8s.io/dynamic-resource-allocation/structured) and the
test/integration/scheduler DRA flows: claim-driven placement, reservation,
unreserve on failure, allocate/deallocate races.
"""

import pytest

from kubernetes_tpu.api.dra import (
    Device,
    DeviceAttributeRequirement,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceSlice,
)
from kubernetes_tpu.api.types import ObjectMeta
from kubernetes_tpu.scheduler import Framework, Scheduler
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils.featuregate import feature_gates


@pytest.fixture(autouse=True)
def dra_gate():
    feature_gates.set("DynamicResourceAllocation", True)
    yield
    feature_gates.set("DynamicResourceAllocation", False)


def _slice(node, devices, driver="tpu.driver", pool="pool0"):
    return ResourceSlice(
        metadata=ObjectMeta(name=f"{node}-slice", namespace=""),
        node_name=node, driver=driver, pool=pool,
        devices=[Device(name=d, attributes={"type": "tpu", "memGiB": 16})
                 for d in devices])


def _class(name="tpu-v5", selectors=()):
    return DeviceClass(
        metadata=ObjectMeta(name=name, namespace=""),
        selectors=list(selectors) or [
            DeviceAttributeRequirement(key="type", op="==", value="tpu")])


def _claim(name, count=1, class_name="tpu-v5", ns="default"):
    return ResourceClaim(
        metadata=ObjectMeta(name=name, namespace=ns),
        requests=[DeviceRequest(name="dev", device_class_name=class_name,
                                count=count)])


def _cluster(store, n_nodes=3, devices_per_node=2):
    for i in range(n_nodes):
        store.create("nodes", MakeNode(f"n{i}").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "20"}).obj())
    store.create("deviceclasses", _class())
    # only node n1 carries devices by default
    store.create("resourceslices", _slice(
        "n1", [f"dev-{j}" for j in range(devices_per_node)]))


class TestDRAScheduling:
    def test_claiming_pod_lands_only_on_device_node(self):
        store = APIStore()
        _cluster(store)
        store.create("resourceclaims", _claim("c1"))
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        store.create("pods", MakePod("p").req({"cpu": "1"}).claim("c1").obj())
        sched.run_until_idle()
        assert store.get("pods", "default/p").spec.node_name == "n1"
        claim = store.get("resourceclaims", "default/c1")
        assert claim.allocation is not None
        assert claim.allocation.node_name == "n1"
        assert len(claim.allocation.devices["dev"]) == 1
        assert "p" in claim.reserved_for

    def test_pod_without_claim_unaffected(self):
        store = APIStore()
        _cluster(store)
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        store.create("pods", MakePod("plain").req({"cpu": "1"}).obj())
        sched.run_until_idle()
        assert store.get("pods", "default/plain").spec.node_name != ""

    def test_missing_claim_gates_pod_until_created(self):
        store = APIStore()
        _cluster(store)
        sched = Scheduler(store, Framework(default_plugins()),
                          pod_initial_backoff=0.01)
        sched.sync()
        store.create("pods", MakePod("p").req({"cpu": "1"}).claim("late").obj())
        sched.run_until_idle()
        assert store.get("pods", "default/p").spec.node_name == ""
        store.create("resourceclaims", _claim("late"))
        sched.pump_events()
        import time

        time.sleep(0.05)
        sched.queue.flush_backoff_completed()
        sched.queue.flush_unschedulable_left_over()
        sched.run_until_idle()
        assert store.get("pods", "default/p").spec.node_name == "n1"

    def test_device_exhaustion_blocks_second_pod(self):
        store = APIStore()
        _cluster(store, devices_per_node=1)
        store.create("resourceclaims", _claim("c1"))
        store.create("resourceclaims", _claim("c2"))
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        store.create("pods", MakePod("p1").req({"cpu": "1"}).claim("c1").obj())
        store.create("pods", MakePod("p2").req({"cpu": "1"}).claim("c2").obj())
        sched.run_until_idle()
        bound = [store.get("pods", f"default/p{i}").spec.node_name for i in (1, 2)]
        assert sorted(bound)[0] == ""  # exactly one placed
        assert sorted(bound)[1] == "n1"

    def test_deallocate_frees_devices_for_next_pod(self):
        from kubernetes_tpu.scheduler.plugins.dynamic_resources import DynamicResources

        store = APIStore()
        _cluster(store, devices_per_node=1)
        store.create("resourceclaims", _claim("c1"))
        store.create("resourceclaims", _claim("c2"))
        sched = Scheduler(store, Framework(default_plugins()),
                          pod_initial_backoff=0.01)
        sched.sync()
        store.create("pods", MakePod("p1").req({"cpu": "1"}).claim("c1").obj())
        sched.run_until_idle()
        assert store.get("pods", "default/p1").spec.node_name == "n1"

        store.create("pods", MakePod("p2").req({"cpu": "1"}).claim("c2").obj())
        sched.run_until_idle()
        assert store.get("pods", "default/p2").spec.node_name == ""

        # pod p1 finishes; its claim is deallocated (kubelet/controller side)
        plugin = next(p for fw in sched.profiles.values() for p in fw.plugins
                      if isinstance(p, DynamicResources))
        store.delete("pods", "default/p1")
        plugin.deallocate("default/c1")
        sched.pump_events()
        import time

        time.sleep(0.05)
        sched.queue.flush_backoff_completed()
        sched.queue.flush_unschedulable_left_over()
        sched.run_until_idle()
        assert store.get("pods", "default/p2").spec.node_name == "n1"
        c2 = store.get("resourceclaims", "default/c2")
        assert c2.allocation is not None

    def test_multi_count_and_selector_requests(self):
        store = APIStore()
        for i in range(2):
            store.create("nodes", MakeNode(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": "20"}).obj())
        store.create("deviceclasses", _class())
        # n0: two small devices; n1: two big devices
        s0 = ResourceSlice(metadata=ObjectMeta(name="s0", namespace=""),
                           node_name="n0", driver="d", pool="p",
                           devices=[Device(name=f"small-{j}",
                                           attributes={"type": "tpu", "memGiB": 8})
                                    for j in range(2)])
        s1 = ResourceSlice(metadata=ObjectMeta(name="s1", namespace=""),
                           node_name="n1", driver="d", pool="p",
                           devices=[Device(name=f"big-{j}",
                                           attributes={"type": "tpu", "memGiB": 32})
                                    for j in range(2)])
        store.create("resourceslices", s0)
        store.create("resourceslices", s1)
        claim = ResourceClaim(
            metadata=ObjectMeta(name="big2", namespace="default"),
            requests=[DeviceRequest(
                name="dev", device_class_name="tpu-v5", count=2,
                selectors=[DeviceAttributeRequirement(
                    key="memGiB", op=">=", value=16)])])
        store.create("resourceclaims", claim)
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        store.create("pods", MakePod("p").req({"cpu": "1"}).claim("big2").obj())
        sched.run_until_idle()
        assert store.get("pods", "default/p").spec.node_name == "n1"
        got = store.get("resourceclaims", "default/big2")
        assert sorted(got.allocation.devices["dev"]) == ["big-0", "big-1"]

    def test_batch_scheduler_routes_claims_to_serial_path(self):
        store = APIStore()
        _cluster(store)
        store.create("resourceclaims", _claim("c1"))
        sched = BatchScheduler(store, Framework(default_plugins()), solver="auto")
        sched.sync()
        store.create("pods", MakePod("claimer").req({"cpu": "1"}).claim("c1").obj())
        for i in range(5):
            store.create("pods", MakePod(f"plain-{i}").req({"cpu": "1"}).obj())
        sched.run_until_idle()
        assert store.get("pods", "default/claimer").spec.node_name == "n1"
        for i in range(5):
            assert store.get("pods", f"default/plain-{i}").spec.node_name != ""

    def test_gate_off_means_no_plugin(self):
        feature_gates.set("DynamicResourceAllocation", False)
        names = {p.name for p in default_plugins()}
        assert "DynamicResources" not in names
        feature_gates.set("DynamicResourceAllocation", True)
        names = {p.name for p in default_plugins()}
        assert "DynamicResources" in names
