"""API server + client + CLI tests: the HTTP surface end to end."""

import json

import pytest

from kubernetes_tpu.cli.ktl import main as ktl_main
from kubernetes_tpu.server import APIError, APIServer, Informer, RESTClient
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod


@pytest.fixture()
def server():
    store = APIStore()
    srv = APIServer(store).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


class TestRESTServer:
    def test_create_get_list_delete(self, server, client):
        client.create("pods", {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "500m"}}}]},
        })
        got = client.get("pods", "web")
        assert got["metadata"]["name"] == "web"
        assert got["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "500m"
        items, rv = client.list("pods")
        assert len(items) == 1 and rv > 0
        client.delete("pods", "web")
        with pytest.raises(APIError) as e:
            client.get("pods", "web")
        assert e.value.code == 404

    def test_cluster_scoped_nodes(self, server, client):
        client.create("nodes", {
            "metadata": {"name": "n1"},
            "status": {"capacity": {"cpu": "8", "memory": "32Gi", "pods": "110"}},
        })
        got = client.get("nodes", "n1", namespace=None)
        assert got["status"]["allocatable"]["cpu"] == "8"

    def test_binding_subresource(self, server, client):
        client.create("pods", {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
        client.bind("default", "p", "node-9")
        assert client.get("pods", "p")["spec"]["nodeName"] == "node-9"
        with pytest.raises(APIError) as e:
            client.bind("default", "p", "node-2")
        assert e.value.code == 409

    def test_conflict_on_stale_update(self, server, client):
        client.create("pods", {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
        obj = client.get("pods", "p")
        client.update("pods", obj)  # bumps rv
        with pytest.raises(APIError) as e:
            client.update("pods", obj)  # stale rv
        assert e.value.code == 409

    def test_watch_streams_events(self, server, client):
        _, rv = client.list("pods")
        events = []
        import threading

        def consume():
            for etype, obj in client.watch("pods", since_rv=rv):
                events.append((etype, obj["metadata"]["name"]))
                if len(events) >= 2:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        client.create("pods", {"metadata": {"name": "a"}, "spec": {"containers": [{"name": "c"}]}})
        client.delete("pods", "a")
        t.join(timeout=5)
        assert events == [("ADDED", "a"), ("DELETED", "a")]

    def test_healthz_and_metrics(self, server, client):
        assert client.request("GET", "/healthz")["status"] == "ok"
        import urllib.request

        body = urllib.request.urlopen(server.url + "/metrics").read().decode()
        assert "scheduler_schedule_attempts_total" in body

    def test_informer_cache(self, server, client):
        client.create("pods", {"metadata": {"name": "a"}, "spec": {"containers": [{"name": "c"}]}})
        inf = Informer(client, "pods").start()
        assert "default/a" in inf.cache
        client.create("pods", {"metadata": {"name": "b"}, "spec": {"containers": [{"name": "c"}]}})
        import time

        deadline = time.time() + 5
        while time.time() < deadline and "default/b" not in inf.cache:
            time.sleep(0.05)
        assert "default/b" in inf.cache
        inf.stop()


class TestCLI:
    def run(self, server, *argv):
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = ktl_main(["--server", server.url, *argv])
        return rc, buf.getvalue()

    def test_create_from_manifest_and_get(self, server, tmp_path):
        manifest = tmp_path / "pod.yaml"
        manifest.write_text(json.dumps({
            "kind": "Pod",
            "metadata": {"name": "cli-pod"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}]},
        }))
        rc, out = self.run(server, "create", "-f", str(manifest))
        assert rc == 0 and "pods/cli-pod created" in out
        rc, out = self.run(server, "get", "pods")
        assert rc == 0 and "cli-pod" in out and "<none>" in out

    def test_apply_updates(self, server, tmp_path):
        manifest = tmp_path / "rs.yaml"
        doc = {
            "kind": "ReplicaSet",
            "metadata": {"name": "web"},
            "spec": {"replicas": 2, "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}},
        }
        manifest.write_text(json.dumps(doc))
        rc, out = self.run(server, "apply", "-f", str(manifest))
        assert rc == 0 and "serverside-applied" in out
        doc["spec"]["replicas"] = 5
        manifest.write_text(json.dumps(doc))
        rc, out = self.run(server, "apply", "-f", str(manifest))
        assert rc == 0 and "serverside-applied" in out
        rc, out = self.run(server, "get", "rs", "web", "-o", "json")
        assert json.loads(out)["spec"]["replicas"] == 5

    def test_scale(self, server, tmp_path):
        manifest = tmp_path / "rs.json"
        manifest.write_text(json.dumps({
            "kind": "ReplicaSet", "metadata": {"name": "web"},
            "spec": {"replicas": 1, "template": {"spec": {"containers": [{"name": "c"}]}}},
        }))
        self.run(server, "create", "-f", str(manifest))
        rc, out = self.run(server, "scale", "rs", "web", "--replicas", "7")
        assert rc == 0
        rc, out = self.run(server, "get", "rs", "web", "-o", "json")
        assert json.loads(out)["spec"]["replicas"] == 7

    def test_cordon_taint_drain(self, server, client):
        client.create("nodes", {"metadata": {"name": "n1"},
                                "status": {"capacity": {"cpu": "8"}}})
        client.create("pods", {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
        client.bind("default", "p", "n1")
        rc, _ = self.run(server, "taint", "nodes", "n1", "gpu=true:NoSchedule")
        assert rc == 0
        node = client.get("nodes", "n1", namespace=None)
        # TaintNodesByCondition admission adds not-ready on create; the CLI
        # taint must append alongside it
        assert {"key": "gpu", "value": "true",
                "effect": "NoSchedule"} in node["spec"]["taints"]
        rc, out = self.run(server, "drain", "n1")
        assert rc == 0 and "pod/p evicted" in out
        node = client.get("nodes", "n1", namespace=None)
        assert node["spec"]["unschedulable"] is True
        with pytest.raises(APIError):
            client.get("pods", "p")

    def test_get_nodes_shows_status(self, server, client):
        client.create("nodes", {"metadata": {"name": "n1"},
                                "status": {"capacity": {"cpu": "8", "memory": "32Gi"}}})
        rc, out = self.run(server, "get", "nodes")
        assert rc == 0 and "n1" in out and "Ready" in out

    def test_version_and_api_resources(self, server):
        rc, out = self.run(server, "version")
        assert rc == 0 and "kubernetes-tpu" in out
        rc, out = self.run(server, "api-resources")
        assert rc == 0 and "deployments" in out


def test_serialization_roundtrip_via_server(server):
    """Pod with every scheduling feature survives HTTP round-trip. Priority
    comes via a PriorityClass — the Priority admission plugin overrides any
    client-set spec.priority (reference plugin behavior)."""
    client = RESTClient(server.url)
    client.create("priorityclasses", {"kind": "PriorityClass",
                                      "metadata": {"name": "p10"}, "value": 10})
    doc = {
        "kind": "Pod",
        "metadata": {"name": "full", "labels": {"app": "x"}},
        "spec": {
            "containers": [{"name": "c", "image": "img:1",
                            "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}},
                            "ports": [{"containerPort": 80, "hostPort": 8080}]}],
            "nodeSelector": {"disk": "ssd"},
            "affinity": {
                "nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["a"]}]}]}},
                "podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "labelSelector": {"matchLabels": {"app": "x"}}}]},
            },
            "tolerations": [{"key": "k", "operator": "Exists", "effect": "NoSchedule"}],
            "topologySpreadConstraints": [{
                "maxSkew": 1, "topologyKey": "zone", "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "x"}}}],
            "priorityClassName": "p10",
        },
    }
    client.create("pods", doc)
    got = client.get("pods", "full")
    assert got["spec"]["nodeSelector"] == {"disk": "ssd"}
    assert got["spec"]["tolerations"][0]["operator"] == "Exists"
    assert got["spec"]["topologySpreadConstraints"][0]["maxSkew"] == 1
    aff = got["spec"]["affinity"]
    assert aff["nodeAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"][
        "nodeSelectorTerms"][0]["matchExpressions"][0]["values"] == ["a"]
    assert aff["podAntiAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"][0][
        "topologyKey"] == "kubernetes.io/hostname"
    # and it re-parses into an equivalent Pod
    from kubernetes_tpu.api import Pod

    pod = Pod.from_dict(got)
    assert pod.spec.affinity.pod_anti_affinity_required[0].topology_key == "kubernetes.io/hostname"
    assert pod.spec.priority == 10


def test_put_honors_url_namespace(server, client):
    # NamespaceLifecycle admission requires the namespace to exist
    client.create("namespaces", {"metadata": {"name": "prod"}})
    client.create("pods", {"metadata": {"name": "web", "namespace": "prod"},
                           "spec": {"containers": [{"name": "c"}]}}, namespace="prod")
    obj = client.get("pods", "web", "prod")
    del obj["metadata"]["namespace"]  # body omits ns; URL must win
    obj["metadata"]["labels"] = {"touched": "yes"}
    client.update("pods", obj, namespace="prod")
    assert client.get("pods", "web", "prod")["metadata"]["labels"] == {"touched": "yes"}
    with pytest.raises(APIError) as e:
        client.get("pods", "web", "default")
    assert e.value.code == 404


class TestCLIBreadth:
    """The kubectl-parity commands added in round 4 (label/annotate/patch/
    rollout/set image/top/wait/autoscale — kubectl/pkg/cmd)."""

    def run(self, server, *argv):
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = ktl_main(["--server", server.url, *argv])
        return rc, buf.getvalue()

    def _mk_pod(self, server, name="p1"):
        store = server.store
        from kubernetes_tpu.testing import MakePod

        store.create("pods", MakePod(name).req({"cpu": "500m", "memory": "1Gi"}).obj())

    def test_label_and_unlabel(self, server):
        self._mk_pod(server)
        rc, _ = self.run(server, "label", "pods", "p1", "tier=web", "team=a")
        assert rc == 0
        pod = server.store.get("pods", "default/p1")
        assert pod.metadata.labels["tier"] == "web"
        rc, _ = self.run(server, "label", "pods", "p1", "team-")
        assert rc == 0
        assert "team" not in server.store.get("pods", "default/p1").metadata.labels

    def test_annotate(self, server):
        self._mk_pod(server)
        rc, _ = self.run(server, "annotate", "pods", "p1", "note=hello")
        assert rc == 0
        assert server.store.get("pods", "default/p1").metadata.annotations["note"] == "hello"

    def test_patch(self, server):
        self._mk_pod(server)
        rc, _ = self.run(server, "patch", "pods", "p1",
                         "-p", '{"metadata": {"labels": {"x": "y"}}}')
        assert rc == 0
        assert server.store.get("pods", "default/p1").metadata.labels["x"] == "y"

    def test_top_nodes_and_pods(self, server):
        from kubernetes_tpu.testing import MakeNode, MakePod

        server.store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        p = MakePod("busy").req({"cpu": "2", "memory": "4Gi"}).obj()
        p.spec.node_name = "n0"
        server.store.create("pods", p)
        rc, out = self.run(server, "top", "nodes")
        assert rc == 0 and "n0" in out and "50%" in out
        rc, out = self.run(server, "top", "pods")
        assert rc == 0 and "busy" in out and "2000m" in out

    def test_wait_for_condition_and_delete(self, server):
        import threading
        import time

        self._mk_pod(server)

        def later():
            time.sleep(0.2)
            server.store.update_pod_status(
                "default", "p1", lambda st: st.conditions.append(
                    __import__("kubernetes_tpu.api.types",
                               fromlist=["PodCondition"]).PodCondition(
                        type="Ready", status="True")))

        threading.Thread(target=later, daemon=True).start()
        rc, out = self.run(server, "wait", "pods/p1", "--for", "condition=Ready",
                           "--timeout", "5")
        assert rc == 0 and "condition met" in out

        def deleter():
            time.sleep(0.2)
            server.store.delete("pods", "default/p1")

        threading.Thread(target=deleter, daemon=True).start()
        rc, out = self.run(server, "wait", "pods/p1", "--for", "delete",
                           "--timeout", "5")
        assert rc == 0

    def test_rollout_and_set_image_and_autoscale(self, server, tmp_path):
        import json as _json

        manifest = tmp_path / "d.json"
        manifest.write_text(_json.dumps({
            "kind": "Deployment", "metadata": {"name": "web"},
            "spec": {"replicas": 1,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [
                                      {"name": "c", "image": "img:1"}]}}},
        }))
        rc, _ = self.run(server, "create", "-f", str(manifest))
        assert rc == 0
        rc, _ = self.run(server, "set", "image", "deployment/web", "c=img:2")
        assert rc == 0
        d = server.store.get("deployments", "default/web")
        assert d.spec.template.spec.containers[0].image == "img:2"
        rc, _ = self.run(server, "rollout", "restart", "deployment/web")
        assert rc == 0
        d = server.store.get("deployments", "default/web")
        assert "kubectl.kubernetes.io/restartedAt" in \
            d.spec.template.metadata.annotations
        # rollout status succeeds once the controller reports readiness
        def mutate(dep):
            dep.status.updated_replicas = 1
            dep.status.ready_replicas = 1
            return dep

        server.store.guaranteed_update("deployments", "default/web", mutate)
        rc, out = self.run(server, "rollout", "status", "deployment/web",
                           "--timeout", "5")
        assert rc == 0 and "successfully rolled out" in out
        rc, _ = self.run(server, "autoscale", "deployment/web", "--max", "5")
        assert rc == 0
        hpa = server.store.get("horizontalpodautoscalers", "default/web")
        assert hpa is not None
