"""API server + client + CLI tests: the HTTP surface end to end."""

import json

import pytest

from kubernetes_tpu.cli.ktl import main as ktl_main
from kubernetes_tpu.server import APIError, APIServer, Informer, RESTClient
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod


@pytest.fixture()
def server():
    store = APIStore()
    srv = APIServer(store).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


class TestRESTServer:
    def test_create_get_list_delete(self, server, client):
        client.create("pods", {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "500m"}}}]},
        })
        got = client.get("pods", "web")
        assert got["metadata"]["name"] == "web"
        assert got["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "500m"
        items, rv = client.list("pods")
        assert len(items) == 1 and rv > 0
        client.delete("pods", "web")
        with pytest.raises(APIError) as e:
            client.get("pods", "web")
        assert e.value.code == 404

    def test_cluster_scoped_nodes(self, server, client):
        client.create("nodes", {
            "metadata": {"name": "n1"},
            "status": {"capacity": {"cpu": "8", "memory": "32Gi", "pods": "110"}},
        })
        got = client.get("nodes", "n1", namespace=None)
        assert got["status"]["allocatable"]["cpu"] == "8"

    def test_binding_subresource(self, server, client):
        client.create("pods", {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
        client.bind("default", "p", "node-9")
        assert client.get("pods", "p")["spec"]["nodeName"] == "node-9"
        with pytest.raises(APIError) as e:
            client.bind("default", "p", "node-2")
        assert e.value.code == 409

    def test_conflict_on_stale_update(self, server, client):
        client.create("pods", {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
        obj = client.get("pods", "p")
        client.update("pods", obj)  # bumps rv
        with pytest.raises(APIError) as e:
            client.update("pods", obj)  # stale rv
        assert e.value.code == 409

    def test_watch_streams_events(self, server, client):
        _, rv = client.list("pods")
        events = []
        import threading

        def consume():
            for etype, obj in client.watch("pods", since_rv=rv):
                events.append((etype, obj["metadata"]["name"]))
                if len(events) >= 2:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        client.create("pods", {"metadata": {"name": "a"}, "spec": {"containers": [{"name": "c"}]}})
        client.delete("pods", "a")
        t.join(timeout=5)
        assert events == [("ADDED", "a"), ("DELETED", "a")]

    def test_healthz_and_metrics(self, server, client):
        assert client.request("GET", "/healthz")["status"] == "ok"
        import urllib.request

        body = urllib.request.urlopen(server.url + "/metrics").read().decode()
        assert "scheduler_schedule_attempts_total" in body

    def test_informer_cache(self, server, client):
        client.create("pods", {"metadata": {"name": "a"}, "spec": {"containers": [{"name": "c"}]}})
        inf = Informer(client, "pods").start()
        assert "default/a" in inf.cache
        client.create("pods", {"metadata": {"name": "b"}, "spec": {"containers": [{"name": "c"}]}})
        import time

        deadline = time.time() + 5
        while time.time() < deadline and "default/b" not in inf.cache:
            time.sleep(0.05)
        assert "default/b" in inf.cache
        inf.stop()


class TestCLI:
    def run(self, server, *argv):
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = ktl_main(["--server", server.url, *argv])
        return rc, buf.getvalue()

    def test_create_from_manifest_and_get(self, server, tmp_path):
        manifest = tmp_path / "pod.yaml"
        manifest.write_text(json.dumps({
            "kind": "Pod",
            "metadata": {"name": "cli-pod"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}]},
        }))
        rc, out = self.run(server, "create", "-f", str(manifest))
        assert rc == 0 and "pods/cli-pod created" in out
        rc, out = self.run(server, "get", "pods")
        assert rc == 0 and "cli-pod" in out and "<none>" in out

    def test_apply_updates(self, server, tmp_path):
        manifest = tmp_path / "rs.yaml"
        doc = {
            "kind": "ReplicaSet",
            "metadata": {"name": "web"},
            "spec": {"replicas": 2, "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}},
        }
        manifest.write_text(json.dumps(doc))
        rc, out = self.run(server, "apply", "-f", str(manifest))
        assert rc == 0 and "created" in out
        doc["spec"]["replicas"] = 5
        manifest.write_text(json.dumps(doc))
        rc, out = self.run(server, "apply", "-f", str(manifest))
        assert rc == 0 and "configured" in out
        rc, out = self.run(server, "get", "rs", "web", "-o", "json")
        assert json.loads(out)["spec"]["replicas"] == 5

    def test_scale(self, server, tmp_path):
        manifest = tmp_path / "rs.json"
        manifest.write_text(json.dumps({
            "kind": "ReplicaSet", "metadata": {"name": "web"},
            "spec": {"replicas": 1, "template": {"spec": {"containers": [{"name": "c"}]}}},
        }))
        self.run(server, "create", "-f", str(manifest))
        rc, out = self.run(server, "scale", "rs", "web", "--replicas", "7")
        assert rc == 0
        rc, out = self.run(server, "get", "rs", "web", "-o", "json")
        assert json.loads(out)["spec"]["replicas"] == 7

    def test_cordon_taint_drain(self, server, client):
        client.create("nodes", {"metadata": {"name": "n1"},
                                "status": {"capacity": {"cpu": "8"}}})
        client.create("pods", {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
        client.bind("default", "p", "n1")
        rc, _ = self.run(server, "taint", "nodes", "n1", "gpu=true:NoSchedule")
        assert rc == 0
        node = client.get("nodes", "n1", namespace=None)
        assert node["spec"]["taints"] == [{"key": "gpu", "value": "true", "effect": "NoSchedule"}]
        rc, out = self.run(server, "drain", "n1")
        assert rc == 0 and "pod/p evicted" in out
        node = client.get("nodes", "n1", namespace=None)
        assert node["spec"]["unschedulable"] is True
        with pytest.raises(APIError):
            client.get("pods", "p")

    def test_get_nodes_shows_status(self, server, client):
        client.create("nodes", {"metadata": {"name": "n1"},
                                "status": {"capacity": {"cpu": "8", "memory": "32Gi"}}})
        rc, out = self.run(server, "get", "nodes")
        assert rc == 0 and "n1" in out and "Ready" in out

    def test_version_and_api_resources(self, server):
        rc, out = self.run(server, "version")
        assert rc == 0 and "kubernetes-tpu" in out
        rc, out = self.run(server, "api-resources")
        assert rc == 0 and "deployments" in out


def test_serialization_roundtrip_via_server(server):
    """Pod with every scheduling feature survives HTTP round-trip."""
    client = RESTClient(server.url)
    doc = {
        "kind": "Pod",
        "metadata": {"name": "full", "labels": {"app": "x"}},
        "spec": {
            "containers": [{"name": "c", "image": "img:1",
                            "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}},
                            "ports": [{"containerPort": 80, "hostPort": 8080}]}],
            "nodeSelector": {"disk": "ssd"},
            "affinity": {
                "nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["a"]}]}]}},
                "podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "labelSelector": {"matchLabels": {"app": "x"}}}]},
            },
            "tolerations": [{"key": "k", "operator": "Exists", "effect": "NoSchedule"}],
            "topologySpreadConstraints": [{
                "maxSkew": 1, "topologyKey": "zone", "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "x"}}}],
            "priority": 10,
        },
    }
    client.create("pods", doc)
    got = client.get("pods", "full")
    assert got["spec"]["nodeSelector"] == {"disk": "ssd"}
    assert got["spec"]["tolerations"][0]["operator"] == "Exists"
    assert got["spec"]["topologySpreadConstraints"][0]["maxSkew"] == 1
    aff = got["spec"]["affinity"]
    assert aff["nodeAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"][
        "nodeSelectorTerms"][0]["matchExpressions"][0]["values"] == ["a"]
    assert aff["podAntiAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"][0][
        "topologyKey"] == "kubernetes.io/hostname"
    # and it re-parses into an equivalent Pod
    from kubernetes_tpu.api import Pod

    pod = Pod.from_dict(got)
    assert pod.spec.affinity.pod_anti_affinity_required[0].topology_key == "kubernetes.io/hostname"
    assert pod.spec.priority == 10


def test_put_honors_url_namespace(server, client):
    # NamespaceLifecycle admission requires the namespace to exist
    client.create("namespaces", {"metadata": {"name": "prod"}})
    client.create("pods", {"metadata": {"name": "web", "namespace": "prod"},
                           "spec": {"containers": [{"name": "c"}]}}, namespace="prod")
    obj = client.get("pods", "web", "prod")
    del obj["metadata"]["namespace"]  # body omits ns; URL must win
    obj["metadata"]["labels"] = {"touched": "yes"}
    client.update("pods", obj, namespace="prod")
    assert client.get("pods", "web", "prod")["metadata"]["labels"] == {"touched": "yes"}
    with pytest.raises(APIError) as e:
        client.get("pods", "web", "default")
    assert e.value.code == 404
