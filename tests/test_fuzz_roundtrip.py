"""Codec round-trip fuzzing — the test/fuzz analog (SURVEY.md §4).

reference: test/fuzz roundtrip fuzzing of API codecs. Property: for every
resource kind, from_dict(to_dict(obj)) == to_dict-stable — serializing a
deserialized object again yields the identical wire form (the invariant the
apiserver's codecs enforce; a lossy field here silently corrupts PATCH
read-modify-write, which round 4's review actually caught by hand).
"""

import string

import pytest

# env gap (ROADMAP): the fuzzing harness isn't baked into every toolchain
# image — collection must skip cleanly, not error, when it's absent
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from kubernetes_tpu.api.serialize import from_dict, to_dict

name_st = st.text(alphabet=string.ascii_lowercase + "-", min_size=1, max_size=12)
label_key = st.text(alphabet=string.ascii_lowercase + ".-/", min_size=1, max_size=20)
label_val = st.text(alphabet=string.ascii_lowercase + string.digits + "-", max_size=15)
labels_st = st.dictionaries(label_key, label_val, max_size=4)
qty_st = st.sampled_from(["100m", "1", "2", "500m", "1Gi", "256Mi", "2G", "0"])


def meta_st():
    return st.fixed_dictionaries(
        {"name": name_st},
        optional={"namespace": name_st, "labels": labels_st,
                  "annotations": labels_st,
                  "resourceVersion": st.integers(0, 10**6),
                  "uid": name_st})


container_st = st.fixed_dictionaries(
    {"name": name_st},
    optional={
        "image": name_st,
        "imagePullPolicy": st.sampled_from(["Always", "IfNotPresent", "Never"]),
        "resources": st.fixed_dictionaries({}, optional={
            "requests": st.dictionaries(
                st.sampled_from(["cpu", "memory"]), qty_st, max_size=2),
            "limits": st.dictionaries(
                st.sampled_from(["cpu", "memory"]), qty_st, max_size=2)}),
        "ports": st.lists(st.fixed_dictionaries(
            {"containerPort": st.integers(1, 65535)},
            optional={"hostPort": st.integers(1, 65535),
                      "protocol": st.sampled_from(["TCP", "UDP"])}),
            max_size=2),
    })

pod_st = st.fixed_dictionaries(
    {"kind": st.just("Pod"), "metadata": meta_st(),
     "spec": st.fixed_dictionaries(
         {"containers": st.lists(container_st, min_size=1, max_size=2)},
         optional={
             "nodeName": name_st,
             "nodeSelector": labels_st,
             "priority": st.integers(-100, 10**6),
             "priorityClassName": name_st,
             "restartPolicy": st.sampled_from(["Always", "OnFailure", "Never"]),
             "terminationGracePeriodSeconds": st.integers(0, 300),
             "preemptionPolicy": st.sampled_from(
                 ["PreemptLowerPriority", "Never"]),
             "hostNetwork": st.booleans(),
             "serviceAccountName": name_st,
             "schedulingGates": st.lists(name_st, max_size=2),
             "tolerations": st.lists(st.fixed_dictionaries(
                 {"key": label_key},
                 optional={"operator": st.sampled_from(["Exists", "Equal"]),
                           "value": label_val,
                           "effect": st.sampled_from(
                               ["NoSchedule", "PreferNoSchedule", "NoExecute"]),
                           "tolerationSeconds": st.integers(0, 3600)}),
                 max_size=2),
             "resourceClaims": st.lists(st.fixed_dictionaries(
                 {"name": name_st, "resourceClaimName": name_st}), max_size=2),
         })},
)


def _stable(resource: str, doc: dict) -> None:
    """to_dict(from_dict(x)) must be a fixed point after one round."""
    once = to_dict(from_dict(resource, doc))
    twice = to_dict(from_dict(resource, once))
    assert once == twice, f"{resource} round-trip not stable:\n{once}\nvs\n{twice}"


@settings(max_examples=150, deadline=None)
@given(pod_st)
def test_pod_roundtrip_stable(doc):
    _stable("pods", doc)


node_st = st.fixed_dictionaries(
    {"kind": st.just("Node"), "metadata": meta_st()},
    optional={
        "spec": st.fixed_dictionaries({}, optional={
            "unschedulable": st.booleans(),
            "taints": st.lists(st.fixed_dictionaries(
                {"key": label_key, "effect": st.sampled_from(
                    ["NoSchedule", "PreferNoSchedule", "NoExecute"])},
                optional={"value": label_val}), max_size=2)}),
        "status": st.fixed_dictionaries({}, optional={
            "capacity": st.dictionaries(
                st.sampled_from(["cpu", "memory", "pods"]), qty_st, max_size=3),
            "allocatable": st.dictionaries(
                st.sampled_from(["cpu", "memory", "pods"]), qty_st, max_size=3)}),
    })


@settings(max_examples=100, deadline=None)
@given(node_st)
def test_node_roundtrip_stable(doc):
    _stable("nodes", doc)


claim_st = st.fixed_dictionaries(
    {"kind": st.just("ResourceClaim"), "metadata": meta_st(),
     "spec": st.fixed_dictionaries({"devices": st.fixed_dictionaries({
         "requests": st.lists(st.fixed_dictionaries(
             {"name": name_st, "deviceClassName": name_st},
             optional={"count": st.integers(1, 8)}), min_size=1, max_size=2)})})})


@settings(max_examples=60, deadline=None)
@given(claim_st)
def test_resourceclaim_roundtrip_stable(doc):
    _stable("resourceclaims", doc)


@settings(max_examples=60, deadline=None)
@given(st.fixed_dictionaries(
    {"kind": st.just("PriorityClass"), "metadata": meta_st(),
     "value": st.integers(-(10**9), 10**9)},
    optional={"globalDefault": st.booleans(),
              "preemptionPolicy": st.sampled_from(
                  ["PreemptLowerPriority", "Never"])}))
def test_priorityclass_roundtrip_stable(doc):
    _stable("priorityclasses", doc)


# ---- label-selector grammar + index-compression properties --------------------

_key_st = st.text(alphabet=string.ascii_lowercase + string.digits + "-._/",
                  min_size=1, max_size=12).filter(
    lambda s: not s.startswith(("-", ".", "/")))
_val_st = st.text(alphabet=string.ascii_lowercase + string.digits,
                  min_size=1, max_size=8)


@st.composite
def _selector_clause(draw):
    kind = draw(st.sampled_from(["eq", "ne", "in", "notin", "exists", "nexists"]))
    k = draw(_key_st)
    if kind == "eq":
        return f"{k}={draw(_val_st)}"
    if kind == "ne":
        return f"{k}!={draw(_val_st)}"
    if kind == "in":
        vals = draw(st.lists(_val_st, min_size=1, max_size=3))
        return f"{k} in ({','.join(vals)})"
    if kind == "notin":
        vals = draw(st.lists(_val_st, min_size=1, max_size=3))
        return f"{k} notin ({','.join(vals)})"
    if kind == "exists":
        return k
    return f"!{k}"


@settings(max_examples=150, deadline=None)
@given(st.lists(_selector_clause(), min_size=1, max_size=4),
       st.dictionaries(_key_st, _val_st, max_size=4))
def test_selector_grammar_parses_and_matches_consistently(clauses, labels):
    """Every grammatical selector parses, and matching equals the AND of its
    clauses evaluated through the same Requirement machinery."""
    from kubernetes_tpu.api.labels import parse_selector_string

    raw = ",".join(clauses)
    sel = parse_selector_string(raw)
    assert len(sel.requirements) == len(clauses)
    expect = all(r.matches(labels) for r in sel.requirements)
    assert sel.matches(labels) == expect


@settings(max_examples=200, deadline=None)
@given(st.sets(st.integers(min_value=0, max_value=200), max_size=40))
def test_compress_indexes_round_trips(indexes):
    """completedIndexes compression is lossless: expanding the ranges gives
    back exactly the input set."""
    from kubernetes_tpu.controllers.job import compress_indexes

    out = compress_indexes(indexes)
    expanded = set()
    for part in out.split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            expanded.update(range(int(lo), int(hi) + 1))
        else:
            expanded.add(int(part))
    assert expanded == set(indexes)
