"""ktl exec / attach / port-forward over the store-channel sessions.

Pins the reference contract (pkg/kubelet/server/server.go streaming
endpoints + kubectl/pkg/cmd/exec/exec.go), transported over PodExec/
PodPortForward session objects instead of SPDY:
  - `ktl exec pod -- cmd` round-trips stdin/stdout through the API server
  - exit codes propagate to the CLI's return code
  - attach returns recent container output and forwards stdin
  - port-forward round-trips opaque bytes via a real local TCP socket
  - sessions are cleaned up after each round and RBAC-scoped (pods/exec)
"""

import io
import json
import socket
import threading
import time
from contextlib import redirect_stdout, redirect_stderr

import pytest

from kubernetes_tpu.agent.cri import FakeRuntime
from kubernetes_tpu.agent.kubelet import Kubelet
from kubernetes_tpu.cli.ktl import main as ktl_main
from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakePod


@pytest.fixture()
def cluster():
    """Store + API server + a ticking in-process kubelet with FakeRuntime."""
    store = APIStore()
    srv = APIServer(store).start()
    runtime = FakeRuntime()
    klet = Kubelet(store, "n1", runtime=runtime)
    klet.register()
    pod = MakePod("web").req({"cpu": "100m"}).obj()
    store.create("pods", pod)
    store.bind("default", "web", "n1")
    klet.tick()
    stop = threading.Event()

    def tick_loop():
        while not stop.is_set():
            klet.tick()
            time.sleep(0.01)

    t = threading.Thread(target=tick_loop, daemon=True)
    t.start()
    yield store, srv, runtime
    stop.set()
    t.join(timeout=2)
    srv.stop()


def run_ktl(srv, *args, stdin: bytes = b""):
    out, err = io.StringIO(), io.StringIO()
    import sys

    old_stdin = sys.stdin
    try:
        if stdin:
            sys.stdin = io.TextIOWrapper(io.BytesIO(stdin))
        with redirect_stdout(out), redirect_stderr(err):
            rc = ktl_main(["--server", srv.url] + list(args))
    finally:
        sys.stdin = old_stdin
    return rc, out.getvalue(), err.getvalue()


class TestExec:
    def test_exec_round_trips_stdout(self, cluster):
        _store, srv, _rt = cluster
        rc, out, _ = run_ktl(srv, "exec", "web", "--", "echo", "hello", "tpu")
        assert rc == 0
        assert out == "hello tpu\n"

    def test_exec_round_trips_stdin(self, cluster):
        _store, srv, _rt = cluster
        rc, out, _ = run_ktl(srv, "exec", "-i", "web", "--", "cat",
                             stdin=b"fed through the api server\n")
        assert rc == 0
        assert out == "fed through the api server\n"

    def test_exit_code_propagates(self, cluster):
        _store, srv, _rt = cluster
        rc, _, _ = run_ktl(srv, "exec", "web", "--", "false")
        assert rc == 1
        rc, _, _ = run_ktl(srv, "exec", "web", "--", "true")
        assert rc == 0

    def test_custom_exec_handler(self, cluster):
        _store, srv, rt = cluster
        rt.set_exec_handler(
            lambda pod, c, cmd, stdin: (b"custom:" + stdin, b"warn\n", 3))
        rc, out, err = run_ktl(srv, "exec", "-i", "web", "--", "anything",
                               stdin=b"x")
        assert rc == 3 and out == "custom:x" and err == "warn\n"

    def test_unscheduled_pod_409(self, cluster):
        store, srv, _rt = cluster
        store.create("pods", MakePod("pending").req({"cpu": "100m"}).obj())
        client = RESTClient(srv.url)
        with pytest.raises(APIError) as e:
            client.exec("pending", ["true"])
        assert e.value.code == 409

    def test_missing_pod_404(self, cluster):
        _store, srv, _rt = cluster
        client = RESTClient(srv.url)
        with pytest.raises(APIError) as e:
            client.exec("nope", ["true"])
        assert e.value.code == 404

    def test_sessions_cleaned_up(self, cluster):
        store, srv, _rt = cluster
        client = RESTClient(srv.url)
        client.exec("web", ["echo", "x"])
        sessions, _ = store.list("podexecs")
        assert sessions == []

    def test_timeout_when_no_kubelet_answers(self, cluster):
        store, srv, _rt = cluster
        # a pod on a node with NO kubelet: the long-poll must time out
        store.create("pods", MakePod("lost").req({"cpu": "100m"}).obj())
        store.bind("default", "lost", "ghost-node")
        client = RESTClient(srv.url)
        with pytest.raises(APIError) as e:
            client.request(
                "POST", "/api/v1/namespaces/default/pods/lost/exec",
                {"command": ["true"], "timeoutSeconds": 0.3}, timeout=5)
        assert e.value.code == 504


class TestAttach:
    def test_attach_shows_output_and_forwards_stdin(self, cluster):
        _store, srv, _rt = cluster
        rc, out, _ = run_ktl(srv, "attach", "-i", "web",
                             stdin=b"typed into the container\n")
        assert rc == 0
        # stdin was folded into the container log, which attach then shows
        rc, out, _ = run_ktl(srv, "attach", "web")
        assert "typed into the container" in out


class TestPortForward:
    def test_port_data_round_trip(self, cluster):
        _store, srv, rt = cluster
        client = RESTClient(srv.url)
        assert client.port_forward("web", 8080, b"ping") == b"ECHO:ping"
        rt.set_port_handler(8080, lambda data: b"HTTP/1.0 200 OK\r\n\r\nhi")
        assert client.port_forward("web", 8080, b"GET / HTTP/1.0\r\n\r\n") \
            == b"HTTP/1.0 200 OK\r\n\r\nhi"

    def test_cli_local_socket_round_trip(self, cluster):
        _store, srv, rt = cluster
        rt.set_port_handler(9091, lambda data: b"srv:" + data)
        local = _free_port()
        t2 = threading.Thread(target=lambda: run_ktl(
            srv, "port-forward", "web", f"{local}:9091", "--one-connection"),
            daemon=True)
        t2.start()
        deadline = time.monotonic() + 5
        data = None
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", local),
                                             timeout=1)
                s.sendall(b"hello")
                s.shutdown(socket.SHUT_WR)
                chunks = []
                while True:
                    b = s.recv(4096)
                    if not b:
                        break
                    chunks.append(b)
                s.close()
                data = b"".join(chunks)
                break
            except OSError:
                time.sleep(0.05)
        t2.join(timeout=10)
        assert data == b"srv:hello"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestHollowHTTPKubelet:
    def test_exec_against_joined_node(self):
        """The HTTP-joined hollow kubelet answers exec sessions too —
        `ktl exec` works on a kadm cluster with no in-process kubelet."""
        from kubernetes_tpu.cli.kadm import init_control_plane, join_node

        res = init_control_plane(use_batch_scheduler=False)
        node = None
        try:
            assert res.wait_ready(30)
            client = RESTClient(res.url)
            node = join_node(res.url, "jn0")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if len(client.list("nodes")[0]) == 1:
                    break
                time.sleep(0.1)
            client.create("pods", {
                "kind": "Pod",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "100m"}}}]}})
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                p = client.get("pods", "web")
                if p["spec"].get("nodeName"):
                    break
                time.sleep(0.1)
            out = client.exec("web", ["echo", "over", "http"],
                              timeout_seconds=15)
            assert out["stdout"] == "over http\n"
            assert out["exitCode"] == 0
            out = client.exec("web", ["cat"], stdin=b"hollow stdin\n",
                              timeout_seconds=15)
            assert out["stdout"] == "hollow stdin\n"
            assert client.port_forward("web", 80, b"hi",
                                       timeout_seconds=15) == b"ECHO:hi"
        finally:
            if node is not None:
                node.stop()
            res.stop()


class TestHardening:
    def test_malformed_stdin_fails_session_not_kubelet(self, cluster):
        store, srv, _rt = cluster
        client = RESTClient(srv.url)
        out = client.request(
            "POST", "/api/v1/namespaces/default/pods/web/exec",
            {"command": ["cat"], "stdin": "!!!not-base64!!!",
             "timeoutSeconds": 5}, timeout=10)
        assert out.get("exitCode") == 1 and out.get("error")
        # the kubelet loop survived: a normal exec still works
        out = client.exec("web", ["echo", "alive"])
        assert out["stdout"] == "alive\n"

    def test_bad_timeout_is_400(self, cluster):
        _store, srv, _rt = cluster
        client = RESTClient(srv.url)
        with pytest.raises(APIError) as e:
            client.request(
                "POST", "/api/v1/namespaces/default/pods/web/exec",
                {"command": ["true"], "timeoutSeconds": "ten"}, timeout=5)
        assert e.value.code == 400

    def test_sessions_excluded_from_wildcard_reads(self):
        # exec stdin/stdout are as sensitive as secrets: carved out of the
        # authenticated wildcard read, granted to nodes explicitly
        from kubernetes_tpu.server.auth import (
            UserInfo,
            default_component_authorizer,
        )

        a = default_component_authorizer()
        user = UserInfo(name="alice", groups=("system:authenticated",))
        assert a.authorize(user, "get", "pods")
        assert not a.authorize(user, "list", "podexecs")
        assert not a.authorize(user, "get", "podportforwards")
        node = UserInfo(name="system:node:n1",
                        groups=("system:nodes", "system:authenticated"))
        assert a.authorize(node, "list", "podexecs")
        assert a.authorize(node, "update", "podportforwards")


class TestCpAndDiff:
    def test_cp_round_trip(self, cluster, tmp_path):
        """ktl cp local -> pod:/path -> local through the exec channel."""
        _store, srv, _rt = cluster
        src = tmp_path / "config.txt"
        src.write_text("tpu settings\nbatch=100000\n")
        rc, _, err = run_ktl(srv, "cp", str(src), "web:/etc/config.txt")
        assert rc == 0, err
        back = tmp_path / "back.txt"
        rc, _, err = run_ktl(srv, "cp", "web:/etc/config.txt", str(back))
        assert rc == 0, err
        assert back.read_text() == "tpu settings\nbatch=100000\n"

    def test_cp_missing_remote_file_fails(self, cluster, tmp_path):
        _store, srv, _rt = cluster
        rc, _, err = run_ktl(srv, "cp", "web:/no/such", str(tmp_path / "x"))
        assert rc == 1
        assert "No such file" in err

    def test_diff_shows_changes_and_exit_codes(self, cluster, tmp_path):
        _store, srv, _rt = cluster
        manifest = tmp_path / "cm.json"
        manifest.write_text(json.dumps({
            "kind": "ConfigMap", "metadata": {"name": "cm",
                                              "namespace": "default"},
            "data": {"k": "1"}}))
        rc, _, _ = run_ktl(srv, "apply", "-f", str(manifest))
        assert rc == 0
        manifest.write_text(json.dumps({
            "kind": "ConfigMap", "metadata": {"name": "cm",
                                              "namespace": "default"},
            "data": {"k": "2"}}))
        rc, out, _ = run_ktl(srv, "diff", "-f", str(manifest))
        assert rc == 1  # differences exist
        assert '-    "k": "1"' in out and '+    "k": "2"' in out
        # apply it, then diff again: clean -> rc 0
        rc, _, _ = run_ktl(srv, "apply", "-f", str(manifest))
        assert rc == 0
        rc, out, _ = run_ktl(srv, "diff", "-f", str(manifest))
        assert rc == 0, out

    def test_cp_binary_round_trip(self, cluster, tmp_path):
        """Binary content survives pod round-trips byte-for-byte (the text
        stdout channel is lossy; cp rides stdoutB64)."""
        _store, srv, _rt = cluster
        src = tmp_path / "img.bin"
        payload = bytes(range(256)) * 3 + b"\x89PNG\r\n"
        src.write_bytes(payload)
        rc, _, err = run_ktl(srv, "cp", str(src), "web:/data/img.bin")
        assert rc == 0, err
        back = tmp_path / "back.bin"
        rc, _, err = run_ktl(srv, "cp", "web:/data/img.bin", str(back))
        assert rc == 0, err
        assert back.read_bytes() == payload

    def test_cp_local_colon_filename_stays_local(self, cluster, tmp_path):
        _store, srv, _rt = cluster
        weird = tmp_path / "backup:2026.txt"
        weird.write_text("colons happen\n")
        rc, _, err = run_ktl(srv, "cp", str(weird), "web:/tmp/b.txt")
        assert rc == 0, err
        rc, out, _ = run_ktl(srv, "exec", "web", "--", "cat", "/tmp/b.txt")
        assert out == "colons happen\n"

    def test_recreated_pod_gets_fresh_filesystem(self, cluster, tmp_path):
        _store, srv, _rt = cluster
        src = tmp_path / "f.txt"
        src.write_text("old pod data")
        rc, _, _ = run_ktl(srv, "cp", str(src), "web:/f.txt")
        assert rc == 0
        store = _store
        store.delete("pods", "default/web")
        time.sleep(0.3)  # kubelet reaps the sandbox (ticking loop)
        pod = MakePod("web").req({"cpu": "100m"}).obj()
        store.create("pods", pod)
        store.bind("default", "web", "n1")
        time.sleep(0.3)
        rc, _, err = run_ktl(srv, "cp", "web:/f.txt", str(tmp_path / "o"))
        assert rc == 1
        assert "No such file" in err
