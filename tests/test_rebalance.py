"""Global rebalancer & slice defragmenter (ISSUE 17 acceptance).

The invariants under test: the jitted defrag kernel is bit-parity with its
numpy oracle (including the forced host-fallback path); the fragmentation
score has the right units (0 on consolidated/single-slice clusters, the
even-split value on smeared ones, inactive dims excluded); a rebalance
cycle consolidates a fragmented cluster within its hard migration budgets
and never touches PDB-exhausted, gang-member, or above-ceiling pods; the
no-op cycle on a below-threshold cluster is allocation-free (zero row
materializations); exactly ONE rebalancer runs per store and shard
pipelines of a partitioned scheduler are inert; a mid-wave injected fault
rolls the wave back and a mid-wave KILL leaves pod conservation clean; and
the sig-column capture satellite keeps re-synced rows seedable.
"""

import time

import numpy as np
import pytest

import kubernetes_tpu.chaos.faultinject as fi
from kubernetes_tpu.chaos.faultinject import FaultKill, FaultPlan
from kubernetes_tpu.models.defrag import (DEFRAG_MAX_VICTIMS, defrag_assign,
                                          defrag_assign_host, defrag_plan,
                                          slice_fragmentation)
from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.scheduler.rebalance import Rebalancer, _mg_name
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import (MakeNode, MakePod,
                                    assert_pod_conservation, make_pod_group,
                                    mutation_detector_guard)


@pytest.fixture(autouse=True)
def _force_mutation_detector(monkeypatch):
    yield from mutation_detector_guard(monkeypatch)


@pytest.fixture(autouse=True)
def _always_disarm():
    fi.disarm()
    yield
    fi.disarm()


def _sched(store, **kw):
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=1024, solver="fast",
                           pipeline_binds=False, **kw)
    sched.sync()
    return sched


def _slice_cluster(store, n_slices=2, per_slice=4, cpu="8"):
    for s in range(n_slices):
        for i in range(per_slice):
            store.create("nodes", MakeNode(f"node-{s}-{i}")
                         .tpu_slice(s, index=i)
                         .capacity({"cpu": cpu, "memory": "32Gi",
                                    "pods": "110"}).obj())


def _fill(store, name, node, cpu="3", prio=1, labels=None):
    p = MakePod(name).priority(prio).req({"cpu": cpu}).obj()
    if labels:
        p.metadata.labels.update(labels)
    p.spec.node_name = node
    store.create("pods", p)
    return p


def _smear(store, n_slices=2, per_slice=4, cpu="3", prio=1):
    """One filler per node: free capacity evenly smeared across slices."""
    return [_fill(store, f"low-{s}-{i}", f"node-{s}-{i}", cpu=cpu, prio=prio)
            for s in range(n_slices) for i in range(per_slice)]


# -- kernel parity -------------------------------------------------------------


def test_defrag_kernel_matches_host_oracle():
    rng = np.random.default_rng(17)
    for _ in range(30):
        ns = int(rng.integers(1, 12))
        r = int(rng.integers(1, 4))
        v = int(rng.integers(0, 16))
        free = rng.integers(0, 20, size=(ns, r)).astype(np.int64)
        head = rng.integers(0, 6, size=ns).astype(np.int64)
        ok = rng.random(ns) > 0.3
        v_req = rng.integers(0, 12, size=(v, r)).astype(np.int64)
        got = defrag_plan(free, head, ok, v_req)
        want = defrag_assign_host(free, head, ok, v_req)
        np.testing.assert_array_equal(got, want)


def test_defrag_plan_host_fallback_parity(monkeypatch):
    import kubernetes_tpu.models.defrag as defrag

    rng = np.random.default_rng(3)
    free = rng.integers(0, 20, size=(6, 3)).astype(np.int64)
    head = rng.integers(0, 6, size=6).astype(np.int64)
    ok = np.ones(6, dtype=bool)
    v_req = rng.integers(0, 12, size=(5, 3)).astype(np.int64)
    on_device = defrag_plan(free, head, ok, v_req)
    monkeypatch.setattr(defrag, "_DEFRAG_KERNEL_MAX_ELEMS", 0)
    np.testing.assert_array_equal(defrag_plan(free, head, ok, v_req),
                                  on_device)


def test_defrag_kernel_padding_invariance():
    """Pad rows (v_valid False) and pad slots (all-zero free, target_ok
    False) never change real rows' targets."""
    free = np.array([[5, 5], [9, 9]], dtype=np.int32)
    head = np.array([2, 2], dtype=np.int32)
    ok = np.array([True, True])
    v_req = np.array([[4, 4], [6, 6]], dtype=np.int64)
    out = defrag_plan(free, head, ok, v_req)
    # best fit: victim0 -> node0 (waste 2 < 8), victim1 -> node1
    np.testing.assert_array_equal(out, [0, 1])


def test_defrag_respects_headroom_and_mask():
    free = np.array([[10], [10]], dtype=np.int64)
    head = np.array([1, 0], dtype=np.int64)  # node1 has no pod slots
    ok = np.array([True, True])
    v_req = np.array([[2], [2]], dtype=np.int64)
    out = defrag_plan(free, head, ok, v_req)
    np.testing.assert_array_equal(out, [0, -1])  # node0 full after first
    out = defrag_plan(free, np.array([5, 5]), np.array([False, False]), v_req)
    np.testing.assert_array_equal(out, [-1, -1])


# -- fragmentation score -------------------------------------------------------


def test_frag_score_units():
    # even 2-slice split: 1 - 1/2
    free = np.array([[4], [4]], dtype=np.int64)
    score, per = slice_fragmentation(free, np.array([0, 1]))
    assert score == pytest.approx(0.5)
    np.testing.assert_array_equal(per, [[4], [4]])
    # all free on one slice: consolidated
    score, _ = slice_fragmentation(np.array([[8], [0]], dtype=np.int64),
                                   np.array([0, 1]))
    assert score == 0.0
    # single slice / unlabeled: moot
    assert slice_fragmentation(free, np.array([0, 0]))[0] == 0.0
    assert slice_fragmentation(free, np.array([-1, -1]))[0] == 0.0


def test_frag_score_inactive_dims_excluded():
    """A dim nothing consumes is evenly spread by construction and must not
    read as fragmentation (the memory-dim trap)."""
    free = np.array([[8, 100], [0, 100]], dtype=np.int64)
    sl = np.array([0, 1])
    assert slice_fragmentation(free, sl)[0] == pytest.approx(0.5)
    active = np.array([True, False])
    assert slice_fragmentation(free, sl, active)[0] == 0.0


# -- end-to-end consolidation --------------------------------------------------


def test_cycle_consolidates_fragmented_cluster():
    store = APIStore()
    _slice_cluster(store)
    pods = _smear(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25, budget_per_wave=2,
                                 budget_per_cycle=8, priority_ceiling=50)
    r1 = rb.cycle()
    assert r1["ran"] and r1["migrations"] == 4 and r1["waves"] == 2
    sched.pump_events()
    r2 = rb.cycle()
    assert r2["migrations"] == 0 and r2["frag"] < 0.25
    # one slice fully drained in the store
    bound = [p.spec.node_name for p in store.list("pods")[0]]
    assert all(n.startswith("node-1-") for n in bound)
    # conservation through the migration chain
    live = rb.resolve_keys([p.key for p in pods])
    assert_pod_conservation(store, sched, live)
    st = rb.stats()
    assert st["migrations"] == 4 and st["plans"] == 1
    assert sched.sched_stats()["rebalance"]["migrations"] == 4


def test_migration_names_stay_bounded():
    assert _mg_name("web-0", 3) == "web-0-mg3"
    assert _mg_name("web-0-mg3", 7) == "web-0-mg7"
    assert _mg_name("web-0-mg3x", 7) == "web-0-mg3x-mg7"


def test_noop_cycle_is_allocation_free():
    """Below-threshold probe must not materialize a single pod row: the
    score comes from the cluster tensors + the sig-free columnar view."""
    store = APIStore()
    _slice_cluster(store)
    # consolidated: all fillers on slice 0, slice 1 fully free
    for i in range(4):
        _fill(store, f"low-{i}", f"node-0-{i}", cpu="6")
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25)
    before = store.columnar_stats()["materialized_total"]
    r = rb.cycle()
    assert r["ran"] and r["migrations"] == 0
    assert store.columnar_stats()["materialized_total"] == before
    assert rb.stats()["noop_cycles"] == 1


def test_unlabeled_cluster_is_noop():
    store = APIStore()
    for i in range(3):
        store.create("nodes", MakeNode(f"plain-{i}").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "110"}).obj())
    _fill(store, "a", "plain-0", cpu="6")
    sched = _sched(store)
    rb = sched.enable_rebalancer()
    r = rb.cycle()
    assert r["ran"] and r["migrations"] == 0
    assert rb.stats()["noop_cycles"] == 1


# -- never-worse randomized sweep ---------------------------------------------


def test_randomized_never_worse_sweep():
    rng = np.random.default_rng(170)
    for trial in range(6):
        store = APIStore()
        n_slices = int(rng.integers(2, 4))
        per_slice = int(rng.integers(2, 5))
        _slice_cluster(store, n_slices=n_slices, per_slice=per_slice)
        keys, protected = [], {}
        gang_named = False
        for s in range(n_slices):
            for i in range(per_slice):
                if rng.random() < 0.3:
                    continue
                kind = rng.random()
                name = f"p-{s}-{i}"
                node = f"node-{s}-{i}"
                if kind < 0.2:
                    # above the priority ceiling: must never move
                    p = _fill(store, name, node, cpu="3", prio=1000)
                    protected[p.key] = node
                elif kind < 0.4:
                    # PDB-exhausted: must never move
                    p = _fill(store, name, node, cpu="3",
                              labels={"app": "guarded"})
                    protected[p.key] = node
                elif kind < 0.55:
                    # gang member: must never move
                    if not gang_named:
                        store.create("podgroups", make_pod_group("g", 1))
                        gang_named = True
                    p = MakePod(name).gang("g", rank=i).priority(1).req(
                        {"cpu": "3"}).obj()
                    p.spec.node_name = node
                    store.create("pods", p)
                    protected[p.key] = node
                else:
                    p = _fill(store, name, node, cpu="3", prio=1)
                keys.append(p.key)
        from kubernetes_tpu.api import ObjectMeta, Selector
        from kubernetes_tpu.api.policy import PodDisruptionBudget
        pdb = PodDisruptionBudget(
            metadata=ObjectMeta(name="guard", namespace="default"),
            selector=Selector.from_match_labels({"app": "guarded"}),
            max_unavailable=0, disruptions_allowed=0)
        store.create("poddisruptionbudgets", pdb)
        sched = _sched(store)
        budget_cycle = int(rng.integers(1, 5))
        rb = sched.enable_rebalancer(frag_threshold=0.05,
                                     budget_per_wave=2,
                                     budget_per_cycle=budget_cycle,
                                     priority_ceiling=100)
        r = rb.cycle()
        assert r.get("migrations", 0) <= budget_cycle, (trial, r)
        sched.pump_events()
        # protected pods never moved, never renamed
        for key, node in protected.items():
            p = store.get("pods", key)
            assert p.spec.node_name == node, (trial, key)
        # conservation: nothing stranded at quiescence
        sched.run_until_idle()
        assert_pod_conservation(store, sched, rb.resolve_keys(keys))
        rb.release()


# -- ownership & partition inertness ------------------------------------------


def test_one_rebalancer_per_store():
    store = APIStore()
    _slice_cluster(store)
    _smear(store)
    s1, s2 = _sched(store), _sched(store)
    rb1 = Rebalancer(s1, frag_threshold=0.25, priority_ceiling=50)
    rb2 = Rebalancer(s2, frag_threshold=0.25, priority_ceiling=50)
    assert rb1.cycle()["ran"]
    r = rb2.cycle()
    assert not r["ran"] and r["reason"] == "conflict"
    assert rb2.stats()["inert_conflict"] == 1
    # the claim releases explicitly; the successor may then own the store
    rb1.release()
    s2.pump_events()
    assert rb2.cycle()["ran"]
    rb2.release()


def test_shard_pipelines_are_inert():
    store = APIStore()
    _slice_cluster(store)
    _smear(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25, priority_ceiling=50)
    sched.partition_index = 0  # shard pipeline: partial view
    r = rb.cycle()
    assert not r["ran"] and r["reason"] == "partition"
    assert rb.stats()["inert_partition"] == 1
    sched.partition_index = -1  # residual full-view pipeline: owns it
    assert rb.cycle()["ran"]
    rb.release()


def test_maybe_cycle_paces():
    store = APIStore()
    _slice_cluster(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(min_interval_s=3600.0)
    assert rb.maybe_cycle() is not None
    assert rb.maybe_cycle() is None  # within the interval
    rb.release()


def test_run_until_idle_admits_gang_after_defrag():
    """The acceptance story end to end: a gang that cannot fit on any one
    fragmented slice admits WITHOUT preemption once the idle-path
    rebalancer consolidates a slice (gang preemption disabled so the
    destructive path cannot race the migration path)."""
    store = APIStore()
    _slice_cluster(store)
    _smear(store)  # 3 cpu used per node -> 5 free; gang needs 6
    sched = _sched(store, gang_preemption=False)
    sched.enable_rebalancer(frag_threshold=0.25, budget_per_wave=4,
                            budget_per_cycle=8, priority_ceiling=50)
    store.create("podgroups", make_pod_group("train", 4))
    gang = [MakePod(f"g-{i}").gang("train", rank=i).priority(100)
            .req({"cpu": "6"}).obj() for i in range(4)]
    store.create_many("pods", gang, consume=True)
    sched.pump_events()
    # drive loop (the gangpreempt idiom): requeues land in the backoff
    # tier, which run_until_idle deliberately does not flush
    deadline = time.time() + 15.0
    bound = {}
    while time.time() < deadline:
        sched.run_until_idle()
        sched.queue.flush_backoff_completed()
        sched.pump_events()
        bound = {p.metadata.name: p.spec.node_name
                 for p in store.list("pods")[0]
                 if p.metadata.name.startswith("g-")}
        if len(bound) == 4 and all(bound.values()):
            break
        time.sleep(0.02)
    assert len(bound) == 4 and all(bound.values()), bound
    assert sched.preemption_count == 0
    assert sched.rebalancer.stats()["migrations"] > 0
    sched.rebalancer.release()


# -- chaos ---------------------------------------------------------------------


def test_injected_cycle_fault_aborts_cleanly():
    store = APIStore()
    _slice_cluster(store)
    pods = _smear(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25, priority_ceiling=50)
    fi.arm([FaultPlan("rebalance.cycle", "fail", count=1, match="cycle")])
    r = rb.cycle()
    assert not r["ran"] and r["reason"] == "fault"
    assert rb.stats()["fault_aborts"] == 1
    assert len(store.list("pods")[0]) == len(pods)  # nothing touched
    fi.disarm()
    assert rb.cycle()["migrations"] > 0
    rb.release()


def test_midwave_fault_rolls_wave_back():
    store = APIStore()
    _slice_cluster(store)
    pods = _smear(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25, budget_per_wave=2,
                                 priority_ceiling=50)
    fi.arm([FaultPlan("rebalance.cycle", "fail", count=1, match="midwave")])
    r = rb.cycle()
    assert r["ran"] and r["aborted"] and r["migrations"] == 0
    # the wave's replacements were rolled back: original pods, original
    # nodes, no -mg duplicates
    names = sorted(p.metadata.name for p in store.list("pods")[0])
    assert names == sorted(p.metadata.name for p in pods)
    # the idle path retries once the plan is disarmed; conservation holds
    # through the (new, successful) migration chain
    sched.pump_events()
    sched.run_until_idle()
    assert_pod_conservation(store, sched,
                            rb.resolve_keys([p.key for p in pods]))
    rb.release()


def test_midwave_kill_conserves_pods():
    """A HARD kill between replacement create and victim delete leaves a
    transient duplicate — but every submitted pod stays bound exactly once
    (the ISSUE 17 chaos invariant)."""
    store = APIStore()
    _slice_cluster(store)
    pods = _smear(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25, budget_per_wave=2,
                                 priority_ceiling=50)
    fi.arm([FaultPlan("rebalance.cycle", "kill", match="midwave")])
    with pytest.raises(FaultKill):
        rb.cycle()
    # BEFORE any retry: every original still bound (delete never ran); the
    # kill's only residue is the wave's duplicate replacements
    assert_pod_conservation(store, sched, [p.key for p in pods])
    fi.disarm()
    sched.pump_events()
    sched.run_until_idle()
    assert_pod_conservation(store, sched,
                            rb.resolve_keys([p.key for p in pods]))
    rb.release()


def test_slo_probe_aborts_before_wave():
    store = APIStore()
    _slice_cluster(store)
    _smear(store)
    sched = _sched(store)
    rb = sched.enable_rebalancer(frag_threshold=0.25, priority_ceiling=50,
                                 slo_probe=lambda: False)
    r = rb.cycle()
    assert r["ran"] and r["aborted"] and r["migrations"] == 0
    assert rb.stats()["slo_aborts"] == 1
    rb.release()


# -- sig-column capture (satellite 1) -----------------------------------------


def test_sync_preserves_captured_sig_components():
    """A re-sync from a memo-less parse (status/relist writes) must not
    clobber previously captured sig refs — and a later parse sharing the
    anchors re-seeds from the preserved column entry."""
    store = APIStore()
    _slice_cluster(store)
    p = MakePod("keep").req({"cpu": "1"}).obj()
    store.create("pods", p)
    stored = store.get("pods", p.key)
    sig = (("sig",),)
    stored.__dict__["_req_sig"] = (stored.spec, sig)
    assert store.capture_sig_memos([stored]) == 1
    # a fresh memo-less object re-syncs the row (update path)
    from kubernetes_tpu.store.store import pod_structural_clone
    fresh = pod_structural_clone(stored)
    for k in ("_req_sig", "_class_sig", "_req_cache"):
        fresh.__dict__.pop(k, None)
    fresh.status.phase = "Running"
    store.update("pods", fresh)
    view = store.pod_columns()
    row = view.key2row[p.key]
    ent = view.sig[row]
    assert ent is not None and ent[1] is not None
    assert ent[1][1] is sig  # the captured ref survived the re-sync
    assert store.columnar_stats()["sig_captured"] == 1


def test_batch_path_captures_sig_memos():
    """Scheduling a batch back-fills the store's sig column for the batch's
    pods (the bind/assume-edge wiring)."""
    store = APIStore()
    _slice_cluster(store)
    pods = [MakePod(f"pend-{i}").req({"cpu": "1"}).obj() for i in range(4)]
    store.create_many("pods", pods, consume=True)
    sched = _sched(store)
    sched.run_until_idle()
    assert store.columnar_stats()["sig_captured"] >= 4
    view = store.pod_columns()
    for p in pods:
        ent = view.sig[view.key2row[p.key]]
        assert ent is not None and ent[1] is not None, p.key
