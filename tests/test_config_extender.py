"""ComponentConfig + profiles + feature gates + extender protocol tests.

Mirrors the reference's apis/config/validation tests, profile tests, and
extender tests (pkg/scheduler/extender_test.go uses a fake extender; here the
fake is a real HTTP server since the protocol is the surface)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.scheduler.config import (
    KubeSchedulerConfiguration,
    build_profiles,
)
from kubernetes_tpu.scheduler.extender import (
    ExtenderConfig,
    HTTPExtender,
    find_nodes_that_pass_extenders,
)
from kubernetes_tpu.scheduler.runtime import Framework
from kubernetes_tpu.scheduler.serial import Scheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils.featuregate import (
    FeatureGates,
    FeatureSpec,
    default_feature_gates,
)


class TestComponentConfig:
    def test_defaults(self):
        cfg = KubeSchedulerConfiguration.from_dict({})
        assert cfg.parallelism == 16
        assert cfg.pod_initial_backoff_seconds == 1.0
        assert cfg.pod_max_backoff_seconds == 10.0
        assert len(cfg.profiles) == 1
        assert cfg.profiles[0].scheduler_name == "default-scheduler"
        cfg.validate()

    @pytest.mark.parametrize("patch,msg", [
        ({"parallelism": 0}, "parallelism"),
        ({"percentageOfNodesToScore": 150}, "percentageOfNodesToScore"),
        ({"podInitialBackoffSeconds": 0}, "podInitialBackoffSeconds"),
        ({"podInitialBackoffSeconds": 20}, "podMaxBackoffSeconds"),
        ({"profiles": [{"schedulerName": "a"}, {"schedulerName": "a"}]}, "duplicate"),
        ({"profiles": [{"schedulerName": "a",
                        "plugins": {"score": {"enabled": [{"name": "NoSuch"}]}}}]},
         "unknown plugin"),
        ({"extenders": [{"weight": 1}]}, "urlPrefix"),
    ])
    def test_validation_rejects(self, patch, msg):
        cfg = KubeSchedulerConfiguration.from_dict(patch)
        with pytest.raises(ValueError, match=msg):
            cfg.validate()

    def test_profile_disable_and_weight(self):
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [
            {"schedulerName": "custom",
             "plugins": {
                 "score": {"disabled": [{"name": "ImageLocality"}],
                           "enabled": [{"name": "TaintToleration", "weight": 7}]},
                 "filter": {"disabled": [{"name": "NodePorts"}]},
             }},
        ]})
        profiles, extenders = build_profiles(cfg)
        fw = profiles["custom"]
        score_names = {p.name for p in fw.score_plugins}
        assert "ImageLocality" not in score_names
        assert "TaintToleration" in score_names
        filter_names = {p.name for p in fw.filter_plugins}
        assert "NodePorts" not in filter_names
        assert "NodeResourcesFit" in filter_names  # untouched defaults remain
        assert fw.weights["TaintToleration"] == 7
        assert not extenders

    def test_disable_star(self):
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [
            {"schedulerName": "scores-off",
             "plugins": {"score": {"disabled": [{"name": "*"}]}}},
        ]})
        profiles, _ = build_profiles(cfg)
        assert profiles["scores-off"].score_plugins == []
        assert profiles["scores-off"].filter_plugins  # other points untouched

    def test_scheduler_routes_by_profile(self):
        cfg = KubeSchedulerConfiguration.from_dict({"profiles": [
            {"schedulerName": "default-scheduler"},
            {"schedulerName": "quiet",
             "plugins": {"score": {"disabled": [{"name": "*"}]}}},
        ]})
        profiles, _ = build_profiles(cfg)
        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("pods", MakePod("a").req({"cpu": "1"}).obj())
        quiet = MakePod("b").req({"cpu": "1"}).obj()
        quiet.spec.scheduler_name = "quiet"
        store.create("pods", quiet)
        other = MakePod("c").req({"cpu": "1"}).obj()
        other.spec.scheduler_name = "not-ours"
        store.create("pods", other)
        sched = Scheduler(store, profiles=profiles)
        sched.sync()
        while sched.schedule_one(timeout=0):
            pass
        assert store.get("pods", "default/a").spec.node_name == "n1"
        assert store.get("pods", "default/b").spec.node_name == "n1"
        # not-ours is ignored entirely (eventhandlers responsibleForPod)
        assert store.get("pods", "default/c").spec.node_name == ""


class TestFromConfig:
    def test_scheduler_from_config_dict(self):
        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("pods", MakePod("a").req({"cpu": "1"}).obj())
        sched = Scheduler.from_config(store, {
            "podInitialBackoffSeconds": 2,
            "podMaxBackoffSeconds": 20,
            "profiles": [{"schedulerName": "default-scheduler"}],
        })
        assert sched.queue._initial_backoff == 2
        assert sched.queue._max_backoff == 20
        sched.sync()
        assert sched.schedule_one()
        assert store.get("pods", "default/a").spec.node_name == "n1"


class TestFeatureGates:
    def test_defaults_and_parse(self):
        gates = default_feature_gates()
        assert gates.enabled("SchedulerQueueingHints") is True
        assert gates.enabled("SchedulerAsyncPreemption") is True  # beta, on
        gates.parse("SchedulerAsyncPreemption=false,SchedulerQueueingHints=false")
        assert gates.enabled("SchedulerAsyncPreemption") is False
        assert gates.enabled("SchedulerQueueingHints") is False

    def test_unknown_and_locked(self):
        gates = FeatureGates({"Locked": FeatureSpec(True, "GA", lock_to_default=True)})
        with pytest.raises(KeyError):
            gates.enabled("NoSuch")
        with pytest.raises(ValueError):
            gates.set("Locked", False)
        gates.set("Locked", True)  # same as default: allowed

    def test_parse_errors(self):
        gates = default_feature_gates()
        with pytest.raises(ValueError):
            gates.parse("SchedulerQueueingHints")
        with pytest.raises(ValueError):
            gates.parse("SchedulerQueueingHints=maybe")


def _fake_extender_server(filter_fn=None, prioritize_fn=None, bind_calls=None):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0) or 0)
            args = json.loads(self.rfile.read(length).decode() or "{}")
            if self.path.endswith("/filter"):
                payload = filter_fn(args)
            elif self.path.endswith("/prioritize"):
                payload = prioritize_fn(args)
            elif self.path.endswith("/bind"):
                bind_calls.append(args)
                payload = {}
            else:
                payload = {"Error": "bad verb"}
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


class TestHTTPExtender:
    def test_filter_and_prioritize_merge(self):
        """Fake extender speaks the Go wire tags exactly: args carry 'pod' and
        'nodenames'; the filter reply uses 'nodenames'/'failedNodes'; the
        prioritize reply is a bare [{'host','score'}] array."""
        def filt(args):
            assert "pod" in args and "nodenames" in args
            names = args["nodenames"]
            return {"nodenames": [n for n in names if n != "n2"],
                    "failedNodes": {"n2": "extender says no"}}

        def prio(args):
            return [{"host": n, "score": 10 if n == "n3" else 0}
                    for n in args["nodenames"]]

        httpd, url = _fake_extender_server(filt, prio)
        try:
            ext = HTTPExtender(ExtenderConfig(url_prefix=url, weight=5))
            pod = MakePod("p").obj()
            failed = {}
            feasible, err = find_nodes_that_pass_extenders(
                [ext], pod, ["n1", "n2", "n3"], failed)
            assert err is None
            assert feasible == ["n1", "n3"]
            assert "n2" in failed
            totals = {"n1": 50, "n3": 50}
            from kubernetes_tpu.scheduler.extender import merge_extender_priorities

            merge_extender_priorities([ext], pod, feasible, totals)
            # 10 (raw) * 5 (weight) * 10 (MaxNodeScore/MaxExtenderPriority)
            assert totals == {"n1": 50, "n3": 550}
        finally:
            httpd.shutdown()

    def test_unreachable_ignorable_vs_fatal(self):
        pod = MakePod("p").obj()
        down = ExtenderConfig(url_prefix="http://127.0.0.1:1", timeout_seconds=0.2)
        ext = HTTPExtender(down)
        feasible, err = find_nodes_that_pass_extenders([ext], pod, ["n1"], {})
        assert err is not None  # non-ignorable extender failure aborts
        down_ok = ExtenderConfig(url_prefix="http://127.0.0.1:1",
                                 ignorable=True, timeout_seconds=0.2)
        feasible, err = find_nodes_that_pass_extenders(
            [HTTPExtender(down_ok)], pod, ["n1"], {})
        assert err is None and feasible == ["n1"]

    def test_managed_resources_interest(self):
        ext = HTTPExtender(ExtenderConfig(
            url_prefix="http://x", managed_resources=["example.com/gpu"]))
        plain = MakePod("p").req({"cpu": "1"}).obj()
        gpu = MakePod("g").req({"cpu": "1", "example.com/gpu": "2"}).obj()
        assert not ext.is_interested(plain)
        assert ext.is_interested(gpu)

    def test_scheduler_with_extender_end_to_end(self):
        """Serial scheduler consults the extender: it vetoes n1, so the pod
        lands on n2; the binder verb receives the binding."""
        bind_calls = []

        def filt(args):
            names = args["nodenames"]
            return {"nodenames": [n for n in names if n != "n1"],
                    "failedNodes": {n: "no" for n in names if n == "n1"}}

        httpd, url = _fake_extender_server(filt, lambda a: [], bind_calls)
        try:
            store = APIStore()
            for name in ("n1", "n2"):
                store.create("nodes", MakeNode(name).capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
            store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
            ext = HTTPExtender(ExtenderConfig(url_prefix=url))
            sched = Scheduler(store, Framework(default_plugins()), extenders=[ext])
            sched.sync()
            assert sched.schedule_one()
            assert store.get("pods", "default/p").spec.node_name == "n2"
        finally:
            httpd.shutdown()


class TestNominatedNodeExtender:
    def test_nominated_node_must_pass_extenders(self):
        """A nominated node an extender rejects must not be used
        (evaluateNominatedNode runs findNodesThatPassExtenders too)."""
        def filt(args):
            names = args["nodenames"]
            return {"nodenames": [n for n in names if n != "n1"],
                    "failedNodes": {n: "no" for n in names if n == "n1"}}

        httpd, url = _fake_extender_server(filt, lambda a: [])
        try:
            store = APIStore()
            for name in ("n1", "n2"):
                store.create("nodes", MakeNode(name).capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
            pod = MakePod("p").req({"cpu": "1"}).obj()
            pod.status.nominated_node_name = "n1"
            store.create("pods", pod)
            ext = HTTPExtender(ExtenderConfig(url_prefix=url))
            sched = Scheduler(store, Framework(default_plugins()), extenders=[ext])
            sched.sync()
            assert sched.schedule_one()
            assert store.get("pods", "default/p").spec.node_name == "n2"
        finally:
            httpd.shutdown()


class TestBatchExtenderServer:
    def test_tpu_row_behind_extender_protocol(self):
        """A stock scheduler's HTTPExtender against the TPU batch extender:
        full nodes are rejected, scores prefer the emptier node."""
        from kubernetes_tpu.scheduler import Cache
        from kubernetes_tpu.server.extender import BatchExtenderServer
        from kubernetes_tpu.utils import FakeClock

        cache = Cache(clock=FakeClock())
        cache.add_node(MakeNode("full").capacity(
            {"cpu": "1", "memory": "1Gi", "pods": "10"}).obj())
        cache.add_node(MakeNode("busy").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "10"}).obj())
        cache.add_node(MakeNode("empty").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "10"}).obj())
        cache.add_pod(MakePod("hog").req({"cpu": "6"}).node("busy").obj())
        server = BatchExtenderServer(cache.update_snapshot).start()
        try:
            ext = HTTPExtender(ExtenderConfig(url_prefix=server.url, timeout_seconds=120.0))  # first call may JIT-compile
            pod = MakePod("p").req({"cpu": "2", "memory": "2Gi"}).obj()
            result = ext.filter(pod, ["full", "busy", "empty"])
            assert result.node_names == ["busy", "empty"]
            assert "full" in result.failed_nodes
            scores = ext.prioritize(pod, ["busy", "empty"])
            assert scores["empty"] > scores["busy"]
        finally:
            server.stop()

    def test_fallback_class_passes_through(self):
        from kubernetes_tpu.scheduler import Cache
        from kubernetes_tpu.server.extender import BatchExtenderServer
        from kubernetes_tpu.utils import FakeClock

        cache = Cache(clock=FakeClock())
        cache.add_node(MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        server = BatchExtenderServer(cache.update_snapshot).start()
        try:
            ext = HTTPExtender(ExtenderConfig(url_prefix=server.url, timeout_seconds=120.0))  # first call may JIT-compile
            pod = MakePod("p").req({"cpu": "1"}).pvc("claim").obj()
            result = ext.filter(pod, ["n1"])
            assert result.node_names == ["n1"]  # pass-through, no veto
        finally:
            server.stop()
