"""Controller + hollow-node tests: the full control loop without machines
(SURVEY.md §4 kubemark tier — real logic, fake CRI)."""

import pytest

from kubernetes_tpu.agent import HollowCluster, HollowKubelet
from kubernetes_tpu.api.workloads import Deployment, ReplicaSet
from kubernetes_tpu.controllers import (
    DeploymentController,
    NodeLifecycleController,
    ReplicaSetController,
)
from kubernetes_tpu.scheduler import Framework, Scheduler
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore, NotFoundError
from kubernetes_tpu.testing import MakeNode
from kubernetes_tpu.utils import FakeClock


def make_rs(name="web", replicas=3, labels=None, cpu="100m"):
    labels = labels or {"app": name}
    return ReplicaSet.from_dict({
        "metadata": {"name": name},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}]},
            },
        },
    })


class TestReplicaSetController:
    def test_scale_up_and_down(self):
        store = APIStore()
        rsc = ReplicaSetController(store)
        rsc.sync_all()
        store.create("replicasets", make_rs(replicas=3))
        rsc.reconcile_once()
        pods, _ = store.list("pods")
        assert len(pods) == 3
        assert all(p.metadata.owner_references[0]["kind"] == "ReplicaSet" for p in pods)

        def scale(rs):
            rs.spec.replicas = 1
            return rs

        store.guaranteed_update("replicasets", "default/web", scale)
        rsc.run_until_stable()
        pods, _ = store.list("pods")
        assert len(pods) == 1

    def test_replaces_deleted_pod(self):
        store = APIStore()
        rsc = ReplicaSetController(store)
        rsc.sync_all()
        store.create("replicasets", make_rs(replicas=2))
        rsc.run_until_stable()
        pods, _ = store.list("pods")
        store.delete("pods", pods[0].key)
        rsc.run_until_stable()
        pods, _ = store.list("pods")
        assert len(pods) == 2

    def test_cascade_delete(self):
        store = APIStore()
        rsc = ReplicaSetController(store)
        rsc.sync_all()
        store.create("replicasets", make_rs(replicas=2))
        rsc.run_until_stable()
        store.delete("replicasets", "default/web")
        rsc.run_until_stable()
        pods, _ = store.list("pods")
        assert pods == []


class TestDeploymentController:
    def test_creates_rs_and_scales(self):
        store = APIStore()
        dc = DeploymentController(store)
        rsc = ReplicaSetController(store)
        dc.sync_all()
        rsc.sync_all()
        store.create("deployments", Deployment.from_dict({
            "metadata": {"name": "api"},
            "spec": {
                "replicas": 4,
                "selector": {"matchLabels": {"app": "api"}},
                "template": {"metadata": {"labels": {"app": "api"}},
                             "spec": {"containers": [{"name": "c"}]}},
            },
        }))
        for _ in range(5):
            dc.reconcile_once()
            rsc.reconcile_once()
        rses, _ = store.list("replicasets")
        assert len(rses) == 1 and rses[0].spec.replicas == 4
        pods, _ = store.list("pods")
        assert len(pods) == 4
        assert all("pod-template-hash" in p.metadata.labels for p in pods)

    def test_rolling_update_creates_new_rs(self):
        store = APIStore()
        dc = DeploymentController(store)
        rsc = ReplicaSetController(store)
        dc.sync_all()
        rsc.sync_all()
        dep = Deployment.from_dict({
            "metadata": {"name": "api"},
            "spec": {
                "replicas": 2,
                "selector": {"matchLabels": {"app": "api"}},
                "template": {"metadata": {"labels": {"app": "api"}},
                             "spec": {"containers": [{"name": "c", "image": "v1"}]}},
            },
        })
        store.create("deployments", dep)
        for _ in range(5):
            dc.reconcile_once()
            rsc.reconcile_once()

        def update(d):
            d.spec.template.spec.containers[0].image = "v2"
            return d

        store.guaranteed_update("deployments", "default/api", update)
        # pods never go Running (no kubelet) -> old RS can shrink only within
        # maxUnavailable; with the default maxUnavailable=0 old stays until new
        # pods run. Mark new pods Running by hand to let the rollout finish.
        for _ in range(10):
            dc.reconcile_once()
            rsc.reconcile_once()
            pods, _ = store.list("pods")
            for p in pods:
                if p.status.phase != "Running":
                    store.update_pod_status(p.metadata.namespace, p.metadata.name,
                                            lambda st: setattr(st, "phase", "Running"))
        rses, _ = store.list("replicasets")
        by_image = {rs.spec.template.spec.containers[0].image: rs.spec.replicas for rs in rses}
        assert by_image.get("v2") == 2
        assert by_image.get("v1", 0) == 0


class TestNodeLifecycle:
    def test_unhealthy_node_tainted_and_evicted(self):
        clock = FakeClock(start=100.0)
        store = APIStore()
        kubelet = HollowKubelet(store, "n0", clock=clock)
        kubelet.register()
        nlc = NodeLifecycleController(store, clock=clock, grace_period=40.0)
        nlc.sync_all()
        nlc.monitor()
        node = store.get("nodes", "n0")
        assert not any(t.key == "node.kubernetes.io/not-ready" for t in node.spec.taints)

        # bind a pod, then stop heartbeating past grace
        from kubernetes_tpu.testing import MakePod

        store.create("pods", MakePod("victim").req({"cpu": "1"}).obj())
        store.bind("default", "victim", "n0")
        clock.step(41)
        nlc.monitor()
        node = store.get("nodes", "n0")
        assert any(t.key == "node.kubernetes.io/not-ready" and t.effect == "NoExecute"
                   for t in node.spec.taints)
        conds = {c.type: c.status for c in node.status.conditions}
        assert conds["Ready"] == "False"
        with pytest.raises(NotFoundError):
            store.get("pods", "default/victim")

        # heartbeat resumes -> taint cleared
        kubelet.heartbeat()
        nlc.monitor()
        node = store.get("nodes", "n0")
        assert not any(t.key == "node.kubernetes.io/not-ready" for t in node.spec.taints)
        conds = {c.type: c.status for c in node.status.conditions}
        assert conds["Ready"] == "True"


class TestFullControlLoop:
    def test_deployment_to_running_pods_via_hollow_nodes(self):
        """The whole system: Deployment -> RS -> pods -> scheduler binds ->
        hollow kubelets run them -> status flows back to RS/Deployment."""
        store = APIStore()
        cluster = HollowCluster(store, n_nodes=4, zone_count=2)
        cluster.register_all()
        sched = BatchScheduler(store, Framework(default_plugins()), solver="auto")
        sched.sync()
        dc = DeploymentController(store)
        rsc = ReplicaSetController(store)
        dc.sync_all()
        rsc.sync_all()

        store.create("deployments", Deployment.from_dict({
            "metadata": {"name": "web"},
            "spec": {
                "replicas": 8,
                "selector": {"matchLabels": {"app": "web"}},
                "template": {"metadata": {"labels": {"app": "web"}},
                             "spec": {"containers": [{"name": "c", "resources": {
                                 "requests": {"cpu": "500m"}}}]}},
            },
        }))
        for _ in range(8):
            dc.reconcile_once()
            rsc.reconcile_once()
            sched.run_until_idle()
            cluster.pump_all()
        pods, _ = store.list("pods")
        assert len(pods) == 8
        assert all(p.spec.node_name for p in pods)
        assert all(p.status.phase == "Running" for p in pods)
        dep = store.get("deployments", "default/web")
        assert dep.status.ready_replicas == 8

    def test_node_failure_reschedules_pods(self):
        """Failure detection end to end: node dies -> taint+evict -> RS
        replaces -> scheduler binds replacements elsewhere."""
        clock = FakeClock(start=0.0)
        store = APIStore()
        kubelets = [HollowKubelet(store, f"n{i}", clock=clock) for i in range(3)]
        for k in kubelets:
            k.register()
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        rsc = ReplicaSetController(store)
        rsc.sync_all()
        nlc = NodeLifecycleController(store, clock=clock, grace_period=40.0)
        nlc.sync_all()

        store.create("replicasets", make_rs(replicas=3))
        for _ in range(4):
            rsc.reconcile_once()
            sched.run_until_idle()
            for k in kubelets:
                k.pump()
        pods, _ = store.list("pods")
        assert all(p.spec.node_name for p in pods)

        # n0 dies: others keep heartbeating
        clock.step(41)
        for k in kubelets[1:]:
            k.heartbeat()
        nlc.monitor()
        for _ in range(6):
            rsc.reconcile_once()
            sched.run_until_idle()
            for k in kubelets[1:]:
                k.pump()
        pods, _ = store.list("pods")
        assert len(pods) == 3
        assert all(p.spec.node_name in ("n1", "n2") for p in pods)


def test_deployment_scale_down():
    """Scaling a deployment down must shrink the current ReplicaSet."""
    store = APIStore()
    dc, rsc = DeploymentController(store), ReplicaSetController(store)
    dc.sync_all()
    rsc.sync_all()
    store.create("deployments", Deployment.from_dict({
        "metadata": {"name": "web"},
        "spec": {"replicas": 6, "selector": {"matchLabels": {"app": "web"}},
                 "template": {"metadata": {"labels": {"app": "web"}},
                              "spec": {"containers": [{"name": "c"}]}}},
    }))
    for _ in range(5):
        dc.reconcile_once()
        rsc.reconcile_once()
    assert len(store.list("pods")[0]) == 6

    def scale(d):
        d.spec.replicas = 2
        return d

    store.guaranteed_update("deployments", "default/web", scale)
    for _ in range(5):
        dc.reconcile_once()
        rsc.reconcile_once()
    assert len(store.list("pods")[0]) == 2
