"""ISSUE 11: native host commit engine — native-vs-Python parity suite.

The C-API engine (native/hostcommit.cpp) must be BYTE-IDENTICAL to the
Python oracles it replaces: same store rows, same RV sequence, same event
stream (lazy slot layout included), same placements — across BOTH
watch_coalesce modes, with the mutation detector forced (autouse below), on
the bind, delete, assume, and build_pod_batch paths. Plus: a forced-fallback
leg proving a rig without g++ (or with the HOSTSCHED_NATIVE_COMMIT kill
switch thrown) runs the identical workload through the Python paths, and a
chaos leg proving a mid-chunk native fault leaves the store untouched.
"""

import json

import numpy as np
import pytest

from kubernetes_tpu.api.serialize import to_dict
from kubernetes_tpu.native import hostcommit
from kubernetes_tpu.store import APIStore, CoalescedEvent
from kubernetes_tpu.testing import MakeNode, MakePod, mutation_detector_guard


@pytest.fixture(autouse=True)
def _force_mutation_detector(monkeypatch):
    yield from mutation_detector_guard(monkeypatch)


NATIVE = hostcommit.available()
needs_native = pytest.mark.skipif(
    not NATIVE, reason="native commit engine unavailable (no g++?)")


def _dump(obj):
    return json.dumps(to_dict(obj), sort_keys=True, default=repr)


def _pods(n, prefix="p"):
    """Deterministic pod set: fixed uids so two independent builds are
    byte-identical (MakePod's uid sequence is process-global)."""
    out = []
    for i in range(n):
        p = MakePod(f"{prefix}-{i}").req({"cpu": "100m",
                                          "memory": "64Mi"}).obj()
        p.metadata.uid = f"uid-{prefix}-{i}"
        out.append(p)
    return out


def _store_with_watchers(native, lazy=None, deep_copy=True, detector=None):
    # detector=False opts a SHARE-MODE store out of the autouse mutation
    # detector: deep_copy_on_write=False means no isolation contract at all
    # (delete() legitimately re-stamps the caller-shared object in place),
    # so the detector's read-only premise doesn't apply there
    # columnar=False: this module pins the DICT commit engine's
    # native-vs-Python parity (the columnar path would bypass the engine's
    # bind/delete loops and leave the inspected dict rows lazily stale);
    # the columnar twin of this suite lives in tests/test_columnar_store.py
    store = APIStore(native_commit=native, lazy_pod_events=lazy,
                     deep_copy_on_write=deep_copy,
                     mutation_detector=detector, columnar=False)
    per_obj = store.watch(kind=("pods",))
    coal = store.watch(kind=("pods",), coalesce=True)
    return store, per_obj, coal


def _event_sig(ev):
    return (type(ev).__name__, ev.type, ev.kind, ev.resource_version,
            _dump(ev.obj), _dump(ev.prev) if ev.prev is not None else None)


def _stream_sig(watch):
    out = []
    for ev in watch.drain():
        if isinstance(ev, CoalescedEvent):
            out.append(("coalesced", ev.type, ev.kind, ev.resource_version,
                        ev.origin, tuple(_event_sig(e) for e in ev.events)))
        else:
            out.append(_event_sig(ev))
    return out


# ---------------------------------------------------------------------------
# store-level parity: bind_many / delete_pods
# ---------------------------------------------------------------------------


@needs_native
@pytest.mark.parametrize("mode", ["lazy", "eager", "share"])
def test_bind_many_parity_rows_rv_events(mode):
    """Same workload through the native engine and the Python oracle: rows,
    RV sequence, error list, per-object AND coalesced event streams all
    byte-identical — including the error paths (missing pod, already bound,
    duplicate key within one batch, which exercises the phase-2 re-validate
    branch: the second commit must see the first). All THREE event modes:
    lazy (default), eager (STORE_LAZY_POD_EVENTS=0 oracle), and share
    (deep_copy_on_write=False — the perf-harness store; native mode 0)."""
    results = {}
    for native in (True, False):
        store, per_obj, coal = _store_with_watchers(
            native, lazy=(mode == "lazy") if mode != "share" else None,
            deep_copy=(mode != "share"))
        store.create_many("pods", _pods(64), consume=True)
        per_obj.drain(), coal.drain()
        rv0 = store.rv
        triples = [("default", f"p-{i}", f"node-{i % 7}") for i in range(64)]
        triples.append(("default", "p-3", "node-9"))   # dup: raced re-check
        triples.append(("default", "ghost", "node-0"))  # missing
        bound, errors = store.bind_many(triples, origin="t")
        # a second call re-binding is all-errors (already bound)
        bound2, errors2 = store.bind_many(triples[:4], origin="t")
        rows = sorted((k, _dump(p))
                      for k, p in store._objects["pods"].items())
        results[native] = (rv0, store.rv, bound, sorted(errors), bound2,
                           sorted(errors2), rows, _stream_sig(per_obj),
                           _stream_sig(coal))
        assert bound == 64 and bound2 == 0
        assert len(errors) == 2, errors
        store.check_mutations()
    assert results[True] == results[False]


@needs_native
@pytest.mark.parametrize("mode", ["lazy", "eager", "share"])
def test_delete_pods_parity(mode):
    """Batched pod delete (the PreemptionAsync victim path): same rows gone,
    same DELETED event stream (one structural clone at the post-delete RV,
    prev=old; share mode stamps the popped object itself, like delete()),
    same errors, native vs Python — all three event modes, like bind."""
    results = {}
    for native in (True, False):
        store, per_obj, coal = _store_with_watchers(
            native, lazy=(mode == "lazy") if mode != "share" else None,
            deep_copy=(mode != "share"),
            detector=(False if mode == "share" else None))
        store.create_many("pods", _pods(20, "v"), consume=True)
        per_obj.drain(), coal.drain()
        n, errors = store.delete_pods(
            [f"default/v-{i}" for i in range(10)] + ["default/missing"],
            origin="t")
        assert n == 10 and errors == [
            ("default/missing", "pods default/missing not found")]
        rows = sorted(store._objects["pods"])
        results[native] = (store.rv, rows, _stream_sig(per_obj),
                           _stream_sig(coal))
        store.check_mutations()
    assert results[True] == results[False]


@needs_native
def test_bind_many_accepts_list_entries_like_oracle():
    """The Python loops unpack ANY sequence (`for ns, name, node in ...`);
    the native engine must accept list triples/pairs identically instead of
    requiring exact tuples (same for the assume pairs)."""
    from kubernetes_tpu.scheduler.cache import Cache
    from kubernetes_tpu.store import pod_bind_clone

    store, _w, _c = _store_with_watchers(True)
    store.create_many("pods", _pods(4, "l"), consume=True)
    bound, errors = store.bind_many(
        [["default", f"l-{i}", "node-0"] for i in range(4)])
    assert bound == 4 and not errors
    cache = Cache()
    cache.add_node(MakeNode("node-0").capacity(
        {"cpu": "8", "memory": "8Gi", "pods": "110"}).obj())
    pairs = [[pod_bind_clone(p), "node-0"] for p in _pods(3, "lc")]
    assert cache.assume_pods_structural(pairs, check_ports=False) == []
    assert cache.pod_count() == 3


@needs_native
def test_bind_commit_raced_row_replacement_keeps_prev_alive():
    """The phase-gap race branch: a row replaced between prepare and commit
    is re-validated and re-cloned from the CURRENT object, and the event's
    prev is that replacement — which the commit's row swap just dropped the
    dict's (sole) reference to. The engine must hold its own strong ref
    (the UAF a borrowed `old = cur` caused); the event fields prove it."""
    from kubernetes_tpu.store.store import MODIFIED, pod_bind_clone

    pods = {}
    first = _pods(1, "r")[0]
    pods["default/r-0"] = first
    prepared, errors, events = [], [], []
    hostcommit.bind_prepare(pods, [("default", "r-0", "node-1")],
                            prepared, errors)
    assert len(prepared) == 1 and not errors
    # a concurrent writer replaces the row in the phase gap; the dict holds
    # the ONLY reference to the replacement
    repl = _pods(1, "r")[0]
    repl.metadata.uid = "uid-replacement"
    pods["default/r-0"] = repl
    del repl, first
    rv, bound = hostcommit.bind_commit(pods, prepared, events, errors,
                                       10, 1, 0.0, pod_bind_clone, MODIFIED)
    assert (rv, bound) == (11, 1) and not errors
    ev = events[0]
    assert ev.prev.metadata.uid == "uid-replacement"  # alive + correct
    assert ev.obj is pods["default/r-0"]
    assert ev.obj.spec.node_name == "node-1"
    assert ev.obj.metadata.resource_version == 11
    # a raced row that came back BOUND errors instead
    prepared2, errors2, events2 = [], [], []
    hostcommit.bind_prepare(pods, [("default", "r-0", "node-2")],
                            prepared2, errors2)
    assert not prepared2 and "already bound to node-1" in errors2[0][1]


@needs_native
def test_delete_pods_duplicate_key_and_midbatch_atomicity():
    """A duplicate key in one batch errors like the pop it replaces ("not
    found" on the second occurrence), on both paths — and the build-then-pop
    structure means an erroring batch never strands popped-but-unnarrated
    rows (every removed row has its DELETED event in the same batch)."""
    for native in (True, False):
        store, per_obj, _ = _store_with_watchers(native)
        store.create_many("pods", _pods(4, "q"), consume=True)
        per_obj.drain()
        n, errors = store.delete_pods(
            ["default/q-0", "default/q-0", "default/q-1"])
        assert n == 2, (native, n)
        assert errors == [("default/q-0", "pods default/q-0 not found")], (
            native, errors)
        evs = [e for e in per_obj.drain() if e.type == "DELETED"]
        assert [e.obj.metadata.name for e in evs] == ["q-0", "q-1"]
        assert "default/q-0" not in store._objects["pods"]


@needs_native
def test_delete_pods_matches_per_pod_delete_semantics():
    """delete_pods' per-pod event must match what N delete() calls emit
    (modulo the coalesced channel): same object content at the same RVs."""
    s_bulk, w_bulk, _ = _store_with_watchers(True)
    s_one, w_one, _ = _store_with_watchers(True)
    for s in (s_bulk, s_one):
        s.create_many("pods", _pods(6, "d"), consume=True)
    w_bulk.drain(), w_one.drain()
    keys = [f"default/d-{i}" for i in range(6)]
    s_bulk.delete_pods(keys)
    for k in keys:
        s_one.delete("pods", k)
    assert [_event_sig(e) for e in w_bulk.drain()] == \
        [_event_sig(e) for e in w_one.drain()]


# ---------------------------------------------------------------------------
# cache + tensorizer parity
# ---------------------------------------------------------------------------


def _cache_fingerprint(cache):
    out = {}
    for name, ni in cache._nodes.items():
        out[name] = (
            sorted(pi.pod.key for pi in ni.pods),
            sorted(pi.pod.key for pi in ni.pods_with_affinity),
            sorted(pi.pod.key for pi in ni.pods_with_required_anti_affinity),
            sorted(ni.used_ports),
        )
    return (out, dict(cache._pod_nodes), dict(cache._assumed))


@needs_native
def test_assume_structural_parity(monkeypatch):
    """Native vs Python assume loop: identical failure list and identical
    NodeInfo membership (pods, affinity sublists) — including a duplicate
    pod, an affinity pod, and a pod with no memoized request pair (the cold
    PodInfo constructor fallback)."""
    from kubernetes_tpu.scheduler.cache import Cache
    from kubernetes_tpu.store import pod_bind_clone

    def build(native_env):
        monkeypatch.setenv("HOSTSCHED_NATIVE_COMMIT",
                           "1" if native_env else "0")
        cache = Cache()
        for i in range(4):
            cache.add_node(MakeNode(f"node-{i}").capacity(
                {"cpu": "8", "memory": "8Gi", "pods": "110"}).obj())
        pods = _pods(12, "a")
        pairs = [(pod_bind_clone(p), f"node-{i % 4}")
                 for i, p in enumerate(pods)]
        # seed the request memo on SOME pods only (both code paths in play)
        from kubernetes_tpu.api import compute_pod_resource_request

        for qp, _node in pairs[:6]:
            qp.__dict__["_req_cache"] = (
                compute_pod_resource_request(qp),
                compute_pod_resource_request(qp, non_zero=True))
        failed = cache.assume_pods_structural(list(pairs),
                                              check_ports=False)
        # duplicate assume must fail identically
        failed2 = cache.assume_pods_structural([pairs[0]],
                                               check_ports=False)
        return failed, failed2, _cache_fingerprint(cache)

    f_nat, f2_nat, fp_nat = build(True)
    f_py, f2_py, fp_py = build(False)
    assert f_nat == f_py == []
    assert f2_nat == f2_py
    assert "already in the cache" in f2_nat[0][1]
    assert fp_nat == fp_py


@needs_native
def test_assume_structural_affinity_sublists(monkeypatch):
    """Pods with inter-pod (anti-)affinity land in the affinity sublists on
    both paths."""
    from kubernetes_tpu.api.labels import Selector
    from kubernetes_tpu.api.types import Affinity, PodAffinityTerm
    from kubernetes_tpu.scheduler.cache import Cache
    from kubernetes_tpu.store import pod_bind_clone

    def mk_aff(name):
        p = MakePod(name).req({"cpu": "100m"}).obj()
        p.metadata.uid = f"uid-{name}"
        term = PodAffinityTerm(
            topology_key="kubernetes.io/hostname",
            selector=Selector.from_match_labels({"k": "v"}))
        p.spec.affinity = Affinity(pod_anti_affinity_required=[term])
        return p

    def build(native_env):
        monkeypatch.setenv("HOSTSCHED_NATIVE_COMMIT",
                           "1" if native_env else "0")
        cache = Cache()
        cache.add_node(MakeNode("node-0").capacity(
            {"cpu": "8", "memory": "8Gi", "pods": "110"}).obj())
        pairs = [(pod_bind_clone(mk_aff(f"aff-{i}")), "node-0")
                 for i in range(3)]
        failed = cache.assume_pods_structural(pairs, check_ports=False)
        return failed, _cache_fingerprint(cache)

    got_nat = build(True)
    got_py = build(False)
    assert got_nat == got_py
    ni = got_nat[1][0]["node-0"]
    assert len(ni[1]) == 3 and len(ni[2]) == 3  # both affinity sublists


@needs_native
def test_build_pod_batch_rows_parity(monkeypatch):
    """The fused per-pod loop: identical class_of_pod / request rows /
    balanced flags / rep_pods native vs Python, over a batch mixing
    template-stamped classes, distinct labels, and distinct requests."""
    from kubernetes_tpu.scheduler.cache import Cache
    from kubernetes_tpu.snapshot.tensorizer import (build_cluster_tensors,
                                                    build_pod_batch)

    def mk_batch():
        pods = []
        for i in range(40):
            p = MakePod(f"b-{i}").req(
                {"cpu": "100m"} if i % 3 else {"cpu": "250m"}).obj()
            p.metadata.uid = f"uid-b-{i}"
            if i % 5 == 0:
                p.metadata.labels = {"grp": f"g{i % 2}"}
            pods.append(p)
        return pods

    def build(native_env):
        monkeypatch.setenv("HOSTSCHED_NATIVE_COMMIT",
                           "1" if native_env else "0")
        cache = Cache()
        for i in range(8):
            cache.add_node(MakeNode(f"node-{i}").capacity(
                {"cpu": "8", "memory": "8Gi", "pods": "110"}).obj())
        snap = cache.update_snapshot()
        cluster = build_cluster_tensors(snap)
        batch = build_pod_batch(mk_batch(), snap, cluster)
        return (batch.class_of_pod.tolist(), batch.req.tolist(),
                batch.req_nz.tolist(), batch.raw_req.tolist(),
                batch.balanced_active.tolist(),
                [p.metadata.name for p in batch.tables.rep_pods])

    assert build(True) == build(False)


@needs_native
def test_scatter_deltas_parity():
    from kubernetes_tpu.native import native_available, native_commit_deltas

    assert native_available()
    rng = np.random.default_rng(7)
    p_all, p, n, r = 500, 300, 40, 4
    rows = rng.integers(0, p_all, p)
    nodes = rng.integers(0, n, p)
    raw = rng.integers(0, 1000, (p_all, r)).astype(np.int64)
    raw_nz = rng.integers(0, 1000, (p_all, r)).astype(np.int64)
    du, dz, dc, touched = native_commit_deltas(rows, nodes, raw, raw_nz, n)
    du2 = np.zeros((n, r), np.int64)
    dz2 = np.zeros((n, r), np.int64)
    np.add.at(du2, nodes, raw[rows])
    np.add.at(dz2, nodes, raw_nz[rows])
    assert (du == du2).all() and (dz == dz2).all()
    assert (dc == np.bincount(nodes, minlength=n)).all()
    assert (touched == np.unique(nodes)).all()


@needs_native
def test_scatter_deltas_bad_index_raises_like_oracle():
    """An out-of-range node/row must surface as a catchable IndexError
    (what np.add.at raises — the assume/dispatch failure-domain guard's
    contract), never a silent out-of-bounds write; the kernel validates
    before writing, so the deltas stay zero."""
    from kubernetes_tpu.native import native_commit_deltas

    raw = np.ones((4, 2), dtype=np.int64)
    with pytest.raises(IndexError):
        native_commit_deltas(np.array([0, 1]), np.array([0, 9]), raw, raw, 3)
    with pytest.raises(IndexError):
        native_commit_deltas(np.array([0, 7]), np.array([0, 1]), raw, raw, 3)
    with pytest.raises(IndexError):
        native_commit_deltas(np.array([-1]), np.array([0]), raw, raw, 3)


# ---------------------------------------------------------------------------
# end-to-end placement parity, both watch_coalesce modes
# ---------------------------------------------------------------------------


@needs_native
@pytest.mark.parametrize("coalesce", [True, False])
def test_e2e_placement_parity_native_vs_python(coalesce, monkeypatch):
    """The whole pipeline — ingest, build_pod_batch, solve, assume, bind —
    with the native engine on vs off must produce byte-identical placements
    and store dumps, in BOTH watch_coalesce modes, with the mutation
    detector forced (autouse)."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins

    def run(native):
        monkeypatch.setenv("HOSTSCHED_NATIVE_COMMIT",
                           "1" if native else "0")
        store = APIStore(native_commit=native)
        for i in range(16):
            store.create("nodes", MakeNode(f"node-{i}").capacity(
                {"cpu": "16", "memory": "64Gi", "pods": "110"}).obj())
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=1024, solver="fast",
                               columnar=coalesce)
        sched.watch_coalesce = coalesce
        sched.sync()
        store.create_many("pods", _pods(512, "e"), consume=True)
        sched.run_until_idle()
        pods, rv = store.list("pods")
        placements = sorted((p.key, p.spec.node_name,
                             p.metadata.resource_version) for p in pods)
        dump = sorted(_dump(p) for p in pods)
        store.check_mutations()
        return placements, rv, dump, sched.scheduled_count

    got_native = run(True)
    got_python = run(False)
    assert got_native == got_python
    assert got_native[3] == 512


# ---------------------------------------------------------------------------
# forced fallback (a rig without g++) + kill switch + chaos
# ---------------------------------------------------------------------------


def test_forced_fallback_without_gxx(monkeypatch):
    """A rig whose compile fails (no g++ / no Python headers) must keep the
    identical store surface on the Python paths: available() False, binds
    and deletes work, and the scheduler pipeline completes — the in-tier
    descendant of the bench_fallback ladder run."""
    monkeypatch.setattr(hostcommit, "_lib", None)
    monkeypatch.setattr(hostcommit, "_build_error",
                        "g++ failed: command not found")
    assert hostcommit.available() is False
    assert "g++" in hostcommit.build_error()
    store = APIStore(native_commit=True)  # wants native, engine dead
    assert store._native_commit_engine() is None
    store.create_many("pods", _pods(8, "f"), consume=True)
    bound, errors = store.bind_many(
        [("default", f"f-{i}", "node-0") for i in range(8)])
    assert bound == 8 and not errors
    n, errs = store.delete_pods(["default/f-0", "default/f-1"])
    assert n == 2 and not errs


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("HOSTSCHED_NATIVE_COMMIT", "0")
    assert hostcommit.available() is False
    monkeypatch.delenv("HOSTSCHED_NATIVE_COMMIT")


@needs_native
def test_chaos_native_commit_fault_leaves_store_untouched():
    """The native.commit injection site fires in bind_many's phase gap —
    clones made, NOTHING committed — so an injected mid-chunk fault must
    leave rows, RV, and events exactly as before, and a plain retry
    succeeds (what the supervised bind worker does)."""
    from kubernetes_tpu.chaos import faultinject as fi

    store, per_obj, coal = _store_with_watchers(True)
    store.create_many("pods", _pods(16, "c"), consume=True)
    per_obj.drain(), coal.drain()
    rv0 = store.rv
    fi.arm([fi.FaultPlan("native.commit", "fail", count=1)])
    try:
        with pytest.raises(fi.FaultInjected):
            store.bind_many([("default", f"c-{i}", "node-0")
                             for i in range(16)])
        assert store.rv == rv0  # nothing committed
        assert not per_obj.drain() and not coal.drain()
        assert all(not p.spec.node_name
                   for p in store._objects["pods"].values())
        bound, errors = store.bind_many(
            [("default", f"c-{i}", "node-0") for i in range(16)])
        assert bound == 16 and not errors
    finally:
        fi.disarm()


@needs_native
def test_chaos_native_fault_e2e_conservation():
    """Mid-chunk native faults under the real bind worker: the supervised
    retry absorbs them and every pod still binds (pod conservation)."""
    from kubernetes_tpu.chaos import faultinject as fi
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.testing import assert_pod_conservation

    store = APIStore(native_commit=True)
    for i in range(8):
        store.create("nodes", MakeNode(f"node-{i}").capacity(
            {"cpu": "16", "memory": "64Gi", "pods": "110"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=256, solver="fast",
                           bind_retry_base_s=0.01)
    sched.bind_chunk = 64
    sched.sync()
    pods = _pods(256, "cc")
    keys = [p.key for p in pods]
    store.create_many("pods", pods, consume=True)
    fi.arm([fi.FaultPlan("native.commit", "fail", count=2)])
    try:
        sched.run_until_idle()
    finally:
        fi.disarm()
    sched.run_until_idle()
    sched.flush_binds()
    assert_pod_conservation(store, sched, keys)
    assert sched.scheduled_count == 256


def test_bench_bind_commit_publishes_native_column(monkeypatch):
    """The BindCommit_20k rung publishes the python-vs-native columns even
    on a forced-fallback rig (native: available False, python number still
    real) — the tier-1 descendant of the bench fallback run."""
    import bench

    monkeypatch.setenv("HOSTSCHED_NATIVE_COMMIT", "0")
    results = {}
    bench.rung_bind_commit(results)
    bc = results["BindCommit_20k"]
    assert "error" not in bc, bc
    assert bc["native"]["available"] is False
    assert bc["native"]["us_per_pod_native"] is None
    assert bc["native"]["us_per_pod_python"] > 0
    assert bc["placed"] == bc["pods"]
