"""Admission chain + kube-proxy equivalent tests.

Mirrors plugin/pkg/admission/{limitranger,resourcequota,namespace/lifecycle,
podtolerationrestriction,noderestriction} tests and pkg/proxy/iptables
proxier_test.go (rendered-rule assertions)."""

import json

import pytest

from kubernetes_tpu.api.networking import Service
from kubernetes_tpu.api.policy import LimitRange, ResourceQuota
from kubernetes_tpu.api.types import Namespace, ObjectMeta
from kubernetes_tpu.controllers import EndpointSliceController
from kubernetes_tpu.proxy import (
    BoundedFrequencyRunner,
    FakeBackend,
    IptablesBackend,
    NftablesBackend,
    Proxier,
)
from kubernetes_tpu.server.admission import (
    AdmissionError,
    default_admission_chain,
)
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock
from kubernetes_tpu.api.types import new_uid


def admit(store, obj, resource="pods", op="CREATE", user=""):
    default_admission_chain().run(store, resource, op, obj, user=user)


class TestNamespaceLifecycle:
    def test_missing_namespace_rejected(self):
        store = APIStore()
        pod = MakePod("p", namespace="ghost").obj()
        with pytest.raises(AdmissionError, match="not found"):
            admit(store, pod)

    def test_bootstrap_namespaces_allowed(self):
        store = APIStore()
        admit(store, MakePod("p", namespace="default").obj())
        admit(store, MakePod("p", namespace="kube-system").obj())

    def test_terminating_namespace_rejects_creates(self):
        store = APIStore()
        ns = Namespace(metadata=ObjectMeta(name="dying"))
        ns.metadata.deletion_timestamp = 123.0
        store.create("namespaces", ns)
        with pytest.raises(AdmissionError, match="terminating"):
            admit(store, MakePod("p", namespace="dying").obj())


class TestLimitRanger:
    def _store(self):
        store = APIStore()
        store.create("limitranges", LimitRange.from_dict({
            "metadata": {"name": "lr", "namespace": "default"},
            "spec": {"limits": [{"type": "Container",
                                 "defaultRequest": {"cpu": "100m", "memory": "64Mi"},
                                 "default": {"cpu": "200m"},
                                 "max": {"cpu": "2"},
                                 "min": {"memory": "16Mi"}}]},
        }))
        return store

    def test_defaults_applied(self):
        store = self._store()
        pod = MakePod("p").container("img").obj()
        admit(store, pod)
        res = pod.spec.containers[0].resources
        assert res["requests"] == {"cpu": "100m", "memory": "64Mi"}
        assert res["limits"] == {"cpu": "200m"}

    def test_explicit_request_kept(self):
        store = self._store()
        pod = MakePod("p").req({"cpu": "500m"}).obj()
        admit(store, pod)
        assert pod.spec.containers[0].resources["requests"]["cpu"] == "500m"

    def test_max_enforced(self):
        store = self._store()
        pod = MakePod("p").req({"cpu": "4"}).obj()
        with pytest.raises(AdmissionError, match="maximum cpu"):
            admit(store, pod)

    def test_min_enforced(self):
        store = self._store()
        pod = MakePod("p").req({"memory": "8Mi"}).obj()
        with pytest.raises(AdmissionError, match="minimum memory"):
            admit(store, pod)


class TestResourceQuotaAdmission:
    def test_quota_enforced_live(self):
        store = APIStore()
        store.create("resourcequotas", ResourceQuota.from_dict({
            "metadata": {"name": "q", "namespace": "default"},
            "spec": {"hard": {"requests.cpu": "1", "pods": "2"}}}))
        admit(store, MakePod("a").req({"cpu": "600m"}).obj())
        store.create("pods", MakePod("a").req({"cpu": "600m"}).obj())
        with pytest.raises(AdmissionError, match="limited: requests.cpu"):
            admit(store, MakePod("b").req({"cpu": "600m"}).obj())
        admit(store, MakePod("c").req({"cpu": "100m"}).obj())
        store.create("pods", MakePod("c").req({"cpu": "100m"}).obj())
        with pytest.raises(AdmissionError, match="limited: pods"):
            admit(store, MakePod("d").obj())


class TestPodTolerationRestriction:
    def test_namespace_default_tolerations_merged(self):
        store = APIStore()
        ns = Namespace(metadata=ObjectMeta(name="batch"))
        ns.metadata.annotations["scheduler.alpha.kubernetes.io/defaultTolerations"] = \
            json.dumps([{"key": "dedicated", "operator": "Equal",
                         "value": "batch", "effect": "NoSchedule"}])
        store.create("namespaces", ns)
        pod = MakePod("p", namespace="batch").obj()
        admit(store, pod)
        assert any(t.key == "dedicated" and t.value == "batch"
                   for t in pod.spec.tolerations)

    def test_whitelist_enforced(self):
        store = APIStore()
        ns = Namespace(metadata=ObjectMeta(name="strict"))
        ns.metadata.annotations["scheduler.alpha.kubernetes.io/tolerationsWhitelist"] = \
            json.dumps([{"key": "ok", "operator": "Exists"}])
        store.create("namespaces", ns)
        bad = MakePod("p", namespace="strict").toleration("forbidden", operator="Exists").obj()
        with pytest.raises(AdmissionError, match="whitelist"):
            admit(store, bad)


class TestNodeRestriction:
    def test_node_cannot_touch_other_node(self):
        store = APIStore()
        other = MakeNode("n2").obj()
        with pytest.raises(AdmissionError, match="may not modify"):
            admit(store, other, resource="nodes", op="UPDATE", user="system:node:n1")
        admit(store, MakeNode("n1").obj(), resource="nodes", op="UPDATE",
              user="system:node:n1")

    def test_node_cannot_write_foreign_pods(self):
        store = APIStore()
        pod = MakePod("p").node("n2").obj()
        with pytest.raises(AdmissionError, match="bound to itself"):
            admit(store, pod, op="UPDATE", user="system:node:n1")

    def test_non_node_identity_unrestricted(self):
        store = APIStore()
        admit(store, MakeNode("n2").obj(), resource="nodes", op="UPDATE",
              user="admin")

    def test_delete_restricted_over_http(self):
        import urllib.request

        from kubernetes_tpu.server import APIServer

        store = APIStore()
        store.create("pods", MakePod("p").node("n2").obj())
        srv = APIServer(store, port=0).start()
        try:
            req = urllib.request.Request(
                f"{srv.url}/api/v1/namespaces/default/pods/p", method="DELETE",
                headers={"X-Remote-User": "system:node:n1"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req)
            assert e.value.code == 403
            req2 = urllib.request.Request(
                f"{srv.url}/api/v1/namespaces/default/pods/p", method="DELETE",
                headers={"X-Remote-User": "system:node:n2"})
            with urllib.request.urlopen(req2) as resp:
                assert resp.status == 200
        finally:
            srv.stop()


class TestAdmissionOverHTTP:
    def test_rest_create_runs_chain(self):
        import urllib.request

        from kubernetes_tpu.server import APIServer

        store = APIStore()
        store.create("resourcequotas", ResourceQuota.from_dict({
            "metadata": {"name": "q", "namespace": "default"},
            "spec": {"hard": {"pods": "0"}}}))
        srv = APIServer(store, port=0).start()
        try:
            body = json.dumps({"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}}).encode()
            req = urllib.request.Request(
                f"{srv.url}/api/v1/namespaces/default/pods", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req)
            assert exc_info.value.code == 403
            payload = json.loads(exc_info.value.read().decode())
            assert "exceeded quota" in payload["message"]
        finally:
            srv.stop()

    def test_uid_defaulted_over_http(self):
        import urllib.request

        from kubernetes_tpu.server import APIServer

        store = APIStore()
        srv = APIServer(store, port=0).start()
        try:
            body = json.dumps({"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}}).encode()
            req = urllib.request.Request(
                f"{srv.url}/api/v1/namespaces/default/pods", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as resp:
                out = json.loads(resp.read().decode())
            assert out["metadata"]["uid"]
        finally:
            srv.stop()


def _cluster_with_service():
    store = APIStore()
    svc = Service.from_dict({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"selector": {"app": "web"},
                 "ports": [{"name": "http", "port": 80, "targetPort": 8080}]},
    })
    svc.metadata.uid = new_uid()
    store.create("services", svc)
    for i in range(3):
        pod = (MakePod(f"w{i}").labels({"app": "web"}).node(f"n{i}")
               .phase("Running").obj())
        store.create("pods", pod)
    es = EndpointSliceController(store, clock=FakeClock())
    es.sync_all()
    es.process()
    return store


class TestProxier:
    def test_rules_built_from_services_and_slices(self):
        store = _cluster_with_service()
        proxier = Proxier(store, backend=FakeBackend(), clock=FakeClock())
        proxier.sync_all()
        rs = proxier.sync_proxy_rules()
        assert len(rs.rules) == 1
        rule = rs.rules[0]
        assert rule.port == 80
        assert len(rule.endpoints) == 3
        assert all(ep.port == 8080 for ep in rule.endpoints)
        assert rule.cluster_ip.startswith("172.16.")

    def test_unready_endpoints_excluded(self):
        store = _cluster_with_service()

        def not_ready(p):
            p.status.phase = "Pending"
            return p

        store.guaranteed_update("pods", "default/w0", not_ready)
        es = EndpointSliceController(store, clock=FakeClock())
        es.sync_all()
        es.process()
        proxier = Proxier(store, clock=FakeClock())
        proxier.sync_all()
        rs = proxier.sync_proxy_rules()
        assert len(rs.rules[0].endpoints) == 2

    def test_iptables_render_shape(self):
        store = _cluster_with_service()
        backend = IptablesBackend()
        proxier = Proxier(store, backend=backend, clock=FakeClock())
        proxier.sync_all()
        proxier.sync_proxy_rules()
        text = backend.render()
        assert "*nat" in text and "COMMIT" in text
        assert text.count("-j DNAT --to-destination") == 3
        assert "--mode random" in text  # balanced split
        assert 'comment "default/web:http cluster IP"' in text

    def test_nftables_render_shape(self):
        store = _cluster_with_service()
        backend = NftablesBackend()
        proxier = Proxier(store, backend=backend, clock=FakeClock())
        proxier.sync_all()
        proxier.sync_proxy_rules()
        text = backend.render()
        assert "table ip kube-proxy" in text
        assert "numgen random mod 3" in text
        assert text.count("dnat to") == 3

    def test_watch_driven_resync(self):
        store = _cluster_with_service()
        proxier = Proxier(store, clock=FakeClock())
        proxier.sync_all()
        proxier.process()
        first = proxier.syncs
        store.delete("pods", "default/w2")
        es = EndpointSliceController(store, clock=FakeClock())
        es.sync_all()
        es.process()
        proxier.reconcile_once()
        assert proxier.syncs > first
        assert len(proxier.backend.current.rules[0].endpoints) == 2

    def test_throttled_sync_retried_on_next_reconcile(self):
        clock = FakeClock(start=0.0)
        store = _cluster_with_service()
        proxier = Proxier(store, clock=clock, min_sync_interval=1.0)
        proxier.sync_all()
        proxier.process()  # first sync at t=0
        store.delete("pods", "default/w2")
        es = EndpointSliceController(store, clock=clock)
        es.sync_all()
        es.process()
        proxier.reconcile_once()  # throttled: pending
        assert len(proxier.backend.current.rules[0].endpoints) == 3  # stale
        clock.step(1.1)
        proxier.reconcile_once()  # no new events, but pending retry fires
        assert len(proxier.backend.current.rules[0].endpoints) == 2

    def test_limitranger_tolerates_null_resources(self):
        store = APIStore()
        store.create("limitranges", LimitRange.from_dict({
            "metadata": {"name": "lr", "namespace": "default"},
            "spec": {"limits": [{"type": "Container",
                                 "defaultRequest": {"cpu": "100m"}}]}}))
        from kubernetes_tpu.api.types import Pod

        pod = Pod.from_dict({"metadata": {"name": "p"},
                             "spec": {"containers": [
                                 {"name": "c", "resources": {"requests": None}}]}})
        admit(store, pod)
        assert pod.spec.containers[0].resources["requests"]["cpu"] == "100m"

    def test_bounded_frequency(self):
        clock = FakeClock(start=0.0)
        calls = []
        runner = BoundedFrequencyRunner(lambda: calls.append(clock.now()),
                                        min_interval=10.0, clock=clock)
        assert runner.run()
        assert not runner.run()  # throttled
        clock.step(5)
        assert not runner.retry_pending()
        clock.step(6)
        assert runner.retry_pending()
        assert calls == [0.0, 11.0]
