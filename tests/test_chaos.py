"""Failure-domain tests (ISSUE 6): the deterministic fault-injection
harness, the solver circuit breaker, bind retry/backoff + bind-worker
supervision/liveness, crash resync from the store, the pod-conservation
checker, and the node-death reference failure chain (node_lifecycle ->
tainteviction -> workload controller -> batched scheduler)."""

import threading
import time
from collections import deque

import pytest

from kubernetes_tpu.chaos import faultinject as fi
from kubernetes_tpu.chaos.faultinject import FaultInjected, FaultPlan
from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.breaker import SolverCircuitBreaker
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.scheduler.queue import QueuedPodInfo
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import (MakeNode, MakePod, assert_pod_conservation,
                                    mutation_detector_guard,
                                    pod_conservation_report)
from kubernetes_tpu.utils import FakeClock


@pytest.fixture(autouse=True)
def _always_disarm():
    """No test may leak an armed injector into its neighbors."""
    fi.disarm()
    yield
    fi.disarm()


@pytest.fixture(autouse=True)
def _force_mutation_detector(monkeypatch):
    """Chaos paths clone/rollback aggressively — run the whole module under
    the mutation detector (the MU001 runtime companion)."""
    yield from mutation_detector_guard(monkeypatch)


def _nodes(n, cpu="8"):
    return [MakeNode(f"node-{i}").capacity(
        {"cpu": cpu, "memory": "32Gi", "pods": "110"}).obj()
        for i in range(n)]


def _pods(n, prefix="p", cpu="100m"):
    return [MakePod(f"{prefix}-{i}").req({"cpu": cpu}).obj()
            for i in range(n)]


def _sched(n_nodes=4, **kw):
    store = APIStore()
    for n in _nodes(n_nodes):
        store.create("nodes", n)
    kw.setdefault("batch_size", 64)
    kw.setdefault("solver", "exact")
    kw.setdefault("pod_initial_backoff", 0.01)
    kw.setdefault("pod_max_backoff", 0.05)
    sched = BatchScheduler(store, Framework(default_plugins()), **kw)
    sched.sync()
    return store, sched


def _drive(store, sched, want, deadline_s=10.0, keys_prefix=None):
    """Drive the scheduler (backoff flushes included) until `want` pods are
    bound in the store or the deadline passes. Returns the bound count."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        sched.run_until_idle()
        sched.queue.flush_backoff_completed()
        sched.queue.move_all_to_active_or_backoff()
        bound = sum(
            1 for p in store.list("pods")[0]
            if p.spec.node_name and (keys_prefix is None
                                     or p.metadata.name.startswith(keys_prefix)))
        if bound >= want:
            return bound
        time.sleep(0.01)
    return sum(1 for p in store.list("pods")[0] if p.spec.node_name)


# -- fault-injection harness ----------------------------------------------


class TestFaultInject:
    def test_fail_next_n_then_passes(self):
        inj = fi.arm([FaultPlan("solver.solve", "fail", count=2)])
        for _ in range(2):
            with pytest.raises(FaultInjected):
                inj.fire("solver.solve")
        inj.fire("solver.solve")  # exhausted: passes
        assert inj.stats()["solver.solve"] == {"fired": 3, "injected": 2}

    def test_rate_plan_is_seeded_deterministic(self):
        def decisions(seed):
            inj = fi.Injector([FaultPlan("store.bind_many", "rate",
                                         rate=0.5, seed=seed)])
            out = []
            for _ in range(50):
                try:
                    inj.fire("store.bind_many")
                    out.append(False)
                except FaultInjected:
                    out.append(True)
            return out

        a, b = decisions(7), decisions(7)
        assert a == b
        assert any(a) and not all(a)
        assert decisions(8) != a

    def test_after_offset_skips_early_fires(self):
        inj = fi.arm([FaultPlan("solver.solve", "fail", count=1, after=2)])
        inj.fire("solver.solve")
        inj.fire("solver.solve")
        with pytest.raises(FaultInjected):
            inj.fire("solver.solve")

    def test_delay_plan_sleeps(self):
        inj = fi.arm([FaultPlan("solver.solve", "delay", count=1,
                                delay_s=0.05)])
        t0 = time.perf_counter()
        inj.fire("solver.solve")
        assert time.perf_counter() - t0 >= 0.04
        t0 = time.perf_counter()
        inj.fire("solver.solve")  # count exhausted: no sleep
        assert time.perf_counter() - t0 < 0.04

    def test_match_scopes_to_key(self):
        inj = fi.arm([FaultPlan("kubelet.heartbeat", "fail", count=10,
                                match="hollow-1")])
        assert not inj.should_drop("kubelet.heartbeat", "hollow-0")
        assert inj.should_drop("kubelet.heartbeat", "hollow-1")
        assert not inj.should_drop("kubelet.heartbeat", "hollow-2")

    def test_unknown_site_and_bad_modes_rejected(self):
        with pytest.raises(ValueError):
            fi.Injector([FaultPlan("no.such.site", "fail")])
        with pytest.raises(ValueError):
            fi.Injector([FaultPlan("watch.deliver", "delay", delay_s=1.0)])
        with pytest.raises(ValueError):
            fi.Injector([FaultPlan("kubelet.heartbeat", "kill")])

    def test_env_spec_parsing(self):
        plans = fi.parse_env(
            "solver.solve=fail:count=3;"
            "store.bind_many=rate:rate=0.1,seed=7;"
            "bind.worker=kill:after=2")
        by_site = {p.site: p for p in plans}
        assert by_site["solver.solve"].count == 3
        assert by_site["store.bind_many"].rate == 0.1
        assert by_site["store.bind_many"].seed == 7
        assert by_site["bind.worker"].mode == "kill"
        assert by_site["bind.worker"].after == 2

    def test_disarmed_is_inert(self):
        assert fi.ACTIVE is None
        assert not fi.enabled()
        assert fi.disabled_check_cost_ns(10_000) > 0


# -- solver circuit breaker ------------------------------------------------


class TestSolverBreaker:
    def test_state_machine_unit(self):
        clock = FakeClock()
        b = SolverCircuitBreaker(clock=clock, threshold=2, cooldown_s=10.0)
        assert b.effective_solver("fast") == "fast"
        b.record_failure("fast", "fast")
        assert b.state == "closed"
        b.record_failure("fast", "fast")
        assert b.state == "open" and b.trips == 1
        assert b.effective_solver("fast") == "exact"
        # degraded-solver failure: counted, no state change
        b.record_failure("exact", "fast")
        assert b.state == "open" and b.degraded_failures == 1
        clock.step(11)
        assert b.effective_solver("fast") == "fast"  # half-open probe
        assert b.state == "half_open"
        b.record_failure("fast", "fast")  # probe failed: trips open again
        assert b.state == "open" and b.trips == 2
        clock.step(11)
        assert b.effective_solver("fast") == "fast"
        b.record_success("fast", "fast")
        assert b.state == "closed" and b.recoveries == 1
        assert b.consecutive_failures == 0

    def test_path_attribution_not_mode_label(self):
        """A constrained batch runs the scan regardless of mode: its outcome
        must neither close a HALF_OPEN breaker nor trip a CLOSED one — the
        breaker reasons about the EXECUTED path, not the mode label."""
        clock = FakeClock()
        b = SolverCircuitBreaker(clock=clock, threshold=2, cooldown_s=10.0)
        # scan failures on constrained batches never count against 'fast'
        b.record_failure("exact", "fast")
        b.record_failure("exact", "fast")
        assert b.state == "closed" and b.trips == 0
        assert b.degraded_failures == 2
        # trip for real, reach the probe window
        b.record_failure("fast", "fast")
        b.record_failure("fast", "fast")
        assert b.state == "open"
        clock.step(11)
        assert b.effective_solver("fast") == "fast"
        assert b.state == "half_open"
        # a constrained probe batch (scan ran) proves nothing: stay probing
        b.record_success("exact", "fast")
        assert b.state == "half_open" and b.recoveries == 0
        # a genuine fast-path success closes
        b.record_success("fast", "fast")
        assert b.state == "closed" and b.recoveries == 1
        # 'auto' mode is represented by the waterfill path
        b2 = SolverCircuitBreaker(clock=clock, threshold=1)
        b2.record_failure("fast", "auto")
        assert b2.state == "open"

    def test_solver_exception_requeues_batch_not_lost(self):
        store, sched = _sched(solver="exact", breaker_threshold=100)
        store.create_many("pods", _pods(10))
        sched.pump_events()
        fi.arm([FaultPlan("solver.solve", "fail", count=1)])
        assert sched.schedule_batch(timeout=0.0) == 10
        # nothing scheduled, nothing assumed, nothing narrated per pod —
        # the batch sits in the backoff tier as a unit
        assert sched.scheduled_count == 0
        assert sched.cache.assumed_count() == 0
        assert sched.queue.lengths()[1] == 10  # backoff tier
        rec = sched.flightrec.last()
        assert rec["outcome"] == "error"
        assert "FaultInjected" in rec["error"]
        # the retry succeeds once the backoff expires
        bound = _drive(store, sched, 10)
        assert bound == 10
        assert_pod_conservation(store, sched,
                                [f"default/p-{i}" for i in range(10)])

    def test_breaker_trips_to_scan_and_recovers(self):
        store, sched = _sched(solver="fast", breaker_threshold=2,
                              breaker_cooldown_s=0.2)
        fi.arm([FaultPlan("solver.solve", "fail", count=2)])
        store.create_many("pods", _pods(8, prefix="a"))
        sched.pump_events()
        sched.schedule_batch(timeout=0.0)  # failure 1
        sched.queue.flush_backoff_completed()
        time.sleep(0.02)
        sched.queue.flush_backoff_completed()
        sched.schedule_batch(timeout=0.0)  # failure 2 -> OPEN
        assert sched.breaker.state == "open"
        assert sched.breaker.trips == 1
        # while OPEN the batch runs the DEGRADED solver (the exact scan)
        bound = _drive(store, sched, 8, keys_prefix="a-")
        assert bound == 8
        solvers = [r["solver"] for r in sched.flightrec.records()
                   if r["pods"] > 0]
        assert "exact" in solvers  # the degraded batches are visible
        # cooldown passes; the next real batch is the half-open probe
        time.sleep(0.25)
        store.create_many("pods", _pods(4, prefix="b"))
        bound = _drive(store, sched, 4, keys_prefix="b-")
        assert bound == 4
        assert sched.breaker.state == "closed"
        assert sched.breaker.recoveries == 1
        assert sched.flightrec.records()[-1]["solver"] == "fast"
        assert_pod_conservation(
            store, sched,
            [f"default/a-{i}" for i in range(8)]
            + [f"default/b-{i}" for i in range(4)])

    def test_repair_path_counts_as_the_fast_mode(self):
        """ISSUE 8: the fast MODE has two kernels — waterfill ('fast') and
        the constrained propose-and-repair pipeline ('repair'). Failures of
        either trip the breaker; a successful repair batch is a genuine
        half-open probe."""
        clock = FakeClock()
        b = SolverCircuitBreaker(clock=clock, threshold=2, cooldown_s=10.0)
        b.record_failure("repair", "fast")
        b.record_failure("repair", "fast")
        assert b.state == "open" and b.trips == 1
        clock.step(11)
        assert b.effective_solver("fast") == "fast"
        b.record_success("repair", "fast")
        assert b.state == "closed" and b.recoveries == 1
        # 'auto' mode likewise
        b2 = SolverCircuitBreaker(clock=clock, threshold=1)
        b2.record_failure("repair", "auto")
        assert b2.state == "open"

    def test_repair_fault_trips_breaker_to_scan_and_recovers(self):
        """ISSUE 8 chaos coverage: a solver.solve fault on a CONSTRAINED
        fast-mode batch attributes to the repair kernel, trips the breaker,
        the degraded batches place the constrained pods on the scan oracle
        (semantics intact), and a constrained half-open probe closes it."""
        store = APIStore()
        for i in range(8):
            store.create("nodes", MakeNode(f"node-{i}").labels(
                {"kubernetes.io/hostname": f"node-{i}"}).capacity(
                {"cpu": "8", "memory": "32Gi", "pods": "110"}).obj())
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=64, solver="fast",
                               breaker_threshold=2, breaker_cooldown_s=0.2,
                               pod_initial_backoff=0.01, pod_max_backoff=0.05)
        sched.sync()

        def anti(prefix, n):
            return [MakePod(f"{prefix}-{i}").labels({"grp": prefix})
                    .pod_anti_affinity("kubernetes.io/hostname",
                                       {"grp": prefix})
                    .req({"cpu": "100m"}).obj() for i in range(n)]

        fi.arm([FaultPlan("solver.solve", "fail", count=2)])
        store.create_many("pods", anti("a", 4))
        sched.pump_events()
        sched.schedule_batch(timeout=0.0)  # failure 1, on the repair path
        assert sched._solve_path == "repair"
        sched.queue.flush_backoff_completed()
        time.sleep(0.02)
        sched.queue.flush_backoff_completed()
        sched.schedule_batch(timeout=0.0)  # failure 2 -> OPEN
        assert sched.breaker.state == "open"
        assert sched.breaker.trips == 1
        # while OPEN, constrained batches run the DEGRADED exact scan —
        # and still honor the anti-affinity
        bound = _drive(store, sched, 4, keys_prefix="a-")
        assert bound == 4
        solvers = [r["solver"] for r in sched.flightrec.records()
                   if r["pods"] > 0]
        assert "exact" in solvers
        nodes = [p.spec.node_name for p in store.list("pods")[0]
                 if p.spec.node_name]
        assert len(set(nodes)) == 4
        # cooldown passes; the CONSTRAINED probe batch exercises the repair
        # kernel and closes the breaker
        time.sleep(0.25)
        store.create_many("pods", anti("b", 4))
        bound = _drive(store, sched, 4, keys_prefix="b-")
        assert bound == 4
        assert sched.breaker.state == "closed"
        assert sched.breaker.recoveries == 1
        assert sched._solve_path == "repair"
        assert_pod_conservation(
            store, sched,
            [f"default/a-{i}" for i in range(4)]
            + [f"default/b-{i}" for i in range(4)])

    def test_retry_metric_counts_solver_requeues(self):
        from kubernetes_tpu.server import metrics as m

        before = m.batch_retries_total.value(stage="solve",
                                             reason="FaultInjected")
        store, sched = _sched(solver="exact", breaker_threshold=100)
        store.create_many("pods", _pods(5, prefix="m"))
        sched.pump_events()
        fi.arm([FaultPlan("solver.solve", "fail", count=1)])
        sched.schedule_batch(timeout=0.0)
        after = m.batch_retries_total.value(stage="solve",
                                            reason="FaultInjected")
        assert after - before == 5


# -- bind retry / backoff --------------------------------------------------


class TestBindRetry:
    def test_transient_bind_error_retries_to_success(self):
        store, sched = _sched(bind_retries=3, bind_retry_base_s=0.001)
        store.create_many("pods", _pods(6, prefix="tr"))
        sched.pump_events()
        # the first two bind_many calls fail, the third lands
        fi.arm([FaultPlan("store.bind_many", "fail", count=2)])
        assert sched.schedule_batch(timeout=0.0) == 6
        sched.flush_binds()
        assert sched.take_bind_failures() == []
        assert sched.scheduled_count == 6
        assert sched.cache.assumed_count() == 0
        assert_pod_conservation(store, sched,
                                [f"default/tr-{i}" for i in range(6)])

    def test_bind_retries_exhausted_requeue_and_recover(self):
        store, sched = _sched(bind_retries=1, bind_retry_base_s=0.001)
        store.create_many("pods", _pods(4, prefix="ex"))
        sched.pump_events()
        fi.arm([FaultPlan("store.bind_many", "fail", count=50)])
        assert sched.schedule_batch(timeout=0.0) == 4
        sched.flush_binds()
        failures = sched.take_bind_failures()
        assert len(failures) == 4
        assert all("injected fault" in msg for _k, msg in failures)
        assert sched.scheduled_count == 0
        assert sched.cache.assumed_count() == 0
        # conservation holds mid-fault: requeued, not lost
        assert_pod_conservation(store, sched,
                                [f"default/ex-{i}" for i in range(4)])
        fi.disarm()
        assert _drive(store, sched, 4) == 4

    def test_bind_failure_log_bounded_with_drop_counter(self):
        store, sched = _sched()
        pods = _pods(8, prefix="bl")
        store.create_many("pods", pods)
        sched.pump_events()
        sched.bind_failures = deque(maxlen=5)
        from kubernetes_tpu.scheduler.framework import Status

        with sched._bind_err_lock:
            for p in pods:
                qp = QueuedPodInfo(pod=p)
                sched._bind_errors.append((qp, Status.error("boom")))
        sched._drain_bind_results()
        assert len(sched.bind_failures) == 5  # newest 5 kept
        assert sched.bind_failures_dropped == 3
        kept = [k for k, _m in sched.take_bind_failures()]
        assert kept == [f"default/bl-{i}" for i in range(3, 8)]


# -- bind-worker supervision ----------------------------------------------


class TestBindWorkerSupervision:
    def test_escaped_exception_requeues_chunk_once(self):
        store, sched = _sched()
        store.create_many("pods", _pods(6, prefix="sw"))
        sched.pump_events()
        fi.arm([FaultPlan("bind.worker", "fail", count=1)])
        assert sched.schedule_batch(timeout=0.0) == 6
        sched.flush_binds()
        # the supervisor caught the escape, re-queued the chunk, and the
        # retry committed: nothing failed, nothing lost
        assert sched.take_bind_failures() == []
        assert sched.scheduled_count == 6
        assert sched.bind_worker_restarts >= 1
        assert_pod_conservation(store, sched,
                                [f"default/sw-{i}" for i in range(6)])

    def test_second_escape_fails_pods_no_livelock(self):
        store, sched = _sched()
        store.create_many("pods", _pods(5, prefix="s2"))
        sched.pump_events()
        fi.arm([FaultPlan("bind.worker", "fail", count=2)])
        assert sched.schedule_batch(timeout=0.0) == 5
        sched.flush_binds()
        failures = sched.take_bind_failures()
        assert len(failures) == 5
        assert all("failed twice" in msg for _k, msg in failures)
        assert sched.cache.assumed_count() == 0
        assert_pod_conservation(store, sched,
                                [f"default/s2-{i}" for i in range(5)])
        fi.disarm()
        assert _drive(store, sched, 5) == 5

    def test_hard_kill_detected_and_recovered(self):
        """An injected FaultKill escapes the supervisor (BaseException by
        design), the worker thread DIES with its chunk in flight — and the
        liveness check in the next drain re-queues the chunk, settles the
        join() debt, and restarts the worker; flush_binds never hangs."""
        store, sched = _sched()
        store.create_many("pods", _pods(6, prefix="kl"))
        sched.pump_events()
        fi.arm([FaultPlan("bind.worker", "kill")])
        assert sched.schedule_batch(timeout=0.0) == 6
        t0 = time.monotonic()
        sched.flush_binds()  # would hang forever on a plain Queue.join()
        assert time.monotonic() - t0 < 5.0
        sched._drain_bind_results()
        assert sched.bind_worker_restarts >= 1
        assert _drive(store, sched, 6) == 6
        assert_pod_conservation(store, sched,
                                [f"default/kl-{i}" for i in range(6)])

    def test_dead_worker_detected_on_empty_queue_drain(self):
        """ISSUE 6 satellite: _ensure_bind_worker only ran at enqueue; the
        drain-side liveness check must notice a dead worker within one
        schedule_batch cycle even with an EMPTY bind queue."""
        store, sched = _sched()
        store.create_many("pods", _pods(3, prefix="dw"))
        sched.pump_events()
        fi.arm([FaultPlan("bind.worker", "kill")])
        assert sched.schedule_batch(timeout=0.0) == 3
        # wait for the worker thread to die without enqueueing anything new
        for _ in range(200):
            w = sched._bind_worker
            if w is not None and not w.is_alive():
                break
            time.sleep(0.005)
        assert sched._bind_worker is not None
        assert not sched._bind_worker.is_alive()
        fi.disarm()
        # one drain (as every schedule_batch cycle runs) detects + recovers
        sched._drain_bind_results()
        assert sched.bind_worker_restarts >= 1
        assert _drive(store, sched, 3) == 3

    def test_enqueue_after_kill_recovers_estate_before_replacement(self):
        """ISSUE 7 regression (found by the first FULL-size ChaosChurn_20k
        run): when the ENQUEUE path observed the dead worker first,
        _ensure_bind_worker started a replacement without recovering the
        estate — the new worker's first cycle overwrote the shared
        _bind_inflight record, the dead worker's task_done debt leaked, and
        flush_binds wedged forever (restarts stayed 0, erasing the
        evidence). The replacement must settle the estate FIRST."""
        store, sched = _sched()
        store.create_many("pods", _pods(5, prefix="eq"))
        sched.pump_events()
        fi.arm([FaultPlan("bind.worker", "kill")])
        assert sched.schedule_batch(timeout=0.0) == 5
        for _ in range(200):
            w = sched._bind_worker
            if w is not None and not w.is_alive():
                break
            time.sleep(0.005)
        assert not sched._bind_worker.is_alive()
        fi.disarm()
        # the enqueue path wins the race against the liveness drain: a new
        # chunk is dispatched before any _drain_bind_results runs
        sched._bind_q.put([])
        sched._ensure_bind_worker()
        assert sched.bind_worker_restarts >= 1  # estate settled, counted
        done = threading.Event()
        threading.Thread(target=lambda: (sched.flush_binds(), done.set()),
                         daemon=True).start()
        assert done.wait(10.0), \
            "flush_binds wedged on leaked task_done debt"
        assert _drive(store, sched, 5) == 5
        assert_pod_conservation(store, sched,
                                [f"default/eq-{i}" for i in range(5)])


# -- crash resync ----------------------------------------------------------


class TestCrashResync:
    def test_resync_rebuilds_from_store(self):
        store, sched = _sched(n_nodes=4)
        store.create_many("pods", _pods(10, prefix="rb"))
        sched.pump_events()
        assert sched.schedule_batch(timeout=0.0) == 10
        sched.flush_binds()
        assert sched.scheduled_count == 10
        # new pending pods arrive; a stale assume is fabricated (a bind that
        # will never land — exactly what a crashed worker leaves behind)
        store.create_many("pods", _pods(5, prefix="pend"))
        stale = MakePod("stale").req({"cpu": "100m"}).obj()
        store.create("pods", stale)
        sched.pump_events()
        from kubernetes_tpu.store import pod_structural_clone

        qp = None
        popped = sched.queue.pop_batch(64, timeout=0.0)
        for q in popped:
            if q.pod.metadata.name == "stale":
                qp = q
            else:
                sched.queue.add(q.pod)  # put the others back
        assert qp is not None
        sched.cache.assume_pod(pod_structural_clone(qp.pod), "node-0")
        assert sched.cache.assumed_count() == 1

        counts = sched.resync_from_store()
        assert counts["bound"] == 10
        assert counts["pending"] == 6  # 5 pend-* + the stale pod
        assert counts["dropped_assumes"] == 1
        # the rebuilt cache holds exactly the bound pods; the stale assume
        # is gone; every pending pod re-entered the queue fresh
        assert sched.cache.pod_count() == 10
        assert sched.cache.assumed_count() == 0
        assert len(sched.queue.tracked_keys()) == 6
        # and the world converges: everything pending binds
        keys = ([f"default/rb-{i}" for i in range(10)]
                + [f"default/pend-{i}" for i in range(5)]
                + ["default/stale"])
        assert _drive(store, sched, 16) == 16
        rep = assert_pod_conservation(store, sched, keys)
        assert rep["counts"]["bound"] == 16

    def test_resync_after_watch_loss_recovers_dropped_events(self):
        """Dropped watch deliveries (the watch.deliver chaos site) starve
        the scheduler of ADDED events; resync_from_store recovers the pods
        from the LIST — the store is the single source of truth."""
        store, sched = _sched()
        fi.arm([FaultPlan("watch.deliver", "fail", count=1000)])
        store.create_many("pods", _pods(5, prefix="drop"))
        sched.pump_events()
        assert sched.schedule_batch(timeout=0.0) == 0  # events never arrived
        fi.disarm()
        keys = [f"default/drop-{i}" for i in range(5)]
        rep = pod_conservation_report(store, sched, keys)
        assert len(rep["lost"]) == 5  # genuinely stranded without resync
        counts = sched.resync_from_store()
        assert counts["pending"] == 5
        assert _drive(store, sched, 5) == 5
        assert_pod_conservation(store, sched, keys)


# -- the conservation checker itself ---------------------------------------


class TestConservationChecker:
    def test_partitions_bound_pending_failed(self):
        store, sched = _sched()
        store.create_many("pods", _pods(3, prefix="ok"))
        failed = MakePod("dead").req({"cpu": "100m"}).obj()
        failed.status.phase = "Failed"
        store.create("pods", failed)
        sched.pump_events()
        sched.run_until_idle()
        rep = pod_conservation_report(
            store, sched,
            [f"default/ok-{i}" for i in range(3)] + ["default/dead"])
        assert rep["counts"] == {"submitted": 4, "bound": 3, "pending": 0,
                                 "failed": 1, "lost": 0, "double_bound": 0}

    def test_flags_lost_pod(self):
        store, sched = _sched()
        store.create("pods", MakePod("ghost").req({"cpu": "100m"}).obj())
        # never pumped: the scheduler has no idea this pod exists
        with pytest.raises(AssertionError, match="LOST"):
            assert_pod_conservation(store, sched, ["default/ghost"])

    def test_flags_double_bind_in_history(self):
        store, sched = _sched()
        p = MakePod("twice").req({"cpu": "100m"}).obj()
        store.create("pods", p)
        store.bind("default", "twice", "node-0")
        store.delete("pods", "default/twice")
        p2 = MakePod("twice").req({"cpu": "100m"}).obj()
        store.create("pods", p2)
        store.bind("default", "twice", "node-1")
        with pytest.raises(AssertionError, match="DOUBLE-BOUND"):
            assert_pod_conservation(store, sched, ["default/twice"])


# -- node death: the reference failure chain through the batch path --------


class TestNodeDeathEndToEnd:
    def test_heartbeat_loss_taints_evicts_and_batch_replaces(self):
        """The reference failure chain (ISSUE 6 satellite), batched: one
        hollow kubelet's heartbeat is dropped by the chaos harness ->
        node_lifecycle taints the node NotReady:NoExecute -> tainteviction
        fires the tolerationSeconds deadline and evicts -> the ReplicaSet
        controller replaces -> the BATCH scheduler re-places every pod on
        the surviving nodes."""
        from kubernetes_tpu.agent.hollow import HollowCluster
        from kubernetes_tpu.api.workloads import ReplicaSet
        from kubernetes_tpu.controllers import (NodeLifecycleController,
                                                ReplicaSetController)
        from kubernetes_tpu.controllers.tainteviction import (
            TaintEvictionController)

        clock = FakeClock(start=100.0)
        store = APIStore()
        cluster = HollowCluster(store, n_nodes=3, clock=clock)
        cluster.register_all()
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=64, solver="exact", clock=clock)
        sched.sync()
        rsc = ReplicaSetController(store, clock=clock)
        rsc.sync_all()
        nlc = NodeLifecycleController(store, clock=clock, grace_period=40.0)
        nlc.sync_all()
        tec = TaintEvictionController(store, clock=clock)
        tec.sync_all()

        store.create("replicasets", ReplicaSet.from_dict({
            "metadata": {"name": "web"},
            "spec": {
                "replicas": 6,
                "selector": {"matchLabels": {"app": "web"}},
                "template": {
                    "metadata": {"labels": {"app": "web"}},
                    "spec": {
                        "containers": [{"name": "c", "resources": {
                            "requests": {"cpu": "500m"}}}],
                        # tolerate not-ready for 5s: node_lifecycle's own
                        # immediate eviction defers to tainteviction's
                        # tolerationSeconds deadline — both controllers in
                        # the chain do real work
                        "tolerations": [{
                            "key": "node.kubernetes.io/not-ready",
                            "operator": "Exists", "effect": "NoExecute",
                            "tolerationSeconds": 5}],
                    }},
            },
        }))
        for _ in range(6):
            rsc.reconcile_once()
            sched.run_until_idle()
            cluster.pump_all()
        pods, _ = store.list("pods")
        assert len(pods) == 6 and all(p.spec.node_name for p in pods)
        victim = pods[0].spec.node_name
        n_victim = sum(1 for p in pods if p.spec.node_name == victim)
        assert n_victim > 0

        # the victim node's heartbeat is DROPPED by the harness; siblings
        # keep renewing through the same heartbeat_all() calls
        fi.arm([FaultPlan("kubelet.heartbeat", "fail", count=10_000,
                          match=victim)])
        clock.step(41)
        cluster.heartbeat_all()
        nlc.monitor()
        node = store.get("nodes", victim)
        assert any(t.key == "node.kubernetes.io/not-ready"
                   and t.effect == "NoExecute" for t in node.spec.taints)
        # tolerationSeconds still running: nothing evicted yet
        tec.pump(), tec.tick()
        assert len(store.list("pods")[0]) == 6
        clock.step(6)  # past the 5s tolerationSeconds deadline
        tec.tick()
        survivors = store.list("pods")[0]
        assert all(p.spec.node_name != victim for p in survivors
                   if p.spec.node_name)
        assert len(survivors) == 6 - n_victim

        # ReplicaSet replaces; the BATCH scheduler re-places on live nodes
        # (the tainted node is filtered by TaintToleration — NoExecute)
        for _ in range(6):
            rsc.pump(), rsc.reconcile_once()
            sched.pump_events()
            sched.run_until_idle()
            sched.queue.flush_backoff_completed()
            cluster.pump_all()
        pods, _ = store.list("pods")
        assert len(pods) == 6
        assert all(p.spec.node_name and p.spec.node_name != victim
                   for p in pods)
        assert sched.scheduled_count >= 6 + n_victim

        # heartbeat resumes -> taint clears -> the node is placeable again
        fi.disarm()
        cluster.heartbeat_all()
        nlc.monitor()
        node = store.get("nodes", victim)
        assert not any(t.key == "node.kubernetes.io/not-ready"
                       for t in node.spec.taints)


# -- end-to-end chaos churn (the rung's shape, test-sized) ------------------


def test_gang_preemption_under_bind_and_commit_faults():
    """ISSUE 14 chaos leg at test scale: a victim cover fired under
    injected bind/native.commit faults plus a mid-run worker kill must
    leave NO gang half-evicted or half-bound — at quiescence the gang is
    bound whole, every surviving pod is conserved, and victims were only
    deleted because a full cover was proven."""
    from kubernetes_tpu.native import hostcommit
    from kubernetes_tpu.testing import make_pod_group

    store = APIStore()
    for s in range(2):
        for i in range(4):
            store.create("nodes", MakeNode(f"node-{s}-{i}")
                         .tpu_slice(s, index=i)
                         .capacity({"cpu": "8", "memory": "32Gi",
                                    "pods": "110"}).obj())
    filler_keys = []
    for s in range(2):
        for i in range(4):
            low = MakePod(f"low-{s}-{i}").priority(1).req({"cpu": "6"}).obj()
            low.spec.node_name = f"node-{s}-{i}"
            store.create("pods", low)
            filler_keys.append(low.key)
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=64, solver="fast",
                           breaker_threshold=3, breaker_cooldown_s=0.1,
                           bind_retries=3, bind_retry_base_s=0.001,
                           pod_initial_backoff=0.01, pod_max_backoff=0.05)
    sched.bind_chunk = 4
    sched.sync()
    store.create("podgroups", make_pod_group("cg", 8))
    pods = [MakePod(f"cg-{i}").gang("cg", rank=i).priority(100)
            .req({"cpu": "3"}).obj() for i in range(8)]
    plans = [FaultPlan("store.bind_many", "rate", rate=0.3, seed=99),
             FaultPlan("bind.worker", "kill", after=1)]
    if hostcommit.available():
        plans.append(FaultPlan("native.commit", "fail", count=2))
    fi.arm(plans)
    store.create_many("pods", pods, consume=True)
    _drive(store, sched, 8, deadline_s=10.0, keys_prefix="cg-")
    fi.disarm()
    bound = _drive(store, sched, 8, deadline_s=10.0, keys_prefix="cg-")
    assert bound == 8, bound
    # the cover really fired (the gang could not fit without eviction)
    stats = sched.gangpreempt.stats()
    assert stats["preempted"] >= 1 and stats["victims"] >= 1, stats
    # all-or-nothing held: the gang is fully bound, never a partial slice
    live = {p.key: p for p in store.list("pods")[0]}
    gang_bound = [p for k, p in live.items()
                  if k.startswith("default/cg-") and p.spec.node_name]
    assert len(gang_bound) == 8
    # conservation over gang + surviving fillers (victims are deleted by
    # design; half-deleted covers release via the deadline sweep and retry)
    survivors = [k for k in filler_keys if k in live]
    rep = assert_pod_conservation(store, sched,
                                  [p.key for p in pods] + survivors)
    assert rep["counts"]["lost"] == 0
    assert sched.queue.gang_parked_count() == 0
    assert sched.gangpreempt.stats()["waiting_gangs"] == 0


def test_chaos_churn_conservation_small():
    """The ChaosChurn rung's invariant at test scale: solver faults, bind
    faults, a worker kill, and a mid-run resync — every pod exactly once."""
    store, sched = _sched(n_nodes=8, solver="fast", batch_size=64,
                          breaker_threshold=2, breaker_cooldown_s=0.1,
                          bind_retries=2, bind_retry_base_s=0.001)
    sched.bind_chunk = 16
    n = 60
    keys = [f"default/cc-{i}" for i in range(n)]
    fi.arm([
        FaultPlan("solver.solve", "fail", count=2),
        FaultPlan("store.bind_many", "rate", rate=0.3, seed=42),
        FaultPlan("bind.worker", "kill", after=1),
    ])
    for lo in range(0, n, 20):
        store.create_many("pods",
                          [MakePod(f"cc-{i}").req({"cpu": "100m"}).obj()
                           for i in range(lo, lo + 20)])
        _drive(store, sched, min(lo + 10, n), deadline_s=5.0,
               keys_prefix="cc-")
        if lo == 20:
            sched.resync_from_store()
    fi.disarm()
    assert _drive(store, sched, n, deadline_s=10.0, keys_prefix="cc-") >= n
    rep = assert_pod_conservation(store, sched, keys)
    assert rep["counts"]["bound"] == n
    assert sched.breaker.trips >= 1
