"""schedlint (ISSUE 5): the static-analysis tier-1 gate.

Three layers:
  (a) the whole-tree run — `kubernetes_tpu/` must carry ZERO unsuppressed
      findings and every inline suppression must have a written reason;
  (b) rule fixtures — every rule provably FIRES on its bad-code fixture and
      stays QUIET on the matching good-code fixture (an analyzer that stops
      firing is worse than none: it certifies rot);
  (c) a wall-time bound so the gate stays cheap.
"""

import os
import time

import pytest

from kubernetes_tpu.analysis.schedlint import (
    analyze_source,
    analyze_sources,
    package_root,
    run_paths,
)

# ---------------------------------------------------------------------------
# (a) the shipped tree is clean
# ---------------------------------------------------------------------------


def test_tree_is_clean_and_suppressions_carry_reasons():
    findings, stats = run_paths([package_root()])
    assert stats["parse_errors"] == 0
    # SL001 findings are reasonless suppressions; anything else is a real
    # invariant violation — both fail the gate
    assert findings == [], "\n".join(f.render() for f in findings)
    # the shipped tree documents its intentional exceptions inline (the
    # Watch._deliver* wake pings, LK002; shm.py's fresh-segment header
    # writes, SEQ002 — generations invisible until the control word flips)
    assert stats["suppressed"] >= 4
    # ISSUE 20: the interprocedural closure actually resolved something
    assert stats["callgraph_edges"] > 500, stats
    assert stats["resolve_depth"] >= 2, stats


def test_wall_time_stays_cheap():
    t0 = time.perf_counter()
    run_paths([package_root()])
    wall = time.perf_counter() - t0
    # ~170 files parse+analyze in a few seconds even on the co-scheduled
    # 2-core rig; 30s means the gate has become the slowest thing in tier-1
    assert wall < 30.0, wall


# ---------------------------------------------------------------------------
# (b) rule fixtures
# ---------------------------------------------------------------------------


def rules_of(findings):
    return {f.rule for f in findings}


LK001_BAD = '''
import threading

class APIStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods_lock = threading.RLock()

    def inverted(self):
        with self._pods_lock:
            with self._lock:
                return 1

    def takes_global(self):
        with self._lock:
            return 2

    def inverted_via_call(self):
        with self._pods_lock:
            return self.takes_global()
'''

LK001_GOOD = '''
import threading

class APIStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods_lock = threading.RLock()
        self._pods_pair = None

    def mandated_order(self):
        with self._lock:
            with self._pods_lock:
                return 1

    def pair(self):
        with self._pods_pair:
            return 2

    def two_phase(self):
        # bind_many's pattern: shard alone, RELEASE, then global+shard
        with self._pods_lock:
            x = 1
        with self._lock:
            with self._pods_lock:
                return x
'''


def test_lk001_fires_on_inversion_and_call_path():
    findings = [f for f in analyze_source(LK001_BAD) if f.rule == "LK001"]
    assert len(findings) == 2, findings
    assert any("call to" in f.message for f in findings)


def test_lk001_quiet_on_mandated_order():
    assert "LK001" not in rules_of(analyze_source(LK001_GOOD))


# LK001 generalized shard rule (ISSUE 15 satellite): the ordering table in
# store/store.py ranks _lock (0) -> _pods_lock (1) -> _nodes_lock (2);
# holding a shard, any acquisition of LOWER rank — the global lock or a
# lower-ranked shard, direct or via a resolved call path — is an inversion.

LK001_NODES_BAD = '''
import threading

class APIStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods_lock = threading.RLock()
        self._nodes_lock = threading.RLock()

    def nodes_then_global(self):
        with self._nodes_lock:
            with self._lock:
                return 1

    def nodes_then_pods(self):
        with self._nodes_lock:
            with self._pods_lock:
                return 2

    def takes_pods_shard(self):
        with self._pods_lock:
            return 3

    def nodes_then_pods_via_call(self):
        with self._nodes_lock:
            return self.takes_pods_shard()
'''

LK001_NODES_GOOD = '''
import threading

class APIStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods_lock = threading.RLock()
        self._nodes_lock = threading.RLock()
        self._nodes_pair = None
        self._store_chain = None

    def full_chain_order(self):
        with self._lock:
            with self._pods_lock:
                with self._nodes_lock:
                    return 1

    def pods_then_nodes(self):
        # ascending rank: legal without the global lock too
        with self._pods_lock:
            with self._nodes_lock:
                return 2

    def nodes_pair(self):
        with self._nodes_pair:
            return 3

    def chain(self):
        with self._store_chain:
            return 4
'''


def test_lk001_generalized_fires_on_nodes_shard_inversions():
    findings = [f for f in analyze_source(LK001_NODES_BAD)
                if f.rule == "LK001"]
    # nodes->global, nodes->pods (direct), nodes->pods (via call)
    assert len(findings) == 3, findings
    assert any("call to" in f.message for f in findings)
    assert any("higher-ranked" in f.message for f in findings)


def test_lk001_generalized_quiet_on_ascending_rank():
    assert "LK001" not in rules_of(analyze_source(LK001_NODES_GOOD))


# LK001 partition extension (ISSUE 12): the dispatch-layer locks
# (PartitionRouter._route_lock / PartitionedScheduler._dispatch_lock) are
# LEAF locks — a store-lock acquisition (direct or via any resolved call
# path) while one is held is an inversion.

LK001_PART_BAD = '''
import threading

class APIStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods_lock = threading.RLock()

    def commit_rows(self):
        with self._lock:
            with self._pods_lock:
                return 1

class PartitionRouter:
    def __init__(self):
        self._route_lock = threading.Lock()
        self.store = APIStore()

    def bad_store_call_under_route_lock(self):
        with self._route_lock:
            # routing decisions must not reach into the store: commit_rows
            # takes the global+shard chain UNDER the leaf lock
            return self.store.commit_rows()

class PartitionedScheduler:
    def __init__(self):
        self._dispatch_lock = threading.Lock()
        self.store = APIStore()

    def bad_store_call_under_dispatch_lock(self):
        with self._dispatch_lock:
            return self.store.commit_rows()
'''

LK001_PART_GOOD = '''
import threading

class APIStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods_lock = threading.RLock()

    def commit_rows(self):
        with self._lock:
            with self._pods_lock:
                return 1

class PartitionRouter:
    def __init__(self):
        self._route_lock = threading.Lock()
        self._overrides = {}
        self.store = APIStore()

    def decide_then_act(self, key):
        # the mandated shape: bookkeeping under the leaf lock, release,
        # THEN call the store
        with self._route_lock:
            target = self._overrides.get(key)
        if target is None:
            return self.store.commit_rows()
        return target

class PartitionedScheduler:
    def __init__(self):
        self._dispatch_lock = threading.Lock()
        self._parked = []

    def park(self, qp):
        with self._dispatch_lock:
            self._parked.append(qp)
'''


def test_lk001_fires_on_store_call_under_partition_lock():
    findings = [f for f in analyze_source(LK001_PART_BAD)
                if f.rule == "LK001"]
    assert len(findings) == 2, findings
    assert all("partition/dispatch leaf lock" in f.message
               for f in findings), findings


def test_lk001_quiet_on_decide_then_act_partition_shape():
    assert "LK001" not in rules_of(analyze_source(LK001_PART_GOOD))


LK002_BAD = '''
import threading
import time

class Store:
    def __init__(self):
        self._lock = threading.RLock()
        self.on_event = None

    def sleepy(self):
        with self._lock:
            time.sleep(0.1)

    def queue_put(self, work_q, item):
        with self._lock:
            work_q.put(item)

    def callback(self):
        with self._lock:
            cb = self.on_event
            cb()

    def _emit(self):
        self._deliver()

    def _deliver(self):
        time.sleep(1.0)  # blocking, reachable from the locked caller

    def locked_entry(self):
        with self._lock:
            self._emit()
'''

LK002_GOOD = '''
import threading
import time

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def nowait(self, work_q, item):
        with self._lock:
            work_q.put_nowait(item)

    def outside(self, work_q, item):
        with self._lock:
            payload = item
        work_q.put(payload)
        time.sleep(0.0)
'''


def test_lk002_fires_on_blocking_calls_under_lock():
    findings = [f for f in analyze_source(LK002_BAD) if f.rule == "LK002"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 4, msgs
    assert "time.sleep" in msgs
    assert "queue .put" in msgs
    assert "watch callback" in msgs
    assert "reachable" in msgs  # the interprocedural one


def test_lk002_quiet_on_nowait_and_outside_lock():
    assert "LK002" not in rules_of(analyze_source(LK002_GOOD))


# ISSUE 11: the GIL-releasing native kernels (ctypes CDLL wrappers in
# native/hostsched.py) are blocking calls under LK002 — dropping the GIL
# inside a store lock region invites GIL/lock interleavings (the NATIVE LOCK
# RULE in store/store.py). The PyDLL commit-engine entries hold the GIL and
# stay legal under the locks.

LK002_NATIVE_BAD = '''
import threading

from kubernetes_tpu.native import native_commit_deltas, native_greedy_solve

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def scatter_under_lock(self, rows, nodes, raw, raw_nz, n):
        with self._lock:
            return native_commit_deltas(rows, nodes, raw, raw_nz, n)

    def solve_under_lock(self, cluster, batch):
        with self._lock:
            return native_greedy_solve(cluster, batch)
'''

LK002_NATIVE_GOOD = '''
import threading

from kubernetes_tpu.native import hostcommit, native_commit_deltas

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def scatter_outside(self, rows, nodes, raw, raw_nz, n):
        with self._lock:
            payload = (rows, nodes)
        return native_commit_deltas(rows, nodes, raw, raw_nz, n)

    def commit_under_lock(self, pods, bindings, prepared, errors):
        # the PyDLL commit engine HOLDS the GIL: legal under the store lock
        with self._lock:
            hostcommit.bind_prepare(pods, bindings, prepared, errors)
'''


def test_lk002_fires_on_native_kernel_under_lock():
    findings = [f for f in analyze_source(LK002_NATIVE_BAD)
                if f.rule == "LK002"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 2, msgs
    assert "GIL-releasing native kernel" in msgs
    assert "native_commit_deltas" in msgs and "native_greedy_solve" in msgs


def test_lk002_quiet_on_pydll_commit_and_outside_lock():
    assert "LK002" not in rules_of(analyze_source(LK002_NATIVE_GOOD))


MU001_BAD = '''
def mutate_get(self):
    pod = self.store.get("pods", "default/a")
    pod.metadata.labels["x"] = "1"

def mutate_event(events):
    for ev in events:
        ev.obj.status.phase = "Failed"

def mutate_list_element(self):
    pods, _rv = self.store.list("pods")
    for p in pods:
        p.spec.node_name = "n1"

def mutate_forced(self, ev):
    payload = ev.obj
    object.__setattr__(payload, "type", "DELETED")
'''

MU001_GOOD = '''
import copy

def clone_then_mutate(self):
    pod = copy.deepcopy(self.store.get("pods", "default/a"))
    pod.metadata.labels["x"] = "1"

def read_only(events, out):
    for ev in events:
        out.append(ev.obj.metadata.name)

def sort_fresh_list(self):
    pods, _rv = self.store.list("pods")
    pods.sort(key=lambda p: p.metadata.name)
    return pods
'''


def test_mu001_fires_on_store_and_event_mutation():
    findings = [f for f in analyze_source(MU001_BAD) if f.rule == "MU001"]
    assert len(findings) == 4, findings


def test_mu001_quiet_on_clones_reads_and_container_ops():
    assert "MU001" not in rules_of(analyze_source(MU001_GOOD))


# MU001 columnar extension (ISSUE 15 satellite): the rows/views handed out
# by the columnar read path (`store.pod_columns()`) are store-returned
# READ-ONLY objects — writes through the view (element stores into its
# arrays/lists, mutator calls on its members) taint exactly like event
# objects; copies launder as usual.

MU001_COLUMNAR_BAD = '''
def poke_view_array(self):
    cols = self.store.pod_columns()
    cols.node_id[0] = 3

def poke_view_table(self):
    view = self.store.pod_columns()
    view.node_names.append("sneaky")

def poke_view_base(self):
    view = self.store.pod_columns()
    view.base[0].spec.node_name = "n1"
'''

MU001_COLUMNAR_GOOD = '''
def read_counts(self):
    cols = self.store.pod_columns()
    return int((cols.node_id >= 0).sum())

def copy_then_mutate(self):
    cols = self.store.pod_columns()
    mine = cols.node_id.copy()
    mine[0] = 3
    return mine

def stats_only(self):
    return self.store.columnar_stats()
'''


def test_mu001_fires_on_columnar_view_mutation():
    findings = [f for f in analyze_source(MU001_COLUMNAR_BAD)
                if f.rule == "MU001"]
    assert len(findings) == 3, findings


def test_mu001_quiet_on_columnar_reads_and_copies():
    assert "MU001" not in rules_of(analyze_source(MU001_COLUMNAR_GOOD))


# MU001 cache-rows extension (ISSUE 16 satellite): Cache.pod_columns() hands
# out a CacheColumnsView over the live scheduler-cache row table — the same
# read-only contract as the store view (runtime-enforced writeable=False
# numpy + this static rule).

MU001_CACHECOLS_BAD = '''
def poke_cache_view_array(self):
    cols = self.cache.pod_columns()
    cols.node_id[0] = 3

def poke_cache_view_pod(self):
    view = self.cache.pod_columns()
    view.pod[0].spec.node_name = "n1"

def poke_cache_view_index(self):
    view = self.cache.pod_columns()
    view.key2row.pop("default/p0")
'''

MU001_CACHECOLS_GOOD = '''
def read_cache_rows(self):
    cols = self.cache.pod_columns()
    return int((cols.node_id >= 0).sum())

def copy_then_mutate(self):
    cols = self.cache.pod_columns()
    mine = cols.node_id.copy()
    mine[0] = 3
    return mine

def stats_only(self):
    return self.cache.columnar_stats()
'''


def test_mu001_fires_on_cache_view_mutation():
    findings = [f for f in analyze_source(MU001_CACHECOLS_BAD)
                if f.rule == "MU001"]
    assert len(findings) == 3, findings


def test_mu001_quiet_on_cache_view_reads_and_copies():
    assert "MU001" not in rules_of(analyze_source(MU001_CACHECOLS_GOOD))


def test_cache_columns_view_is_runtime_readonly():
    """The CacheColumnsView numpy member enforces the contract at runtime,
    like the store's PodColumnsView (ro() writeable=False pattern)."""
    import pytest

    from kubernetes_tpu.scheduler.cachecols import (CacheColumns,
                                                    CacheColumnsView,
                                                    numpy_available)
    if not numpy_available():
        pytest.skip("numpy required")
    cols = CacheColumns()

    class _P:
        pass

    cols.insert("default/p0", _P(), "node-1")
    view = CacheColumnsView(cols)
    with pytest.raises(ValueError):
        view.node_id[0] = 7
    assert view.n == 1 and view.node_names[view.node_id[0]] == "node-1"


JT001_BAD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k_slots",))
def solve(x, k_slots):
    return x[:k_slots]

def driver(x, members):
    return solve(x, k_slots=len(members))
'''

JT001_GOOD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k_slots", "has_gang"))
def solve(x, k_slots, has_gang=False):
    return x[:k_slots]

def driver(x, members, gang):
    k_slots = 1 << (len(members) - 1).bit_length()  # pow2 bucket
    return solve(x, k_slots=k_slots, has_gang=bool(gang.size))
'''


def test_jt001_fires_on_raw_len_into_static_arg():
    findings = [f for f in analyze_source(JT001_BAD) if f.rule == "JT001"]
    assert len(findings) == 1, findings
    assert "k_slots" in findings[0].message


def test_jt001_quiet_on_bucketed_and_bool_gated_statics():
    assert "JT001" not in rules_of(analyze_source(JT001_GOOD))


JT002_BAD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=())
def solve(x):
    total = jnp.sum(x)
    host = float(total)          # host sync inside the traced body
    arr = np.asarray(x)          # numpy inside jit
    return host, arr

def helper(v):
    return v.item()              # host sync, traced via solve2

@jax.jit
def solve2(x):
    return helper(jnp.max(x))
'''

JT002_GOOD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=())
def solve(x):
    return jnp.sum(x).astype(jnp.float32)

def host_driver(x):
    out = solve(jnp.asarray(x))
    return float(out), np.asarray(out)   # host conversion OUTSIDE the jit
'''


def test_jt002_fires_on_host_sync_inside_jit_bodies():
    findings = [f for f in analyze_source(JT002_BAD) if f.rule == "JT002"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 3, msgs
    assert "float()" in msgs and "numpy call" in msgs and ".item()" in msgs
    assert "traced via" in msgs  # helper reached through the call graph


def test_jt002_quiet_outside_the_jit_boundary():
    assert "JT002" not in rules_of(analyze_source(JT002_GOOD))


# ISSUE 8: the repair kernel's static-gate discipline. The propose-and-
# repair solver (models/repair.py) keys its jitted violation check on bool
# constraint gates and a pow2-bucketed pod axis; the bug class JT001 guards
# is someone keying it on the raw batch length or a raw round count instead
# (one compile per batch size / per repair round — tens of seconds each at
# TPU scale).

JT001_REPAIR_BAD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("pb", "has_affinity"))
def repair_check(node_of, pb, has_affinity=True):
    return node_of[:pb]

def check(assignment, violators):
    # raw lengths key the jit: a compile per batch size AND per violator
    # count — the exact retrace class the pow2 bucket exists to prevent
    return repair_check(assignment, pb=len(assignment),
                        has_affinity=len(violators) > 0)
'''

JT001_REPAIR_GOOD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("pb", "has_affinity", "has_ct"))
def repair_check(node_of, pb, has_affinity=True, has_ct=True):
    return node_of[:pb]

def check(assignment, batch, p):
    # the shipped discipline: pow2 pod-axis bucket (floored so small
    # batches share one shape) + bool constraint-family gates
    pb = max(256, 1 << (p - 1).bit_length())
    return repair_check(assignment, pb=pb,
                        has_affinity=bool(batch.ipa.has_any),
                        has_ct=bool(batch.ct_class.size))
'''


def test_jt001_fires_on_repair_kernel_raw_static_keys():
    findings = [f for f in analyze_source(JT001_REPAIR_BAD)
                if f.rule == "JT001"]
    assert len(findings) >= 1, findings
    assert any("pb" in f.message for f in findings)


def test_jt001_quiet_on_repair_kernel_shipped_gates():
    assert "JT001" not in rules_of(analyze_source(JT001_REPAIR_GOOD))


JT002_REPAIR_BAD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=("d_max",))
def repair_check(node_of, counts, d_max):
    placed = node_of >= 0
    host = np.nonzero(np.asarray(placed))[0]   # numpy readback INSIDE jit
    return host

def violators(node_of, counts, d_max):
    return repair_check(node_of, counts, d_max)
'''

JT002_REPAIR_GOOD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=("d_max",))
def repair_check(node_of, counts, d_max):
    return node_of >= 0

def violators(node_of, counts, d_max, p):
    # the shipped discipline: the host readback (_check's np.asarray +
    # nonzero) happens OUTSIDE the traced body, once per round
    v = repair_check(node_of, counts, d_max)
    return np.nonzero(np.asarray(v)[:p])[0]
'''


def test_jt002_fires_on_host_readback_inside_repair_kernel():
    findings = [f for f in analyze_source(JT002_REPAIR_BAD)
                if f.rule == "JT002"]
    assert len(findings) >= 1, findings


def test_jt002_quiet_on_host_readback_outside_repair_kernel():
    assert "JT002" not in rules_of(analyze_source(JT002_REPAIR_GOOD))


# ISSUE 14: the gang victim-cover / rank-adjacency kernels' static-gate
# discipline (models/gangcover.py). cover_curve keys on pow2 node/victim
# buckets and rank_align_kernel on the pow2 pod axis; the guarded bug class
# is keying either on a RAW slice size / victim count / batch length — one
# compile per cluster shape or per cover attempt.

JT001_GANGCOVER_BAD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("n_slots", "k_max"))
def cover_curve(free, v_node, n_slots, k_max):
    return free[:n_slots], v_node[:k_max]

def cover_curves(free, v_node):
    # raw slice-node and victim counts key the jit: a compile per slice
    # shape AND per candidate-victim count
    return cover_curve(free, v_node, n_slots=len(free),
                       k_max=len(v_node))
'''

JT001_GANGCOVER_GOOD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("n_slots", "k_max"))
def cover_curve(free, v_node, n_slots, k_max):
    return free[:n_slots], v_node[:k_max]

def cover_curves(free, v_node, ns, k):
    # the shipped discipline: pow2 buckets over both padded axes
    n_slots = 1 << max(0, ns - 1).bit_length()
    k_max = 1 << max(0, k - 1).bit_length()
    return cover_curve(free, v_node, n_slots=n_slots, k_max=k_max)
'''


def test_jt001_fires_on_gangcover_raw_static_keys():
    findings = [f for f in analyze_source(JT001_GANGCOVER_BAD)
                if f.rule == "JT001"]
    assert len(findings) >= 1, findings
    assert any("n_slots" in f.message or "k_max" in f.message
               for f in findings)


def test_jt001_quiet_on_gangcover_shipped_buckets():
    assert "JT001" not in rules_of(analyze_source(JT001_GANGCOVER_GOOD))


JT002_GANGCOVER_BAD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=("p_max",))
def rank_align_kernel(assignment, group_id, rank, pos_key, p_max):
    idx = jnp.arange(p_max)
    order_rank = jnp.lexsort((idx, rank, group_id))
    # host sort INSIDE the traced body: a device round-trip per call
    order_pos = np.lexsort((np.asarray(idx), np.asarray(pos_key)))
    return assignment[order_rank], order_pos
'''

JT002_GANGCOVER_GOOD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=("p_max",))
def rank_align_kernel(assignment, group_id, rank, pos_key, p_max):
    idx = jnp.arange(p_max)
    order_rank = jnp.lexsort((idx, rank, group_id))
    order_pos = jnp.lexsort((idx, pos_key, group_id))
    return jnp.zeros_like(assignment).at[order_rank].set(
        assignment[order_pos])

def rank_align(assignment, group_id, rank, pos_key, p):
    # the shipped discipline: numpy padding happens OUTSIDE the traced body
    p_max = 1 << max(0, p - 1).bit_length()
    a = np.asarray(assignment)
    return rank_align_kernel(a, group_id, rank, pos_key, p_max=p_max)
'''


def test_jt002_fires_on_host_sort_inside_gangcover_kernel():
    findings = [f for f in analyze_source(JT002_GANGCOVER_BAD)
                if f.rule == "JT002"]
    assert len(findings) >= 1, findings


def test_jt002_quiet_on_host_padding_outside_gangcover_kernel():
    assert "JT002" not in rules_of(analyze_source(JT002_GANGCOVER_GOOD))


JT001_DEFRAG_BAD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("n_slots", "v_max"))
def defrag_assign(free, headroom, target_ok, v_req, v_valid, n_slots, v_max):
    return free[:n_slots], v_req[:v_max]

def defrag_plan(free, headroom, target_ok, v_req):
    # raw node and victim counts key the jit: a compile per cluster size
    # AND per candidate-victim count — the rebalancer would recompile on
    # every cycle whose donor slice drains a different number of pods
    return defrag_assign(free, headroom, target_ok, v_req, v_req,
                         n_slots=len(free), v_max=len(v_req))
'''

JT001_DEFRAG_GOOD = '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("n_slots", "v_max"))
def defrag_assign(free, headroom, target_ok, v_req, v_valid, n_slots, v_max):
    return free[:n_slots], v_req[:v_max]

def defrag_plan(free, headroom, target_ok, v_req, ns, v):
    # the shipped discipline: pow2 buckets over both padded axes, so the
    # kernel compiles once per doubling, not once per cycle
    n_slots = 1 << max(0, ns - 1).bit_length()
    v_max = 1 << max(0, v - 1).bit_length()
    return defrag_assign(free, headroom, target_ok, v_req, v_req,
                         n_slots=n_slots, v_max=v_max)
'''


def test_jt001_fires_on_defrag_raw_static_keys():
    findings = [f for f in analyze_source(JT001_DEFRAG_BAD)
                if f.rule == "JT001"]
    assert len(findings) >= 1, findings
    assert any("n_slots" in f.message or "v_max" in f.message
               for f in findings)


def test_jt001_quiet_on_defrag_shipped_buckets():
    assert "JT001" not in rules_of(analyze_source(JT001_DEFRAG_GOOD))


JT002_DEFRAG_BAD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=("n_slots", "v_max"))
def defrag_assign(free, headroom, target_ok, v_req, v_valid, n_slots, v_max):
    def step(carry, xs):
        fr, hd = carry
        vr, valid = xs
        fits = (fr >= vr[None, :]).all(axis=1) & (hd > 0) & target_ok
        waste = jnp.sum(fr - vr[None, :], axis=1)
        # host argmin INSIDE the scan body: a device round-trip per victim
        tgt = int(np.argmin(np.where(np.asarray(fits),
                                     np.asarray(waste), 2**30)))
        fr = fr.at[tgt].add(-vr)
        hd = hd.at[tgt].add(-1)
        return (fr, hd), tgt
    _, out = jax.lax.scan(step, (free, headroom), (v_req, v_valid),
                          length=v_max)
    return out
'''

JT002_DEFRAG_GOOD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np

@functools.partial(jax.jit, static_argnames=("n_slots", "v_max"))
def defrag_assign(free, headroom, target_ok, v_req, v_valid, n_slots, v_max):
    def step(carry, xs):
        fr, hd = carry
        vr, valid = xs
        fits = (fr >= vr[None, :]).all(axis=1) & (hd > 0) & target_ok
        waste = jnp.sum(fr - vr[None, :], axis=1)
        key = jnp.where(fits, waste, jnp.int32(2**30))
        tgt = jnp.argmin(key).astype(jnp.int32)
        place = (key[tgt] < jnp.int32(2**30)) & valid
        fr = fr.at[tgt].add(-vr * place)
        hd = hd.at[tgt].add(-place.astype(hd.dtype))
        return (fr, hd), jnp.where(place, tgt, jnp.int32(-1))
    _, out = jax.lax.scan(step, (free, headroom), (v_req, v_valid),
                          length=v_max)
    return out

def defrag_plan(free, headroom, target_ok, v_req, ns, v):
    # the shipped discipline: numpy padding happens OUTSIDE the traced body
    n_slots = 1 << max(0, ns - 1).bit_length()
    v_max = 1 << max(0, v - 1).bit_length()
    free_p = np.zeros((n_slots, free.shape[1]), dtype=np.int32)
    free_p[:ns] = free
    return defrag_assign(free_p, headroom, target_ok, v_req, v_req,
                         n_slots=n_slots, v_max=v_max)
'''


def test_jt002_fires_on_host_argmin_inside_defrag_scan():
    findings = [f for f in analyze_source(JT002_DEFRAG_BAD)
                if f.rule == "JT002"]
    assert len(findings) >= 1, findings


def test_jt002_quiet_on_host_padding_outside_defrag_kernel():
    assert "JT002" not in rules_of(analyze_source(JT002_DEFRAG_GOOD))


HP001_BAD = '''
import time

def schedule_batch(self, qps, m):
    for qp in qps:
        t0 = time.perf_counter()
        self.place(qp)
        m.batch_stage_duration.observe(time.perf_counter() - t0, "pod")
'''

HP001_GOOD = '''
import time

def schedule_batch(self, qps, m):
    t0 = time.perf_counter()
    for qp in qps:
        self.place(qp)
    m.batch_stage_duration.observe(time.perf_counter() - t0, "batch")

def chunk_timing_ok(self, to_bind, m):
    # 3-arg range = CHUNK loop (pods/bind_chunk iterations): per-chunk
    # instrumentation is the recorder's own design
    for lo in range(0, len(to_bind), 4096):
        t0 = time.perf_counter()
        self.commit(to_bind[lo:lo + 4096])
        m.batch_stage_duration.observe(time.perf_counter() - t0, "bind")
'''

_HOT = "kubernetes_tpu/scheduler/batch.py"


def test_hp001_fires_on_per_pod_instrumentation():
    findings = [f for f in analyze_source(HP001_BAD, filename=_HOT)
                if f.rule == "HP001"]
    assert len(findings) >= 2, findings


def test_hp001_quiet_per_batch_and_per_chunk():
    assert "HP001" not in rules_of(analyze_source(HP001_GOOD, filename=_HOT))


def test_hp001_scoped_to_hot_files():
    # the same bad code outside scheduler/batch.py is not HP001's business
    assert "HP001" not in rules_of(
        analyze_source(HP001_BAD, filename="kubernetes_tpu/cli/ktl.py"))


# ISSUE 7: the pod tracer's per-pod lifecycle stamping is legal ONLY behind
# a membership check against the sampled set — the guard bounds the paying
# population to the K reservoir slots. Unguarded stamping in a pod-scale
# loop of podtrace.py is the same 100k-multiplier bug HP001 exists for.

HP001_TRACE_BAD = '''
def batch_popped(self, qps, now):
    for qp in qps:
        sp = self._live.get(qp.key)
        sp.stamp("pop", now)
'''

HP001_TRACE_GOOD = '''
def batch_popped(self, qps, now):
    for qp in qps:
        if qp.key in self._sampled:
            sp = self._live.get(qp.key)
            sp.stamp("pop", now)

def chunk_bound(self, items, t_commit, errkeys):
    for qp, _node, _a in items:
        if qp.key in self._sampled and qp.key not in errkeys:
            sp = self._live.get(qp.key)
            sp.stamp("bind_commit", t_commit)
'''

_TRACE = "kubernetes_tpu/scheduler/podtrace.py"


def test_hp001_fires_on_unguarded_tracer_stamp():
    findings = [f for f in analyze_source(HP001_TRACE_BAD, filename=_TRACE)
                if f.rule == "HP001"]
    assert len(findings) == 1, findings
    assert ".stamp()" in findings[0].message


def test_hp001_quiet_behind_sampled_membership_guard():
    assert "HP001" not in rules_of(
        analyze_source(HP001_TRACE_GOOD, filename=_TRACE))


# ISSUE 9: controllers/base.py reconcile loops are HP001 hot paths too — a
# per-key metrics observe (or per-event perf_counter) inside the workqueue
# drain or the watch-buffer drain is the same multiplier bug; the
# ReconcileRecorder taps are per LOOP (recorder.loop()/pump() around the
# whole drain).

HP001_CONTROLLER_BAD = '''
import time

def process(self, keys, m):
    for key in keys:
        t0 = time.perf_counter()
        self.sync(key)
        m.controller_reconcile_duration.observe(
            time.perf_counter() - t0, "key")

def pump(self, clock):
    for ev in self._watch.drain(10_000):
        clock.mark("event")
        self._mark(ev.obj.key)
'''

HP001_CONTROLLER_GOOD = '''
import time

def process(self, keys, recorder):
    t0 = time.perf_counter()
    for key in keys:
        self.sync(key)
    recorder.loop(keys=len(keys), errors=0, requeues=0,
                  seconds=time.perf_counter() - t0, depth=0)

def pump(self, recorder):
    t0 = time.perf_counter()
    n = 0
    for ev in self._watch.drain(10_000):
        self._mark(ev.obj.key)
        n += 1
    recorder.pump(n, time.perf_counter() - t0)
'''

_CTRL = "kubernetes_tpu/controllers/base.py"


def test_hp001_fires_on_per_key_reconcile_instrumentation():
    findings = [f for f in analyze_source(HP001_CONTROLLER_BAD,
                                          filename=_CTRL)
                if f.rule == "HP001"]
    # per-key perf_counter + observe in process(), per-event clock.mark in
    # the drain loop of pump() — all three are the multiplier bug
    assert len(findings) >= 3, findings


def test_hp001_quiet_on_per_loop_reconcile_taps():
    assert "HP001" not in rules_of(
        analyze_source(HP001_CONTROLLER_GOOD, filename=_CTRL))


# ISSUE 13: the steady-state telemetry files (obs/timeseries.py,
# obs/resource.py) are HP001 hot paths — their contract is one tap per
# WINDOW close / per SAMPLE tick. Someone "improving accuracy" by feeding
# the window per pod inside a pod-scale loop is the 100k multiplier bug.

HP001_OBS_BAD = '''
import time

def note_batch_per_pod(self, qps, m):
    for qp in qps:
        t0 = time.perf_counter()
        self._fold(qp)
        m.batch_stage_duration.observe(time.perf_counter() - t0, "pod")
'''

HP001_OBS_GOOD = '''
import time

def note_batch(self, stages, qps):
    t0 = time.perf_counter()
    with self._lock:
        w = self._advance_locked(t0)
        for name, sec in stages.items():
            w.stage_samples.setdefault(name, []).append(sec)
        w.pods += len(qps)
    self._bill(time.perf_counter() - t0)
'''


@pytest.mark.parametrize("hot", ["kubernetes_tpu/obs/timeseries.py",
                                 "kubernetes_tpu/obs/resource.py"])
def test_hp001_fires_on_per_pod_window_feed(hot):
    findings = [f for f in analyze_source(HP001_OBS_BAD, filename=hot)
                if f.rule == "HP001"]
    assert len(findings) >= 2, findings


def test_hp001_quiet_on_per_window_taps():
    assert "HP001" not in rules_of(analyze_source(
        HP001_OBS_GOOD, filename="kubernetes_tpu/obs/timeseries.py"))
    # the identical bad code OUTSIDE the hot files stays out of scope
    assert "HP001" not in rules_of(analyze_source(
        HP001_OBS_BAD, filename="kubernetes_tpu/obs/recorder.py"))


def test_hp001_controller_scope_is_base_py_only():
    # a concrete controller's sync() body is per-OBJECT by design (one key
    # at a time); only the base reconcile loops are the hot path
    assert "HP001" not in rules_of(analyze_source(
        HP001_CONTROLLER_BAD,
        filename="kubernetes_tpu/controllers/replicaset.py"))


def test_hp001_guard_does_not_launder_batch_py_metrics():
    # the sampled-set exception is for tracer STAMPS; a metrics observe per
    # pod is still a finding even when some unrelated guard wraps it —
    # unless that guard IS a sampled-set membership check
    src = '''
def schedule_batch(self, qps, m):
    for qp in qps:
        if qp.key in self._ready_set:
            m.batch_stage_duration.observe(0.1, "pod")
'''
    findings = [f for f in analyze_source(
        src, filename="kubernetes_tpu/scheduler/batch.py")
        if f.rule == "HP001"]
    assert len(findings) == 1, findings


# ---------------------------------------------------------------------------
# ISSUE 18: the trace timeline (obs/tracebuf.py + obs/critpath.py) is an
# HP001 hot path — taps are per batch / per chunk / per cycle / per window,
# never per pod outside a sampled-set check
# ---------------------------------------------------------------------------

HP001_TRACEBUF_BAD = '''
def feed(self, qps, tracebuf):
    for qp in qps:
        tracebuf.ACTIVE.instant("sched", "pod", args={"key": qp.key})
'''

HP001_TRACEBUF_GOOD = '''
def feed(self, qps, clock, t_fin, tracebuf):
    if tracebuf.ACTIVE is not None:
        tb = tracebuf.ACTIVE
        tb.note_batch("sched", t_end=t_fin, stages=clock.stages,
                      pods=len(qps), scheduled=len(qps),
                      outcome="scheduled", solver="fast")
    for qp in qps:
        if qp.key in self._sampled:
            tracebuf.ACTIVE.instant("sched", "sampled-pod")
'''


@pytest.mark.parametrize("hot", ["kubernetes_tpu/obs/tracebuf.py",
                                 "kubernetes_tpu/obs/critpath.py",
                                 "kubernetes_tpu/scheduler/batch.py"])
def test_hp001_fires_on_per_pod_trace_tap(hot):
    findings = [f for f in analyze_source(HP001_TRACEBUF_BAD, filename=hot)
                if f.rule == "HP001"]
    assert len(findings) == 1, findings


def test_hp001_quiet_on_per_batch_trace_tap_and_sampled_guard():
    assert "HP001" not in rules_of(analyze_source(
        HP001_TRACEBUF_GOOD, filename="kubernetes_tpu/obs/tracebuf.py"))
    # the identical per-pod tap OUTSIDE the hot files stays out of scope
    assert "HP001" not in rules_of(analyze_source(
        HP001_TRACEBUF_BAD, filename="kubernetes_tpu/cli/ktl.py"))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

SUPPRESSED_WITH_REASON = '''
import threading
import time

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def sleepy(self):
        with self._lock:
            # schedlint: allow(LK002) test fixture: documented exception
            time.sleep(0.1)
'''

SUPPRESSED_BARE = '''
import threading
import time

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def sleepy(self):
        with self._lock:
            time.sleep(0.1)  # schedlint: allow(LK002)
'''


def test_suppression_with_reason_silences_the_finding():
    findings = analyze_source(SUPPRESSED_WITH_REASON)
    assert findings == [], findings


def test_bare_suppression_is_itself_a_finding():
    findings = analyze_source(SUPPRESSED_BARE)
    rules = rules_of(findings)
    assert "SL001" in rules          # reasonless suppression flagged
    assert "LK002" not in rules      # ... but it still suppresses


def test_wrong_rule_suppression_does_not_silence():
    src = SUPPRESSED_WITH_REASON.replace("allow(LK002)", "allow(MU001)")
    assert "LK002" in rules_of(analyze_source(src))


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_json_exit_codes(tmp_path):
    import json
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text(MU001_BAD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis.schedlint",
         "--json", str(bad)],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["stats"]["findings"] == 4
    assert all(f["rule"] == "MU001" for f in doc["findings"])

    good = tmp_path / "good.py"
    good.write_text(MU001_GOOD)
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis.schedlint",
         str(good)],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert proc.returncode == 0, proc.stdout

    # a typo'd path must NOT report a clean tree: exit 2 + a PARSE finding
    # (an analyzer that saw nothing must not certify anything)
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis.schedlint",
         "--json", str(tmp_path / "no_such_dir")],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert proc.returncode == 2, (proc.returncode, proc.stdout)
    doc = json.loads(proc.stdout)
    assert doc["stats"]["findings"] == 1
    assert doc["findings"][0]["rule"] == "PARSE"


# ---------------------------------------------------------------------------
# MP001 / MP002 — cross-process hygiene (ISSUE 19)
# ---------------------------------------------------------------------------

MP001_BAD = """
import multiprocessing

def dispatch(out_q, pod, qps):
    out_q.put(("work", pod))            # bare pod object

def relay(conn, batch):
    conn.send([qp for qp in batch])     # laundered? no: comprehension is
                                        # not flagged, but the next line is
def relay2(conn, pods):
    conn.send(pods)                     # the whole pod list

def nested(out_q, qp):
    out_q.put_nowait({"item": (1, qp)}) # pod buried in a container
"""

MP001_GOOD = """
import multiprocessing

def dispatch(out_q, pod, rows):
    out_q.put(("work", pod.key, 3))     # field access extracts a scalar
    out_q.put(("rows", rows))
    out_q.put_nowait(("bind", [(1, 2, 3), (4, 5, 6)]))

def relay(conn, pod):
    conn.send(key_of(pod))              # a call launders (returns a key)
"""


def test_mp001_fires_on_pod_objects_crossing_process_boundary():
    findings = [f for f in analyze_source(MP001_BAD) if f.rule == "MP001"]
    assert len(findings) == 3, findings
    assert {f.line for f in findings} == {5, 11, 14}


def test_mp001_quiet_on_keys_rows_and_laundered_fields():
    assert "MP001" not in rules_of(analyze_source(MP001_GOOD))


def test_mp001_quiet_without_multiprocessing_import():
    # a plain thread-safe queue in a non-mp module is not a process
    # boundary — the rule must not fire on ordinary producer/consumer code
    src = """
import queue

def feed(q, pod):
    q.put(pod)
"""
    assert "MP001" not in rules_of(analyze_source(src))


MP002_BAD = """
from multiprocessing import shared_memory

class Seg:
    def start(self):
        self.seg = shared_memory.SharedMemory(
            name="x", create=True, size=64)

    def run(self):
        return bytes(self.seg.buf[:8])
"""

MP002_GOOD = """
from multiprocessing import shared_memory

class Seg:
    def start(self):
        self.seg = shared_memory.SharedMemory(
            name="x", create=True, size=64)

    def stop(self):
        self.seg.close()
        self.seg.unlink()
"""

MP002_GOOD_FINALLY = """
from multiprocessing import shared_memory

def once():
    seg = shared_memory.SharedMemory(name="x", create=True, size=64)
    try:
        return bytes(seg.buf[:8])
    finally:
        seg.close()
        seg.unlink()
"""

MP002_GOOD_ATTACH = """
from multiprocessing import shared_memory

def read(name):
    # attach (create=False default) is the READER side: it must never
    # unlink, so the rule does not demand a teardown pairing here
    seg = shared_memory.SharedMemory(name=name)
    return bytes(seg.buf[:8])
"""


def test_mp002_fires_on_create_without_teardown():
    findings = [f for f in analyze_source(MP002_BAD) if f.rule == "MP002"]
    assert len(findings) == 1, findings


def test_mp002_quiet_on_stop_path_and_finally_teardown():
    assert "MP002" not in rules_of(analyze_source(MP002_GOOD))
    assert "MP002" not in rules_of(analyze_source(MP002_GOOD_FINALLY))
    assert "MP002" not in rules_of(analyze_source(MP002_GOOD_ATTACH))


# ---------------------------------------------------------------------------
# ISSUE 20 tentpole: the interprocedural closure. The pinned LK002
# regression first — a blocking call ONE HELPER DEEP in another module,
# under a store lock, resolved through a module-qualified call
# (`helpers.pause(...)`). The legacy resolver (module_qualified=False)
# cannot see through it (top-level functions are not in the unique-method
# map), so the bug sails through; the whole-program resolver reports it
# with the full call chain, no suppression needed.
# ---------------------------------------------------------------------------

LK002_VIA_HELPERS_MOD = '''
import subprocess
import time

def pause_for_settle():
    time.sleep(0.5)

def spawn_flush(cmd):
    subprocess.run(cmd, check=True)
'''

LK002_VIA_STORE_MOD = '''
import threading

from fixturepkg import helpers

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def locked_settle(self):
        with self._lock:
            helpers.pause_for_settle()

    def locked_flush(self):
        with self._lock:
            helpers.spawn_flush(["sync"])
'''

LK002_VIA_STORE_GOOD_MOD = '''
import threading

from fixturepkg import helpers

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def settle_outside(self):
        with self._lock:
            payload = 1
        helpers.pause_for_settle()
        helpers.spawn_flush(["sync"])
        return payload
'''


def _lk002_via_sources(store_src):
    return [
        (LK002_VIA_HELPERS_MOD, "fixturepkg/helpers.py",
         "fixturepkg.helpers"),
        (store_src, "fixturepkg/store_mod.py", "fixturepkg.store_mod"),
    ]


def test_lk002_pinned_regression_old_resolver_misses_the_helper():
    # the documented MISS: before the module-qualified resolver, the
    # blocking helper in another module was invisible — zero findings
    findings = analyze_sources(_lk002_via_sources(LK002_VIA_STORE_MOD),
                               module_qualified=False)
    assert "LK002" not in rules_of(findings), findings


def test_lk002_pinned_regression_new_resolver_reports_the_chain():
    findings = [f for f in
                analyze_sources(_lk002_via_sources(LK002_VIA_STORE_MOD))
                if f.rule == "LK002"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 2, msgs
    assert "time.sleep" in msgs and "subprocess.run" in msgs
    assert "blocks on the child process" in msgs
    # the full resolved path is printed, both ends module-qualified
    assert ("via call chain fixturepkg.store_mod.Store.locked_settle "
            "-> fixturepkg.helpers.pause_for_settle") in msgs
    assert ("via call chain fixturepkg.store_mod.Store.locked_flush "
            "-> fixturepkg.helpers.spawn_flush") in msgs
    # green suppression-free: the findings anchor in the helper module
    assert all(f.file == "fixturepkg/helpers.py" for f in findings)


def test_lk002_quiet_when_helper_called_outside_the_lock():
    findings = analyze_sources(
        _lk002_via_sources(LK002_VIA_STORE_GOOD_MOD))
    assert "LK002" not in rules_of(findings), findings


LK002_SUBPROCESS_BAD = '''
import subprocess
import threading

class Store:
    def __init__(self):
        self._lock = threading.RLock()

    def fork_under_lock(self, cmd):
        with self._lock:
            return subprocess.check_output(cmd)
'''


def test_lk002_fires_on_direct_subprocess_under_lock():
    findings = [f for f in analyze_source(LK002_SUBPROCESS_BAD)
                if f.rule == "LK002"]
    assert len(findings) == 1, findings
    assert "subprocess.check_output()" in findings[0].message
    assert "blocks on the child process" in findings[0].message


# ---------------------------------------------------------------------------
# ISSUE 20: HP001's via-call-chain form — unguarded per-pod call into a
# hot-file helper that instruments unconditionally, one or two hops deep
# ---------------------------------------------------------------------------

HP001_VIA_BAD = '''
class Batcher:
    def _note_pod(self, qp, m):
        m.batch_stage_duration.observe(0.1, "pod")

    def _account(self, qp, m):
        self._note_pod(qp, m)

    def schedule_batch(self, qps, m):
        for qp in qps:
            self._account(qp, m)
'''

HP001_VIA_GOOD = '''
class Batcher:
    def _note_pod(self, qp, m):
        m.batch_stage_duration.observe(0.1, "pod")

    def _requeue_failed(self, qp, m):
        m.batch_stage_duration.observe(0.1, "requeue")

    def _lookup(self, qp):
        return qp.key

    def schedule_batch(self, qps, m):
        for qp in qps:
            k = self._lookup(qp)
            self._requeue_failed(qp, m)
            if qp.key in self._sampled:
                self._note_pod(qp, m)
'''


def test_hp001_fires_via_call_chain_into_hot_helper():
    findings = [f for f in analyze_source(HP001_VIA_BAD, filename=_HOT)
                if f.rule == "HP001"]
    assert len(findings) == 1, findings
    msg = findings[0].message
    assert "via call chain" in msg
    assert ("schedule_batch -> " in msg and "_account" in msg
            and "_note_pod" in msg), msg
    assert ".observe()" in msg


def test_hp001_via_chain_quiet_on_terminal_path_and_sampled_guard():
    # _requeue_failed is a terminal-path helper by name; the _note_pod
    # call sits behind the sampled-set membership guard; _lookup does not
    # instrument — none of the three is the multiplier bug
    assert "HP001" not in rules_of(
        analyze_source(HP001_VIA_GOOD, filename=_HOT))


# ---------------------------------------------------------------------------
# ISSUE 20: MP001's via-helper form — a pod handed from the mp boundary
# module into a helper module that does the .put() — the pickle is the
# same, laundered through one call
# ---------------------------------------------------------------------------

MP001_VIA_MP_MOD = '''
import multiprocessing

from fixturepkg import shiputil

def dispatch(out_q, pod):
    shiputil.ship(out_q, pod)
'''

MP001_VIA_HELPER_MOD = '''
def ship(out_q, pod):
    out_q.put(("work", pod))
'''

MP001_VIA_GOOD_MP_MOD = '''
import multiprocessing

from fixturepkg import shiputil

def dispatch(out_q, pod):
    shiputil.ship(out_q, pod.key)
'''

MP001_VIA_GOOD_HELPER_MOD = '''
def ship(out_q, key):
    out_q.put(("work", key))
'''


def test_mp001_fires_via_helper_in_another_module():
    findings = [f for f in analyze_sources([
        (MP001_VIA_MP_MOD, "fixturepkg/mpmod.py", "fixturepkg.mpmod"),
        (MP001_VIA_HELPER_MOD, "fixturepkg/shiputil.py",
         "fixturepkg.shiputil"),
    ]) if f.rule == "MP001"]
    assert len(findings) == 1, findings
    msg = findings[0].message
    assert "reached via call chain" in msg
    assert ("fixturepkg.mpmod.dispatch -> fixturepkg.shiputil.ship"
            in msg), msg
    assert findings[0].file == "fixturepkg/shiputil.py"


def test_mp001_via_helper_quiet_when_only_the_key_crosses():
    findings = analyze_sources([
        (MP001_VIA_GOOD_MP_MOD, "fixturepkg/mpmod.py", "fixturepkg.mpmod"),
        (MP001_VIA_GOOD_HELPER_MOD, "fixturepkg/shiputil.py",
         "fixturepkg.shiputil"),
    ])
    assert "MP001" not in rules_of(findings), findings


# ---------------------------------------------------------------------------
# ISSUE 20: AL001/AL002 — steady-state allocation discipline (the static
# complement of the pod_obj_allocs == 0 runtime gauge)
# ---------------------------------------------------------------------------

AL001_BAD = '''
def schedule_batch(qps, rows):
    for qp in qps:
        pod = PodInfo(qp.key)
        snap = qp.pod.copy()
        rows.append((pod, snap))
'''

AL001_VIA_BAD = '''
def _expand(qp):
    return PodInfo(qp.key)

def schedule_batch(qps, rows):
    for qp in qps:
        rows.append(_expand(qp))
'''

AL_GOOD = '''
def schedule_batch(qps, cols_rows_ok, rows):
    for qp in qps:
        pod = qp.pod if cols_rows_ok else pod_bind_clone(qp.pod)
        rows.append(qp.row)
    try:
        commit(rows)
    except ValueError:
        failed = PodInfo(rows[0])
        _requeue_one(failed)
    return rows

def materialize_columnar_rows(rows):
    return [PodInfo(r) for r in rows]
'''

AL002_BAD = '''
def schedule_batch(qps):
    snapshot = [PodInfo(qp.key) for qp in qps]
    return snapshot
'''

AL002_GOOD = '''
def schedule_batch(qps, use_columnar):
    if not use_columnar:
        return [PodInfo(qp.key) for qp in qps]
    return [qp.row for qp in qps]
'''

_AL_HOT = "kubernetes_tpu/scheduler/batch.py"


def test_al001_fires_on_steady_state_pod_allocation():
    findings = [f for f in analyze_source(AL001_BAD, filename=_AL_HOT)
                if f.rule == "AL001"]
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 2, msgs
    assert "PodInfo(...)" in msgs
    assert ".copy() of pod object" in msgs
    assert "zero-alloc steady-state path" in msgs


def test_al001_fires_via_call_chain_through_ungated_helper():
    findings = [f for f in analyze_source(AL001_VIA_BAD, filename=_AL_HOT)
                if f.rule == "AL001"]
    assert len(findings) == 1, findings
    msg = findings[0].message
    assert "via call chain" in msg
    assert "schedule_batch -> " in msg and "_expand" in msg, msg


def test_al_quiet_behind_gates_barriers_and_except_paths():
    # the gated ternary clone, the except-handler PodInfo (error paths are
    # not steady state), the requeue helper call out of the handler, and
    # the materialize* barrier function's comprehension are all declared
    # exits from the zero-alloc regime
    findings = analyze_source(AL_GOOD, filename=_AL_HOT)
    assert "AL001" not in rules_of(findings), findings
    assert "AL002" not in rules_of(findings), findings


def test_al002_fires_on_pod_materializing_comprehension():
    findings = [f for f in analyze_source(AL002_BAD, filename=_AL_HOT)
                if f.rule == "AL002"]
    assert len(findings) == 1, findings
    assert "materializes a pod object per element" in findings[0].message


def test_al002_quiet_behind_a_gate_predicate():
    assert "AL002" not in rules_of(
        analyze_source(AL002_GOOD, filename=_AL_HOT))


def test_al_rules_scoped_to_the_designated_hot_paths():
    # the identical allocation outside the designated files/functions is
    # not AL's business ...
    assert rules_of(analyze_source(
        AL001_BAD, filename="kubernetes_tpu/cli/ktl.py")).isdisjoint(
            {"AL001", "AL002"})
    # ... and cachecols.py is hot WHOLESALE (every function is a root)
    findings = [f for f in analyze_source(
        AL002_BAD.replace("schedule_batch", "refresh_rows"),
        filename="kubernetes_tpu/scheduler/cachecols.py")
        if f.rule == "AL002"]
    assert len(findings) == 1, findings


# ---------------------------------------------------------------------------
# ISSUE 20: SEQ001/SEQ002 — the shm seqlock protocol
# ---------------------------------------------------------------------------

SEQ001_BAD = '''
class Reader:
    def nrows(self):
        v0 = int(self._hdr[_H_VER])
        n = int(self._hdr[_H_NROWS])
        return n
'''

SEQ001_GOOD = '''
class Reader:
    def nrows(self):
        for _ in range(64):
            v0 = int(self._hdr[_H_VER])
            n = int(self._hdr[_H_NROWS])
            if v0 % 2 == 0 and int(self._hdr[_H_VER]) == v0:
                return n
        raise RuntimeError("torn read")
'''

SEQ001_ESCAPE_BAD = '''
class Reader:
    def column(self, name):
        v0 = int(self._hdr[_H_VER])
        arrs = self.arrays
        self._cached_view = arrs[name]
        return arrs[name]
'''

SEQ001_ESCAPE_GOOD = '''
class Reader:
    def column(self, name):
        for _ in range(64):
            v0 = int(self._hdr[_H_VER])
            arrs = self.arrays
            out = arrs[name].copy()
            if v0 % 2 == 0 and int(self._hdr[_H_VER]) == v0:
                return out
        raise RuntimeError("torn read")
'''

SEQ002_BAD = '''
class Arena:
    def publish(self, n):
        self._hdr[_H_NROWS] = n
        self._hdr[_H_VER] += 1
'''

SEQ002_COLS_BAD = '''
class Arena:
    def write_row(self, i, cpu):
        arrs = self.arrays
        arrs["cpu"][i] = cpu
'''

SEQ002_GOOD = '''
class Arena:
    def publish(self, n):
        self._hdr[_H_VER] += 1
        self._hdr[_H_NROWS] = n
        self._hdr[_H_VER] += 1

    def append_row(self, i, cpu, n):
        arrs = self.arrays
        arrs["cpu"][i] = cpu
        self.publish(n)
'''

_SEQ_FILE = "kubernetes_tpu/store/shm.py"


def test_seq001_fires_on_missing_version_recheck():
    findings = [f for f in analyze_source(SEQ001_BAD, filename=_SEQ_FILE)
                if f.rule == "SEQ001"]
    assert len(findings) == 1, findings
    assert "never re-checks" in findings[0].message


def test_seq001_quiet_on_the_retry_loop_shape():
    assert "SEQ001" not in rules_of(
        analyze_source(SEQ001_GOOD, filename=_SEQ_FILE))


def test_seq001_fires_on_raw_view_escaping_the_retry_scope():
    findings = [f for f in
                analyze_source(SEQ001_ESCAPE_BAD, filename=_SEQ_FILE)
                if f.rule == "SEQ001"]
    msgs = "\n".join(f.message for f in findings)
    # stored on self AND returned raw — both escapes
    assert len(findings) == 2, msgs
    assert "stored on self" in msgs and "returns raw" in msgs


def test_seq001_quiet_when_the_value_is_laundered_in_scope():
    assert "SEQ001" not in rules_of(
        analyze_source(SEQ001_ESCAPE_GOOD, filename=_SEQ_FILE))


def test_seq002_fires_on_one_sided_version_bump():
    findings = [f for f in analyze_source(SEQ002_BAD, filename=_SEQ_FILE)
                if f.rule == "SEQ002"]
    assert len(findings) == 1, findings
    assert "BOTH sides" in findings[0].message


def test_seq002_fires_on_unpublished_column_writes():
    findings = [f for f in
                analyze_source(SEQ002_COLS_BAD, filename=_SEQ_FILE)
                if f.rule == "SEQ002"]
    assert len(findings) == 1, findings
    assert "never calls .publish" in findings[0].message


def test_seq002_quiet_on_the_publish_shape():
    assert "SEQ002" not in rules_of(
        analyze_source(SEQ002_GOOD, filename=_SEQ_FILE))


def test_seq_rules_scoped_to_the_seqlock_files():
    findings = analyze_source(SEQ002_BAD,
                              filename="kubernetes_tpu/store/store.py")
    assert rules_of(findings).isdisjoint({"SEQ001", "SEQ002"}), findings


# ---------------------------------------------------------------------------
# ISSUE 20: the runtime lock-graph witness (store/lockgraph.py)
# ---------------------------------------------------------------------------


def test_lock_graph_witness_reports_seeded_inversion_with_both_stacks():
    from kubernetes_tpu.store.lockgraph import LockGraphWitness
    from kubernetes_tpu.store.store import _LockOrderState, _OrderedRLock

    # a deliberate inversion in a scratch SAME-RANK pair (equal rank
    # passes the runtime assertion, so both orders get witnessed),
    # isolated from the process-wide witness and lock stack
    w = LockGraphWitness()
    state = _LockOrderState()
    a = _OrderedRLock("scratch_a", 0, state, witness=w)
    b = _OrderedRLock("scratch_b", 0, state, witness=w)

    def forward_order():
        with a:
            with b:
                pass

    def reversed_order():
        with b:
            with a:
                pass

    forward_order()
    reversed_order()

    table = {"scratch_a": 0, "scratch_b": 1}
    report = w.diff(table)
    assert not report["clean"]
    assert len(report["violations"]) == 1, report["violations"]
    v = report["violations"][0]
    assert v["edge"] == "scratch_b -> scratch_a"
    # BOTH first-seen stacks: the offending edge's and its reverse's
    assert "reversed_order" in v["stack"]
    assert v["reverse_stack"] and "forward_order" in v["reverse_stack"]
    # both orders witnessed = a cycle, each edge carrying its first stack
    assert len(report["cycles"]) == 1, report["cycles"]
    assert "scratch_a" in report["cycles"][0]["cycle"]
    assert len(report["cycles"][0]["stacks"]) == 2
    text = w.render(table)
    assert "INVERSION" in text and "first acquisition stack" in text
    assert "CYCLE" in text


def test_lock_graph_witness_clean_on_the_mandated_order():
    from kubernetes_tpu.store.lockgraph import (ORDER_TABLE,
                                                LockGraphWitness)
    from kubernetes_tpu.store.store import _LockOrderState, _OrderedRLock

    w = LockGraphWitness()
    state = _LockOrderState()
    names = sorted(ORDER_TABLE, key=ORDER_TABLE.get)
    locks = [_OrderedRLock(n, ORDER_TABLE[n], state, witness=w)
             for n in names]
    for lk in locks:
        lk.acquire()
    for lk in reversed(locks):
        lk.release()
    report = w.diff()
    assert report["clean"], report
    assert report["edges"] == len(names) - 1
    assert "CLEAN against the LK001 ordering table" in w.render()


def test_lock_graph_export_roundtrip_renders_the_inversion(tmp_path):
    from kubernetes_tpu.analysis.schedlint import lock_graph_report
    from kubernetes_tpu.store.lockgraph import LockGraphWitness
    from kubernetes_tpu.store.store import _LockOrderState, _OrderedRLock

    w = LockGraphWitness()
    state = _LockOrderState()
    a = _OrderedRLock("scratch_a", 0, state, witness=w)
    b = _OrderedRLock("scratch_b", 0, state, witness=w)
    with b:
        with a:
            pass
    path = tmp_path / "lockgraph.json"
    w.export(str(path), {"scratch_a": 0, "scratch_b": 1})
    text, clean = lock_graph_report(str(path))
    assert not clean
    assert "INVERSION" in text and "scratch_b -> scratch_a" in text


def test_lock_graph_report_scratch_store_walks_the_mandated_chain():
    from kubernetes_tpu.analysis.schedlint import lock_graph_report

    text, clean = lock_graph_report()
    assert clean, text
    assert "CLEAN against the LK001 ordering table" in text


def test_store_acquisitions_record_into_the_process_witness():
    # the autouse STORE_LOCK_ORDER_CHECK fixture arms every test store;
    # exercising one must land its edges in the process-wide witness the
    # session-teardown gate diffs (tests/conftest.py)
    from kubernetes_tpu.store.lockgraph import WITNESS
    from kubernetes_tpu.store.store import APIStore

    store = APIStore()
    with store._lock:
        with store._pods_lock:
            pass
    key = ("_lock (global RV)", "_pods_lock (pods shard)")
    assert key in WITNESS.edges
    assert WITNESS.diff()["clean"]


# ---------------------------------------------------------------------------
# ISSUE 20: --diff scope and the baseline stats block
# ---------------------------------------------------------------------------


def test_diff_scope_merges_reverse_import_and_call_deps():
    from kubernetes_tpu.analysis.index import ProjectIndex
    from kubernetes_tpu.analysis.schedlint import diff_scope

    idx = ProjectIndex.from_sources([
        (LK002_VIA_HELPERS_MOD, "fixturepkg/helpers.py",
         "fixturepkg.helpers"),
        (LK002_VIA_STORE_GOOD_MOD, "fixturepkg/store_mod.py",
         "fixturepkg.store_mod"),
        ("def standalone():\n    return 1\n", "fixturepkg/other.py",
         "fixturepkg.other"),
    ])
    scope = diff_scope(idx, ["fixturepkg/helpers.py"])
    # the changed file itself + the module that imports/calls into it;
    # the unrelated module stays out of scope
    assert "fixturepkg/helpers.py" in scope
    assert "fixturepkg/store_mod.py" in scope
    assert "fixturepkg/other.py" not in scope


def test_cli_json_carries_baseline_and_callgraph_stats(tmp_path):
    import json
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text(MU001_BAD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis.schedlint",
         "--json", str(bad)],
        capture_output=True, text=True, cwd=repo, timeout=120)
    doc = json.loads(proc.stdout)
    base = doc["stats"]["baseline"]
    assert base["findings_by_rule"] == {"MU001": 4}
    assert base["suppression_count"] == 0
    assert base["parse_errors"] == []
    cg = doc["stats"]["callgraph"]
    assert cg["depth_cap"] == 12 and cg["fanout_cap"] == 64
    assert doc["stats"]["callgraph_edges"] == cg["edges"]

    sup = tmp_path / "sup.py"
    sup.write_text(SUPPRESSED_WITH_REASON)
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis.schedlint",
         "--json", str(sup)],
        capture_output=True, text=True, cwd=repo, timeout=120)
    doc = json.loads(proc.stdout)
    base = doc["stats"]["baseline"]
    assert base["suppression_count"] == 1
    assert base["suppressions"][0]["rules"] == ["LK002"]
    assert "documented exception" in base["suppressions"][0]["reason"]


def test_cli_diff_mode_scopes_and_reports(tmp_path):
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis.schedlint",
         "--json", "--diff", "HEAD"],
        capture_output=True, text=True, cwd=repo, timeout=300)
    assert proc.returncode == 0, proc.stdout[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["stats"]["diff"]["ref"] == "HEAD"
    assert doc["stats"]["diff"]["scope_files"] <= doc["stats"]["files"]
    assert doc["stats"]["findings"] == 0
