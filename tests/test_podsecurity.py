"""PodSecurity levels + round-4 admission breadth plugins.

reference: staging/src/k8s.io/pod-security-admission/policy,
plugin/pkg/admission/{extendedresourcetoleration,nodetaint,antiaffinity}.
"""

import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    Namespace,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    Volume,
)
from kubernetes_tpu.server.admission import (
    AdmissionChain,
    AdmissionError,
    ExtendedResourceToleration,
    LimitPodHardAntiAffinityTopology,
    MetadataDefaulter,
    PodSecurityAdmission,
    TaintNodesByCondition,
)
from kubernetes_tpu.server.podsecurity import check_baseline, check_restricted
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod


def _ns(store, name, level=None):
    ns = Namespace(metadata=ObjectMeta(name=name))
    if level:
        ns.metadata.labels["pod-security.kubernetes.io/enforce"] = level
    store.create("namespaces", ns)
    return ns


def _restricted_ok_pod(ns="default"):
    pod = MakePod("web", namespace=ns).req({"cpu": "100m"}).obj()
    for c in pod.spec.containers:
        c.security_context = {
            "runAsNonRoot": True,
            "allowPrivilegeEscalation": False,
            "capabilities": {"drop": ["ALL"]},
            "seccompProfile": {"type": "RuntimeDefault"},
        }
    return pod


class TestLevelChecks:
    def test_baseline_flags_host_surfaces(self):
        pod = MakePod("p").req({"cpu": "1"}).obj()
        pod.spec.host_network = True
        pod.spec.host_pid = True
        pod.spec.volumes.append(Volume(name="h", host_path="/etc"))
        pod.spec.containers[0].security_context = {"privileged": True}
        errs = check_baseline(pod)
        assert len(errs) == 4
        assert any("privileged" in e for e in errs)
        assert any("hostPath" in e for e in errs)

    def test_baseline_capability_allowlist(self):
        pod = MakePod("p").req({"cpu": "1"}).obj()
        pod.spec.containers[0].security_context = {
            "capabilities": {"add": ["CHOWN", "SYS_ADMIN"]}}
        errs = check_baseline(pod)
        assert len(errs) == 1
        assert "SYS_ADMIN" in errs[0] and "CHOWN" not in errs[0]

    def test_restricted_requires_hardening(self):
        pod = MakePod("p").req({"cpu": "1"}).obj()
        errs = check_restricted(pod)
        assert any("runAsNonRoot" in e for e in errs)
        assert any("allowPrivilegeEscalation" in e for e in errs)
        assert any("drop ALL" in e for e in errs)
        assert any("seccompProfile" in e for e in errs)
        assert check_restricted(_restricted_ok_pod()) == []

    def test_pod_level_security_context_inherited(self):
        pod = _restricted_ok_pod()
        for c in pod.spec.containers:
            del c.security_context["runAsNonRoot"]
            del c.security_context["seccompProfile"]
        pod.spec.security_context = {"runAsNonRoot": True,
                                     "seccompProfile": {"type": "RuntimeDefault"}}
        assert check_restricted(pod) == []


class TestPodSecurityAdmission:
    def test_enforced_by_namespace_label(self):
        store = APIStore()
        _ns(store, "locked", level="restricted")
        chain = AdmissionChain([PodSecurityAdmission()])
        bad = MakePod("p", namespace="locked").req({"cpu": "1"}).obj()
        with pytest.raises(AdmissionError) as e:
            chain.run(store, "pods", "CREATE", bad)
        assert "violates PodSecurity" in str(e.value)
        chain.run(store, "pods", "CREATE", _restricted_ok_pod("locked"))

    def test_unlabelled_namespace_not_enforced(self):
        store = APIStore()
        _ns(store, "open")
        chain = AdmissionChain([PodSecurityAdmission()])
        pod = MakePod("p", namespace="open").obj()
        pod.spec.host_network = True
        chain.run(store, "pods", "CREATE", pod)  # no error

    def test_unknown_level_fails_closed(self):
        store = APIStore()
        _ns(store, "weird", level="bogus")
        chain = AdmissionChain([PodSecurityAdmission()])
        with pytest.raises(AdmissionError):
            chain.run(store, "pods", "CREATE",
                      MakePod("p", namespace="weird").req({"cpu": "1"}).obj())


class TestBreadthPlugins:
    def test_extended_resource_toleration(self):
        store = APIStore()
        pod = MakePod("p").req({"cpu": "1", "tpu.dev/chips": "4"}).obj()
        AdmissionChain([ExtendedResourceToleration()]).run(
            store, "pods", "CREATE", pod)
        tols = [t for t in pod.spec.tolerations if t.key == "tpu.dev/chips"]
        assert len(tols) == 1 and tols[0].operator == "Exists"
        # idempotent: re-running does not duplicate
        AdmissionChain([ExtendedResourceToleration()]).run(
            store, "pods", "CREATE", pod)
        assert len([t for t in pod.spec.tolerations
                    if t.key == "tpu.dev/chips"]) == 1

    def test_extended_resource_requires_domain(self):
        """helper.IsExtendedResourceName: unqualified and kubernetes.io/
        hugepages keys never earn tolerations."""
        store = APIStore()
        pod = MakePod("p").req({"gpu": "1", "hugepages-512Mi": "512Mi",
                                "kubernetes.io/batch-cpu": "1"}).obj()
        AdmissionChain([ExtendedResourceToleration()]).run(
            store, "pods", "CREATE", pod)
        assert pod.spec.tolerations == []

    def test_admission_taint_does_not_mask_lifecycle_escalation(self):
        """A never-heartbeating node with the admission NoSchedule taint must
        still get Ready=False and the NoExecute taint from node_lifecycle."""
        from kubernetes_tpu.controllers.node_lifecycle import (
            NodeLifecycleController,
        )
        from kubernetes_tpu.utils.clock import FakeClock

        store = APIStore()
        node = MakeNode("n1").capacity({"cpu": "4"}).obj()
        AdmissionChain([TaintNodesByCondition()]).run(
            store, "nodes", "CREATE", node)
        store.create("nodes", node)
        clock = FakeClock(1000.0)
        ctrl = NodeLifecycleController(store, clock=clock)
        ctrl.monitor()
        got = store.get("nodes", "n1")
        effects = {(t.key, t.effect) for t in got.spec.taints}
        assert ("node.kubernetes.io/not-ready", "NoExecute") in effects
        assert any(c.type == "Ready" and c.status == "False"
                   for c in got.status.conditions)

    def test_taint_nodes_by_condition(self):
        store = APIStore()
        node = MakeNode("n1").capacity({"cpu": "4"}).obj()
        AdmissionChain([TaintNodesByCondition()]).run(
            store, "nodes", "CREATE", node)
        assert any(t.key == "node.kubernetes.io/not-ready" and
                   t.effect == "NoSchedule" for t in node.spec.taints)

    def test_limit_hard_anti_affinity_topology(self):
        store = APIStore()
        chain = AdmissionChain([LimitPodHardAntiAffinityTopology()])
        pod = MakePod("p").req({"cpu": "1"}).obj()
        pod.spec.affinity = Affinity(pod_anti_affinity_required=[
            PodAffinityTerm(selector=None, topology_key="topology.kubernetes.io/zone")])
        with pytest.raises(AdmissionError) as e:
            chain.run(store, "pods", "CREATE", pod)
        assert e.value.code == 422
        ok = MakePod("q").obj()
        ok.spec.affinity = Affinity(pod_anti_affinity_required=[
            PodAffinityTerm(selector=None, topology_key="kubernetes.io/hostname")])
        chain.run(store, "pods", "CREATE", ok)

    def test_security_context_round_trips(self):
        from kubernetes_tpu.api.serialize import to_dict

        pod = _restricted_ok_pod()
        pod.spec.host_pid = True
        pod.spec.security_context = {"runAsUser": 1000}
        d = to_dict(pod)
        back = Pod.from_dict(d)
        assert back.spec.host_pid is True
        assert back.spec.security_context == {"runAsUser": 1000}
        assert back.spec.containers[0].security_context["capabilities"] == {
            "drop": ["ALL"]}
        assert to_dict(back) == d
