"""Steady-state telemetry (ISSUE 13): windowed time-series math (quantiles,
rotation under 3x-capacity churn, probes), trend/slope/drift gates on known
series, the resource/GIL sampler (per-thread CPU attribution, honesty
flags, gc pauses), sampler on/off placement parity (both watch_coalesce
modes, mutation detector forced), the leak-detector proof (the PR-11
parked-bind-worker heap pin caught by the trend gate, passing once
released), ring=true subscription pins for observability consumers, and
the /debug/timeseries + `ktl sched top` surfaces."""

import gc
import io
import json
import threading
import time
import urllib.request
from contextlib import redirect_stdout
from types import SimpleNamespace

import pytest

from kubernetes_tpu.obs.resource import (ResourceSampler, probe_thread_clock,
                                         read_thread_cpu_s)
from kubernetes_tpu.obs.timeseries import (TimeSeriesRecorder, drift_ratio,
                                           fit_slope)
from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.flightrec import timeseries_snapshot
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.scheduler.slo import (SOAK_SLO, TREND_MIN_WINDOWS,
                                          evaluate_slo)
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod, mutation_detector_guard


@pytest.fixture(autouse=True)
def _force_mutation_detector(monkeypatch):
    """The PR 4 CI pattern: every store this module builds runs with the
    mutation detector FORCE-ENABLED and checked at teardown — the sampler
    and window probes read live scheduler/store state and must never
    mutate it."""
    yield from mutation_detector_guard(monkeypatch)


def _nodes(n, cpu="8", mem="32Gi"):
    return [MakeNode(f"node-{i}").capacity(
        {"cpu": cpu, "memory": mem, "pods": "110"}).obj() for i in range(n)]


def _pods(n, prefix="p", cpu="100m", mem="128Mi"):
    return [MakePod(f"{prefix}-{i}").req({"cpu": cpu, "memory": mem}).obj()
            for i in range(n)]


def _sched(store, **kw):
    kw.setdefault("batch_size", 1024)
    kw.setdefault("solver", "exact")
    kw.setdefault("pipeline_binds", False)
    sched = BatchScheduler(store, Framework(default_plugins()), **kw)
    sched.sync()
    return sched


# -- windowed time-series core ---------------------------------------------------


class TestTimeSeriesRecorder:
    def test_windows_settle_nearest_rank_quantiles(self):
        # 100 batches in ONE window with solve = 1..100 ms: nearest-rank
        # p50/p99 over the window's per-batch samples are EXACT
        ts = TimeSeriesRecorder(window_s=10.0)
        for i in range(1, 101):
            ts.note_batch({"solve": i / 1000.0}, pods=1, scheduled=1,
                          now=100.0 + i * 0.01)
        ts.note_batch({}, now=200.0)  # next window: closes the first
        w = ts.windows()[0]
        assert w["batches"] == 100
        row = w["stages"]["solve"]
        assert row["p50_ms"] == 50.0
        assert row["p99_ms"] == 99.0
        assert row["total_ms"] == pytest.approx(5050.0, abs=0.5)
        assert row["batches"] == 100

    def test_pods_per_sec_and_counts(self):
        ts = TimeSeriesRecorder(window_s=2.0)
        ts.note_batch({"solve": 0.001}, pods=100, scheduled=90, failed=10,
                      now=50.0)
        ts.note_batch({"solve": 0.001}, pods=50, scheduled=50, now=51.0)
        ts.note_batch({}, now=53.0)
        w = ts.windows()[0]
        assert (w["pods"], w["scheduled"], w["failed"]) == (150, 140, 10)
        assert w["pods_per_sec"] == pytest.approx(140 / 2.0, rel=0.01)

    def test_ring_bounded_under_3x_capacity_churn(self):
        # 3x capacity worth of windows: the ring keeps the newest CAPACITY,
        # seq stays monotonic, nothing leaks
        ts = TimeSeriesRecorder(window_s=1.0, capacity=8)
        for i in range(24):
            ts.note_batch({"solve": 0.001}, pods=1, now=1000.0 + i)
        ts.note_batch({}, now=2000.0)
        ws = ts.windows()
        assert len(ws) == 8
        assert ts.windows_closed == 25  # 24 churn + the 2000.0 stale close
        seqs = [w["seq"] for w in ws]
        assert seqs == sorted(seqs) and seqs[-1] >= 24

    def test_idle_gap_emits_no_fabricated_windows(self):
        ts = TimeSeriesRecorder(window_s=1.0)
        ts.note_batch({"solve": 0.001}, now=10.0)
        ts.note_batch({"solve": 0.001}, now=500.0)  # long idle gap
        ws = ts.windows()  # the read closes the open window (real clock)
        assert len(ws) == 2  # one per ACTIVE period, no empty filler
        assert ws[1]["start_ts"] == 500.0  # fresh epoch AT the batch
        assert all(w["batches"] == 1 for w in ws)

    def test_note_stage_outside_bucket_joins_window(self):
        ts = TimeSeriesRecorder(window_s=5.0)
        ts.note_batch({"solve": 0.002}, pods=1, now=10.0)
        ts.note_stage("bind", 0.004, now=11.0)
        ts.note_stage("bind", 0.008, now=12.0)
        ts.note_batch({}, now=20.0)
        w = ts.windows()[0]
        assert w["stages"]["bind"]["batches"] == 2
        assert w["stages"]["bind"]["total_ms"] == pytest.approx(12.0, abs=0.5)
        assert w["batches"] == 1  # outside taps don't count as batches

    def test_probes_fire_once_per_close_and_failures_skip(self):
        ts = TimeSeriesRecorder(window_s=1.0)
        calls = []

        def probe():
            calls.append(1)
            return {"depth": len(calls)}

        def bad_probe():
            raise RuntimeError("wedged")

        ts.add_probe("queue", probe)
        ts.add_probe("broken", bad_probe)
        for i in range(3):
            ts.note_batch({"solve": 0.001}, now=100.0 + i)
        ts.note_batch({}, now=200.0)
        ws = ts.windows()
        assert len(calls) == len(ws)
        assert ws[0]["queue"] == {"depth": 1}
        assert all("broken" not in w for w in ws)

    def test_series_path_extraction_skips_missing(self):
        ts = TimeSeriesRecorder(window_s=1.0)
        probe_val = {"rss_mb": None}
        ts.add_probe("resource",
                     lambda: ({"rss_mb": probe_val["rss_mb"]}
                              if probe_val["rss_mb"] is not None else None))
        ts.note_batch({"solve": 0.001}, now=10.0)
        probe_val["rss_mb"] = 100.0
        ts.note_batch({"solve": 0.001}, now=11.0)
        probe_val["rss_mb"] = None  # this window contributes NO resource
        ts.note_batch({"solve": 0.001}, now=12.0)
        ts.note_batch({}, now=100.0)
        pts = ts.series("resource", "rss_mb")
        assert len(pts) == 1 and pts[0][1] == 100.0
        assert len(ts.series("stages", "solve", "p99_ms")) == 3

    def test_clear_resets_everything(self):
        ts = TimeSeriesRecorder(window_s=1.0)
        ts.note_batch({"solve": 0.001}, now=10.0)
        ts.note_batch({}, now=20.0)
        assert ts.windows()
        ts.clear()
        assert ts.windows_closed == 0
        assert ts.self_seconds == 0.0
        assert ts.windows() == []

    def test_windows_close_stale_open_window_on_read(self):
        ts = TimeSeriesRecorder(window_s=0.01)
        ts.note_batch({"solve": 0.001}, pods=3)
        time.sleep(0.03)
        ws = ts.windows()  # read-side settle: no second batch needed
        assert len(ws) == 1 and ws[0]["pods"] == 3

    def test_disabled_recorder_is_inert(self):
        ts = TimeSeriesRecorder(window_s=0.01, enabled=False)
        ts.note_batch({"solve": 0.001}, now=10.0)
        ts.note_stage("bind", 0.001, now=11.0)
        assert ts.windows() == []
        assert ts.self_seconds == 0.0

    def test_self_time_accrues_and_bills_sink(self):
        sink_total = []

        class Sink:
            def note_self_time(self, s):
                sink_total.append(s)

        ts = TimeSeriesRecorder(window_s=1.0, stat_sink=Sink())
        for i in range(50):
            ts.note_batch({"solve": 0.001}, now=10.0 + i * 0.01)
        assert ts.self_seconds > 0
        assert sum(sink_total) == pytest.approx(ts.self_seconds, rel=0.01)


# -- trend math on known series --------------------------------------------------


class TestTrendMath:
    def test_fit_slope_exact_line(self):
        assert fit_slope([(i, 3.0 * i + 7) for i in range(10)]) == \
            pytest.approx(3.0)

    def test_fit_slope_flat_and_degenerate(self):
        assert fit_slope([(i, 42.0) for i in range(5)]) == pytest.approx(0.0)
        assert fit_slope([(0.0, 1.0)]) is None
        assert fit_slope([]) is None
        assert fit_slope([(5.0, 1.0), (5.0, 9.0)]) is None  # one timestamp

    def test_fit_slope_noisy_line(self):
        pts = [(i, 2.0 * i + (1 if i % 2 else -1)) for i in range(50)]
        assert fit_slope(pts) == pytest.approx(2.0, abs=0.05)

    def test_drift_ratio_flat_grow_short(self):
        assert drift_ratio([5.0] * 9) == pytest.approx(1.0)
        assert drift_ratio([float(i) for i in range(1, 10)]) == \
            pytest.approx(8.0 / 2.0)
        assert drift_ratio([1.0, 2.0]) is None
        assert drift_ratio([0.0, 0.0, 0.0]) is None  # zero first third

    def test_drift_ratio_median_absorbs_one_spike(self):
        # one co-scheduling stall in the tail third must not fake a drift
        flat = [10.0] * 12
        flat[-1] = 500.0
        assert drift_ratio(flat) == pytest.approx(1.0)


# -- the windowed SLO gates ------------------------------------------------------


def _mk_windows(n, rss=None, alloc=None, p99=None, t0=1000.0, dt=5.0):
    out = []
    for i in range(n):
        w = {"end_ts": t0 + i * dt, "stages": {}, "resource": {}}
        if p99 is not None:
            w["stages"]["solve"] = {"p99_ms": p99[i]}
        if rss is not None:
            w["resource"]["rss_mb"] = rss[i]
        if alloc is not None:
            w["resource"]["alloc_blocks"] = alloc[i]
        out.append(w)
    return out


class TestTrendGates:
    def test_per_window_ceiling_fails_on_worst_window(self):
        # whole-run aggregate would absorb one stalled window; the windowed
        # key must not
        wins = _mk_windows(10, p99=[100.0] * 9 + [9000.0])
        res = evaluate_slo({"windows": wins},
                           {"stage_p99_ms_per_window": {"solve": 5000.0}})
        assert res["failed"] == ["stage_p99_ms_per_window:solve"]
        checks = {c["name"]: c for c in res["checks"]}
        assert checks["stage_p99_ms_per_window:solve"]["actual"] == 9000.0

    def test_rss_slope_gate_pass_flat_fail_growing(self):
        flat = _mk_windows(12, rss=[500.0 + (i % 2) * 0.5 for i in range(12)])
        grow = _mk_windows(12, rss=[500.0 + 10.0 * i for i in range(12)])
        spec = {"rss_slope_mb_per_min": 30.0}
        assert evaluate_slo({"windows": flat}, spec)["pass"] is True
        res = evaluate_slo({"windows": grow}, spec)
        # 10 MB per 5s window = 120 MB/min
        assert res["failed"] == ["rss_slope_mb_per_min"]
        actual = res["checks"][0]["actual"]
        assert actual == pytest.approx(120.0, rel=0.05)

    def test_alloc_block_slope_gate(self):
        grow = _mk_windows(
            12, alloc=[10**6 + 200_000 * i for i in range(12)])
        res = evaluate_slo({"windows": grow},
                           {"alloc_block_slope_per_s": 10_000.0})
        assert res["failed"] == ["alloc_block_slope_per_s"]
        assert res["checks"][0]["actual"] == pytest.approx(40_000.0,
                                                           rel=0.05)

    def test_trend_checks_skip_under_min_windows(self):
        wins = _mk_windows(TREND_MIN_WINDOWS - 1,
                           rss=[500.0] * (TREND_MIN_WINDOWS - 1),
                           alloc=[1] * (TREND_MIN_WINDOWS - 1),
                           p99=[1e9] * (TREND_MIN_WINDOWS - 1))
        res = evaluate_slo({"windows": wins}, {
            "rss_slope_mb_per_min": 30.0,
            "alloc_block_slope_per_s": 1.0,
            "p99_drift_ratio": 2.0})
        # unavailable trend = reported SKIP, never a silent pass — but the
        # per-window ceiling still sees the windows it has
        assert set(res["skipped"]) == {"rss_slope_mb_per_min",
                                       "alloc_block_slope_per_s",
                                       "p99_drift_ratio"}
        assert res["pass"] is True

    def test_drift_gate_fails_on_creep_ignores_submillisecond(self):
        creep = _mk_windows(12, p99=[10.0 * (1.3 ** i) for i in range(12)])
        res = evaluate_slo({"windows": creep}, {"p99_drift_ratio": 3.0})
        assert res["failed"] == ["p99_drift_ratio"]
        # the same creep entirely below 1ms is noise, not regression: the
        # check reports SKIP (no qualifying stage), never a false FAIL
        tiny = _mk_windows(12, p99=[0.01 * (1.3 ** i) for i in range(12)])
        res2 = evaluate_slo({"windows": tiny}, {"p99_drift_ratio": 3.0})
        assert res2["skipped"] == ["p99_drift_ratio"]

    def test_soak_spec_keys_are_known(self):
        # a typo in SOAK_SLO itself would FAIL loudly via unknown_spec_key
        res = evaluate_slo({"windows": _mk_windows(
            12, rss=[1.0] * 12, alloc=[1] * 12, p99=[1.0] * 12)}, SOAK_SLO)
        assert not any(c["name"].startswith("unknown_spec_key")
                       for c in res["checks"])

    def test_no_windows_section_skips_all_trends(self):
        res = evaluate_slo({}, {"rss_slope_mb_per_min": 30.0,
                                "p99_drift_ratio": 2.0,
                                "stage_p99_ms_per_window": {"solve": 1.0}})
        assert res["pass"] is True
        assert len(res["skipped"]) == 3


# -- the resource / GIL sampler --------------------------------------------------


class TestResourceSampler:
    def test_sample_once_fields(self):
        s = ResourceSampler(interval_s=0.1)
        rec = s.sample_once()
        assert rec["rss_mb"] > 0
        assert rec["alloc_blocks"] > 0
        assert len(rec["gc"]["gen_counts"]) == 3
        assert rec["process_cpu_s"] > 0
        assert s.samples_taken == 1
        assert s.self_seconds > 0

    def test_honesty_flags_published(self):
        s = ResourceSampler(interval_s=0.1)
        summ = s.summary()
        assert summ["clock_source"] in ("clockid", "schedstat",
                                        "unavailable")
        if summ["clock_source"] != "unavailable":
            # the resolution is MEASURED (clock_getres lies on some
            # containers), and published right next to the cpu columns
            assert summ["clock_resolution_s"] is None or \
                summ["clock_resolution_s"] > 0
        assert "overhead_frac" in summ

    def test_thread_cpu_attribution(self):
        probe = probe_thread_clock()
        if probe["source"] == "unavailable":
            pytest.skip("no per-thread CPU clock on this platform")
        s = ResourceSampler(interval_s=0.05)
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(1000))

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        s.register_thread("spin", t)
        s.register_thread("idle")  # this thread: sleeps through the window
        s.sample_once()
        deadline = time.perf_counter() + 2.0
        spin_cpu = 0.0
        while time.perf_counter() < deadline:
            time.sleep(0.05)
            rec = s.sample_once()
            spin_cpu = rec["threads"].get("spin", {}).get("cpu_s", 0.0)
            if spin_cpu > 0.02:
                break
        stop.set()
        t.join()
        assert spin_cpu > 0.02, "spinning thread accrued no CPU"
        summ = s.summary()
        assert summ["thread_cpu_s"]["spin"] >= spin_cpu * 0.5
        assert summ["thread_cpu_s"]["idle"] < summ["thread_cpu_s"]["spin"]

    def test_reregistration_keeps_column_monotonic(self):
        probe = probe_thread_clock()
        if probe["source"] == "unavailable":
            pytest.skip("no per-thread CPU clock on this platform")
        s = ResourceSampler(interval_s=0.05)

        def burn():
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.1:
                sum(range(1000))

        for _ in range(2):
            t = threading.Thread(target=burn)
            t.start()
            s.register_thread("worker", t)
            while t.is_alive():
                s.sample_once()
                time.sleep(0.01)
            t.join()
        total = s.summary()["thread_cpu_s"]["worker"]
        # both generations' CPU lands in ONE monotonic column
        assert total > 0.05, total

    def test_gc_pause_accounting(self):
        s = ResourceSampler(interval_s=0.1)
        s._install_gc_cb()
        try:
            junk = [[i] for i in range(1000)]
            del junk
            gc.collect()
            rec = s.sample_once()
            assert rec["gc"]["collections"] >= 1
            assert rec["gc"]["pause_s"] > 0
            assert rec["gc"]["pause_max_s"] <= rec["gc"]["pause_s"]
        finally:
            s._remove_gc_cb()

    def test_ring_bounded_and_reset(self):
        s = ResourceSampler(interval_s=0.1, capacity=4)
        for _ in range(10):
            s.sample_once()
        assert len(s.samples()) == 4
        s.reset()
        assert s.samples() == []
        assert s.samples_taken == 0
        assert s.latest() is None

    def test_sampler_thread_start_stop(self):
        s = ResourceSampler(interval_s=0.01)
        s.start()
        deadline = time.perf_counter() + 2.0
        while s.samples_taken < 3 and time.perf_counter() < deadline:
            time.sleep(0.01)
        s.stop()
        assert s.samples_taken >= 3
        taken = s.samples_taken
        time.sleep(0.05)
        assert s.samples_taken == taken  # really stopped

    def test_dead_thread_column_goes_quiet_not_fatal(self):
        probe = probe_thread_clock()
        if probe["source"] == "unavailable":
            pytest.skip("no per-thread CPU clock on this platform")
        s = ResourceSampler(interval_s=0.05)
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        s.register_thread("gone", t)
        rec = s.sample_once()  # dead tid: the column is absent, no raise
        assert "gone" not in rec["threads"] or \
            rec["threads"]["gone"]["cpu_s"] >= 0

    def test_read_thread_cpu_bad_source(self):
        assert read_thread_cpu_s(1, "nonsense") is None


# -- scheduler integration -------------------------------------------------------


class TestSchedulerIntegration:
    def _run(self, columnar, sampler=None, **kw):
        store = APIStore()
        for n in _nodes(6):
            store.create("nodes", n)
        sched = _sched(store, columnar=columnar, ts_window_s=0.02, **kw)
        if sampler is not None:
            sched.attach_resource_sampler(sampler)
            sampler.sample_once()
        store.create_many("pods", _pods(40, prefix="ti"), consume=True)
        sched.run_until_idle()
        time.sleep(0.03)  # let the open window expire
        return store, sched

    def _placements(self, store):
        return {p.metadata.name: p.spec.node_name
                for p in store.list("pods")[0] if p.spec.node_name}

    @pytest.mark.parametrize("columnar", [True, False])
    def test_sampler_onoff_placements_byte_identical(self, columnar):
        s_on, sched_on = self._run(columnar,
                                   sampler=ResourceSampler(interval_s=0.05))
        s_off, sched_off = self._run(columnar, sampler=None)
        on = self._placements(s_on)
        off = self._placements(s_off)
        assert len(on) == 40
        assert json.dumps(sorted(on.items())) == \
            json.dumps(sorted(off.items()))
        # and the sampled run's windows carry the resource columns
        ws = sched_on.timeseries.windows()
        assert ws and any("resource" in w for w in ws)
        assert sched_off.sched_stats()["resource"] is None

    def test_windows_in_sched_stats_with_probe_columns(self):
        _store, sched = self._run(True)
        st = sched.sched_stats()
        assert st["timeseries"]["enabled"] is True
        assert st["timeseries"]["windows_closed"] >= 1
        ws = st["windows"]
        assert ws, "no closed windows in sched_stats"
        # the solve batch lands in SOME window (outside buckets like
        # queue_add may open their own earlier/later windows)
        assert any((w["stages"].get("solve") or {}).get("p99_ms") is not None
                   for w in ws), ws
        w = ws[0]
        assert "active" in w["queue"]
        assert w["breaker"]["state"] == "closed"
        assert w["watch"]["subscribers"] >= 1
        assert "self_s" in w  # per-window instrumentation self-time
        assert "partition" not in w  # standalone: the probe contributes none

    def test_outside_stages_window_via_flightrec_forwarding(self):
        store = APIStore()
        for n in _nodes(6):
            store.create("nodes", n)
        sched = _sched(store, columnar=True, pipeline_binds=True,
                       ts_window_s=0.02)
        store.create_many("pods", _pods(40, prefix="ob"), consume=True)
        sched.run_until_idle()
        sched.flush_binds()
        time.sleep(0.03)
        stages = {name for w in sched.timeseries.windows()
                  for name in w["stages"]}
        assert "bind" in stages  # the worker's outside bucket windowed
        assert "bind_wait" in stages

    def test_recorder_off_disables_timeseries(self):
        store = APIStore()
        for n in _nodes(3):
            store.create("nodes", n)
        sched = _sched(store, flight_recorder=False)
        store.create_many("pods", _pods(10, prefix="off"), consume=True)
        sched.run_until_idle()
        time.sleep(0.02)
        assert sched.timeseries.enabled is False
        assert sched.timeseries.windows() == []

    def test_partition_probe_columns(self):
        from kubernetes_tpu.scheduler.partition import PartitionedScheduler

        store = APIStore()
        for n in _nodes(8):
            store.create("nodes", n)
        coord = PartitionedScheduler(
            store, lambda: Framework(default_plugins()), partitions=2,
            batch_size=256, solver="exact")
        for p in coord.pipelines:
            p.timeseries.window_s = 0.02
        sampler = ResourceSampler(interval_s=0.05)
        coord.attach_resource_sampler(sampler)
        sampler.sample_once()
        coord.sync()
        store.create_many("pods", _pods(40, prefix="pp"), consume=True)
        coord.run_until_idle()
        coord.flush_binds()
        time.sleep(0.03)
        idx_seen = set()
        for p in coord.pipelines:
            for w in p.timeseries.windows():
                part = w.get("partition")
                if part:
                    idx_seen.add(part["index"])
                    assert "conflicts" in part and "reroutes" in part
        assert idx_seen, "no partition columns in any window"
        coord.stop()


# -- the leak-detector proof -----------------------------------------------------


class TestLeakGateProof:
    """Re-introduce the PR-11 parked-bind-worker heap pin: a discarded
    BatchScheduler whose bind worker still parks in q.get() pins the whole
    scheduler object graph. The RSS/live-object trend gate must CATCH the
    pin, and pass once stop() releases the worker (the PR-11 fix)."""

    # per-5s-window ceilings: the pinned graph leaks ~60k blocks + a few
    # MB per window, an order of magnitude past both
    LEAK_SPEC = {"rss_slope_mb_per_min": 20.0,
                 "alloc_block_slope_per_s": 2_000.0}

    def _leak_iteration(self, release: bool):
        store = APIStore()
        for n in _nodes(4):
            store.create("nodes", n)
        sched = _sched(store, pipeline_binds=True)
        store.create_many("pods", _pods(30, prefix="lk"), consume=True)
        sched.run_until_idle()
        sched.flush_binds()
        assert sched._bind_worker is not None and \
            sched._bind_worker.is_alive()
        # the heap the parked worker pins: reachable from the scheduler
        sched._leak_ballast = list(range(60_000))
        if release:
            worker = sched._bind_worker
            sched.stop()  # the PR-11 fix: sentinel the worker out
            if worker is not None:
                worker.join(timeout=5)  # deterministic: the frame is gone
        # discard every reference; without stop() the parked worker's
        # frame keeps the graph alive
        del sched, store

    def _windows_under(self, release: bool):
        sampler = ResourceSampler(interval_s=1.0)
        wins = []
        # one unsampled warmup iteration: lazy imports / first-call caches
        # must not masquerade as growth in either leg
        self._leak_iteration(release)
        gc.collect()
        for i in range(6):
            self._leak_iteration(release)
            gc.collect()
            rec = sampler.sample_once()
            # the fixture simulates a soak cadence: one iteration per 5s
            # window (the real rung's axis) — the leak-per-window is what
            # the gate fits, not how fast this test loops
            wins.append({"end_ts": i * 5.0,
                         "resource": {"rss_mb": rec["rss_mb"],
                                      "alloc_blocks": rec["alloc_blocks"]}})
        return wins

    def test_parked_worker_pin_caught_then_released_passes(self):
        leaky = self._windows_under(release=False)
        res = evaluate_slo({"windows": leaky}, self.LEAK_SPEC)
        assert res["pass"] is False, res["checks"]
        # the live-object signal is the deterministic one (RSS may or may
        # not also trip depending on allocator arena reuse)
        assert "alloc_block_slope_per_s" in res["failed"], res["checks"]

        clean = self._windows_under(release=True)
        res2 = evaluate_slo({"windows": clean}, self.LEAK_SPEC)
        assert res2["pass"] is True, res2["checks"]


# -- ring-mode subscription pins (ISSUE 13 satellite) ----------------------------


class TestRingSubscriptionPins:
    def test_client_watch_ring_param_builds_ring_url(self, monkeypatch):
        from kubernetes_tpu.server.client import RESTClient

        seen = {}

        class _Resp:
            def __iter__(self):
                return iter([])

        def fake_urlopen(req, timeout=None):
            seen["url"] = req.full_url
            return _Resp()

        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        c = RESTClient("http://127.0.0.1:1")
        list(c.watch("pods", ring=True))
        assert "ring=true" in seen["url"]
        list(c.watch("pods"))  # the cache-building default: NO ring
        assert "ring=true" not in seen["url"]

    def test_ktl_get_watch_subscribes_ring_true(self):
        # the `-w` dashboard is an observability consumer: its subscription
        # must be lossy (ring=True), never able to trigger the
        # terminate->relist storm PR 11 fixed
        from kubernetes_tpu.cli.ktl import cmd_get

        seen = {}

        class _StubClient:
            def list(self, resource, ns, label_selector=""):
                return [], 7

            def watch(self, resource, **kw):
                seen.update(kw)
                return iter([])

        args = SimpleNamespace(resource="pods", name=None, namespace=None,
                               output="wide", watch=True, selector="",
                               all_namespaces=False)
        buf = io.StringIO()
        with redirect_stdout(buf):
            cmd_get(_StubClient(), args)
        assert seen.get("ring") is True

    def test_informer_keeps_eviction_contract(self):
        # Informer builds a cache: it NEEDS terminate-on-overflow to know
        # it missed events (410 -> relist). Its watch must stay ring-less.
        import inspect

        from kubernetes_tpu.server.client import Informer, RESTClient

        src = inspect.getsource(Informer)
        assert "ring=True" not in src
        # and the client default itself is ring-less
        sig = inspect.signature(RESTClient.watch)
        assert sig.parameters["ring"].default is False

    def test_server_ring_watch_via_http(self):
        # end to end: a ?ring=true subscription lands a ring-mode Watch on
        # the server store (the PR-11 plumbing), pinned from the client API
        from kubernetes_tpu.server import APIServer

        store = APIStore()
        srv = APIServer(store).start()
        try:
            req = urllib.request.Request(
                f"{srv.url}/api/v1/pods?watch=true&resourceVersion=-1"
                "&ring=true")
            resp = urllib.request.urlopen(req, timeout=5)
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                with store._lock:
                    watchers = list(store._watchers)
                if watchers:
                    break
                time.sleep(0.01)
            assert watchers and watchers[-1].ring is True
            resp.close()
        finally:
            srv.stop()


# -- the /debug/timeseries + ktl sched top surfaces ------------------------------


class TestTimeseriesSurfaces:
    def _server_with_traffic(self):
        from kubernetes_tpu.server import APIServer

        store = APIStore()
        srv = APIServer(store).start()
        for n in _nodes(3):
            store.create("nodes", n)
        sched = _sched(store, ts_window_s=0.02)
        sched.attach_resource_sampler(ResourceSampler(interval_s=0.05))
        sched.resource_sampler.sample_once()
        store.create_many("pods", _pods(20, prefix="sv"), consume=True)
        sched.run_until_idle()
        time.sleep(0.03)
        return store, srv, sched

    def test_debug_timeseries_endpoint(self):
        store, srv, sched = self._server_with_traffic()
        try:
            name = sched._bind_origin
            snap = timeseries_snapshot()
            assert name in snap and snap[name]["windows"]
            with urllib.request.urlopen(
                    f"{srv.url}/debug/timeseries") as resp:
                payload = json.loads(resp.read())
            assert name in payload
            doc = payload[name]
            assert doc["windows"]
            assert doc["resource"]["rss_mb"] > 0
            assert doc["resource"]["clock_source"]
        finally:
            srv.stop()

    def test_ktl_sched_top_renders(self):
        from kubernetes_tpu.cli.ktl import main as ktl_main

        store, srv, sched = self._server_with_traffic()
        try:
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "sched", "top"]) == 0
            out = buf.getvalue()
            assert "WIN" in out and "PODS/S" in out and "BREAKER" in out
            assert "ALLOCS" in out  # live zero-alloc gauge column (ISSUE 16)
            assert "resource:" in out and "clock=" in out
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "sched", "top",
                                 "-o", "json"]) == 0
            doc = json.loads(buf.getvalue())
            assert sched._bind_origin in doc
        finally:
            srv.stop()

    def test_sched_top_empty_registry_message(self):
        from kubernetes_tpu.cli.ktl import _render_sched_top

        assert "no batch scheduler" in _render_sched_top({})
