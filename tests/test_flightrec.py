"""Pipeline flight recorder (ISSUE 3): ring-buffer bounds, record schema
stability, Prometheus label escaping, the outcome-labeled batch histogram,
gang observability counters, the /debug/schedstats surface, and the
disabled-recorder parity invariant (identical placements with the recorder
on and off — instrumentation must never steer scheduling)."""

import json
import urllib.request

from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.flightrec import (
    BATCH_STAGES,
    FlightRecorder,
    StageClock,
    schedstats_snapshot,
)
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.server import metrics as m
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod, make_pod_group
from kubernetes_tpu.utils import FakeClock


def _nodes(n, cpu="8", mem="32Gi"):
    return [MakeNode(f"node-{i}").capacity(
        {"cpu": cpu, "memory": mem, "pods": "110"}).obj() for i in range(n)]


def _sched(store, solver="fast", **kw):
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=1024, solver=solver,
                           pipeline_binds=False, **kw)
    sched.sync()
    return sched


def _placements(store):
    return {p.metadata.name: p.spec.node_name
            for p in store.list("pods")[0] if p.spec.node_name}


# -- FlightRecorder unit surface -----------------------------------------------


def _mk_record(fr, seq_pods=1):
    return fr.record(pods=seq_pods, nodes=2, outcome="scheduled",
                     solver="fast", stages={"solve": 0.01}, total_s=0.02)


class TestRingBuffer:
    def test_capacity_bound_evicts_oldest(self):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            _mk_record(fr, seq_pods=i)
        assert len(fr) == 4
        recs = fr.records()
        assert [r["seq"] for r in recs] == [7, 8, 9, 10]
        assert fr.last()["seq"] == 10

    def test_aggregates_survive_eviction(self):
        fr = FlightRecorder(capacity=2)
        for _ in range(5):
            _mk_record(fr)
        # stage table covers ALL 5 batches, not just the 2 still in the ring
        table = fr.stage_table()
        assert table["solve"]["batches"] == 5
        assert abs(table["solve"]["total_ms"] - 50.0) < 1e-6

    def test_disabled_recorder_records_nothing(self):
        fr = FlightRecorder(enabled=False)
        assert _mk_record(fr) is None
        fr.add_outside("bind", 1.0)
        assert len(fr) == 0
        assert fr.stage_table() == {}

    def test_outside_buckets_and_overlap_flag(self):
        fr = FlightRecorder()
        _mk_record(fr)
        fr.add_outside("bind", 0.5)
        fr.add_outside("bind_wait", 0.25)
        table = fr.stage_table()
        assert table["bind"]["overlapped"] is True
        assert table["bind_wait"]["overlapped"] is False
        assert abs(table["bind"]["total_ms"] - 500.0) < 1e-6
        assert fr.outside_seconds("bind", "bind_wait") == 0.75

    def test_clear_resets_everything(self):
        fr = FlightRecorder()
        _mk_record(fr)
        fr.add_outside("bind", 0.5)
        fr.note_self_time(0.1)
        fr.clear()
        assert len(fr) == 0 and fr.stage_table() == {}
        assert fr.self_seconds == 0.0


class TestStageClock:
    def test_marks_are_disjoint_and_sum_to_total(self):
        clock = StageClock()
        clock.mark("a")
        clock.mark("b")
        clock.skip()  # unattributed span
        clock.mark("c")
        total = clock.total()
        assert set(clock.stages) == {"a", "b", "c"}
        assert sum(clock.stages.values()) <= total

    def test_sub_floors_at_zero(self):
        clock = StageClock()
        clock.mark("a")
        clock.sub("a", 10.0)
        assert clock.stages["a"] == 0.0


# -- record schema (the contract bench.py and ktl render from) ------------------

RECORD_KEYS = {"seq", "ts", "pods", "nodes", "outcome", "solver", "total_ms",
               "stages", "scheduled", "unschedulable", "fallback",
               "preempted", "reasons", "gang", "repair", "solver_iterations",
               "breaker", "error", "bind_failures"}


class TestRecordSchema:
    def test_live_batch_record_schema(self):
        store = APIStore()
        for n in _nodes(4):
            store.create("nodes", n)
        sched = _sched(store)
        store.create_many("pods", [MakePod(f"p-{i}").req(
            {"cpu": "100m"}).obj() for i in range(6)], consume=True)
        sched.run_until_idle()
        rec = sched.flightrec.last()
        assert set(rec) == RECORD_KEYS
        assert rec["outcome"] == "scheduled"
        assert rec["pods"] == 6 and rec["nodes"] == 4
        assert rec["scheduled"] == 6 and rec["unschedulable"] == 0
        assert rec["stages"] and all(
            isinstance(v, float) and v >= 0 for v in rec["stages"].values())
        assert set(rec["stages"]) <= set(BATCH_STAGES)
        # the big serial stages are all present for a real solved batch
        for stage in ("ingest", "pop", "tensorize", "build_pod_batch",
                      "solve", "assume", "dispatch"):
            assert stage in rec["stages"], stage

    def test_unschedulable_batch_attributes_reasons(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "1", "memory": "1Gi", "pods": "10"}).obj())
        sched = _sched(store)
        store.create("pods", MakePod("huge").req({"cpu": "64"}).obj())
        sched.schedule_batch(timeout=0.0)
        rec = sched.flightrec.last()
        assert rec["outcome"] == "unschedulable"
        assert rec["unschedulable"] == 1
        assert sum(rec["reasons"].values()) == 1
        assert "NodeResourcesFit" in rec["reasons"]

    def test_no_nodes_batch_records_unschedulable(self):
        store = APIStore()
        sched = _sched(store)
        store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
        before = m.batch_solve_duration.child("unschedulable").snapshot()[1]
        sched.schedule_batch(timeout=0.0)
        rec = sched.flightrec.last()
        assert rec is not None and rec["outcome"] == "unschedulable"
        assert rec["nodes"] == 0
        # the satellite fix: the early-return path now observes the
        # outcome-labeled batch_solve_duration histogram
        after = m.batch_solve_duration.child("unschedulable").snapshot()[1]
        assert after == before + 1

    def test_empty_pop_records_no_batch(self):
        store = APIStore()
        for n in _nodes(2):
            store.create("nodes", n)
        sched = _sched(store)
        sched.schedule_batch(timeout=0.0)
        assert sched.flightrec.last() is None


# -- Prometheus text exposition escaping ----------------------------------------


class TestLabelEscaping:
    def test_counter_escapes_quotes_backslashes_newlines(self):
        c = m.Counter("test_escape_total", "h")
        c.inc(pod='we"ird\\name\nx')
        line = [ln for ln in c.render() if not ln.startswith("#")][0]
        assert line == 'test_escape_total{pod="we\\"ird\\\\name\\nx"} 1.0'

    def test_labeled_histogram_escapes_label(self):
        h = m.LabeledHistogram("test_hist_seconds", "h", label="stage",
                               buckets=(1,))
        h.observe(0.5, 'a"b\\c')
        lines = h.render()
        assert any('stage="a\\"b\\\\c"' in ln for ln in lines)
        # exposition shape: HELP/TYPE once, then buckets/sum/count per child
        assert lines[0].startswith("# HELP") and lines[1].startswith("# TYPE")
        assert any("test_hist_seconds_count" in ln for ln in lines)

    def test_registry_render_roundtrips(self):
        reg = m.Registry()
        c = reg.counter("a_total")
        c.inc(x="1")
        g = reg.gauge("b")
        g.set(2.0)
        h = reg.labeled_histogram("c_seconds", label="stage", buckets=(1,))
        h.observe(0.1, "s")
        text = reg.render()
        assert 'a_total{x="1"} 1.0' in text
        assert "b 2.0" in text
        assert 'c_seconds_bucket{stage="s",le="1"} 1' in text


# -- gang observability ---------------------------------------------------------


class TestGangCounters:
    def test_orphan_release_increments_counter(self):
        clock = FakeClock()
        store = APIStore()
        for n in _nodes(4):
            store.create("nodes", n)
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=1024, solver="fast",
                               pipeline_binds=False, clock=clock)
        sched.sync()
        store.create("podgroups", make_pod_group("doomed", 3))
        store.create("podgroups", make_pod_group("other", 2))
        store.create_many("pods", [
            MakePod(f"g-{i}").gang("doomed").req({"cpu": "100m"}).obj()
            for i in range(2)])
        sched.pump_events()
        assert sched.queue.gang_staged_count() == 2
        store.delete("podgroups", "default/doomed")
        sched.pump_events()
        before = m.gang_orphan_released_total.value()
        clock.step(31.0)
        sched.queue.flush_unschedulable_left_over()
        assert m.gang_orphan_released_total.value() == before + 2

    def test_gang_veto_counter_and_record(self):
        store = APIStore()
        # 2 nodes x 1 cpu: a 3-member gang needing 1cpu each can never place
        for n in _nodes(2, cpu="1"):
            store.create("nodes", n)
        sched = _sched(store)
        store.create("podgroups", make_pod_group("big", 3))
        store.create_many("pods", [
            MakePod(f"g-{i}").gang("big").req({"cpu": "800m"}).obj()
            for i in range(3)])
        before = m.gang_vetoed_total.value(reason="solver")
        sched.schedule_batch(timeout=0.0)
        assert m.gang_vetoed_total.value(reason="solver") == before + 1
        rec = sched.flightrec.last()
        assert rec["gang"] is not None and rec["gang"]["vetoed"] == 1
        assert rec["reasons"].get("GangScheduling") == 3

    def test_quorum_expired_assumes_measurable(self):
        from kubernetes_tpu.scheduler.gang import GangDirectory

        gd = GangDirectory()
        gd.observe_podgroup("ADDED", make_pod_group("t", 2))
        p = MakePod("r0").gang("t").obj()
        gd.note_assumed(p)
        # cache no longer knows the pod (assume expired): the leak is counted
        assert gd.quorum_expired_count(lambda key: False) == 1
        assert gd.quorum_expired_count(lambda key: True) == 0


# -- parity: the recorder must never steer placement ----------------------------


class TestRecorderParity:
    def test_disabled_recorder_identical_placements(self):
        def run(flight_recorder):
            store = APIStore()
            for n in _nodes(6):
                store.create("nodes", n)
            sched = _sched(store, flight_recorder=flight_recorder)
            store.create_many("pods", [
                MakePod(f"p-{i}").req(
                    {"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(40)], consume=True)
            sched.run_until_idle()
            return _placements(store), sched

        on_placed, on_sched = run(True)
        off_placed, off_sched = run(False)
        assert len(on_placed) == 40
        assert on_placed == off_placed
        assert len(on_sched.flightrec) > 0
        assert len(off_sched.flightrec) == 0
        assert off_sched.sched_stats()["recorder"]["enabled"] is False


# -- the HTTP + registry surface ------------------------------------------------


class TestSchedStatsSurface:
    def test_registry_snapshot_and_http_endpoint(self):
        from kubernetes_tpu.server import APIServer

        store = APIStore()
        srv = APIServer(store).start()
        try:
            for n in _nodes(3):
                store.create("nodes", n)
            sched = _sched(store)
            store.create_many("pods", [MakePod(f"p-{i}").req(
                {"cpu": "100m"}).obj() for i in range(5)], consume=True)
            sched.run_until_idle()
            name = sched._bind_origin
            snap = schedstats_snapshot()
            assert name in snap
            assert snap[name]["scheduled"] == 5
            assert "solve" in snap[name]["stages"]
            with urllib.request.urlopen(
                    f"{srv.url}/debug/schedstats") as resp:
                payload = json.loads(resp.read())
            assert name in payload
            assert payload[name]["batches_solved"] >= 1
            assert payload[name]["last_batch"]["outcome"] == "scheduled"
        finally:
            srv.stop()

    def test_ktl_sched_stats_renders_table(self):
        import io
        from contextlib import redirect_stdout

        from kubernetes_tpu.cli.ktl import main as ktl_main
        from kubernetes_tpu.server import APIServer

        store = APIStore()
        srv = APIServer(store).start()
        try:
            for n in _nodes(3):
                store.create("nodes", n)
            sched = _sched(store)
            store.create_many("pods", [MakePod(f"p-{i}").req(
                {"cpu": "100m"}).obj() for i in range(5)], consume=True)
            sched.run_until_idle()
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "sched", "stats"]) == 0
            out = buf.getvalue()
            assert "STAGE" in out and "solve" in out
            assert sched._bind_origin in out
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "sched", "stats",
                                 "-o", "json"]) == 0
            doc = json.loads(buf.getvalue())
            assert sched._bind_origin in doc
        finally:
            srv.stop()
