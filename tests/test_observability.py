"""Tracing, structured logging, configz, and the scheduler cache debugger."""

import io
import json

from kubernetes_tpu.scheduler.debugger import compare, dump
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.scheduler.runtime import Framework
from kubernetes_tpu.scheduler.serial import Scheduler
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock
from kubernetes_tpu.utils.tracing import (
    StructuredLogger,
    Trace,
    configz_snapshot,
    register_config,
)


class TestTrace:
    def test_below_threshold_not_logged(self):
        clock = FakeClock()
        stream = io.StringIO()
        log = StructuredLogger("test", stream=stream)
        t = Trace("Op", logger=log, clock=clock)
        clock.step(0.05)
        assert not t.log_if_long(0.1)
        assert stream.getvalue() == ""

    def test_long_trace_logged_with_steps(self):
        clock = FakeClock()
        stream = io.StringIO()
        log = StructuredLogger("test", stream=stream)
        t = Trace("Scheduling", logger=log, clock=clock, pod="default/p")
        clock.step(0.08)
        t.step("Computing predicates done", feasible=3)
        clock.step(0.07)
        t.step("Prioritizing done")
        assert t.log_if_long(0.1)
        record = json.loads(stream.getvalue())
        assert record["total_ms"] == 150.0
        assert record["pod"] == "default/p"
        steps = {s["msg"]: s for s in record["steps"]}
        assert steps["Computing predicates done"]["ms"] == 80.0
        assert steps["Computing predicates done"]["feasible"] == 3
        assert steps["Prioritizing done"]["ms"] == 70.0

    def test_logger_levels(self):
        stream = io.StringIO()
        log = StructuredLogger("c", stream=stream, level="warning")
        log.info("hidden")
        log.warning("shown", code=7)
        lines = [json.loads(x) for x in stream.getvalue().splitlines()]
        assert len(lines) == 1 and lines[0]["msg"] == "shown" and lines[0]["code"] == 7


class TestConfigz:
    def test_register_and_http(self):
        import urllib.request

        from kubernetes_tpu.server import APIServer

        register_config("testcomponent", {"percentageOfNodesToScore": 40})
        assert configz_snapshot()["testcomponent"]["percentageOfNodesToScore"] == 40
        srv = APIServer(APIStore(), port=0).start()
        try:
            with urllib.request.urlopen(f"{srv.url}/configz") as resp:
                payload = json.loads(resp.read())
            assert payload["testcomponent"] == {"percentageOfNodesToScore": 40}
        finally:
            srv.stop()


class TestCacheDebugger:
    def _scheduler(self):
        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
        sched = Scheduler(store, Framework(default_plugins()), clock=FakeClock())
        sched.sync()
        sched.schedule_one()
        return store, sched

    def test_dump_shape(self):
        store, sched = self._scheduler()
        d = dump(sched)
        assert "n1" in d["nodes"]
        assert d["nodes"]["n1"]["pods"] == ["default/p"]
        assert d["nodes"]["n1"]["requested"]["milliCPU"] == 1000
        assert set(d["queue"]) == {"active", "backoff", "unschedulable"}

    def test_compare_consistent(self):
        store, sched = self._scheduler()
        sched.pump_events()
        assert compare(sched) == []

    def test_compare_detects_divergence(self):
        store, sched = self._scheduler()
        sched.pump_events()
        # write a bound pod behind the scheduler's back (no pump)
        store.create("pods", MakePod("ghost").node("n1").obj())
        problems = compare(sched)
        assert any("ghost" in p and "missing from cache" in p for p in problems)

    def test_slow_cycle_traced(self):
        """A schedule_pod call past the 100ms threshold emits a trace record."""
        store, sched = self._scheduler()
        stream = io.StringIO()
        from kubernetes_tpu.utils import tracing

        old = tracing.default_logger
        tracing.default_logger = StructuredLogger("sched", stream=stream)
        try:
            import kubernetes_tpu.utils.tracing as tr

            real_perf = tr.time.perf_counter
            ticks = iter([0.0, 0.0, 0.2, 0.25, 0.3, 0.35, 0.4])
            tr.time.perf_counter = lambda: next(ticks, 1.0)
            sched.schedule_pod(MakePod("slow").req({"cpu": "1"}).obj())
            tr.time.perf_counter = real_perf
        finally:
            tracing.default_logger = old
        record = json.loads(stream.getvalue().splitlines()[0])
        assert record["msg"].startswith("Trace 'Scheduling'")
        assert record["pod"] == "default/slow"


class TestEventsAPI:
    """core/v1 Events + EventRecorder (client-go tools/record analog):
    the scheduler narrates Scheduled/FailedScheduling/Preempted; repeats
    aggregate into one Event with a bumped count."""

    def test_scheduler_records_scheduled_and_failed(self):
        from kubernetes_tpu.api.events import events_for
        from kubernetes_tpu.scheduler import Framework, Scheduler
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.store import APIStore
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "2", "memory": "4Gi", "pods": "10"}).obj())
        sched = Scheduler(store, Framework(default_plugins()),
                          pod_initial_backoff=0.01)
        sched.sync()
        store.create("pods", MakePod("ok").req({"cpu": "1"}).obj())
        store.create("pods", MakePod("big").req({"cpu": "64"}).obj())
        sched.run_until_idle()

        ok_evs = events_for(store, "Pod", "default", "ok")
        assert any(e.reason == "Scheduled" and "n0" in e.message
                   for e in ok_evs)
        big_evs = events_for(store, "Pod", "default", "big")
        fails = [e for e in big_evs if e.reason == "FailedScheduling"]
        assert fails and fails[0].type == "Warning"

    def test_repeat_failures_aggregate(self):
        from kubernetes_tpu.api.events import EventRecorder
        from kubernetes_tpu.store import APIStore
        from kubernetes_tpu.testing import MakePod

        store = APIStore()
        rec = EventRecorder(store, component="test")
        pod = MakePod("p").obj()
        for _ in range(5):
            rec.event(pod, "Warning", "FailedScheduling", "0/1 nodes available")
        evs, _ = store.list("events")
        assert len(evs) == 1
        assert evs[0].count == 5

    def test_preemption_emits_preempted_event(self):
        import time

        from kubernetes_tpu.api.events import events_for
        from kubernetes_tpu.scheduler import Framework, Scheduler
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.store import APIStore
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity(
            {"cpu": "2", "pods": "10"}).obj())
        sched = Scheduler(store, Framework(default_plugins()),
                          pod_initial_backoff=0.01)
        sched.sync()
        store.create("pods", MakePod("low").priority(1).req({"cpu": "2"}).obj())
        sched.run_until_idle()
        store.create("pods", MakePod("high").priority(100).req({"cpu": "2"}).obj())
        for _ in range(5):
            sched.run_until_idle()
            time.sleep(0.05)
            sched.queue.flush_backoff_completed()
            sched.queue.flush_unschedulable_left_over()
        evs = events_for(store, "Pod", "default", "low")
        assert any(e.reason == "Preempted" for e in evs)

    def test_ktl_get_and_describe_events(self):
        import io
        from contextlib import redirect_stdout

        from kubernetes_tpu.api.events import EventRecorder
        from kubernetes_tpu.cli.ktl import main as ktl_main
        from kubernetes_tpu.server.rest import APIServer
        from kubernetes_tpu.store import APIStore
        from kubernetes_tpu.testing import MakePod

        store = APIStore()
        srv = APIServer(store).start()
        try:
            store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
            EventRecorder(store, component="test").event(
                store.get("pods", "default/p"), "Normal", "Scheduled",
                "assigned to n0")
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "get", "events"]) == 0
            assert "Scheduled" in buf.getvalue()
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "describe", "pods", "p"]) == 0
            out = buf.getvalue()
            assert "Events:" in out and "Scheduled" in out
        finally:
            srv.stop()

    def test_event_ttl_controller_expires(self):
        from kubernetes_tpu.api.events import EventRecorder
        from kubernetes_tpu.controllers import EventTTLController
        from kubernetes_tpu.store import APIStore, NotFoundError
        from kubernetes_tpu.testing import MakePod
        from kubernetes_tpu.utils import FakeClock
        import pytest

        store = APIStore()
        clock = FakeClock(start=1000.0)
        rec = EventRecorder(store, component="t", clock=clock)
        rec.event(MakePod("p").obj(), "Normal", "Scheduled", "x")
        c = EventTTLController(store, clock=clock, event_ttl=60.0)
        c.sync_all()
        c.run_until_stable()
        assert len(store.list("events")[0]) == 1  # not expired yet
        clock.step(61)
        c.run_until_stable()
        assert store.list("events")[0] == []
