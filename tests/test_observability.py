"""Tracing, structured logging, configz, and the scheduler cache debugger."""

import io
import json

from kubernetes_tpu.scheduler.debugger import compare, dump
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.scheduler.runtime import Framework
from kubernetes_tpu.scheduler.serial import Scheduler
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock
from kubernetes_tpu.utils.tracing import (
    StructuredLogger,
    Trace,
    configz_snapshot,
    register_config,
)


class TestTrace:
    def test_below_threshold_not_logged(self):
        clock = FakeClock()
        stream = io.StringIO()
        log = StructuredLogger("test", stream=stream)
        t = Trace("Op", logger=log, clock=clock)
        clock.step(0.05)
        assert not t.log_if_long(0.1)
        assert stream.getvalue() == ""

    def test_long_trace_logged_with_steps(self):
        clock = FakeClock()
        stream = io.StringIO()
        log = StructuredLogger("test", stream=stream)
        t = Trace("Scheduling", logger=log, clock=clock, pod="default/p")
        clock.step(0.08)
        t.step("Computing predicates done", feasible=3)
        clock.step(0.07)
        t.step("Prioritizing done")
        assert t.log_if_long(0.1)
        record = json.loads(stream.getvalue())
        assert record["total_ms"] == 150.0
        assert record["pod"] == "default/p"
        steps = {s["msg"]: s for s in record["steps"]}
        assert steps["Computing predicates done"]["ms"] == 80.0
        assert steps["Computing predicates done"]["feasible"] == 3
        assert steps["Prioritizing done"]["ms"] == 70.0

    def test_logger_levels(self):
        stream = io.StringIO()
        log = StructuredLogger("c", stream=stream, level="warning")
        log.info("hidden")
        log.warning("shown", code=7)
        lines = [json.loads(x) for x in stream.getvalue().splitlines()]
        assert len(lines) == 1 and lines[0]["msg"] == "shown" and lines[0]["code"] == 7


class TestConfigz:
    def test_register_and_http(self):
        import urllib.request

        from kubernetes_tpu.server import APIServer

        register_config("testcomponent", {"percentageOfNodesToScore": 40})
        assert configz_snapshot()["testcomponent"]["percentageOfNodesToScore"] == 40
        srv = APIServer(APIStore(), port=0).start()
        try:
            with urllib.request.urlopen(f"{srv.url}/configz") as resp:
                payload = json.loads(resp.read())
            assert payload["testcomponent"] == {"percentageOfNodesToScore": 40}
        finally:
            srv.stop()


class TestCacheDebugger:
    def _scheduler(self):
        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
        sched = Scheduler(store, Framework(default_plugins()), clock=FakeClock())
        sched.sync()
        sched.schedule_one()
        return store, sched

    def test_dump_shape(self):
        store, sched = self._scheduler()
        d = dump(sched)
        assert "n1" in d["nodes"]
        assert d["nodes"]["n1"]["pods"] == ["default/p"]
        assert d["nodes"]["n1"]["requested"]["milliCPU"] == 1000
        assert set(d["queue"]) == {"active", "backoff", "unschedulable"}

    def test_compare_consistent(self):
        store, sched = self._scheduler()
        sched.pump_events()
        assert compare(sched) == []

    def test_compare_detects_divergence(self):
        store, sched = self._scheduler()
        sched.pump_events()
        # write a bound pod behind the scheduler's back (no pump)
        store.create("pods", MakePod("ghost").node("n1").obj())
        problems = compare(sched)
        assert any("ghost" in p and "missing from cache" in p for p in problems)

    def test_slow_cycle_traced(self):
        """A schedule_pod call past the 100ms threshold emits a trace record."""
        store, sched = self._scheduler()
        stream = io.StringIO()
        from kubernetes_tpu.utils import tracing

        old = tracing.default_logger
        tracing.default_logger = StructuredLogger("sched", stream=stream)
        try:
            import kubernetes_tpu.utils.tracing as tr

            real_perf = tr.time.perf_counter
            ticks = iter([0.0, 0.0, 0.2, 0.25, 0.3, 0.35, 0.4])
            tr.time.perf_counter = lambda: next(ticks, 1.0)
            sched.schedule_pod(MakePod("slow").req({"cpu": "1"}).obj())
            tr.time.perf_counter = real_perf
        finally:
            tracing.default_logger = old
        record = json.loads(stream.getvalue().splitlines()[0])
        assert record["msg"].startswith("Trace 'Scheduling'")
        assert record["pod"] == "default/slow"
