"""PV binder, attach/detach, and resourceclaim controllers.

Pins the reference contracts:
  - pv_controller.go: an unbound PVC binds WITHOUT any pod (Immediate
    class); smallest satisfying PV wins; user-pre-bound PVs complete;
    deleted claims release volumes (Retain -> Released, Delete -> gone);
    WaitForFirstConsumer claims are left to the scheduler.
  - attach_detach_controller.go: a scheduled pod's bound PVC yields a
    VolumeAttachment for (PV, node); pod deletion detaches.
  - resourceclaim/controller.go: templates spawn per-pod claims recorded
    in status.resourceClaimStatuses; orphaned generated claims are reaped;
    the scheduler resolves template-backed claims end to end.
"""

import pytest

from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaimTemplate
from kubernetes_tpu.api.storage import (
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.controllers import (
    AttachDetachController,
    PersistentVolumeBinder,
    ResourceClaimController,
)
from kubernetes_tpu.controllers.volume import attachment_name
from kubernetes_tpu.store import APIStore, NotFoundError
from kubernetes_tpu.testing import MakeNode, MakePod


def make_pv(name, capacity, class_name="", modes=("ReadWriteOnce",),
            reclaim="Retain", claim_ref=""):
    pv = PersistentVolume.from_dict({
        "metadata": {"name": name},
        "spec": {"capacity": {"storage": capacity},
                 "accessModes": list(modes),
                 **({"storageClassName": class_name} if class_name else {}),
                 "persistentVolumeReclaimPolicy": reclaim,
                 **({"claimRef": {"namespace": claim_ref.split("/")[0],
                                  "name": claim_ref.split("/")[1]}}
                    if claim_ref else {})},
    })
    return pv


def make_pvc(name, request, class_name=None, modes=("ReadWriteOnce",)):
    spec = {"accessModes": list(modes),
            "resources": {"requests": {"storage": request}}}
    if class_name is not None:
        spec["storageClassName"] = class_name
    return PersistentVolumeClaim.from_dict(
        {"metadata": {"name": name, "namespace": "default"}, "spec": spec})


@pytest.fixture()
def store():
    return APIStore()


@pytest.fixture()
def binder(store):
    b = PersistentVolumeBinder(store)
    b.sync_all()
    return b


class TestPVBinder:
    def test_unbound_pvc_binds_without_a_pod(self, store, binder):
        store.create("persistentvolumes", make_pv("pv-a", 10_000_000_000))
        store.create("persistentvolumeclaims", make_pvc("data", 5_000_000_000))
        binder.run_until_stable()
        claim = store.get("persistentvolumeclaims", "default/data")
        pv = store.get("persistentvolumes", "pv-a")
        assert claim.spec.volume_name == "pv-a"
        assert claim.phase == "Bound"
        assert pv.spec.claim_ref == "default/data"
        assert pv.phase == "Bound"

    def test_smallest_satisfying_pv_wins(self, store, binder):
        store.create("persistentvolumes", make_pv("pv-big", 100))
        store.create("persistentvolumes", make_pv("pv-small", 10))
        store.create("persistentvolumes", make_pv("pv-tiny", 4))
        store.create("persistentvolumeclaims", make_pvc("c", 8))
        binder.run_until_stable()
        claim = store.get("persistentvolumeclaims", "default/c")
        assert claim.spec.volume_name == "pv-small"

    def test_class_must_match(self, store, binder):
        store.create("persistentvolumes", make_pv("pv-fast", 10, "fast"))
        store.create("persistentvolumeclaims", make_pvc("c", 5, ""))
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").spec.volume_name == ""
        store.create("persistentvolumeclaims", make_pvc("c2", 5, "fast"))
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c2").spec.volume_name == "pv-fast"

    def test_access_modes_subset(self, store, binder):
        store.create("persistentvolumes",
                     make_pv("pv-rwo", 10, modes=("ReadWriteOnce",)))
        store.create("persistentvolumeclaims",
                     make_pvc("c", 5, modes=("ReadWriteMany",)))
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").spec.volume_name == ""

    def test_prebound_pv_completes_claim(self, store, binder):
        store.create("persistentvolumes",
                     make_pv("pv-pre", 10, claim_ref="default/mine"))
        store.create("persistentvolumeclaims", make_pvc("mine", 5))
        binder.run_until_stable()
        claim = store.get("persistentvolumeclaims", "default/mine")
        assert claim.spec.volume_name == "pv-pre"
        assert store.get("persistentvolumes", "pv-pre").phase == "Bound"

    def test_prebound_pv_waits_for_claim_created_later(self, store, binder):
        # PV pre-bound to a claim that does NOT exist yet: it must stay
        # Available (never Released/deleted) and bind when the claim appears
        store.create("persistentvolumes",
                     make_pv("pv-wait", 10, claim_ref="default/later",
                             reclaim="Delete"))
        binder.run_until_stable()
        assert store.get("persistentvolumes", "pv-wait").phase == "Available"
        store.create("persistentvolumeclaims", make_pvc("later", 5))
        binder.run_until_stable()
        claim = store.get("persistentvolumeclaims", "default/later")
        assert claim.spec.volume_name == "pv-wait"
        assert store.get("persistentvolumes", "pv-wait").phase == "Bound"

    def test_claim_naming_missing_pv_stays_pending(self, store, binder):
        c = make_pvc("c", 5)
        c.spec.volume_name = "does-not-exist"
        store.create("persistentvolumeclaims", c)
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").phase == "Pending"

    def test_user_prebound_claim_binds_when_pv_appears(self, store, binder):
        c = make_pvc("c", 5)
        c.spec.volume_name = "pv-late"
        store.create("persistentvolumeclaims", c)
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").phase == "Pending"
        store.create("persistentvolumes", make_pv("pv-late", 10))
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").phase == "Bound"
        assert store.get("persistentvolumes",
                         "pv-late").spec.claim_ref == "default/c"

    def test_wffc_claims_left_to_scheduler(self, store, binder):
        store.create("storageclasses", StorageClass.from_dict({
            "metadata": {"name": "wffc"},
            "volumeBindingMode": "WaitForFirstConsumer"}))
        store.create("persistentvolumes", make_pv("pv-w", 10, "wffc"))
        store.create("persistentvolumeclaims", make_pvc("c", 5, "wffc"))
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").spec.volume_name == ""

    def test_deleted_claim_releases_retain_pv(self, store, binder):
        store.create("persistentvolumes", make_pv("pv-r", 10))
        store.create("persistentvolumeclaims", make_pvc("c", 5))
        binder.run_until_stable()
        store.delete("persistentvolumeclaims", "default/c")
        binder.run_until_stable()
        assert store.get("persistentvolumes", "pv-r").phase == "Released"

    def test_deleted_claim_reclaims_delete_pv(self, store, binder):
        store.create("persistentvolumes",
                     make_pv("pv-d", 10, reclaim="Delete"))
        store.create("persistentvolumeclaims", make_pvc("c", 5))
        binder.run_until_stable()
        store.delete("persistentvolumeclaims", "default/c")
        binder.run_until_stable()
        with pytest.raises(NotFoundError):
            store.get("persistentvolumes", "pv-d")

    def test_default_class_resolution(self, store, binder):
        store.create("storageclasses", StorageClass.from_dict({
            "metadata": {"name": "standard",
                         "annotations": {
                             "storageclass.kubernetes.io/is-default-class":
                                 "true"}},
            "volumeBindingMode": "Immediate"}))
        store.create("persistentvolumes", make_pv("pv-s", 10, "standard"))
        # storageClassName ABSENT -> default class applies
        store.create("persistentvolumeclaims", make_pvc("c", 5, None))
        binder.run_until_stable()
        assert store.get("persistentvolumeclaims",
                         "default/c").spec.volume_name == "pv-s"


class TestAttachDetach:
    def test_attach_and_detach(self, store):
        binder = PersistentVolumeBinder(store)
        binder.sync_all()
        ad = AttachDetachController(store)
        ad.sync_all()
        store.create("nodes", MakeNode("n1").capacity({"cpu": "8"}).obj())
        store.create("persistentvolumes", make_pv("pv-a", 10))
        store.create("persistentvolumeclaims", make_pvc("data", 5))
        binder.run_until_stable()
        pod = MakePod("p").req({"cpu": "100m"}).obj()
        from kubernetes_tpu.api.types import Volume as PodVolume

        pod.spec.volumes = [PodVolume(name="v", pvc_claim_name="data")]
        store.create("pods", pod)
        store.bind("default", "p", "n1")
        ad.run_until_stable()
        va = store.get("volumeattachments", attachment_name("pv-a", "n1"))
        assert va.attached and va.node_name == "n1" and va.pv_name == "pv-a"
        store.delete("pods", "default/p")
        ad.run_until_stable()
        with pytest.raises(NotFoundError):
            store.get("volumeattachments", attachment_name("pv-a", "n1"))


class TestResourceClaimController:
    def _template(self, store, name="gpu-tmpl"):
        t = ResourceClaimTemplate(
            requests=[DeviceRequest(name="gpu",
                                    device_class_name="gpu.example.com")])
        t.metadata.name = name
        t.metadata.namespace = "default"
        store.create("resourceclaimtemplates", t)

    def test_template_spawns_claim(self, store):
        self._template(store)
        rc = ResourceClaimController(store)
        rc.sync_all()
        pod = MakePod("worker").req({"cpu": "100m"}).obj()
        pod.spec.resource_claim_templates = [("gpu", "gpu-tmpl")]
        store.create("pods", pod)
        rc.run_until_stable()
        claim = store.get("resourceclaims", "default/worker-gpu")
        assert claim.requests[0].device_class_name == "gpu.example.com"
        assert claim.metadata.owner_references[0]["name"] == "worker"
        pod = store.get("pods", "default/worker")
        assert pod.status.resource_claim_statuses == {"gpu": "worker-gpu"}

    def test_orphan_reaped(self, store):
        self._template(store)
        rc = ResourceClaimController(store)
        rc.sync_all()
        pod = MakePod("gone").req({"cpu": "100m"}).obj()
        pod.spec.resource_claim_templates = [("gpu", "gpu-tmpl")]
        store.create("pods", pod)
        rc.run_until_stable()
        assert store.get("resourceclaims", "default/gone-gpu")
        store.delete("pods", "default/gone")
        rc.run_until_stable()
        with pytest.raises(NotFoundError):
            store.get("resourceclaims", "default/gone-gpu")

    def test_recreated_pod_regenerates_claim(self, store):
        # same-name pod recreated with a new uid while the old generated
        # claim lingers: the stale claim must NOT be adopted — it is reaped
        # and a fresh one generated for the new incarnation
        self._template(store)
        rc = ResourceClaimController(store)
        rc.sync_all()
        pod = MakePod("w3").req({"cpu": "100m"}).obj()
        pod.spec.resource_claim_templates = [("gpu", "gpu-tmpl")]
        store.create("pods", pod)
        rc.run_until_stable()
        old_claim = store.get("resourceclaims", "default/w3-gpu")
        old_uid = old_claim.metadata.owner_references[0]["uid"]
        store.delete("pods", "default/w3")
        # recreate BEFORE the controller reaps
        pod2 = MakePod("w3").req({"cpu": "100m"}).obj()
        pod2.spec.resource_claim_templates = [("gpu", "gpu-tmpl")]
        store.create("pods", pod2)
        rc.run_until_stable()
        claim = store.get("resourceclaims", "default/w3-gpu")
        new_uid = claim.metadata.owner_references[0]["uid"]
        assert new_uid == pod2.metadata.uid != old_uid
        got = store.get("pods", "default/w3")
        assert got.status.resource_claim_statuses == {"gpu": "w3-gpu"}

    def test_periodic_sweep_reaps_without_events(self, store):
        self._template(store)
        rc = ResourceClaimController(store)
        rc.sync_all()
        pod = MakePod("w2").req({"cpu": "100m"}).obj()
        pod.spec.resource_claim_templates = [("gpu", "gpu-tmpl")]
        store.create("pods", pod)
        rc.run_until_stable()
        store.delete("pods", "default/w2")
        # drop the delete event on the floor (fresh controller, no watch
        # history) — only the sweep can find the orphan
        rc2 = ResourceClaimController(store)
        rc2.sync_all()
        rc2._dirty.clear()
        rc2.reap_orphans()
        with pytest.raises(NotFoundError):
            store.get("resourceclaims", "default/w2-gpu")

    def test_scheduler_resolves_template_claim(self, store):
        """End to end: template-backed pod waits for its generated claim,
        then schedules through the DRA plugin once the controller stamps
        status.resourceClaimStatuses."""
        from kubernetes_tpu.api.dra import Device, DeviceClass, ResourceSlice
        from kubernetes_tpu.scheduler import Framework
        from kubernetes_tpu.scheduler.serial import Scheduler
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.utils.featuregate import feature_gates

        self._template(store)
        dc = DeviceClass(); dc.metadata.name = "gpu.example.com"
        store.create("deviceclasses", dc)
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "10"}).obj())
        sl = ResourceSlice(node_name="n1",
                           devices=[Device(name="gpu0")])
        sl.metadata.name = "n1-slice"
        store.create("resourceslices", sl)
        rc = ResourceClaimController(store)
        rc.sync_all()
        feature_gates.set("DynamicResourceAllocation", True)
        try:
            sched = Scheduler(store, Framework(default_plugins()))
            sched.sync()
            pod = MakePod("worker").req({"cpu": "100m"}).obj()
            pod.spec.resource_claim_templates = [("gpu", "gpu-tmpl")]
            store.create("pods", pod)
            rc.run_until_stable()
            sched.run_until_idle()
            assert store.get("pods",
                             "default/worker").spec.node_name == "n1"
            claim = store.get("resourceclaims", "default/worker-gpu")
            assert claim.allocation is not None
            assert claim.allocation.node_name == "n1"
        finally:
            feature_gates.set("DynamicResourceAllocation", False)
