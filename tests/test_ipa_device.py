"""InterPodAffinity on the device path: parity vs the serial oracle.

Covers the four filter rules of interpodaffinity/filtering.go:415 (existing
pods' required anti-affinity symmetry, incoming required affinity with the
first-pod exception, incoming required anti-affinity), the weighted scoring of
scoring.go (incoming preferred terms, symmetric existing preferred terms,
hardPodAffinityWeight), namespaceSelector semantics, and in-batch dynamics
(placed pods feed later pods' counts, as serial binds do).
"""

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    Namespace,
    ObjectMeta,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.scheduler import Framework, Scheduler
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def run_both(nodes, pods, namespaces=()):
    results = []
    for cls in (Scheduler, BatchScheduler):
        store = APIStore()
        for ns in namespaces:
            store.create("namespaces", ns)
        for n in nodes:
            store.create("nodes", n)
        for p in pods:
            store.create("pods", p)
        sched = cls(store, Framework(default_plugins()))
        sched.sync()
        sched.run_until_idle()
        got, _ = store.list("pods")
        results.append({p.metadata.name: p.spec.node_name
                        for p in got if not p.spec.node_name or True})
    serial, batch = results
    assert serial == batch, (
        "serial vs batch divergence:\n" +
        "\n".join(f"  {k}: serial={serial[k]!r} batch={batch[k]!r}"
                  for k in serial if serial[k] != batch[k]))
    return serial


def zone_nodes(n_per_zone=2, zones=3, cpu="8"):
    nodes = []
    for z in range(zones):
        for i in range(n_per_zone):
            nodes.append(MakeNode(f"z{z}n{i}")
                         .labels({ZONE: f"z{z}", HOST: f"z{z}n{i}"})
                         .capacity({"cpu": cpu}).obj())
    return nodes


def make_ns(name, labels):
    return Namespace(metadata=ObjectMeta(name=name, labels=labels))


class TestIPADevicePath:
    def test_ipa_pods_stay_on_device(self):
        """IPA classes must not set fallback_class (VERDICT round-1 item 1)."""
        from kubernetes_tpu.scheduler.cache import Cache
        from kubernetes_tpu.snapshot.tensorizer import (
            build_cluster_tensors, build_pod_batch)

        cache = Cache()
        for n in zone_nodes():
            cache.add_node(n)
        snap = cache.update_snapshot()
        cluster = build_cluster_tensors(snap)
        pods = [MakePod(f"p{i}").labels({"app": "web"})
                .pod_anti_affinity(HOST, {"app": "web"})
                .pod_affinity(ZONE, {"app": "web"})
                .preferred_pod_affinity(10, ZONE, {"app": "cache"})
                .req({"cpu": "100m"}).obj() for i in range(4)]
        batch = build_pod_batch(pods, snap, cluster)
        assert not batch.fallback_class.any()
        assert batch.ipa.has_any
        assert (batch.ipa.ra_key >= 0).sum() == 1  # one class, one term each
        assert (batch.ipa.rn_key >= 0).sum() == 1
        assert (batch.ipa.pp_key >= 0).sum() == 1

    def test_required_affinity_colocates_with_existing(self):
        nodes = zone_nodes()
        existing = MakePod("db").labels({"app": "db"}).node("z1n0").req({"cpu": "100m"}).obj()
        pods = [existing] + [
            MakePod(f"w{i}").labels({"app": "web"}).req({"cpu": "100m"})
            .pod_affinity(ZONE, {"app": "db"}).obj()
            for i in range(3)
        ]
        got = run_both(nodes, pods)
        for i in range(3):
            assert got[f"w{i}"].startswith("z1"), got

    def test_required_affinity_unsatisfiable_stays_pending(self):
        nodes = zone_nodes()
        pods = [MakePod("w").labels({"app": "web"}).req({"cpu": "100m"})
                .pod_affinity(ZONE, {"app": "nothing-matches"}).obj()]
        got = run_both(nodes, pods)
        assert got["w"] == ""

    def test_first_pod_exception_self_affine_series(self):
        # a self-affine series: first pod admitted by the first-pod rule,
        # the rest colocate in its zone (filtering.go satisfyPodAffinity)
        nodes = zone_nodes()
        pods = [MakePod(f"g{i}").labels({"app": "grp"}).req({"cpu": "100m"})
                .pod_affinity(ZONE, {"app": "grp"}).obj() for i in range(4)]
        got = run_both(nodes, pods)
        zones = {v[:2] for v in got.values()}
        assert len(zones) == 1 and all(got.values())

    def test_anti_affinity_spreads_within_batch(self):
        nodes = zone_nodes(n_per_zone=1, zones=4)
        pods = [MakePod(f"a{i}").labels({"app": "a"}).req({"cpu": "100m"})
                .pod_anti_affinity(ZONE, {"app": "a"}).obj() for i in range(5)]
        got = run_both(nodes, pods)
        placed = [v for v in got.values() if v]
        assert len(placed) == 4  # one per zone; the 5th is unschedulable
        assert len(set(placed)) == 4

    def test_existing_pod_anti_affinity_symmetry(self):
        # rule 1: an existing pod's required anti-affinity keeps matching
        # incoming pods out of its topology domain
        nodes = zone_nodes()
        guard = (MakePod("guard").labels({"team": "solo"}).node("z0n0")
                 .pod_anti_affinity(ZONE, {"team": "x"}).req({"cpu": "100m"}).obj())
        pods = [guard] + [
            MakePod(f"x{i}").labels({"team": "x"}).req({"cpu": "100m"}).obj()
            for i in range(4)
        ]
        got = run_both(nodes, pods)
        for i in range(4):
            assert got[f"x{i}"] and not got[f"x{i}"].startswith("z0"), got

    def test_preferred_affinity_attracts(self):
        nodes = zone_nodes()
        cache_pod = MakePod("cache").labels({"app": "cache"}).node("z2n1").req(
            {"cpu": "100m"}).obj()
        pods = [cache_pod] + [
            MakePod(f"w{i}").req({"cpu": "100m"})
            .preferred_pod_affinity(100, ZONE, {"app": "cache"}).obj()
            for i in range(2)
        ]
        got = run_both(nodes, pods)
        for i in range(2):
            assert got[f"w{i}"].startswith("z2"), got

    def test_preferred_anti_affinity_repels(self):
        nodes = zone_nodes()
        noisy = MakePod("noisy").labels({"app": "noisy"}).node("z0n0").req(
            {"cpu": "100m"}).obj()
        pods = [noisy] + [
            MakePod(f"q{i}").req({"cpu": "100m"})
            .preferred_pod_anti_affinity(100, ZONE, {"app": "noisy"}).obj()
            for i in range(2)
        ]
        got = run_both(nodes, pods)
        for i in range(2):
            assert not got[f"q{i}"].startswith("z0"), got

    def test_symmetric_preferred_terms_of_existing_pods(self):
        # scoring.go processExistingPod: an existing pod's preferred affinity
        # toward the incoming pod pulls it in, even when the incoming pod has
        # no affinity of its own
        nodes = zone_nodes()
        magnet = (MakePod("magnet").labels({"app": "magnet"}).node("z1n1")
                  .preferred_pod_affinity(100, ZONE, {"role": "friend"})
                  .req({"cpu": "100m"}).obj())
        pods = [magnet] + [
            MakePod(f"f{i}").labels({"role": "friend"}).req({"cpu": "100m"}).obj()
            for i in range(2)
        ]
        got = run_both(nodes, pods)
        for i in range(2):
            assert got[f"f{i}"].startswith("z1"), got

    def test_hard_pod_affinity_weight_symmetry(self):
        # an existing pod's REQUIRED affinity term matching the incoming pod
        # scores via hardPodAffinityWeight (scoring.go)
        nodes = zone_nodes()
        anchor = (MakePod("anchor").labels({"app": "anchor"}).node("z2n0")
                  .pod_affinity(ZONE, {"role": "peer"})
                  .req({"cpu": "100m"}).obj())
        pods = [anchor] + [
            MakePod(f"peer{i}").labels({"role": "peer"}).req({"cpu": "100m"}).obj()
            for i in range(2)
        ]
        got = run_both(nodes, pods)
        for i in range(2):
            assert got[f"peer{i}"].startswith("z2"), got

    def test_namespace_scoping_default(self):
        # terms default to the source pod's namespace: anti-affinity in ns
        # "other" must not block same-labeled pods in "default"
        nodes = zone_nodes(n_per_zone=1, zones=2)
        guard = (MakePod("guard", namespace="other").labels({"x": "1"}).node("z0n0")
                 .pod_anti_affinity(ZONE, {"app": "t"}).req({"cpu": "100m"}).obj())
        pods = [guard] + [
            MakePod("t0").labels({"app": "t"}).req({"cpu": "100m"}).obj()]
        got = run_both(nodes, pods)
        # guard's term defaults to ns "other"; t0 is in "default" => not blocked
        assert got["t0"] != ""

    def test_namespace_selector(self):
        # namespaceSelector selects namespaces by label across the cluster
        nodes = zone_nodes(n_per_zone=1, zones=3)
        namespaces = [make_ns("default", {}), make_ns("prod", {"env": "prod"}),
                      make_ns("dev", {"env": "dev"})]
        victim = MakePod("prodpod", namespace="prod").labels({"app": "svc"}).node(
            "z1n0").req({"cpu": "100m"}).obj()
        # incoming pod in "default" anti-affine to app=svc in env=prod namespaces
        term = PodAffinityTerm(
            topology_key=ZONE,
            selector=Selector.from_match_labels({"app": "svc"}),
            namespace_selector=Selector.from_match_labels({"env": "prod"}),
        )
        p = MakePod("incoming").labels({"app": "svc"}).req({"cpu": "100m"}).obj()
        from kubernetes_tpu.api.types import Affinity

        p.spec.affinity = Affinity(pod_anti_affinity_required=[term])
        got = run_both(nodes, [victim, p], namespaces=namespaces)
        assert got["incoming"] and not got["incoming"].startswith("z1"), got

    def test_mixed_ipa_with_spread_and_resources(self):
        # IPA + PTS + fit all active in one batch
        import random

        rng = random.Random(3)
        nodes = zone_nodes(n_per_zone=2, zones=3, cpu="4")
        pods = []
        for i in range(6):
            pods.append(MakePod(f"db{i}").labels({"app": "db"})
                        .pod_anti_affinity(HOST, {"app": "db"})
                        .req({"cpu": "500m"}).obj())
        for i in range(6):
            pods.append(MakePod(f"w{i}").labels({"app": "web"})
                        .pod_affinity(ZONE, {"app": "db"})
                        .topology_spread(2, ZONE, "DoNotSchedule", {"app": "web"})
                        .req({"cpu": f"{rng.choice([100, 300])}m"}).obj())
        run_both(nodes, pods)

    def test_weight_interactions_parity_stress(self):
        import random

        rng = random.Random(11)
        nodes = zone_nodes(n_per_zone=2, zones=4, cpu="8")
        existing = []
        for i in range(8):
            p = MakePod(f"e{i}").labels({"svc": f"s{i % 3}"}).node(
                f"z{i % 4}n{i % 2}").req({"cpu": "200m"})
            if i % 2 == 0:
                p = p.preferred_pod_affinity(rng.randint(1, 100), ZONE,
                                             {"svc": f"s{(i + 1) % 3}"})
            existing.append(p.obj())
        incoming = []
        for i in range(10):
            p = MakePod(f"p{i}").labels({"svc": f"s{i % 3}"}).req({"cpu": "300m"})
            r = i % 4
            if r == 0:
                p = p.preferred_pod_affinity(rng.randint(1, 100), ZONE, {"svc": "s0"})
            elif r == 1:
                p = p.preferred_pod_anti_affinity(rng.randint(1, 100), ZONE, {"svc": "s1"})
            elif r == 2:
                p = p.pod_anti_affinity(HOST, {"svc": f"s{i % 3}"})
            incoming.append(p.obj())
        run_both(nodes, existing + incoming)
