"""Volume plugin semantics — mirrors the reference's volumebinding,
volumerestrictions, volumezone and nodevolumelimits plugin unit tests."""

from kubernetes_tpu.api.storage import (
    BINDING_IMMEDIATE,
    BINDING_WAIT_FOR_FIRST_CONSUMER,
    CLAIM_BOUND,
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    READ_WRITE_ONCE_POD,
    StorageClass,
    VOLUME_BOUND,
)
from kubernetes_tpu.api.labels import NodeSelector
from kubernetes_tpu.api.types import ObjectMeta
from kubernetes_tpu.scheduler import CycleState, NodeInfo, PodInfo, Snapshot
from kubernetes_tpu.scheduler.plugins import (
    NodeVolumeLimits,
    VolumeBinding,
    VolumeLister,
    VolumeRestrictions,
    VolumeZone,
)
from kubernetes_tpu.testing import MakeNode, MakePod


def make_pvc(name, request=100, modes=("ReadWriteOnce",), sc="std", volume="",
             ns="default", phase=None):
    pvc = PersistentVolumeClaim(metadata=ObjectMeta(name=name, namespace=ns))
    pvc.spec.access_modes = list(modes)
    pvc.spec.request = request
    pvc.spec.storage_class_name = sc
    pvc.spec.volume_name = volume
    pvc.phase = phase or (CLAIM_BOUND if volume else "Pending")
    return pvc


def make_pv(name, capacity=100, modes=("ReadWriteOnce",), sc="std",
            zone=None, node_affinity=None, claim_ref="", csi_driver=""):
    pv = PersistentVolume(metadata=ObjectMeta(name=name))
    pv.spec.capacity = capacity
    pv.spec.access_modes = list(modes)
    pv.spec.storage_class_name = sc
    pv.spec.claim_ref = claim_ref
    pv.spec.csi_driver = csi_driver
    if claim_ref:
        pv.phase = VOLUME_BOUND
    if zone:
        pv.metadata.labels["topology.kubernetes.io/zone"] = zone
    if node_affinity:
        key, values = node_affinity
        pv.spec.node_affinity = NodeSelector.from_dict({"nodeSelectorTerms": [
            {"matchExpressions": [{"key": key, "operator": "In", "values": values}]}
        ]})
    return pv


def make_class(name, mode=BINDING_WAIT_FOR_FIRST_CONSUMER, provisioner="csi.example.com",
               topo=None):
    sc = StorageClass(metadata=ObjectMeta(name=name))
    sc.provisioner = provisioner
    sc.volume_binding_mode = mode
    if topo:
        key, values = topo
        sc.allowed_topologies = NodeSelector.from_dict({"nodeSelectorTerms": [
            {"matchExpressions": [{"key": key, "operator": "In", "values": values}]}
        ]})
    return sc


def node_info(node, pods=()):
    ni = NodeInfo(node)
    for p in pods:
        ni.add_pod(PodInfo(p))
    return ni


def snap_of(*nis):
    return Snapshot({ni.node.metadata.name: ni for ni in nis})


def run(plugin, pod, ni, snap=None):
    state = CycleState()
    snap = snap or snap_of(ni)
    state.write("Snapshot", snap)
    if hasattr(plugin, "pre_filter"):
        _, st = plugin.pre_filter(state, pod, snap)
        if not st.is_success() and not st.is_skip():
            return state, st
    return state, plugin.filter(state, pod, ni)


class TestVolumeBinding:
    def test_no_volumes_skips(self):
        plugin = VolumeBinding(VolumeLister())
        pod = MakePod().obj()
        state = CycleState()
        _, st = plugin.pre_filter(state, pod, snap_of())
        assert st.is_skip()

    def test_missing_pvc_unresolvable(self):
        plugin = VolumeBinding(VolumeLister())
        pod = MakePod().pvc("missing").obj()
        _, st = plugin.pre_filter(CycleState(), pod, snap_of())
        assert st.is_rejected() and "not found" in st.message()

    def test_unbound_immediate_rejected(self):
        lister = VolumeLister()
        lister.add(make_class("std", mode=BINDING_IMMEDIATE))
        lister.add(make_pvc("claim", sc="std"))
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        _, st = plugin.pre_filter(CycleState(), pod, snap_of())
        assert st.is_rejected() and "unbound immediate" in st.message()

    def test_bound_pv_node_affinity(self):
        lister = VolumeLister()
        lister.add(make_pv("pv1", node_affinity=("zone", ["a"]), claim_ref="default/claim"))
        lister.add(make_pvc("claim", volume="pv1"))
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        good = node_info(MakeNode("n1").labels({"zone": "a"}).obj())
        bad = node_info(MakeNode("n2").labels({"zone": "b"}).obj())
        assert run(plugin, pod, good)[1].is_success()
        _, st = run(plugin, pod, bad)
        assert st.is_rejected() and "affinity conflict" in st.message()

    def test_wfc_static_binding_and_prebind(self):
        lister = VolumeLister()
        lister.add(make_class("std"))
        lister.add(make_pv("pv-small", capacity=50, node_affinity=("zone", ["a"])))
        lister.add(make_pv("pv-big", capacity=500, node_affinity=("zone", ["a"])))
        pvc = make_pvc("claim", request=40)
        lister.add(pvc)
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        ni = node_info(MakeNode("n1").labels({"zone": "a"}).obj())
        state, st = run(plugin, pod, ni)
        assert st.is_success()
        assert plugin.reserve(state, pod, "n1").is_success()
        assert plugin.pre_bind(state, pod, "n1").is_success()
        # smallest fitting PV chosen, binding committed both ways
        assert pvc.spec.volume_name == "pv-small"
        assert pvc.phase == CLAIM_BOUND
        assert lister.pvs["pv-small"].spec.claim_ref == "default/claim"

    def test_wfc_no_pv_no_class_topology_rejected(self):
        lister = VolumeLister()
        lister.add(make_class("std", topo=("zone", ["a"])))
        lister.add(make_pvc("claim"))
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        ni_bad = node_info(MakeNode("n2").labels({"zone": "b"}).obj())
        _, st = run(plugin, pod, ni_bad)
        assert st.is_rejected()

    def test_wfc_provisioning_creates_pv(self):
        lister = VolumeLister()
        lister.add(make_class("std", topo=("zone", ["a"])))
        pvc = make_pvc("claim", request=77)
        lister.add(pvc)
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        ni = node_info(MakeNode("n1").labels({"zone": "a"}).obj())
        state, st = run(plugin, pod, ni)
        assert st.is_success()
        assert plugin.reserve(state, pod, "n1").is_success()
        assert plugin.pre_bind(state, pod, "n1").is_success()
        assert pvc.spec.volume_name and pvc.phase == CLAIM_BOUND
        assert lister.pvs[pvc.spec.volume_name].spec.capacity == 77

    def test_score_prefers_tight_fit(self):
        lister = VolumeLister()
        lister.add(make_class("std"))
        lister.add(make_pv("pv-tight", capacity=100, node_affinity=("h", ["n1"])))
        lister.add(make_pv("pv-loose", capacity=1000, node_affinity=("h", ["n2"])))
        lister.add(make_pvc("claim", request=90))
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        ni1 = node_info(MakeNode("n1").labels({"h": "n1"}).obj())
        ni2 = node_info(MakeNode("n2").labels({"h": "n2"}).obj())
        state, st = run(plugin, pod, ni1, snap_of(ni1, ni2))
        assert st.is_success()
        s1, _ = plugin.score(state, pod, ni1)
        s2, _ = plugin.score(state, pod, ni2)
        assert s1 > s2


class TestVolumeRestrictions:
    def test_gce_pd_conflict(self):
        plugin = VolumeRestrictions()
        existing = MakePod("other").volume(gce_pd="disk1").obj()
        ni = node_info(MakeNode("n1").obj(), [existing])
        pod = MakePod().volume(gce_pd="disk1").obj()
        _, st = run(plugin, pod, ni)
        assert st.is_rejected()

    def test_gce_pd_both_read_only_ok(self):
        plugin = VolumeRestrictions()
        existing = MakePod("other").volume(gce_pd="disk1", gce_read_only=True).obj()
        ni = node_info(MakeNode("n1").obj(), [existing])
        pod = MakePod().volume(gce_pd="disk1", gce_read_only=True).obj()
        _, st = run(plugin, pod, ni)
        assert st.is_success()

    def test_ebs_always_conflicts(self):
        plugin = VolumeRestrictions()
        existing = MakePod("other").volume(aws_ebs="vol-1").obj()
        ni = node_info(MakeNode("n1").obj(), [existing])
        pod = MakePod().volume(aws_ebs="vol-1").obj()
        _, st = run(plugin, pod, ni)
        assert st.is_rejected()

    def test_rwop_conflict_cluster_wide(self):
        lister = VolumeLister()
        lister.add(make_pvc("claim", modes=(READ_WRITE_ONCE_POD,), volume="pv1"))
        plugin = VolumeRestrictions(lister)
        user = MakePod("user").pvc("claim").obj()
        other_node = node_info(MakeNode("n2").obj(), [user])
        this_node = node_info(MakeNode("n1").obj())
        pod = MakePod("newpod").pvc("claim").obj()
        _, st = run(plugin, pod, this_node, snap_of(this_node, other_node))
        assert st.is_rejected() and "ReadWriteOncePod" in st.message()


class TestVolumeZone:
    def test_zone_conflict(self):
        lister = VolumeLister()
        lister.add(make_pvc("claim", volume="pv1"))
        lister.add(make_pv("pv1", zone="us-a", claim_ref="default/claim"))
        plugin = VolumeZone(lister)
        pod = MakePod().pvc("claim").obj()
        good = node_info(MakeNode("n1").labels(
            {"topology.kubernetes.io/zone": "us-a"}).obj())
        bad = node_info(MakeNode("n2").labels(
            {"topology.kubernetes.io/zone": "us-b"}).obj())
        _, st = run(plugin, pod, good)
        assert st.is_success()
        _, st = run(plugin, pod, bad)
        assert st.is_rejected()

    def test_multi_zone_pv_label(self):
        lister = VolumeLister()
        lister.add(make_pvc("claim", volume="pv1"))
        lister.add(make_pv("pv1", zone="us-a__us-b", claim_ref="default/claim"))
        plugin = VolumeZone(lister)
        pod = MakePod().pvc("claim").obj()
        ni = node_info(MakeNode("n1").labels(
            {"topology.kubernetes.io/zone": "us-b"}).obj())
        _, st = run(plugin, pod, ni)
        assert st.is_success()


class TestNodeVolumeLimits:
    def _lister(self, limit=2):
        lister = VolumeLister()
        lister.add(CSINode(metadata=ObjectMeta(name="n1"),
                           drivers={"csi.example.com": limit}))
        for i in range(3):
            lister.add(make_pvc(f"claim{i}", volume=f"pv{i}"))
            lister.add(make_pv(f"pv{i}", csi_driver="csi.example.com",
                               claim_ref=f"default/claim{i}"))
        return lister

    def test_under_limit(self):
        lister = self._lister(limit=2)
        plugin = NodeVolumeLimits(lister)
        existing = MakePod("other").pvc("claim0").obj()
        ni = node_info(MakeNode("n1").obj(), [existing])
        pod = MakePod().pvc("claim1").obj()
        _, st = run(plugin, pod, ni)
        assert st.is_success()

    def test_over_limit(self):
        lister = self._lister(limit=2)
        plugin = NodeVolumeLimits(lister)
        ni = node_info(MakeNode("n1").obj(),
                       [MakePod("a").pvc("claim0").obj(), MakePod("b").pvc("claim1").obj()])
        pod = MakePod().pvc("claim2").obj()
        _, st = run(plugin, pod, ni)
        assert st.is_rejected() and "max volume count" in st.message()

    def test_nil_allocatable_count_means_no_limit(self):
        """A registered driver without allocatable.count is unenforced
        (nil Allocatable.Count in nodevolumelimits/csi.go)."""
        lister = self._lister(limit=2)
        csinode = CSINode.from_dict({
            "metadata": {"name": "n1"},
            "spec": {"drivers": [{"name": "csi.example.com"}]},
        })
        assert csinode.drivers == {"csi.example.com": None}
        assert CSINode.from_dict(csinode.to_dict()).drivers == csinode.drivers
        lister.csinodes["n1"] = csinode
        plugin = NodeVolumeLimits(lister)
        ni = node_info(MakeNode("n1").obj(),
                       [MakePod("a").pvc("claim0").obj(), MakePod("b").pvc("claim1").obj()])
        pod = MakePod().pvc("claim2").obj()
        _, st = run(plugin, pod, ni)
        assert st.is_success()

    def test_no_csinode_no_limit(self):
        lister = self._lister(limit=0)
        lister.csinodes.clear()
        plugin = NodeVolumeLimits(lister)
        ni = node_info(MakeNode("n1").obj(),
                       [MakePod("a").pvc("claim0").obj()])
        pod = MakePod().pvc("claim1").obj()
        _, st = run(plugin, pod, ni)
        assert st.is_success()


class TestStoreWiring:
    def test_scheduler_feeds_lister_from_store_and_persists_binding(self):
        """Storage objects created in the API store reach the plugins' lister
        via sync(), and PreBind writes the PVC/PV binding back to the store."""
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.scheduler.runtime import Framework
        from kubernetes_tpu.scheduler.serial import Scheduler
        from kubernetes_tpu.store import APIStore

        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("storageclasses", make_class("std"))
        store.create("persistentvolumeclaims", make_pvc("claim", request=10))
        store.create("persistentvolumes",
                     make_pv("pv1", capacity=20,
                             node_affinity=("kubernetes.io/hostname", ["n1"])))
        store.create("pods", MakePod("p").req({"cpu": "1"}).pvc("claim").obj())
        sched = Scheduler(store, Framework(default_plugins()))
        sched.sync()
        assert sched.schedule_one()
        assert store.get("pods", "default/p").spec.node_name == "n1"
        pvc = store.get("persistentvolumeclaims", "default/claim")
        assert pvc.spec.volume_name == "pv1" and pvc.phase == CLAIM_BOUND
        assert store.get("persistentvolumes", "pv1").spec.claim_ref == "default/claim"

    def test_pv_created_after_sync_unblocks_pod(self):
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.scheduler.runtime import Framework
        from kubernetes_tpu.scheduler.serial import Scheduler
        from kubernetes_tpu.store import APIStore

        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("storageclasses", make_class("std", provisioner=""))
        store.create("persistentvolumeclaims", make_pvc("claim", request=10))
        store.create("pods", MakePod("p").req({"cpu": "1"}).pvc("claim").obj())
        from kubernetes_tpu.utils import FakeClock

        clock = FakeClock()
        sched = Scheduler(store, Framework(default_plugins()), clock=clock)
        sched.sync()
        sched.schedule_one()  # no PV, no provisioner -> unschedulable
        assert store.get("pods", "default/p").spec.node_name == ""
        store.create("persistentvolumes", make_pv("pv1", capacity=20))
        sched.pump_events()
        clock.step(11)  # past max backoff so the requeued pod is poppable
        sched.queue.flush_backoff_completed()
        assert sched.schedule_one()
        assert store.get("pods", "default/p").spec.node_name == "n1"

    def test_pv_node_affinity_roundtrip(self):
        from kubernetes_tpu.api.serialize import from_dict, to_dict

        pv = make_pv("pv1", node_affinity=("zone", ["a", "b"]))
        pv2 = from_dict("persistentvolumes", to_dict(pv))
        assert pv2.spec.node_affinity is not None
        assert to_dict(pv2) == to_dict(pv)
        node_a = MakeNode("n1").labels({"zone": "a"}).obj()
        node_c = MakeNode("n2").labels({"zone": "c"}).obj()
        assert pv2.spec.node_affinity.matches(node_a)
        assert not pv2.spec.node_affinity.matches(node_c)

    def test_default_class_resolution_in_matching(self):
        """A PVC without an explicit class must only match PVs of the cluster
        default class (volume_binding.go findMatchingVolumes)."""
        lister = VolumeLister()
        default_sc = make_class("fast")
        default_sc.is_default = True
        lister.add(default_sc)
        lister.add(make_class("slow"))
        lister.add(make_pv("pv-slow", sc="slow"))
        pvc = make_pvc("claim", sc=None)
        lister.add(pvc)
        plugin = VolumeBinding(lister)
        pod = MakePod().pvc("claim").obj()
        ni = node_info(MakeNode("n1").obj())
        _, st = run(plugin, pod, ni)
        # only a 'slow' PV exists; the claim resolves to default class 'fast'
        # whose provisioner can still provision -> feasible via provisioning
        assert st.is_success()
        state = CycleState()
        snap = snap_of(ni)
        state.write("Snapshot", snap)
        plugin.pre_filter(state, pod, snap)
        binding, _ = plugin._node_binding(state, pod, ni.node)
        assert not binding.static and len(binding.provision) == 1

    def test_batch_scheduler_commits_volume_binding(self):
        """End to end through BatchScheduler: the volume pod takes the serial
        fallback and its PVC/PV binding is committed via Reserve/PreBind."""
        from kubernetes_tpu.scheduler.batch import BatchScheduler
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.scheduler.runtime import Framework
        from kubernetes_tpu.store import APIStore

        store = APIStore()
        for name in ("n1", "n2"):
            store.create("nodes", MakeNode(name).capacity(
                {"cpu": "8", "memory": "16Gi", "pods": "20"}).obj())
        store.create("storageclasses", make_class("std"))
        store.create("persistentvolumeclaims", make_pvc("claim", request=10))
        store.create("persistentvolumes",
                     make_pv("pv1", capacity=20,
                             node_affinity=("kubernetes.io/hostname", ["n2"])))
        store.create("pods", MakePod("vol").req({"cpu": "1"}).pvc("claim").obj())
        for i in range(4):
            store.create("pods", MakePod(f"plain-{i}").req({"cpu": "1"}).obj())
        sched = BatchScheduler(store, Framework(default_plugins()), solver="scan")
        sched.sync()
        sched.run_until_idle()
        assert store.get("pods", "default/vol").spec.node_name == "n2"
        pvc = store.get("persistentvolumeclaims", "default/claim")
        assert pvc.spec.volume_name == "pv1" and pvc.phase == CLAIM_BOUND
        assert store.get("persistentvolumes", "pv1").spec.claim_ref == "default/claim"
        for i in range(4):
            assert store.get("pods", f"default/plain-{i}").spec.node_name

    def test_batch_scheduler_routes_volume_pods_to_serial(self):
        """Pods with volumes must take the serial fallback (volume constraints
        are not dense-encoded), so PV affinity is honored and PreBind runs."""
        from kubernetes_tpu.snapshot.tensorizer import build_pod_batch, build_cluster_tensors
        from kubernetes_tpu.scheduler import Cache
        from kubernetes_tpu.utils import FakeClock

        cache = Cache(clock=FakeClock())
        for name in ("n1", "n2"):
            cache.add_node(MakeNode(name).capacity(
                {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        snap = cache.update_snapshot()
        cluster = build_cluster_tensors(snap)
        pods = [MakePod("vol").req({"cpu": "1"}).pvc("claim").obj(),
                MakePod("plain").req({"cpu": "1"}).obj()]
        batch = build_pod_batch(pods, snap, cluster)
        fallback = batch.fallback_class[batch.class_of_pod]
        assert list(fallback) == [True, False]

    def test_config_volumes_stay_on_device(self):
        """configMap/secret/emptyDir volumes never constrain placement; pods
        carrying only those take the device path (VERDICT weak item 2)."""
        from kubernetes_tpu.api.types import Volume
        from kubernetes_tpu.snapshot.tensorizer import build_pod_batch, build_cluster_tensors
        from kubernetes_tpu.scheduler import Cache
        from kubernetes_tpu.utils import FakeClock

        cache = Cache(clock=FakeClock())
        cache.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())
        snap = cache.update_snapshot()
        cluster = build_cluster_tensors(snap)
        # the wire shapes a real pod would carry
        cfg = Volume.from_dict({"name": "cfg", "configMap": {"name": "app-config"}})
        sec = Volume.from_dict({"name": "creds", "secret": {"secretName": "s"}})
        tmp = Volume.from_dict({"name": "scratch", "emptyDir": {}})
        pod = MakePod("cfgpod").req({"cpu": "1"}).obj()
        pod.spec.volumes = [cfg, sec, tmp]
        ephemeral = MakePod("eph").req({"cpu": "1"}).volume(
            name="data", ephemeral=True).obj()
        batch = build_pod_batch([pod, ephemeral], snap, cluster)
        fallback = batch.fallback_class[batch.class_of_pod]
        assert list(fallback) == [False, True]


class TestEndToEndSerial:
    def test_serial_scheduler_binds_wfc_claim(self):
        from kubernetes_tpu.scheduler.plugins import default_plugins
        from kubernetes_tpu.scheduler.runtime import Framework
        from kubernetes_tpu.scheduler.serial import Scheduler
        from kubernetes_tpu.store import APIStore

        lister = VolumeLister()
        lister.add(make_class("std"))
        pvc = make_pvc("claim", request=10)
        lister.add(pvc)
        lister.add(make_pv("pv1", capacity=20, node_affinity=(
            "kubernetes.io/hostname", ["n1"])))
        store = APIStore()
        for name in ("n1", "n2"):
            store.create("nodes", MakeNode(name).capacity(
                {"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
        store.create("pods", MakePod("p").req({"cpu": "1"}).pvc("claim").obj())
        sched = Scheduler(store, Framework(default_plugins(volume_lister=lister)))
        sched.sync()
        assert sched.schedule_one()
        bound = store.get("pods", "default/p")
        assert bound.spec.node_name == "n1"  # only n1 satisfies the PV affinity
        assert pvc.spec.volume_name == "pv1" and pvc.phase == CLAIM_BOUND
