"""Serial scheduler end-to-end + queue + cache tests.

Mirrors the structure of the reference's schedule_one_test.go and
backend/queue,cache tests (SURVEY.md §4): fake clock, fluent builders,
store-backed integration without any node agents (pods just become Bound)."""

import pytest

from kubernetes_tpu.scheduler import (
    Cache,
    Framework,
    QueuedPodInfo,
    Scheduler,
    SchedulingQueue,
    num_feasible_nodes_to_find,
)
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock


def make_scheduler(store, **kw):
    return Scheduler(store, Framework(default_plugins()), **kw)


class TestQueue:
    def test_priority_ordering(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        q.add(MakePod("low").priority(1).obj())
        q.add(MakePod("high").priority(10).obj())
        q.add(MakePod("mid").priority(5).obj())
        names = [q.pop().pod.metadata.name for _ in range(3)]
        assert names == ["high", "mid", "low"]

    def test_fifo_within_priority(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        for i in range(3):
            q.add(MakePod(f"p{i}").obj())
            clock.step(1)
        names = [q.pop().pod.metadata.name for _ in range(3)]
        assert names == ["p0", "p1", "p2"]

    def test_unschedulable_backoff_flow(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        q.add(MakePod("p").obj())
        qp = q.pop()
        assert qp.attempts == 1
        q.add_unschedulable(qp)
        assert q.lengths() == (0, 0, 1)
        # cluster event moves it to backoff (1 attempt -> 1s backoff)
        q.move_all_to_active_or_backoff()
        assert q.lengths() == (0, 1, 0)
        assert q.pop(timeout=0) is None
        clock.step(1.1)
        q.flush_backoff_completed()
        assert q.pop(timeout=0) is not None

    def test_backoff_exponential_capped(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        assert q._backoff_duration(1) == 1.0
        assert q._backoff_duration(3) == 4.0
        assert q._backoff_duration(10) == 10.0  # capped

    def test_flush_unschedulable_after_timeout(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        q.add(MakePod("p").obj())
        qp = q.pop()
        q.add_unschedulable(qp)
        clock.step(31)
        q.flush_unschedulable_left_over()
        assert q.pop(timeout=0) is not None


class TestCache:
    def test_assume_confirm_lifecycle(self):
        clock = FakeClock()
        c = Cache(clock=clock)
        c.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())
        pod = MakePod("p").req({"cpu": "1"}).obj()
        c.assume_pod(pod, "n1")
        snap = c.update_snapshot()
        assert snap.get("n1").requested.milli_cpu == 1000
        c.finish_binding(pod)
        # informer confirms
        bound = MakePod("p").req({"cpu": "1"}).obj()
        bound.metadata.uid = pod.metadata.uid
        bound.spec.node_name = "n1"
        c.add_pod(bound)
        assert not c.is_assumed(pod.key)
        assert c.update_snapshot().get("n1").requested.milli_cpu == 1000

    def test_assumed_pod_expiry(self):
        clock = FakeClock()
        c = Cache(clock=clock, ttl=15.0)
        c.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())
        pod = MakePod("p").req({"cpu": "1"}).obj()
        c.assume_pod(pod, "n1")
        c.finish_binding(pod)
        clock.step(16)
        expired = c.cleanup_expired_assumed_pods()
        assert expired == [pod.key]
        assert c.update_snapshot().get("n1").requested.milli_cpu == 0

    def test_forget_pod(self):
        c = Cache(clock=FakeClock())
        c.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())
        pod = MakePod("p").req({"cpu": "1"}).obj()
        c.assume_pod(pod, "n1")
        c.forget_pod(pod)
        assert c.update_snapshot().get("n1").requested.milli_cpu == 0

    def test_incremental_snapshot_reuses_unchanged_nodeinfos(self):
        c = Cache(clock=FakeClock())
        c.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())
        c.add_node(MakeNode("n2").capacity({"cpu": "4"}).obj())
        s1 = c.update_snapshot()
        pod = MakePod("p").req({"cpu": "1"}).obj()
        pod.spec.node_name = "n1"
        c.add_pod(pod)
        s2 = c.update_snapshot()
        # n2 untouched -> same object reused (generation diffing, cache.go:186)
        assert s2.get("n2") is s1.get("n2")
        assert s2.get("n1") is not s1.get("n1")

    def test_snapshot_cached_when_no_changes(self):
        c = Cache(clock=FakeClock())
        c.add_node(MakeNode("n1").obj())
        assert c.update_snapshot() is c.update_snapshot()


def test_num_feasible_nodes_to_find():
    # schedule_one.go:675: <100 nodes -> all; adaptive percentage above
    assert num_feasible_nodes_to_find(50) == 50
    assert num_feasible_nodes_to_find(100) == 100  # 50-0.8 = 49% -> 49 -> min 100
    assert num_feasible_nodes_to_find(1000) == 420  # 50-8=42%
    assert num_feasible_nodes_to_find(5000) == 500  # 50-40=10%
    assert num_feasible_nodes_to_find(6000) == 300  # floor 5%
    assert num_feasible_nodes_to_find(1000, percentage=100) == 1000


class TestEndToEnd:
    def test_schedule_pending_pods(self):
        store = APIStore()
        for i in range(4):
            store.create("nodes", MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj())
        for i in range(8):
            store.create("pods", MakePod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        cycles = sched.run_until_idle()
        assert sched.scheduled_count == 8
        pods, _ = store.list("pods")
        assert all(p.spec.node_name for p in pods)
        # LeastAllocated + BalancedAllocation spread 8 pods evenly over 4 nodes
        per_node = {}
        for p in pods:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert sorted(per_node.values()) == [2, 2, 2, 2]

    def test_unschedulable_pod_gets_condition(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity({"cpu": "1"}).obj())
        store.create("pods", MakePod("big").req({"cpu": "4"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        assert sched.scheduled_count == 0 and sched.failed_count >= 1
        pod = store.get("pods", "default/big")
        conds = {c.type: c for c in pod.status.conditions}
        assert conds["PodScheduled"].status == "False"
        assert conds["PodScheduled"].reason == "Unschedulable"

    def test_pod_becomes_schedulable_on_node_add(self):
        store = APIStore()
        store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        assert sched.scheduled_count == 0
        # node arrives -> cluster event moves pod out of unschedulable
        store.create("nodes", MakeNode("n0").capacity({"cpu": "4"}).obj())
        sched.pump_events()
        sched.queue.flush_backoff_completed()  # backoff is wall-clock; force
        import time

        time.sleep(1.1)  # real clock backoff (1 attempt -> 1s)
        sched.queue.flush_backoff_completed()
        sched.run_until_idle()
        assert sched.scheduled_count == 1

    def test_scheduling_gates_hold_pod(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity({"cpu": "4"}).obj())
        store.create("pods", MakePod("gated").req({"cpu": "1"}).scheduling_gate("wait").obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        assert sched.scheduled_count == 0
        assert sched.queue.lengths() == (0, 0, 1)

    def test_priority_scheduled_first_under_scarcity(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity({"cpu": "1", "pods": "10"}).obj())
        store.create("pods", MakePod("low").priority(1).req({"cpu": "1"}).obj())
        store.create("pods", MakePod("high").priority(100).req({"cpu": "1"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        assert store.get("pods", "default/high").spec.node_name == "n0"
        assert store.get("pods", "default/low").spec.node_name == ""

    def test_topology_spread_end_to_end(self):
        store = APIStore()
        for i in range(4):
            zone = "a" if i < 2 else "b"
            store.create("nodes", MakeNode(f"n{i}").labels(
                {"topology.kubernetes.io/zone": zone}).capacity({"cpu": "8"}).obj())
        for i in range(6):
            store.create("pods", MakePod(f"w{i}").labels({"app": "web"}).req({"cpu": "100m"})
                         .topology_spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                                          {"app": "web"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        assert sched.scheduled_count == 6
        pods, _ = store.list("pods")
        zone_counts = {"a": 0, "b": 0}
        for p in pods:
            n = store.get("nodes", p.spec.node_name)
            zone_counts[n.metadata.labels["topology.kubernetes.io/zone"]] += 1
        assert zone_counts == {"a": 3, "b": 3}

    def test_anti_affinity_end_to_end(self):
        store = APIStore()
        for i in range(3):
            store.create("nodes", MakeNode(f"n{i}").capacity({"cpu": "8"}).obj())
        for i in range(3):
            store.create("pods", MakePod(f"w{i}").labels({"app": "web"}).req({"cpu": "100m"})
                         .pod_anti_affinity("kubernetes.io/hostname", {"app": "web"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        pods, _ = store.list("pods")
        hosts = {p.spec.node_name for p in pods}
        assert len(hosts) == 3  # one per node

    def test_binding_visible_via_watch(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity({"cpu": "4"}).obj())
        w = store.watch("pods", since_rv=store.resource_version())
        store.create("pods", MakePod("p").req({"cpu": "1"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        evs = w.drain()
        assert any(ev.type == "MODIFIED" and ev.obj.spec.node_name == "n0" for ev in evs)
        w.stop()


class TestReviewRegressions:
    def test_node_flap_keeps_pod_accounting(self):
        """Node delete + re-add must not lose bound pods' resource usage
        (cache.go RemoveNode keeps the NodeInfo while pods remain)."""
        c = Cache(clock=FakeClock())
        c.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())
        pod = MakePod("p").req({"cpu": "3"}).obj()
        pod.spec.node_name = "n1"
        c.add_pod(pod)
        c.remove_node("n1")
        assert c.update_snapshot().get("n1") is None  # gone from snapshots
        c.add_node(MakeNode("n1").capacity({"cpu": "4"}).obj())  # kubelet flap
        ni = c.update_snapshot().get("n1")
        assert ni is not None and ni.requested.milli_cpu == 3000

    def test_queue_priority_update_resorts(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        q.add(MakePod("a").priority(5).obj())
        q.add(MakePod("b").priority(1).obj())
        boosted = MakePod("b").priority(100).obj()
        assert q.update(boosted)
        assert q.pop().pod.metadata.name == "b"

    def test_image_counts_incremental(self):
        c = Cache(clock=FakeClock())
        big = 500 * 1024 * 1024
        c.add_node(MakeNode("n1").images({"img:1": big}).capacity({"cpu": "1"}).obj())
        c.add_node(MakeNode("n2").images({"img:1": big}).capacity({"cpu": "1"}).obj())
        snap = c.update_snapshot()
        assert snap.get("n1").image_states["img:1"].num_nodes == 2
        c.remove_node("n2")
        assert snap.get("n1").image_states["img:1"].num_nodes == 1  # shared entry

    def test_gated_pod_survives_cluster_events(self):
        """A cluster event must not promote a gated pod into activeQ
        (PreEnqueue re-runs on promotion, like moveToActiveQ)."""
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity({"cpu": "4"}).obj())
        store.create("pods", MakePod("gated").req({"cpu": "1"}).scheduling_gate("wait").obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        store.create("nodes", MakeNode("n1").capacity({"cpu": "4"}).obj())  # event
        sched.pump_events()
        sched.queue.flush_unschedulable_left_over()
        sched.run_until_idle()
        assert sched.scheduled_count == 0
        assert store.get("pods", "default/gated").spec.node_name == ""

    def test_terminal_queued_pod_not_scheduled(self):
        store = APIStore()
        store.create("pods", MakePod("doomed").req({"cpu": "1"}).obj())
        sched = make_scheduler(store)
        sched.sync()

        def fail_it(st):
            st.phase = "Failed"

        store.update_pod_status("default", "doomed", fail_it)
        store.create("nodes", MakeNode("n0").capacity({"cpu": "4"}).obj())
        sched.run_until_idle()
        assert sched.scheduled_count == 0
        assert store.get("pods", "default/doomed").spec.node_name == ""

    def test_bound_pod_label_update_reaches_cache(self):
        store = APIStore()
        store.create("nodes", MakeNode("n0").capacity({"cpu": "4"}).obj())
        store.create("pods", MakePod("p").labels({"app": "old"}).req({"cpu": "1"}).obj())
        sched = make_scheduler(store)
        sched.sync()
        sched.run_until_idle()
        pod = store.get("pods", "default/p")
        pod.metadata.labels["app"] = "new"
        store.update("pods", pod)
        sched.pump_events()
        snap = sched.cache.update_snapshot()
        labels = [pi.pod.metadata.labels["app"] for pi in snap.get("n0").pods]
        assert labels == ["new"]
