"""ConfigMap/Secret types, immutability, kubelet reference resolution,
PodGC, and the thread-leak checker.

reference: core/v1 ConfigMap/Secret, pkg/apis/core/validation
(ValidateConfigMapUpdate), kuberuntime makeEnvironmentVariables
(CreateContainerConfigError), pkg/controller/podgc/gc_controller.go,
test/integration/framework/goleak.go.
"""

import base64

import pytest

from kubernetes_tpu.api.config import ConfigMap, Secret
from kubernetes_tpu.api.serialize import from_dict, to_dict
from kubernetes_tpu.api.types import ObjectMeta, Volume
from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.store import APIStore, NotFoundError
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock, assert_no_thread_leaks


@pytest.fixture()
def server():
    srv = APIServer(APIStore()).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


class TestTypes:
    def test_configmap_roundtrip(self):
        cm = ConfigMap(metadata=ObjectMeta(name="c"), data={"k": "v"},
                       immutable=True)
        d = to_dict(cm)
        back = from_dict("configmaps", d)
        assert back.data == {"k": "v"} and back.immutable
        assert to_dict(back) == d

    def test_secret_string_data_folds_to_b64(self):
        s = Secret.from_dict({"metadata": {"name": "s"},
                              "stringData": {"pw": "hunter2"},
                              "data": {"pw": "overridden"}})
        assert s.data["pw"] == base64.b64encode(b"hunter2").decode()
        assert s.decoded("pw") == "hunter2"
        # stringData never echoed on the wire
        assert "stringData" not in to_dict(s)


class TestImmutability:
    def test_immutable_configmap_rejects_update(self, client):
        client.create("configmaps", {"kind": "ConfigMap",
                                     "metadata": {"name": "c"},
                                     "data": {"k": "v"}, "immutable": True})
        with pytest.raises(APIError) as e:
            client.patch("configmaps", "c", {"data": {"k": "v2"}})
        assert e.value.code == 422
        # the flag cannot be unset either
        with pytest.raises(APIError) as e:
            client.patch("configmaps", "c", {"immutable": False})
        assert e.value.code == 422
        # metadata-only changes remain allowed
        client.patch("configmaps", "c", {"metadata": {"labels": {"a": "b"}}})

    def test_mutable_configmap_updates(self, client):
        client.create("configmaps", {"kind": "ConfigMap",
                                     "metadata": {"name": "c"},
                                     "data": {"k": "v"}})
        out = client.patch("configmaps", "c", {"data": {"k": "v2"}})
        assert out["data"]["k"] == "v2"

    def test_immutable_secret_rejects_data_change(self, client):
        client.create("secrets", {"kind": "Secret", "metadata": {"name": "s"},
                                  "stringData": {"a": "1"}, "immutable": True})
        with pytest.raises(APIError) as e:
            client.patch("secrets", "s", {"stringData": {"a": "2"}})
        assert e.value.code == 422


class TestKubeletConfigRefs:
    def _kubelet(self, store):
        from kubernetes_tpu.agent.kubelet import Kubelet

        clock = FakeClock(100.0)
        store.create("nodes", MakeNode("n1").capacity({"cpu": "8"}).obj())
        k = Kubelet(store, "n1", clock=clock)
        k.register()
        return k

    def _bound_pod(self, store, mutate):
        pod = MakePod("w").req({"cpu": "100m"}).obj()
        pod.spec.node_name = "n1"
        mutate(pod)
        store.create("pods", pod)
        return pod

    def test_missing_configmap_blocks_start(self):
        store = APIStore()
        k = self._kubelet(store)

        def add_env(pod):
            pod.spec.containers[0].env = [{"name": "K", "valueFrom": {
                "configMapKeyRef": {"name": "app-config", "key": "k"}}}]

        self._bound_pod(store, add_env)
        k.tick()
        pod = store.get("pods", "default/w")
        assert pod.status.phase == "Pending"
        log = store.get("podlogs", "default/w")
        assert any("CreateContainerConfigError" in line for line in log.entries)
        # reference appears -> next tick starts the pod
        store.create("configmaps", ConfigMap(
            metadata=ObjectMeta(name="app-config"), data={"k": "v"}))
        k.tick()
        assert store.get("pods", "default/w").status.phase == "Running"

    def test_optional_and_volume_refs(self):
        store = APIStore()
        k = self._kubelet(store)

        def add_refs(pod):
            pod.spec.containers[0].env = [{"name": "K", "valueFrom": {
                "configMapKeyRef": {"name": "nope", "key": "k",
                                    "optional": True}}}]
            pod.spec.volumes.append(Volume(name="v", secret="creds"))

        self._bound_pod(store, add_refs)
        k.tick()
        assert store.get("pods", "default/w").status.phase == "Pending"
        store.create("secrets", Secret(metadata=ObjectMeta(name="creds")))
        k.tick()
        assert store.get("pods", "default/w").status.phase == "Running"


class TestPodGC:
    def test_orphaned_and_terminated_reaped(self):
        from kubernetes_tpu.controllers.podgc import PodGCController

        store = APIStore()
        clock = FakeClock(1000.0)
        store.create("nodes", MakeNode("n1").capacity({"cpu": "8"}).obj())
        bound = MakePod("ok").req({"cpu": "1"}).obj()
        bound.spec.node_name = "n1"
        store.create("pods", bound)
        orphan = MakePod("orphan").req({"cpu": "1"}).obj()
        orphan.spec.node_name = "gone-node"
        store.create("pods", orphan)
        for i in range(5):
            t = MakePod(f"done-{i}").req({"cpu": "1"}).obj()
            t.metadata.creation_timestamp = float(i)
            t.status.phase = "Succeeded"
            store.create("pods", t)
        gc = PodGCController(store, clock=clock, terminated_threshold=2)
        gc.sync_all()
        gc.reconcile_once()
        names = {p.metadata.name for p in store.list("pods")[0]}
        assert "ok" in names and "orphan" not in names
        # threshold keeps the NEWEST 2 terminated pods
        assert names & {"done-3", "done-4"} == {"done-3", "done-4"}
        assert not names & {"done-0", "done-1", "done-2"}

    def test_unscheduled_terminating_reaped(self):
        from kubernetes_tpu.controllers.podgc import PodGCController

        store = APIStore()
        p = MakePod("limbo").req({"cpu": "1"}).obj()
        p.metadata.deletion_timestamp = 5.0
        store.create("pods", p)
        gc = PodGCController(store, clock=FakeClock(1000.0))
        gc.sync_all()
        gc.reconcile_once()
        with pytest.raises(NotFoundError):
            store.get("pods", "default/limbo")


class TestLeakCheck:
    def test_clean_lifecycle_passes(self):
        from kubernetes_tpu.server.controlplane import ControlPlane

        store = APIStore()
        with assert_no_thread_leaks():
            cp = ControlPlane(store, identity="lk-1",
                              use_batch_scheduler=False).start()
            import time as _t

            deadline = _t.time() + 10
            while not cp.is_leader and _t.time() < deadline:
                _t.sleep(0.02)
            assert cp.is_leader
            cp.stop()

    def test_detects_leak(self):
        import threading
        import time as _t

        stop = threading.Event()
        with pytest.raises(AssertionError, match="leaked threads"):
            with assert_no_thread_leaks(grace=0.3):
                threading.Thread(target=stop.wait, name="leaky-thread",
                                 daemon=True).start()
        stop.set()
        _t.sleep(0.05)


class TestSecretReadRestriction:
    def test_wildcard_read_excludes_secrets(self):
        """The system:authenticated read-all bootstrap rule must NOT cover
        secret payloads; nodes get an explicit grant."""
        from kubernetes_tpu.server.auth import (
            TokenAuthenticator,
            default_component_authorizer,
        )

        authn = TokenAuthenticator()
        authn.add("t-user", "someuser")
        authn.add("t-node", "system:node:n1", ["system:nodes"])
        srv = APIServer(APIStore(), authenticator=authn,
                        authorizer=default_component_authorizer()).start()
        try:
            srv.store.create("secrets", Secret(
                metadata=ObjectMeta(name="s"),
                data={"k": base64.b64encode(b"v").decode()}))
            user = RESTClient(srv.url, token="t-user")
            with pytest.raises(APIError) as e:
                user.list("secrets")
            assert e.value.code == 403
            # other resources stay readable
            user.list("pods")
            # node identity reads secrets (pod config resolution)
            node = RESTClient(srv.url, token="t-node")
            items, _ = node.list("secrets")
            assert items[0]["data"]["k"]
            # CRD-served plurals stay readable under the wildcard carve-out
            srv.store.create("customresourcedefinitions", __import__(
                "kubernetes_tpu.api.crd", fromlist=["CustomResourceDefinition"]
            ).CustomResourceDefinition.from_dict({
                "metadata": {"name": "widgets.x.dev"},
                "spec": {"group": "x.dev", "scope": "Namespaced",
                         "names": {"plural": "widgets", "kind": "Widget"},
                         "versions": [{"name": "v1"}]}}))
            items, _ = user.list("widgets")
            assert items == []
        finally:
            srv.stop()


class TestOptionalVolumeRefs:
    def test_optional_volume_source_does_not_block(self):
        from kubernetes_tpu.agent.kubelet import Kubelet

        store = APIStore()
        store.create("nodes", MakeNode("n1").capacity({"cpu": "8"}).obj())
        k = Kubelet(store, "n1", clock=FakeClock(100.0))
        k.register()
        pod = MakePod("w").req({"cpu": "100m"}).obj()
        pod.spec.node_name = "n1"
        pod.spec.volumes.append(Volume(name="v", config_map="nope",
                                       config_map_optional=True))
        store.create("pods", pod)
        k.tick()
        assert store.get("pods", "default/w").status.phase == "Running"

    def test_volume_optional_round_trips(self):
        from kubernetes_tpu.api.types import Pod

        d = {"metadata": {"name": "p"},
             "spec": {"containers": [{"name": "c"}],
                      "volumes": [{"name": "v",
                                   "configMap": {"name": "cm",
                                                 "optional": True}}]}}
        pod = Pod.from_dict(d)
        v = pod.spec.volumes[0]
        assert v.config_map == "cm" and v.config_map_optional
        assert to_dict(pod)["spec"]["volumes"][0]["configMap"]["optional"] is True


class TestKtlConfigCommands:
    def test_create_configmap_and_secret(self, server, client, capsys):
        from kubernetes_tpu.cli.ktl import main as ktl

        S = ["--server", server.url]
        assert ktl(S + ["create", "configmap", "app", "--from-literal",
                        "k=v", "--from-literal", "x=y"]) == 0
        cm = client.get("configmaps", "app")
        assert cm["data"] == {"k": "v", "x": "y"}
        assert ktl(S + ["create", "secret", "generic", "creds",
                        "--from-literal", "pw=s3cret"]) == 0
        sec = client.get("secrets", "creds")
        assert base64.b64decode(sec["data"]["pw"]).decode() == "s3cret"
        # NAME required after "generic"
        assert ktl(S + ["create", "secret", "generic",
                        "--from-literal", "a=b"]) == 1
        with pytest.raises(APIError):
            client.get("secrets", "generic")
        # unsupported subtypes error instead of becoming the NAME
        assert ktl(S + ["create", "secret", "tls", "web-cert",
                        "--from-literal", "a=b"]) == 1
        with pytest.raises(APIError):
            client.get("secrets", "tls")

    def test_certificate_conflicting_verdict_rejected(self, server, client, capsys):
        from kubernetes_tpu.cli.ktl import main as ktl

        S = ["--server", server.url]
        client.create("certificatesigningrequests", {
            "kind": "CertificateSigningRequest", "metadata": {"name": "c1"},
            "spec": {"request": {"user": "u"}, "signerName": "x/y"},
        }, namespace=None)
        assert ktl(S + ["certificate", "approve", "c1"]) == 0
        assert ktl(S + ["certificate", "deny", "c1"]) == 1
        csr = client.get("certificatesigningrequests", "c1", namespace=None)
        types = [c["type"] for c in csr["status"]["conditions"]]
        assert types == ["Approved"]
