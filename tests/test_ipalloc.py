"""Service ClusterIP allocation (registry/core/service/ipallocator)."""

import pytest

from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.server.ipalloc import ClusterIPAllocator
from kubernetes_tpu.store import APIStore


def svc(name, **spec):
    return {"kind": "Service", "metadata": {"name": name},
            "spec": {"selector": {"app": name},
                     "ports": [{"port": 80}], **spec}}


@pytest.fixture()
def server():
    s = APIServer(APIStore()).start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


class TestAllocator:
    def test_sequential_allocation_and_release(self):
        a = ClusterIPAllocator(APIStore(), cidr="10.96.0.0/29")  # 6 usable
        ips = [a.allocate() for _ in range(6)]
        assert len(set(ips)) == 6
        with pytest.raises(ValueError, match="exhausted"):
            a.allocate()
        a.release(ips[2])
        assert a.allocate() == ips[2]

    def test_specific_request_and_conflict(self):
        a = ClusterIPAllocator(APIStore(), cidr="10.96.0.0/24")
        assert a.allocate("10.96.0.10") == "10.96.0.10"
        with pytest.raises(ValueError, match="already allocated"):
            a.allocate("10.96.0.10")
        with pytest.raises(ValueError, match="not in range"):
            a.allocate("192.168.1.1")
        with pytest.raises(ValueError, match="invalid"):
            a.allocate("not-an-ip")

    def test_repair_rebuilds_from_store(self):
        store = APIStore()
        from kubernetes_tpu.api.networking import Service

        store.create("services", Service.from_dict(
            svc("pre", clusterIP="10.96.0.5")))
        a = ClusterIPAllocator(store, cidr="10.96.0.0/24")
        with pytest.raises(ValueError, match="already allocated"):
            a.allocate("10.96.0.5")


class TestServedAllocation:
    def test_create_assigns_and_delete_releases(self, client):
        out = client.create("services", svc("web"))
        ip = out["spec"]["clusterIP"]
        assert ip.startswith("10.96.")
        out2 = client.create("services", svc("db"))
        assert out2["spec"]["clusterIP"] != ip
        client.delete("services", "web")
        # released address becomes assignable again (explicit request)
        out3 = client.create("services", svc("web2", clusterIP=ip))
        assert out3["spec"]["clusterIP"] == ip

    def test_explicit_conflict_422(self, client):
        out = client.create("services", svc("a"))
        with pytest.raises(APIError) as e:
            client.create("services", svc("b", clusterIP=out["spec"]["clusterIP"]))
        assert e.value.code == 422

    def test_headless_gets_no_ip(self, client):
        out = client.create("services", svc("hs", clusterIP="None"))
        assert out["spec"]["clusterIP"] == "None"


class TestAllocationHardening:
    def test_failed_create_releases_address(self, client):
        client.create("services", svc("web"))
        # exhaust-by-retry scenario: repeated conflicting creates must not
        # burn addresses
        import pytest as _pytest

        for _ in range(5):
            with _pytest.raises(APIError) as e:
                client.create("services", svc("web"))
            assert e.value.code == 409
        # the 5 failed creates leaked nothing: a tiny window of sequential
        # allocations stays contiguous
        a = client.create("services", svc("a"))["spec"]["clusterIP"]
        b = client.create("services", svc("b"))["spec"]["clusterIP"]
        import ipaddress

        assert (int(ipaddress.ip_address(b))
                - int(ipaddress.ip_address(a))) == 1

    def test_cluster_ip_immutable_on_update_and_patch(self, client):
        import pytest as _pytest

        out = client.create("services", svc("web"))
        with _pytest.raises(APIError) as e:
            client.patch("services", "web", {"spec": {"clusterIP": "10.96.0.200"}})
        assert e.value.code == 422
        cur = client.get("services", "web")
        cur["spec"]["clusterIP"] = "10.96.0.201"
        with _pytest.raises(APIError) as e:
            client.update("services", cur)
        assert e.value.code == 422
        # non-IP updates still work
        client.patch("services", "web", {"metadata": {"labels": {"a": "b"}}})

    def test_headless_service_renders_no_rules(self, server, client):
        from kubernetes_tpu.proxy.proxier import Proxier

        client.create("services", svc("hs", clusterIP="None"))
        p = Proxier(server.store)
        p.sync_all()
        p.reconcile_once()
        ruleset = p.sync_proxy_rules()
        assert all("None" not in r.cluster_ip for r in ruleset.rules)


class TestWatchDrivenRelease:
    def test_namespace_sweep_releases_addresses(self, server, client):
        """Services deleted AROUND the REST layer (namespace sweep, GC,
        direct store deletes) must still release their ClusterIPs."""
        ip = client.create("services", svc("web"))["spec"]["clusterIP"]
        server.store.delete("services", "default/web")  # direct store delete
        # the address is reusable (allocator drains its watch on allocate)
        out = client.create("services", svc("web2", clusterIP=ip))
        assert out["spec"]["clusterIP"] == ip

    def test_direct_store_create_marks_address(self, server, client):
        from kubernetes_tpu.api.networking import Service

        server.store.create("services", Service.from_dict(
            svc("direct", clusterIP="10.96.0.77")))
        import pytest as _pytest

        with _pytest.raises(APIError) as e:
            client.create("services", svc("clash", clusterIP="10.96.0.77"))
        assert e.value.code == 422
