"""API Priority & Fairness: classification, seat limits, 429s, exemptions.

reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol + the
flowcontrol.apiserver.k8s.io bootstrap configuration.
"""

import threading
import time

import pytest

from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.server.auth import TokenAuthenticator, UserInfo
from kubernetes_tpu.server.flowcontrol import (
    FlowController,
    FlowSchema,
    PriorityLevel,
    default_flow_controller,
)
from kubernetes_tpu.store import APIStore


def user(name, *groups):
    return UserInfo(name=name, groups=tuple(groups) + ("system:authenticated",))


class TestClassification:
    def test_bootstrap_schemas(self):
        fc = default_flow_controller()
        assert fc.classify(user("admin", "system:masters"),
                           "create", "pods").name == "exempt"
        assert fc.classify(user("system:node:n1", "system:nodes"),
                           "update", "pods").name == "system"
        assert fc.classify(user("sched", "system:kube-scheduler"),
                           "bind", "pods").name == "system"
        assert fc.classify(user("alice"), "list", "pods").name == "global-default"
        assert fc.classify(None, "get", "pods").name == "global-default"

    def test_first_match_wins_and_verb_resource_filters(self):
        fc = FlowController(
            [PriorityLevel("a", seats=1), PriorityLevel("b", seats=1)],
            [FlowSchema("writes", "a", verbs=("create", "update")),
             FlowSchema("catch-all", "b")])
        assert fc.classify(user("u"), "create", "pods").name == "a"
        assert fc.classify(user("u"), "get", "pods").name == "b"

    def test_unknown_level_rejected(self):
        with pytest.raises(ValueError):
            FlowController([PriorityLevel("a")],
                           [FlowSchema("s", "missing")])

    def test_catch_all_required(self):
        with pytest.raises(ValueError):
            FlowController([PriorityLevel("a")], [])
        with pytest.raises(ValueError):
            # last schema filters on verbs: not a universal catch-all
            FlowController([PriorityLevel("a")],
                           [FlowSchema("writes", "a", verbs=("create",))])

    def test_list_verb_classification(self):
        """Collection GETs classify as 'list' (the handler's verb), so
        schemas throttling heavy lists actually engage."""
        fc = FlowController(
            [PriorityLevel("slow", seats=1, queue_length=0),
             PriorityLevel("fast", seats=50)],
            [FlowSchema("heavy-lists", "slow", verbs=("list",)),
             FlowSchema("catch-all", "fast")])
        authn = TokenAuthenticator()
        authn.add("t-user", "alice")
        srv = APIServer(APIStore(), authenticator=authn,
                        flowcontrol=fc).start()
        try:
            assert fc.levels["slow"].acquire()  # saturate the list level
            alice = RESTClient(srv.url, token="t-user")
            with pytest.raises(APIError) as e:
                alice.list("pods")
            assert e.value.code == 429
            # named GET rides the catch-all and succeeds
            with pytest.raises(APIError) as e:
                alice.get("pods", "nope")
            assert e.value.code == 404  # not 429: different level
        finally:
            srv.stop()

    def test_429_keeps_connection_usable(self):
        """A rejected POST must drain its body so the keep-alive connection
        still parses the NEXT request correctly."""
        import http.client

        fc = FlowController(
            [PriorityLevel("tiny", seats=1, queue_length=0)],
            [FlowSchema("catch-all", "tiny")])
        authn = TokenAuthenticator()
        authn.add("t-user", "alice")
        srv = APIServer(APIStore(), authenticator=authn,
                        flowcontrol=fc).start()
        try:
            assert fc.levels["tiny"].acquire()
            host, port = srv._httpd.server_address[:2]
            conn = http.client.HTTPConnection(host, port)
            body = b'{"metadata": {"name": "p"}, "spec": {"containers": []}}'
            conn.request("POST", "/api/v1/namespaces/default/pods", body,
                         {"Authorization": "Bearer t-user",
                          "Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 429
            resp.read()
            fc.levels["tiny"].release()
            # SAME connection: the next request must parse cleanly
            conn.request("GET", "/api/v1/namespaces/default/pods",
                         headers={"Authorization": "Bearer t-user"})
            resp = conn.getresponse()
            assert resp.status == 200
            conn.close()
        finally:
            srv.stop()


class TestPriorityLevel:
    def test_seats_queue_and_reject(self):
        lvl = PriorityLevel("t", seats=1, queue_length=1, queue_timeout=0.2)
        assert lvl.acquire()  # seat 1
        # next caller queues then times out
        t0 = time.monotonic()
        assert not lvl.acquire()
        assert time.monotonic() - t0 >= 0.2
        assert lvl.stats()["rejected"] == 1
        lvl.release()
        assert lvl.acquire()

    def test_queue_overflow_rejects_immediately(self):
        lvl = PriorityLevel("t", seats=1, queue_length=0, queue_timeout=5.0)
        assert lvl.acquire()
        t0 = time.monotonic()
        assert not lvl.acquire()  # queue full (length 0): instant 429
        assert time.monotonic() - t0 < 1.0

    def test_waiter_gets_freed_seat(self):
        lvl = PriorityLevel("t", seats=1, queue_length=5, queue_timeout=5.0)
        assert lvl.acquire()
        got = []
        t = threading.Thread(target=lambda: got.append(lvl.acquire()))
        t.start()
        time.sleep(0.05)
        lvl.release()
        t.join(timeout=2)
        assert got == [True]

    def test_exempt_never_blocks(self):
        lvl = PriorityLevel("x", seats=0, exempt=True)
        for _ in range(10):
            assert lvl.acquire()


class TestServerIntegration:
    def _server(self, fc):
        authn = TokenAuthenticator()
        authn.add("t-user", "alice")
        authn.add("t-admin", "admin", ["system:masters"])
        return APIServer(APIStore(), authenticator=authn,
                         flowcontrol=fc).start()

    def test_429_when_level_saturated(self):
        fc = FlowController(
            [PriorityLevel("exempt", exempt=True),
             PriorityLevel("tiny", seats=1, queue_length=0)],
            [FlowSchema("exempt", "exempt", users=(), groups=("system:masters",)),
             FlowSchema("catch-all", "tiny")])
        srv = self._server(fc)
        try:
            # hold the only seat
            assert fc.levels["tiny"].acquire()
            alice = RESTClient(srv.url, token="t-user")
            with pytest.raises(APIError) as e:
                alice.list("pods")
            assert e.value.code == 429
            # admins ride the exempt level regardless
            admin = RESTClient(srv.url, token="t-admin")
            admin.list("pods")
            # health endpoints always answer
            admin.request("GET", "/healthz")
            fc.levels["tiny"].release()
            alice.list("pods")  # seat free again
        finally:
            srv.stop()

    def test_watch_bypasses_seats(self):
        fc = FlowController(
            [PriorityLevel("tiny", seats=1, queue_length=0)],
            [FlowSchema("catch-all", "tiny")])
        srv = self._server(fc)
        try:
            assert fc.levels["tiny"].acquire()  # saturate
            alice = RESTClient(srv.url, token="t-user")
            seen = []

            def consume():
                for et, obj in alice.watch("pods", since_rv=0):
                    seen.append(et)
                    return

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            time.sleep(0.2)
            fc.levels["tiny"].release()
            alice.create("pods", {"metadata": {"name": "p"},
                                  "spec": {"containers": [{"name": "c"}]}})
            t.join(timeout=5)
            assert seen == ["ADDED"]  # watch streamed despite saturation
        finally:
            srv.stop()

    def test_watch_param_on_writes_does_not_bypass(self):
        """?watch=true glued onto a POST (or a named GET) must still be
        seat-accounted — only collection GET watches are long-running."""
        fc = FlowController(
            [PriorityLevel("tiny", seats=1, queue_length=0)],
            [FlowSchema("catch-all", "tiny")])
        srv = self._server(fc)
        try:
            assert fc.levels["tiny"].acquire()  # saturate
            alice = RESTClient(srv.url, token="t-user")
            with pytest.raises(APIError) as e:
                alice.request("POST", "/api/v1/namespaces/default/pods?watch=true",
                              {"metadata": {"name": "p"},
                               "spec": {"containers": [{"name": "c"}]}})
            assert e.value.code == 429
        finally:
            srv.stop()

    def test_metrics_expose_levels(self):
        srv = self._server(default_flow_controller())
        try:
            admin = RESTClient(srv.url, token="t-admin")
            admin.list("pods")
            text = admin.request_text("/metrics")
            assert 'apiserver_flowcontrol_dispatched{priority_level="exempt"}' in text
            assert 'priority_level="global-default"' in text
        finally:
            srv.stop()


class TestAPFAsAPIObjects:
    """flowcontrol.apiserver.k8s.io: config objects reconfigure dispatch
    live; with none present the bootstrap defaults serve."""

    def test_round_trip(self):
        from kubernetes_tpu.api.flowcontrolapi import (
            FlowSchemaConfiguration,
            PriorityLevelConfiguration,
        )

        plc = PriorityLevelConfiguration.from_dict({
            "metadata": {"name": "batch"},
            "spec": {"type": "Limited",
                     "limited": {"seats": 3, "queueLength": 7,
                                 "queueTimeoutSeconds": 2.5}}})
        assert PriorityLevelConfiguration.from_dict(
            plc.to_dict()).to_dict() == plc.to_dict()
        lvl = plc.to_level()
        assert lvl.seats == 3 and lvl.queue_length == 7 and not lvl.exempt
        fsc = FlowSchemaConfiguration.from_dict({
            "metadata": {"name": "heavy"},
            "spec": {"priorityLevelConfiguration": {"name": "batch"},
                     "matchingPrecedence": 100, "verbs": ["list"]}})
        assert fsc.to_schema().verbs == ("list",)

    def test_objects_reconfigure_live_server(self):
        authn = TokenAuthenticator()
        authn.add("t-u", "alice")
        srv = APIServer(APIStore(), authenticator=authn,
                        flowcontrol="default").start()
        try:
            alice = RESTClient(srv.url, token="t-u")
            alice.list("pods")  # bootstrap config serves initially
            # install a tiny level + schemas via the API
            alice.create("prioritylevelconfigurations", {
                "kind": "PriorityLevelConfiguration",
                "metadata": {"name": "tiny"},
                "spec": {"type": "Limited",
                         "limited": {"seats": 1, "queueLength": 0,
                                     "queueTimeoutSeconds": 0.2}}},
                namespace=None)
            alice.create("flowschemas", {
                "kind": "FlowSchema", "metadata": {"name": "lists"},
                "spec": {"priorityLevelConfiguration": {"name": "tiny"},
                         "matchingPrecedence": 10, "verbs": ["list"],
                         "resources": ["pods"]}}, namespace=None)
            alice.create("flowschemas", {
                "kind": "FlowSchema", "metadata": {"name": "catch-all"},
                "spec": {"priorityLevelConfiguration": {"name": "tiny"},
                         "matchingPrecedence": 9999}}, namespace=None)
            fc = srv._httpd.flowcontrol
            level = fc.classify(None, "list", "pods")
            assert level.name == "tiny" and level.seats == 1
            # saturate it: pod lists now 429 while the level is held
            assert level.acquire()
            with pytest.raises(APIError) as e:
                alice.list("pods")
            assert e.value.code == 429
            level.release()
            alice.list("pods")
            # deleting the config objects falls back to bootstrap
            alice.delete("flowschemas", "lists", namespace=None)
            alice.delete("flowschemas", "catch-all", namespace=None)
            assert fc.classify(None, "list", "pods").name == "global-default"
        finally:
            srv.stop()


class TestFlowConfigHardening:
    def test_explicit_zero_queue_length_respected(self):
        from kubernetes_tpu.api.flowcontrolapi import PriorityLevelConfiguration

        plc = PriorityLevelConfiguration.from_dict({
            "metadata": {"name": "t"},
            "spec": {"type": "Limited", "limited": {"seats": 1,
                                                    "queueLength": 0}}})
        assert plc.queue_length == 0

    def test_mandatory_bootstrap_survives_custom_config(self):
        """Custom config must not strip the exempt/system guarantees — the
        control plane's own traffic never rides a saturated custom level."""
        from kubernetes_tpu.server.flowcontrol import FlowConfigSource

        store = APIStore()
        from kubernetes_tpu.api.flowcontrolapi import (
            FlowSchemaConfiguration,
            PriorityLevelConfiguration,
        )

        store.create("prioritylevelconfigurations",
                     PriorityLevelConfiguration.from_dict({
                         "metadata": {"name": "tiny"},
                         "spec": {"type": "Limited",
                                  "limited": {"seats": 1, "queueLength": 0}}}))
        store.create("flowschemas", FlowSchemaConfiguration.from_dict({
            "metadata": {"name": "workload"},
            "spec": {"priorityLevelConfiguration": {"name": "tiny"},
                     "matchingPrecedence": 100, "verbs": ["list"]}}))
        src = FlowConfigSource(store, default_flow_controller())
        # masters still exempt; nodes still on the system level
        assert src.classify(user("admin", "system:masters"),
                            "list", "pods").name == "exempt"
        assert src.classify(user("n", "system:nodes"),
                            "update", "pods").name == "system"
        # the custom schema engages for plain users
        assert src.classify(user("alice"), "list", "pods").name == "tiny"
        # synthesized catch-all lands on a LIMITED level, never exempt
        lvl = src.classify(user("alice"), "create", "pods")
        assert not lvl.exempt

    def test_exempt_only_custom_config_keeps_previous(self):
        """A config whose only levels are Exempt cannot host a catch-all:
        the previous configuration keeps serving (no fail-open)."""
        from kubernetes_tpu.server.flowcontrol import FlowConfigSource
        from kubernetes_tpu.api.flowcontrolapi import (
            FlowSchemaConfiguration,
            PriorityLevelConfiguration,
        )

        store = APIStore()
        src = FlowConfigSource(store, default_flow_controller())
        store.create("prioritylevelconfigurations",
                     PriorityLevelConfiguration.from_dict({
                         "metadata": {"name": "free"},
                         "spec": {"type": "Exempt"}}))
        store.create("flowschemas", FlowSchemaConfiguration.from_dict({
            "metadata": {"name": "exempt"},  # overrides mandatory exempt
            "spec": {"priorityLevelConfiguration": {"name": "free"},
                     "verbs": ["list"]}}))
        # bootstrap levels merge in, so a Limited level still exists and the
        # config builds; unmatched traffic must land on a non-exempt level
        lvl = src.classify(user("alice"), "create", "pods")
        assert not lvl.exempt
