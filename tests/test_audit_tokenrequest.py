"""Audit logging + ServiceAccount TokenRequest subresource.

reference: apiserver/pkg/audit (+ apis/audit/v1 policy levels),
registry/core/serviceaccount TokenREST (authentication.k8s.io TokenRequest).
"""

import pytest

from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.server.audit import (
    AuditLogger,
    AuditPolicy,
    AuditRule,
    LEVEL_NONE,
    default_audit_policy,
)
from kubernetes_tpu.server.auth import (
    SignedTokenAuthenticator,
    TokenAuthenticator,
    UserInfo,
)
from kubernetes_tpu.store import APIStore


def user(name, *groups):
    return UserInfo(name=name, groups=tuple(groups))


class TestPolicy:
    def test_default_drops_node_reads_keeps_writes(self):
        p = default_audit_policy()
        node = user("system:node:n1", "system:nodes")
        assert p.level_for(node, "get", "pods") == LEVEL_NONE
        assert p.level_for(node, "update", "pods") == "Metadata"
        alice = user("alice", "system:authenticated")
        assert p.level_for(alice, "list", "events") == LEVEL_NONE
        assert p.level_for(alice, "list", "pods") == "Metadata"

    def test_user_and_group_criteria_and_together(self):
        """Specified users AND groups must both match (audit/v1 rule
        semantics) — an over-broad OR would silently drop audit events."""
        r = AuditRule(level=LEVEL_NONE, users=("ci-bot",), groups=("ops",))
        assert r.matches(user("ci-bot", "ops"), "get", "pods")
        assert not r.matches(user("ci-bot", "dev"), "get", "pods")
        assert not r.matches(user("someone-else", "ops"), "get", "pods")

    def test_rule_order_first_match(self):
        p = AuditPolicy(rules=[
            AuditRule(level=LEVEL_NONE, verbs=("get",)),
            AuditRule(level="Metadata"),
        ])
        assert p.level_for(user("u"), "get", "pods") == LEVEL_NONE
        assert p.level_for(user("u"), "create", "pods") == "Metadata"


class TestAuditedServer:
    def test_writes_and_denials_recorded(self):
        audit = AuditLogger(policy=AuditPolicy())  # audit everything
        authn = TokenAuthenticator()
        authn.add("t-u", "alice")
        srv = APIServer(APIStore(), authenticator=authn, audit=audit).start()
        try:
            c = RESTClient(srv.url, token="t-u")
            c.create("pods", {"metadata": {"name": "p"},
                              "spec": {"containers": [{"name": "c"}]}})
            with pytest.raises(APIError):
                c.get("pods", "nope")
            evs = audit.events()
            create = [e for e in evs if e["verb"] == "create"]
            assert create and create[0]["user"] == "alice"
            assert create[0]["resource"] == "pods" and create[0]["code"] == 201
            missing = [e for e in evs if e["name"] == "nope"]
            assert missing and missing[0]["code"] == 404
        finally:
            srv.stop()

    def test_file_sink(self, tmp_path):
        path = tmp_path / "audit.log"
        audit = AuditLogger(policy=AuditPolicy(), path=str(path))
        srv = APIServer(APIStore(), audit=audit).start()
        try:
            RESTClient(srv.url).list("pods")
        finally:
            srv.stop()
            audit.close()
        import json

        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert lines and lines[0]["verb"] == "list"


class TestTokenRequest:
    def _server(self):
        signer = SignedTokenAuthenticator(b"k" * 32)
        srv = APIServer(APIStore(), token_signer=signer).start()
        return srv, signer

    def test_mint_and_authenticate_sa_token(self):
        srv, signer = self._server()
        try:
            c = RESTClient(srv.url)
            c.create("serviceaccounts", {"kind": "ServiceAccount",
                                         "metadata": {"name": "builder"}})
            out = c.request(
                "POST",
                "/api/v1/namespaces/default/serviceaccounts/builder/token",
                {"spec": {"expirationSeconds": 1200}})
            tok = out["status"]["token"]
            assert out["status"]["expirationSeconds"] == 1200
            ident = signer.authenticate(f"Bearer {tok}")
            assert ident.name == "system:serviceaccount:default:builder"
            assert "system:serviceaccounts" in ident.groups
            assert "system:serviceaccounts:default" in ident.groups
        finally:
            srv.stop()

    def test_missing_sa_404_and_unconfigured_501(self):
        srv, _ = self._server()
        try:
            c = RESTClient(srv.url)
            with pytest.raises(APIError) as e:
                c.request("POST",
                          "/api/v1/namespaces/default/serviceaccounts/ghost/token",
                          {})
            assert e.value.code == 404
        finally:
            srv.stop()
        bare = APIServer(APIStore()).start()
        try:
            c = RESTClient(bare.url)
            c.create("serviceaccounts", {"kind": "ServiceAccount",
                                         "metadata": {"name": "sa"}})
            with pytest.raises(APIError) as e:
                c.request("POST",
                          "/api/v1/namespaces/default/serviceaccounts/sa/token",
                          {})
            assert e.value.code == 501
        finally:
            bare.stop()

    def test_nonpositive_expiration_rejected(self):
        srv, _ = self._server()
        try:
            c = RESTClient(srv.url)
            c.create("serviceaccounts", {"kind": "ServiceAccount",
                                         "metadata": {"name": "sa"}})
            for bad in (0, -5):
                with pytest.raises(APIError) as e:
                    c.request(
                        "POST",
                        "/api/v1/namespaces/default/serviceaccounts/sa/token",
                        {"spec": {"expirationSeconds": bad}})
                assert e.value.code == 400
        finally:
            srv.stop()

    def test_crd_alias_audited_under_plural(self):
        """Audit must record the canonical plural for alias-spelled URLs —
        the name authz and audit rules are written against."""
        audit = AuditLogger(policy=AuditPolicy())
        srv = APIServer(APIStore(), audit=audit).start()
        try:
            c = RESTClient(srv.url)
            c.create("customresourcedefinitions", {
                "metadata": {"name": "widgets.x.dev"},
                "spec": {"group": "x.dev", "scope": "Namespaced",
                         "names": {"plural": "widgets", "kind": "Widget",
                                   "shortNames": ["wgt"]},
                         "versions": [{"name": "v1"}]}}, namespace=None)
            c.request("GET", "/apis/x.dev/v1/namespaces/default/wgt")
            listed = [e for e in audit.events() if e["verb"] == "list"]
            assert listed and listed[-1]["resource"] == "widgets"
        finally:
            srv.stop()

    def test_expiration_clamped(self):
        srv, signer = self._server()
        try:
            c = RESTClient(srv.url)
            c.create("serviceaccounts", {"kind": "ServiceAccount",
                                         "metadata": {"name": "sa"}})
            out = c.request(
                "POST", "/api/v1/namespaces/default/serviceaccounts/sa/token",
                {"spec": {"expirationSeconds": 10}})
            assert out["status"]["expirationSeconds"] == 600  # floor
            out = c.request(
                "POST", "/api/v1/namespaces/default/serviceaccounts/sa/token",
                {"spec": {"expirationSeconds": 10_000_000}})
            assert out["status"]["expirationSeconds"] == 86400  # ceiling
        finally:
            srv.stop()

    def test_token_subresource_needs_its_own_grant(self):
        """create on `serviceaccounts` must NOT allow minting tokens: the
        subresource authorizes as `serviceaccounts/token` (privilege
        escalation otherwise)."""
        from kubernetes_tpu.server.auth import RBACAuthorizer

        signer = SignedTokenAuthenticator(b"k" * 32)
        authn = TokenAuthenticator()
        authn.add("t-sa-admin", "sa-admin")
        authn.add("t-minter", "minter")
        authz = (RBACAuthorizer()
                 .grant("sa-admin", ["create", "get", "list"],
                        ["serviceaccounts"])
                 .grant("minter", ["create"], ["serviceaccounts/token"])
                 .grant("minter", ["get", "list"], ["serviceaccounts"]))
        srv = APIServer(APIStore(), authenticator=authn, authorizer=authz,
                        token_signer=signer).start()
        try:
            sa_admin = RESTClient(srv.url, token="t-sa-admin")
            sa_admin.create("serviceaccounts", {"kind": "ServiceAccount",
                                                "metadata": {"name": "app"}})
            with pytest.raises(APIError) as e:
                sa_admin.request(
                    "POST",
                    "/api/v1/namespaces/default/serviceaccounts/app/token", {})
            assert e.value.code == 403
            minter = RESTClient(srv.url, token="t-minter")
            out = minter.request(
                "POST",
                "/api/v1/namespaces/default/serviceaccounts/app/token", {})
            assert out["status"]["token"]
        finally:
            srv.stop()

    def test_denied_watch_audited_as_watch(self):
        """A 403'd watch must record verb=watch, not list (audit shares the
        handler's verb derivation)."""
        from kubernetes_tpu.server.auth import RBACAuthorizer

        audit = AuditLogger(policy=AuditPolicy())
        authn = TokenAuthenticator()
        authn.add("t-u", "alice")
        authz = RBACAuthorizer().grant("alice", ["get", "list"], ["pods"])
        srv = APIServer(APIStore(), authenticator=authn, authorizer=authz,
                        audit=audit).start()
        try:
            c = RESTClient(srv.url, token="t-u")
            with pytest.raises(APIError) as e:
                c.request("GET", "/api/v1/namespaces/default/pods?watch=true")
            assert e.value.code == 403
            denied = [ev for ev in audit.events() if ev["code"] == 403]
            assert denied and denied[-1]["verb"] == "watch"
        finally:
            srv.stop()

    def test_secure_cluster_sa_token_end_to_end(self):
        """kadm secure init: mint an SA token via the admin credential, then
        use it — it authenticates and can read (authenticated group) but not
        write (no grant)."""
        from kubernetes_tpu.cli.kadm import init_control_plane

        res = init_control_plane(secure=True, use_batch_scheduler=False)
        try:
            assert res.wait_ready(30)
            admin = RESTClient(res.url, token=res.token)
            admin.create("serviceaccounts", {"kind": "ServiceAccount",
                                             "metadata": {"name": "app"}})
            out = admin.request(
                "POST", "/api/v1/namespaces/default/serviceaccounts/app/token",
                {"spec": {"expirationSeconds": 900}})
            sa = RESTClient(res.url, token=out["status"]["token"])
            sa.list("pods")  # authenticated read
            with pytest.raises(APIError) as e:
                sa.create("pods", {"metadata": {"name": "x"},
                                   "spec": {"containers": [{"name": "c"}]}})
            assert e.value.code == 403
            with pytest.raises(APIError) as e:
                sa.list("secrets")  # secrets carved out of wildcard read
            assert e.value.code == 403
        finally:
            res.stop()
