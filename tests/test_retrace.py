"""Jit-retrace guard (ISSUE 5 satellite): repeated same-bucket batches must
NOT grow the solver jit cache.

The waterfill fast path buckets its static args (j_max from STATIC node
capacity, k_slots floored at 256 and pow2-bucketed — models/waterfill.py) so
that steady-state scheduling reuses ONE compiled program. A regression there
(e.g. someone passing a raw batch length as a static arg — schedlint JT001's
bug class) compiles per batch: invisible to placement tests, tens of seconds
per batch at TPU scale. This drives schedule_batch over repeated same-shape
batches and pins the cache size; bench.py --quick surfaces the same signal
as `jit_cache` / `solver_compiles_during_run` in the end-to-end rung JSON.
"""

from kubernetes_tpu.models.repair import repair_check
from kubernetes_tpu.models.waterfill import waterfill_group
from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod


def _cache_size():
    return int(waterfill_group._cache_size())


def _repair_cache_size():
    return int(repair_check._cache_size())


def _synced_sched(n_nodes=16):
    store = APIStore()
    for i in range(n_nodes):
        store.create("nodes", MakeNode(f"node-{i}").capacity(
            {"cpu": "64", "memory": "256Gi", "pods": "110"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()),
                          batch_size=1024, solver="fast",
                          pipeline_binds=False)
    sched.sync()
    return store, sched


def _batch(store, sched, round_no, n_pods):
    store.create_many(
        "pods",
        [MakePod(f"r{round_no}-p{i}").req(
            {"cpu": "100m", "memory": "64Mi"}).obj() for i in range(n_pods)],
        consume=True)
    before = sched.scheduled_count
    sched.run_until_idle()
    assert sched.scheduled_count - before == n_pods


def test_same_bucket_batches_do_not_retrace():
    store, sched = _synced_sched()
    # round 1 pays the compile for this (j_max, k_slots, has_gang) bucket
    _batch(store, sched, 1, 48)
    warm = _cache_size()
    assert warm >= 1
    # same bucket again and again: k_slots floor (256) absorbs every batch
    # size below it, j_max derives from static capacity — zero new compiles
    for round_no in (2, 3, 4):
        _batch(store, sched, round_no, 48)
        assert _cache_size() == warm, (
            f"solver retraced on round {round_no}: jit cache grew "
            f"{warm} -> {_cache_size()} on an identical batch bucket")


def test_batch_size_jitter_within_bucket_does_not_retrace():
    """The k_slots floor exists exactly so requeue trickles / churny small
    batches (1..256 pods) share one compiled shape."""
    store, sched = _synced_sched()
    _batch(store, sched, 10, 64)
    warm = _cache_size()
    for round_no, n in ((11, 17), (12, 130), (13, 3)):
        _batch(store, sched, round_no, n)
    assert _cache_size() == warm


# -- ISSUE 8: the repair kernel's static gates ------------------------------


def _synced_hostname_sched(n_nodes=64):
    store = APIStore()
    for i in range(n_nodes):
        store.create("nodes", MakeNode(f"node-{i}").labels(
            {"kubernetes.io/hostname": f"node-{i}"}).capacity(
            {"cpu": "64", "memory": "256Gi", "pods": "110"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=1024, solver="fast",
                           pipeline_binds=False)
    sched.sync()
    return store, sched


def _anti_batch(store, sched, round_no, n_pods):
    """One constrained batch: n_pods hostname-anti-affine pods sharing ONE
    selector (a stable selector keeps the selcls/holder-group tensor widths
    — and therefore the repair_check shapes — fixed across rounds)."""
    store.create_many(
        "pods",
        [MakePod(f"a{round_no}-p{i}").labels({"anti": "one"})
         .pod_anti_affinity("kubernetes.io/hostname", {"anti": "one"})
         .req({"cpu": "100m", "memory": "64Mi"}).obj()
         for i in range(n_pods)],
        consume=True)
    before = sched.scheduled_count
    sched.run_until_idle()
    assert sched.scheduled_count - before == n_pods
    assert sched._solve_path == "repair"


def test_mixed_constrained_batches_do_not_retrace():
    """Alternating constrained/unconstrained batches share compiled shapes:
    the repair kernel buckets its pod axis to pow2 (floored at 256), gates
    constraint families with static bools (has_affinity / has_ct), and the
    cap-one propose pins run_j=1 — so a mixed steady state compiles each
    variant ONCE (the acceptance gate behind `solver_compiles_during_run`)."""
    store, sched = _synced_hostname_sched()
    # warm every shape: two constrained rounds (the second sees the first's
    # bound pods as existing holders — the holder-group tables go from
    # empty-padded to populated exactly once) and one unconstrained round
    _batch(store, sched, 20, 48)
    _anti_batch(store, sched, 21, 8)
    _anti_batch(store, sched, 22, 6)
    warm_wf = _cache_size()
    warm_rc = _repair_cache_size()
    assert warm_rc >= 1
    plan = (("plain", 23, 17), ("anti", 24, 12), ("plain", 25, 130),
            ("anti", 26, 3), ("plain", 27, 48), ("anti", 28, 9))
    for kind, round_no, n in plan:
        if kind == "plain":
            _batch(store, sched, round_no, n)
        else:
            _anti_batch(store, sched, round_no, n)
        assert _cache_size() == warm_wf, (
            f"waterfill retraced on {kind} round {round_no}: "
            f"{warm_wf} -> {_cache_size()}")
        assert _repair_cache_size() == warm_rc, (
            f"repair_check retraced on {kind} round {round_no}: "
            f"{warm_rc} -> {_repair_cache_size()}")
