"""CPU manager static policy + topology manager hints (kubelet cm/).

Pins the reference contract (pkg/kubelet/cm/cpumanager/policy_static.go,
cm/topologymanager):
  - a guaranteed-QoS pod with integer CPU requests gets EXCLUSIVE cpus;
    burstable/fractional pods stay in the shared pool
  - allocations prefer a single NUMA node (topology hints); restricted
    policy rejects unaligned pods with TopologyAffinityError
  - assignments are checkpointed and survive kubelet restart; stale state
    for dead pods is pruned on startup
  - pinning surfaces in `ktl describe node`
"""

import io
from contextlib import redirect_stdout

import pytest

from kubernetes_tpu.agent.cm import (
    CPUManager,
    CPUTopology,
    TopologyAffinityError,
    pod_is_guaranteed,
)
from kubernetes_tpu.agent.kubelet import CheckpointManager, Kubelet
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakePod


def guaranteed_pod(name, cpu="2", memory="2Gi"):
    p = MakePod(name).req({"cpu": cpu, "memory": memory}).obj()
    for c in p.spec.containers:
        c.resources["limits"] = dict(c.resources["requests"])
    return p


class TestQoS:
    def test_guaranteed_requires_requests_equal_limits(self):
        assert pod_is_guaranteed(guaranteed_pod("g"))
        assert not pod_is_guaranteed(
            MakePod("burstable").req({"cpu": "2", "memory": "2Gi"}).obj())
        p = guaranteed_pod("uneven")
        p.spec.containers[0].resources["limits"]["cpu"] = "4"
        assert not pod_is_guaranteed(p)


class TestStaticPolicy:
    def test_exclusive_cpus_for_guaranteed_integer_pod(self):
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2))
        got = cm.allocate_pod(guaranteed_pod("g", cpu="2"))
        assert got == {"c0": [0, 1]}
        assert 0 not in cm.shared_pool() and 1 not in cm.shared_pool()

    def test_fractional_guaranteed_stays_shared(self):
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2))
        assert cm.allocate_pod(guaranteed_pod("g", cpu="1500m")) == {}
        assert len(cm.shared_pool()) == 8

    def test_burstable_stays_shared(self):
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2))
        p = MakePod("b").req({"cpu": "2", "memory": "2Gi"}).obj()
        assert cm.allocate_pod(p) == {}
        assert len(cm.shared_pool()) == 8

    def test_numa_alignment_preferred(self):
        # NUMA0 = cpus 0-3, NUMA1 = 4-7; first pod takes 3 from NUMA0;
        # second pod wanting 3 must come from NUMA1 whole, not straddle
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2))
        a = cm.allocate_pod(guaranteed_pod("a", cpu="3"))["c0"]
        b = cm.allocate_pod(guaranteed_pod("b", cpu="3"))["c0"]
        assert a == [0, 1, 2]
        assert b == [4, 5, 6], "must prefer whole NUMA1 over straddling"

    def test_best_effort_spills_across_numa(self):
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2))
        cm.allocate_pod(guaranteed_pod("a", cpu="3"))
        cm.allocate_pod(guaranteed_pod("b", cpu="3"))
        # 2 free: cpu 3 (NUMA0) + cpu 7 (NUMA1) — best-effort spills
        got = cm.allocate_pod(guaranteed_pod("c", cpu="2"))["c0"]
        assert got == [3, 7]

    def test_restricted_rejects_unaligned(self):
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2),
                        topology_policy="restricted")
        cm.allocate_pod(guaranteed_pod("a", cpu="3"))
        cm.allocate_pod(guaranteed_pod("b", cpu="3"))
        with pytest.raises(TopologyAffinityError):
            cm.allocate_pod(guaranteed_pod("c", cpu="2"))

    def test_pool_exhaustion_raises(self):
        cm = CPUManager(CPUTopology(n_cpus=4, numa_nodes=1))
        cm.allocate_pod(guaranteed_pod("a", cpu="3"))
        with pytest.raises(RuntimeError):
            cm.allocate_pod(guaranteed_pod("b", cpu="2"))

    def test_release_returns_cpus(self):
        cm = CPUManager(CPUTopology(n_cpus=4, numa_nodes=1))
        pod = guaranteed_pod("a", cpu="3")
        cm.allocate_pod(pod)
        cm.release_pod(pod.key)
        assert len(cm.shared_pool()) == 4

    def test_multi_container_all_or_nothing(self):
        from kubernetes_tpu.api.types import Container

        cm = CPUManager(CPUTopology(n_cpus=4, numa_nodes=1))
        p = guaranteed_pod("multi", cpu="2")
        extra = Container(name="c1", resources={
            "requests": {"cpu": "3", "memory": "1Gi"},
            "limits": {"cpu": "3", "memory": "1Gi"}})
        p.spec.containers.append(extra)
        with pytest.raises(RuntimeError):
            cm.allocate_pod(p)
        # nothing leaked from the failed pod
        assert len(cm.shared_pool()) == 4


class TestCheckpointRestart:
    def test_assignments_survive_restart_and_prune_stale(self, tmp_path):
        ckpt = CheckpointManager(str(tmp_path))
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2),
                        checkpoints=ckpt)
        live = guaranteed_pod("live", cpu="2")
        dead = guaranteed_pod("dead", cpu="2")
        a_live = cm.allocate_pod(live)
        cm.allocate_pod(dead)
        # "restart": a fresh manager over the same checkpoint dir
        cm2 = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2),
                         checkpoints=CheckpointManager(str(tmp_path)))
        assert cm2.assignments[live.key] == a_live
        released = cm2.reconcile([live.key])
        assert released == 1
        assert dead.key not in cm2.assignments
        assert len(cm2.shared_pool()) == 8 - 2

    def test_kubelet_restart_keeps_exclusive_cpus(self, tmp_path):
        """The VERDICT 'done' bar: a guaranteed-QoS pod's exclusive CPUs
        survive a kubelet restart."""
        store = APIStore()
        klet = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "32Gi",
                                              "pods": "110"},
                       checkpoint_dir=str(tmp_path))
        klet.register()
        pod = guaranteed_pod("pinned", cpu="2")
        store.create("pods", pod)
        store.bind("default", "pinned", "n1")
        klet.tick()
        before = klet.cpu_manager.assignments["default/pinned"]
        assert before["c0"] == [0, 1]
        # restart: new kubelet instance, same checkpoint dir + store
        klet2 = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "32Gi",
                                               "pods": "110"},
                        checkpoint_dir=str(tmp_path))
        klet2.register()
        assert klet2.cpu_manager.assignments["default/pinned"] == before

    def test_describe_node_shows_pinning(self, tmp_path):
        from kubernetes_tpu.cli.ktl import main as ktl_main
        from kubernetes_tpu.server import APIServer

        store = APIStore()
        srv = APIServer(store).start()
        try:
            klet = Kubelet(store, "n1",
                           capacity={"cpu": "8", "memory": "32Gi",
                                     "pods": "110"},
                           checkpoint_dir=str(tmp_path))
            klet.register()
            store.create("pods", guaranteed_pod("pinned", cpu="2"))
            store.bind("default", "pinned", "n1")
            klet.tick()
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert ktl_main(["--server", srv.url, "describe",
                                 "node", "n1"]) == 0
            out = buf.getvalue()
            assert "CPU Manager" in out
            assert "default/pinned/c0: 0,1" in out
        finally:
            srv.stop()

    def test_topology_rejection_fails_pod(self, tmp_path):
        """restricted policy: an unaligned pod FAILS at kubelet admission
        (TopologyAffinityError), mirroring the reference's pod-level
        admission failure."""
        from kubernetes_tpu.agent.cm import CPUManager as CM, CPUTopology

        store = APIStore()
        klet = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "32Gi",
                                              "pods": "110"})
        klet.cpu_manager = CM(CPUTopology(n_cpus=8, numa_nodes=2),
                              topology_policy="restricted")
        klet.register()
        for name, cpu in (("a", "3"), ("b", "3")):
            store.create("pods", guaranteed_pod(name, cpu=cpu))
            store.bind("default", name, "n1")
        klet.tick()
        store.create("pods", guaranteed_pod("c", cpu="2"))
        store.bind("default", "c", "n1")
        klet.tick()
        got = store.get("pods", "default/c")
        assert got.status.phase == "Failed"

    def test_terminated_pod_releases_cpus(self):
        """Completed Jobs must return their exclusive CPUs to the pool —
        terminal phase transitions release, not just pod deletion."""
        from kubernetes_tpu.agent.cri import FakeRuntime

        store = APIStore()
        runtime = FakeRuntime()
        klet = Kubelet(store, "n1", runtime=runtime, relist_period=0,
                       capacity={"cpu": "8", "memory": "32Gi",
                                 "pods": "110"})
        klet.register()
        job = guaranteed_pod("job", cpu="4")
        job.spec.restart_policy = "Never"
        store.create("pods", job)
        store.bind("default", "job", "n1")
        klet.tick()
        assert klet.cpu_manager.assignments["default/job"]["c0"] == [0, 1, 2, 3]
        runtime.exit_container("default/job", "c0", 0)
        klet.tick()
        assert store.get("pods", "default/job").status.phase == "Succeeded"
        assert "default/job" not in klet.cpu_manager.assignments
        assert len(klet.cpu_manager.shared_pool()) == 8

    def test_topology_change_discards_checkpoint(self, tmp_path):
        ckpt = CheckpointManager(str(tmp_path))
        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2),
                        checkpoints=ckpt)
        cm.allocate_pod(guaranteed_pod("g", cpu="2"))
        # restart with HALF the cpus: stale ids would be meaningless
        cm2 = CPUManager(CPUTopology(n_cpus=4, numa_nodes=1),
                         checkpoints=CheckpointManager(str(tmp_path)))
        assert cm2.assignments == {}
        assert len(cm2.shared_pool()) == 4

    def test_init_containers_allocated(self):
        from kubernetes_tpu.api.types import Container

        cm = CPUManager(CPUTopology(n_cpus=8, numa_nodes=2))
        p = guaranteed_pod("init", cpu="2")
        p.spec.init_containers.append(Container(name="setup", resources={
            "requests": {"cpu": "3", "memory": "1Gi"},
            "limits": {"cpu": "3", "memory": "1Gi"}}))
        got = cm.allocate_pod(p)
        assert got["setup"] == [0, 1, 2]
        assert got["c0"] == [4, 5]  # aligned in the other NUMA node
