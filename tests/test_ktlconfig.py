"""ktl config (kubeconfig analog): contexts, precedence, secured round-trip.

reference: client-go tools/clientcmd + kubectl config.
"""

import json
import os

import pytest

from kubernetes_tpu.cli.ktl import main as ktl
from kubernetes_tpu.cli.ktlconfig import load_config, resolve, save_config
from kubernetes_tpu.server import APIServer, RESTClient
from kubernetes_tpu.server.auth import TokenAuthenticator, RBACAuthorizer
from kubernetes_tpu.store import APIStore


@pytest.fixture()
def kcfg(tmp_path, monkeypatch):
    path = tmp_path / "config"
    monkeypatch.setenv("KTLCONFIG", str(path))
    monkeypatch.delenv("KTL_SERVER", raising=False)
    return path


class TestConfigFile:
    def test_set_use_view_roundtrip(self, kcfg, capsys):
        assert ktl(["config", "set-cluster", "dev",
                    "--server-url", "http://127.0.0.1:9999"]) == 0
        assert ktl(["config", "set-credentials", "admin",
                    "--token", "sekrit"]) == 0
        assert ktl(["config", "set-context", "dev-admin", "--cluster", "dev",
                    "--user", "admin", "--namespace", "team-a"]) == 0
        assert ktl(["config", "use-context", "dev-admin"]) == 0
        capsys.readouterr()
        assert ktl(["config", "current-context"]) == 0
        assert capsys.readouterr().out.strip() == "dev-admin"
        server, token, ns = resolve()
        assert server == "http://127.0.0.1:9999"
        assert token == "sekrit" and ns == "team-a"
        # view redacts tokens
        assert ktl(["config", "view"]) == 0
        out = capsys.readouterr().out
        assert "REDACTED" in out and "sekrit" not in out

    def test_use_unknown_context_errors(self, kcfg, capsys):
        assert ktl(["config", "use-context", "nope"]) == 1

    def test_delete_context_clears_current(self, kcfg, capsys):
        ktl(["config", "set-cluster", "c", "--server-url", "http://x"])
        ktl(["config", "set-context", "ctx", "--cluster", "c", "--user", "u"])
        ktl(["config", "use-context", "ctx"])
        assert ktl(["config", "delete-context", "ctx"]) == 0
        assert resolve() == (None, None, None)

    def test_corrupt_file_treated_as_empty(self, kcfg):
        kcfg.write_text("{not json")
        assert load_config()["contexts"] == {}


class TestPrecedence:
    def test_flag_beats_env_beats_context(self, kcfg, monkeypatch, capsys):
        srv = APIServer(APIStore()).start()
        try:
            # context points at a dead server; the flag must win
            ktl(["config", "set-cluster", "dead",
                 "--server-url", "http://127.0.0.1:1"])
            ktl(["config", "set-context", "d", "--cluster", "dead",
                 "--user", "x"])
            ktl(["config", "use-context", "d"])
            assert ktl(["--server", srv.url, "get", "pods"]) == 0
            # env beats context too
            monkeypatch.setenv("KTL_SERVER", srv.url)
            assert ktl(["get", "pods"]) == 0
        finally:
            srv.stop()

    def test_context_supplies_token_and_namespace(self, kcfg, capsys):
        authn = TokenAuthenticator()
        authn.add("tok-a", "alice")
        authz = RBACAuthorizer().grant("alice", ["*"], ["*"])
        srv = APIServer(APIStore(), authenticator=authn,
                        authorizer=authz).start()
        try:
            store = srv.store
            from kubernetes_tpu.api.types import Namespace, ObjectMeta

            store.create("namespaces", Namespace(metadata=ObjectMeta(name="team-a")))
            ktl(["config", "set-cluster", "c", "--server-url", srv.url])
            ktl(["config", "set-credentials", "alice", "--token", "tok-a"])
            ktl(["config", "set-context", "ctx", "--cluster", "c",
                 "--user", "alice", "--namespace", "team-a"])
            ktl(["config", "use-context", "ctx"])
            capsys.readouterr()
            # no flags at all: server, token, and namespace from the context
            assert ktl(["run", "w", "--image", "i"]) == 0
            c = RESTClient(srv.url, token="tok-a")
            pod = c.get("pods", "w", "team-a")
            assert pod["metadata"]["namespace"] == "team-a"
        finally:
            srv.stop()


class TestHardening:
    def test_file_mode_0600(self, kcfg):
        ktl(["config", "set-credentials", "a", "--token", "t"])
        assert oct(os.stat(kcfg).st_mode & 0o777) == "0o600"

    def test_bare_filename_ktlconfig(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("KTLCONFIG", "cfgfile")
        assert ktl(["config", "set-cluster", "c",
                    "--server-url", "http://x"]) == 0
        assert (tmp_path / "cfgfile").exists()

    def test_job_completion_mode_immutable(self):
        from kubernetes_tpu.server import APIError

        srv = APIServer(APIStore()).start()
        try:
            c = RESTClient(srv.url)
            c.create("jobs", {"kind": "Job", "metadata": {"name": "j"},
                              "spec": {"parallelism": 1, "completions": 2,
                                       "template": {"spec": {"containers": [
                                           {"name": "c"}]}}}})
            with pytest.raises(APIError) as e:
                c.patch("jobs", "j", {"spec": {"completionMode": "Indexed"}})
            assert e.value.code == 422
            with pytest.raises(APIError) as e:
                c.patch("jobs", "j", {"spec": {"completions": 5}})
            assert e.value.code == 422
            # parallelism stays mutable (scale)
            out = c.patch("jobs", "j", {"spec": {"parallelism": 3}})
            assert out["spec"]["parallelism"] == 3
        finally:
            srv.stop()
