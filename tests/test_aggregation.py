"""Aggregation layer: APIService objects route foreign API groups to
extension apiservers (kube-aggregator; delegation chain server.go:173).

Pins:
  - a request under an aggregated group proxies WHOLESALE (method, body,
    query, response code/body) to the extension server
  - the authenticated identity forwards as X-Remote-User front-proxy headers
  - built-in and CRD-served groups are never proxied
  - an unavailable backend yields 503 (availability controller probes
    /healthz); an unreachable one yields 502
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api.apiservice import APIService
from kubernetes_tpu.controllers import APIServiceAvailabilityController
from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.store import APIStore


class _Extension(BaseHTTPRequestHandler):
    """Fake extension apiserver recording requests."""

    def _serve(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        body = self.rfile.read(length) if length else b""
        self.server.seen.append({  # type: ignore[attr-defined]
            "method": self.command, "path": self.path,
            "user": self.headers.get("X-Remote-User", ""),
            "body": body.decode() or None})
        if self.path.endswith("/healthz"):
            payload = b"ok"
            self.send_response(200)
        elif "boom" in self.path:
            payload = json.dumps({"message": "boom"}).encode()
            self.send_response(418)
        else:
            payload = json.dumps(
                {"kind": "WidgetList", "served": self.path,
                 "echo": body.decode() or None}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    do_GET = do_POST = do_PUT = do_DELETE = _serve

    def log_message(self, *a):
        pass


@pytest.fixture()
def extension():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Extension)
    httpd.seen = []  # type: ignore[attr-defined]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield httpd
    httpd.shutdown()


@pytest.fixture()
def server():
    srv = APIServer(APIStore()).start()
    yield srv
    srv.stop()


def register(server, extension, group="widgets.example.com",
             available=True):
    url = f"http://127.0.0.1:{extension.server_address[1]}"
    svc = APIService(group=group, service_url=url, available=available)
    server.store.create("apiservices", svc)
    return svc


class TestAggregation:
    def test_get_proxied_with_identity(self, server, extension):
        register(server, extension)
        c = RESTClient(server.url, user="alice")
        out = c.request(
            "GET", "/apis/widgets.example.com/v1/namespaces/default/widgets")
        assert out["kind"] == "WidgetList"
        seen = extension.seen[-1]
        assert seen["method"] == "GET"
        assert seen["path"] == \
            "/apis/widgets.example.com/v1/namespaces/default/widgets"
        assert seen["user"] == "alice"

    def test_post_body_and_error_codes_pass_through(self, server, extension):
        register(server, extension)
        c = RESTClient(server.url)
        out = c.request(
            "POST", "/apis/widgets.example.com/v1/namespaces/default/widgets",
            {"kind": "Widget", "metadata": {"name": "w1"}})
        assert json.loads(out["echo"])["metadata"]["name"] == "w1"
        with pytest.raises(APIError) as e:
            c.request("GET", "/apis/widgets.example.com/v1/boom")
        assert e.value.code == 418

    def test_builtin_groups_never_proxied(self, server, extension):
        register(server, extension, group="apps")
        c = RESTClient(server.url)
        items, _ = c.list("deployments")
        assert items == []  # served locally, not by the extension
        assert all("deployments" not in s["path"] for s in extension.seen)

    def test_unavailable_apiservice_503(self, server, extension):
        register(server, extension, available=False)
        c = RESTClient(server.url)
        with pytest.raises(APIError) as e:
            c.request("GET", "/apis/widgets.example.com/v1/widgets")
        assert e.value.code == 503

    def test_unreachable_backend_502(self, server):
        svc = APIService(group="gone.example.com",
                         service_url="http://127.0.0.1:9", available=True)
        server.store.create("apiservices", svc)
        c = RESTClient(server.url)
        with pytest.raises(APIError) as e:
            c.request("GET", "/apis/gone.example.com/v1/things")
        assert e.value.code == 502

    def test_availability_controller_probes(self, server, extension):
        svc = register(server, extension, available=False)
        ctl = APIServiceAvailabilityController(server.store)
        ctl.sync_all()
        ctl.run_until_stable()
        got = server.store.get("apiservices", svc.metadata.name)
        assert got.available
        # backend dies -> availability flips off on the next probe
        extension.shutdown()
        ctl._mark(svc.metadata.name)
        ctl.process()
        got = server.store.get("apiservices", svc.metadata.name)
        assert not got.available
        assert "unreachable" in got.available_message

    def test_crd_groups_precede_aggregation(self, server, extension):
        register(server, extension, group="crd.example.com")
        c = RESTClient(server.url)
        c.create("customresourcedefinitions", {
            "kind": "CustomResourceDefinition",
            "metadata": {"name": "gadgets.crd.example.com"},
            "spec": {"group": "crd.example.com",
                     "names": {"plural": "gadgets", "kind": "Gadget"},
                     "scope": "Namespaced",
                     "versions": [{"name": "v1", "served": True,
                                   "storage": True}]}}, namespace=None)
        c.request("POST",
                  "/apis/crd.example.com/v1/namespaces/default/gadgets",
                  {"apiVersion": "crd.example.com/v1", "kind": "Gadget",
                   "metadata": {"name": "g1"}})
        got = c.request(
            "GET", "/apis/crd.example.com/v1/namespaces/default/gadgets/g1")
        assert got["metadata"]["name"] == "g1"
        assert all("gadgets" not in s["path"] for s in extension.seen)


class TestAggregationSecurity:
    def test_auth_gate_applies_to_aggregated_paths(self, extension):
        """The proxy must never launder a request past authn/authz."""
        from kubernetes_tpu.server.auth import (
            TokenAuthenticator,
            default_component_authorizer,
        )

        store = APIStore()
        authn = TokenAuthenticator()
        authn.add("good-token", "alice")
        srv = APIServer(store, authenticator=authn,
                        authorizer=default_component_authorizer()).start()
        try:
            url = f"http://127.0.0.1:{extension.server_address[1]}"
            store.create("apiservices", APIService(
                group="widgets.example.com", service_url=url,
                available=True))
            # no token -> 401, never proxied
            anon = RESTClient(srv.url)
            with pytest.raises(APIError) as e:
                anon.request("GET", "/apis/widgets.example.com/v1/widgets")
            assert e.value.code == 401
            # authenticated reader: wildcard read grant covers it, proxied
            # with front-proxy identity
            alice = RESTClient(srv.url, token="good-token")
            alice.request("GET", "/apis/widgets.example.com/v1/widgets")
            assert extension.seen[-1]["user"] == "alice"
            # but a WRITE is not in the read-all grant -> 403, not proxied
            before = len(extension.seen)
            with pytest.raises(APIError) as e:
                alice.request("POST",
                              "/apis/widgets.example.com/v1/widgets",
                              {"kind": "Widget"})
            assert e.value.code == 403
            assert len(extension.seen) == before
        finally:
            srv.stop()

    def test_version_picks_apiservice(self, server, extension):
        # v1 -> extension; v2 -> a dead backend: version routing must pick
        # the matching APIService, not the highest priority one
        url = f"http://127.0.0.1:{extension.server_address[1]}"
        server.store.create("apiservices", APIService(
            group="metrics.example.com", version="v1", service_url=url,
            available=True, group_priority_minimum=100))
        server.store.create("apiservices", APIService(
            group="metrics.example.com", version="v2",
            service_url="http://127.0.0.1:9", available=True,
            group_priority_minimum=9000))
        c = RESTClient(server.url)
        out = c.request("GET", "/apis/metrics.example.com/v1/nodes")
        assert out["kind"] == "WidgetList"
        with pytest.raises(APIError) as e:
            c.request("GET", "/apis/metrics.example.com/v2/nodes")
        assert e.value.code == 502


class TestAggregatedWatch:
    def test_watch_streams_through_proxy(self, server):
        """?watch=true on an aggregated group streams events AS THEY ARRIVE:
        the first event must be readable through the proxy while the
        backend stream is STILL OPEN (a buffering proxy passes nothing
        until EOF — resp.read vs read1 regression guard)."""
        import urllib.request

        release = threading.Event()

        class _Streamer(BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def send(i):
                    line = json.dumps({"type": "ADDED", "object": {
                        "metadata": {"name": f"w{i}"}}}).encode() + b"\n"
                    self.wfile.write(
                        f"{len(line):x}\r\n".encode() + line + b"\r\n")
                    self.wfile.flush()

                send(0)
                # hold the stream OPEN until the test confirms delivery
                release.wait(timeout=10)
                send(1)
                send(2)
                self.wfile.write(b"0\r\n\r\n")

            def log_message(self, *a):
                pass

        backend = ThreadingHTTPServer(("127.0.0.1", 0), _Streamer)
        t = threading.Thread(target=backend.serve_forever, daemon=True)
        t.start()
        try:
            server.store.create("apiservices", APIService(
                group="streams.example.com",
                service_url=f"http://127.0.0.1:{backend.server_address[1]}",
                available=True))
            req = urllib.request.Request(
                f"{server.url}/apis/streams.example.com/v1/widgets"
                f"?watch=true")
            names = []
            with urllib.request.urlopen(req, timeout=10) as resp:
                first = resp.readline()
                assert json.loads(first)["object"]["metadata"]["name"] \
                    == "w0", "first event must stream BEFORE backend EOF"
                release.set()  # only now may the backend finish
                for raw in resp:
                    if raw.strip():
                        names.append(json.loads(raw)["object"]["metadata"]
                                     ["name"])
            assert names == ["w1", "w2"]
        finally:
            release.set()
            backend.shutdown()
