"""Auction/Sinkhorn transportation solvers: feasibility always holds, capacity
is never violated, utility is near the greedy scan's, and warm-started duals
carry across churn (the incremental re-solve path)."""

import numpy as np

from kubernetes_tpu.models.transport import (
    assignment_from_plan,
    auction_solve,
    build_group_problem,
    repair_plan,
    round_plan,
    sinkhorn_solve,
    transport_solve,
)
from kubernetes_tpu.models.waterfill import make_groups
from kubernetes_tpu.ops.solver import greedy_scan_solve, make_inputs
from kubernetes_tpu.scheduler import Cache, Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.snapshot.tensorizer import build_cluster_tensors, build_pod_batch
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils import FakeClock


def problem_inputs(nodes, pods):
    cache = Cache(clock=FakeClock())
    for n in nodes:
        cache.add_node(n)
    snap = cache.update_snapshot()
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, d_max = make_inputs(cluster, batch)
    return inputs, d_max, cluster, batch


def check_valid(inputs, assignment):
    """No capacity/pod-count violation under exact integer arithmetic."""
    a = np.asarray(assignment)
    alloc = np.asarray(inputs.alloc, np.int64)
    used = np.asarray(inputs.used, np.int64).copy()
    cnt = np.asarray(inputs.pod_count, np.int64).copy()
    maxp = np.asarray(inputs.max_pods, np.int64)
    req = np.asarray(inputs.req, np.int64)
    for p, n in enumerate(a):
        if n < 0:
            continue
        used[n] += req[p]
        cnt[n] += 1
    assert (used <= alloc).all(), "resource over-commit"
    assert (cnt <= maxp).all(), "pod-count over-commit"


def total_utility(inputs, d_max, assignment):
    from kubernetes_tpu.parallel.sharded import feasibility_cost_matrices

    f, c = feasibility_cost_matrices(inputs, d_max)
    c = np.asarray(c)
    a = np.asarray(assignment)
    return sum(int(c[p, n]) for p, n in enumerate(a) if n >= 0)


def make_cluster(n_nodes=12, cpu="8", mem="16Gi"):
    return [
        MakeNode(f"n{i}").capacity({"cpu": cpu, "memory": mem, "pods": "110"}).obj()
        for i in range(n_nodes)
    ]


def test_auction_places_all_when_capacity_ample():
    nodes = make_cluster()
    pods = [MakePod(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj() for i in range(30)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    out = transport_solve(inputs, make_groups(batch), method="auction",
                          node_names=cluster.node_names)
    assert out is not None
    a, state = out
    assert (a >= 0).all()
    check_valid(inputs, a)
    assert state.iterations > 0


def test_auction_utility_close_to_greedy():
    nodes = make_cluster(8)
    pods = [MakePod(f"a{i}").req({"cpu": "2", "memory": "4Gi"}).obj() for i in range(8)]
    pods += [MakePod(f"b{i}").req({"cpu": "1", "memory": "1Gi"}).obj() for i in range(12)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    scan, _, _ = greedy_scan_solve(inputs, d_max)
    a, _ = transport_solve(inputs, make_groups(batch), method="auction",
                           node_names=cluster.node_names)
    check_valid(inputs, a)
    assert (a >= 0).sum() == (np.asarray(scan) >= 0).sum()
    # joint objective (initial-state utility) should be at least greedy's
    assert total_utility(inputs, d_max, a) >= 0.95 * total_utility(inputs, d_max, scan)


def test_auction_respects_scarce_capacity():
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "2", "pods": "110"}).obj() for i in range(3)]
    pods = [MakePod(f"p{i}").req({"cpu": "1500m"}).obj() for i in range(6)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    a, _ = transport_solve(inputs, make_groups(batch), method="auction",
                           node_names=cluster.node_names)
    check_valid(inputs, a)
    assert (a >= 0).sum() == 3  # one 1500m pod per 2-cpu node


def test_sinkhorn_places_and_respects_capacity():
    nodes = make_cluster(6, cpu="4", mem="8Gi")
    pods = [MakePod(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj() for i in range(20)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    a, state = transport_solve(inputs, make_groups(batch), method="sinkhorn",
                               node_names=cluster.node_names)
    check_valid(inputs, a)
    # 6 nodes x 4 cpu = 24 slots of 1cpu, but memory caps at 4/node = 24; all fit
    assert (a >= 0).sum() == 20


def test_heterogeneous_node_selector_groups():
    nodes = []
    for i in range(6):
        nodes.append(MakeNode(f"n{i}").labels({"disk": "ssd" if i % 2 == 0 else "hdd"})
                     .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj())
    pods = [MakePod(f"ssd{i}").node_selector({"disk": "ssd"}).req({"cpu": "1"}).obj()
            for i in range(6)]
    pods += [MakePod(f"any{i}").req({"cpu": "500m", "memory": "1Gi"}).obj() for i in range(8)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    for method in ("auction", "sinkhorn"):
        a, _ = transport_solve(inputs, make_groups(batch), method=method,
                               node_names=cluster.node_names)
        check_valid(inputs, a)
        for j in range(6):  # ssd pods only on even nodes
            assert a[j] >= 0 and a[j] % 2 == 0, (method, j, a[j])
        assert (a >= 0).all()


def test_warm_start_carries_prices_across_churn():
    nodes = make_cluster(10)
    pods = [MakePod(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj() for i in range(20)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    problem = build_group_problem(inputs, make_groups(batch))
    _, cold = auction_solve(problem, node_names=cluster.node_names)

    # churn: drop two nodes, add three new ones; same pod batch
    nodes2 = nodes[2:] + make_cluster(3, cpu="16")[:3]
    for i, n in enumerate(nodes2[-3:]):
        n.metadata.name = f"new{i}"
    inputs2, d2, cluster2, batch2 = problem_inputs(nodes2, pods)
    problem2 = build_group_problem(inputs2, make_groups(batch2))
    x_warm, warm = auction_solve(problem2, state=cold, node_names=cluster2.node_names)
    x2 = repair_plan(problem2, x_warm)
    a = assignment_from_plan(problem2, x2, len(pods))
    check_valid(inputs2, a)
    assert (a >= 0).all()
    # price vector remapped by name: surviving nodes keep non-negative prices
    assert warm.price.shape == (len(nodes2),)


def test_round_plan_respects_caps():
    nodes = make_cluster(4, cpu="3")
    pods = [MakePod(f"p{i}").req({"cpu": "1"}).obj() for i in range(12)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    problem = build_group_problem(inputs, make_groups(batch))
    frac, _ = sinkhorn_solve(problem, node_names=cluster.node_names)
    x = round_plan(problem, frac)
    assert (x.sum(axis=0) <= np.asarray(problem.slots)).all()
    assert (x <= np.asarray(problem.jcap)).all()
    x = repair_plan(problem, x)
    a = assignment_from_plan(problem, x, len(pods))
    check_valid(inputs, a)


def test_batch_scheduler_auction_end_to_end():
    store = APIStore()
    for i in range(8):
        store.create("nodes", MakeNode(f"n{i}")
                     .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj())
    for i in range(24):
        store.create("pods", MakePod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()), solver="auction",
                           clock=FakeClock())
    sched.sync()
    sched.run_until_idle()
    bound = [p for p in store.list("pods")[0] if p.spec.node_name]
    assert len(bound) == 24
    assert sched.transport_state is not None  # duals retained for next batch


def test_batch_scheduler_sinkhorn_end_to_end():
    store = APIStore()
    for i in range(6):
        store.create("nodes", MakeNode(f"n{i}")
                     .capacity({"cpu": "4", "memory": "8Gi", "pods": "110"}).obj())
    for i in range(12):
        store.create("pods", MakePod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()), solver="sinkhorn",
                           clock=FakeClock())
    sched.sync()
    sched.run_until_idle()
    bound = [p for p in store.list("pods")[0] if p.spec.node_name]
    assert len(bound) == 12


def test_host_ports_fall_back_from_transport():
    """Classes with host ports aren't transport-eligible; build returns None
    and the batch driver falls through to the scan solver."""
    nodes = make_cluster(4)
    pods = [MakePod(f"p{i}").req({"cpu": "1"}, host_port=8080).obj() for i in range(4)]
    inputs, d_max, cluster, batch = problem_inputs(nodes, pods)
    assert build_group_problem(inputs, make_groups(batch)) is None
    store = APIStore()
    for n in make_cluster(4):
        store.create("nodes", n)
    for i in range(4):
        store.create("pods", MakePod(f"p{i}").req({"cpu": "1"}, host_port=8080).obj())
    sched = BatchScheduler(store, Framework(default_plugins()), solver="auction",
                           clock=FakeClock())
    sched.sync()
    sched.run_until_idle()
    bound = [p for p in store.list("pods")[0] if p.spec.node_name]
    assert len(bound) == 4
    assert len({p.spec.node_name for p in bound}) == 4  # one per node (port)


def test_sharded_transport_parity():
    """Node-sharded transport (mesh over the 'nodes' axis) must produce the
    identical per-pod assignment as the unsharded solve, for both methods,
    with warm-dual state carried by true node name (BASELINE ladder #4)."""
    import numpy as np

    from kubernetes_tpu.models.transport import transport_solve
    from kubernetes_tpu.models.waterfill import make_groups
    from kubernetes_tpu.ops.solver import make_inputs
    from kubernetes_tpu.parallel.sharded import make_mesh
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.snapshot.tensorizer import (
        build_cluster_tensors,
        build_pod_batch,
    )
    from kubernetes_tpu.testing import MakeNode, MakePod
    from kubernetes_tpu.utils import FakeClock

    cache = Cache(clock=FakeClock())
    for i in range(35):  # odd: node padding crosses shard boundaries
        cache.add_node(MakeNode(f"n{i}").labels(
            {"kubernetes.io/hostname": f"n{i}"}).capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "64"}).obj())
    snap = cache.update_snapshot()
    pods = [MakePod(f"p{i}").req(
        {"cpu": "500m" if i % 2 else "250m", "memory": "512Mi"}).obj()
        for i in range(48)]
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, _ = make_inputs(cluster, batch)
    groups = make_groups(batch)
    mesh = make_mesh(n_devices=8, dp=2)
    for method in ("sinkhorn", "auction"):
        a_sh, st_sh = transport_solve(inputs, groups, method=method,
                                      node_names=cluster.node_names,
                                      mesh=mesh)
        a_one, _ = transport_solve(inputs, groups, method=method,
                                   node_names=cluster.node_names)
        assert (np.asarray(a_sh) == np.asarray(a_one)).all(), method
        assert int((np.asarray(a_sh) >= 0).sum()) == 48, method
        assert len(st_sh.price) == 35, "duals must map to TRUE nodes"
        # warm re-solve through the sharded path with carried duals
        a_warm, _ = transport_solve(inputs, groups, method=method,
                                    state=st_sh,
                                    node_names=cluster.node_names,
                                    mesh=mesh)
        assert int((np.asarray(a_warm) >= 0).sum()) == 48, method


def test_auction_single_group_large_supply():
    """The G=1 degenerate case: one group with supply far above any node's
    capacity must still fully place (one-bid-per-round capped it at
    rounds x jcap before multi-node bidding)."""
    import numpy as np

    from kubernetes_tpu.models.transport import transport_solve
    from kubernetes_tpu.models.waterfill import make_groups
    from kubernetes_tpu.ops.solver import make_inputs
    from kubernetes_tpu.scheduler import Cache
    from kubernetes_tpu.snapshot.tensorizer import (
        build_cluster_tensors,
        build_pod_batch,
    )
    from kubernetes_tpu.testing import MakeNode, MakePod
    from kubernetes_tpu.utils import FakeClock

    cache = Cache(clock=FakeClock())
    for i in range(50):
        cache.add_node(MakeNode(f"n{i}").labels(
            {"kubernetes.io/hostname": f"n{i}"}).capacity(
            {"cpu": "16", "memory": "64Gi", "pods": "110"}).obj())
    snap = cache.update_snapshot()
    # ONE group, 800 identical pods; ~16 fit per node -> needs all 50 nodes
    pods = [MakePod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj()
            for i in range(800)]
    cluster = build_cluster_tensors(snap)
    batch = build_pod_batch(pods, snap, cluster)
    inputs, _ = make_inputs(cluster, batch)
    a, _ = transport_solve(inputs, make_groups(batch), method="auction",
                           node_names=cluster.node_names)
    assert int((np.asarray(a) >= 0).sum()) == 800
