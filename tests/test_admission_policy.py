"""ValidatingAdmissionPolicy (restricted-CEL) + admission webhooks.

Pins the reference's admission extensibility contract
(apiserver/pkg/admission/plugin/policy/validating/plugin.go,
plugin/webhook/{mutating,validating}):
  - a policy API object rejects a live write with NO tree change
  - policies are inert without a binding; namespaceSelector scopes bindings
  - failurePolicy Fail vs Ignore on expression errors / unreachable hooks
  - mutating webhooks patch objects via base64 JSONPatch; validating
    webhooks deny with the webhook's status message
  - webhook HTTP round-trips never run under the store transaction
"""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.server import APIError, APIServer, RESTClient
from kubernetes_tpu.server.celexpr import (
    ExpressionError,
    compile_expression,
)
from kubernetes_tpu.store import APIStore


class TestCelExpr:
    def run(self, src, obj=None, request=None):
        return compile_expression(src)({
            "object": obj or {}, "oldObject": None,
            "request": request or {}})

    def test_basic_comparison(self):
        assert self.run("object.spec.replicas <= 5",
                        {"spec": {"replicas": 3}})
        assert not self.run("object.spec.replicas <= 5",
                            {"spec": {"replicas": 9}})

    def test_boolean_operators(self):
        obj = {"spec": {"a": 1, "b": "x"}}
        assert self.run("object.spec.a == 1 && object.spec.b == 'x'", obj)
        assert self.run("object.spec.a == 2 || object.spec.b == 'x'", obj)
        assert self.run("!(object.spec.a == 2)", obj)

    def test_has_and_absent_fields(self):
        assert self.run("has(object.metadata.labels)",
                        {"metadata": {"labels": {"a": "b"}}})
        assert not self.run("has(object.metadata.labels)", {"metadata": {}})
        # comparisons against absent fields don't match
        assert not self.run("object.spec.replicas > 0", {})
        # != is vacuously true against absence
        assert self.run("object.spec.x != 'y'", {})

    def test_string_methods_and_size(self):
        obj = {"metadata": {"name": "web-frontend"},
               "spec": {"containers": [1, 2, 3]}}
        assert self.run("object.metadata.name.startsWith('web-')", obj)
        assert self.run("object.metadata.name.contains('front')", obj)
        assert self.run("object.metadata.name.matches('^web-[a-z]+$')", obj)
        assert self.run("size(object.spec.containers) == 3", obj)

    def test_in_operator(self):
        assert self.run("object.spec.tier in ['gold', 'silver']",
                        {"spec": {"tier": "gold"}})

    def test_request_variables(self):
        assert self.run("request.operation == 'CREATE'",
                        request={"operation": "CREATE"})

    def test_keyword_strings_untouched(self):
        # 'true'/'false'/'null' inside string literals stay verbatim
        assert self.run("object.spec.x == 'true'", {"spec": {"x": "true"}})
        assert self.run("object.spec.x == 'null'", {"spec": {"x": "null"}})
        assert not self.run("object.spec.x == 'false'",
                            {"spec": {"x": "False"}})

    def test_null_literal(self):
        assert self.run("object.spec.x == null", {"spec": {"x": None}})

    def test_disallowed_syntax_rejected(self):
        for bad in ("__import__('os')", "object.__class__",
                    "[x for x in object]", "lambda: 1",
                    "open('/etc/passwd')"):
            with pytest.raises(ExpressionError):
                compile_expression(bad)({"object": {}})

    def test_non_boolean_result_rejected(self):
        with pytest.raises(ExpressionError):
            self.run("object.spec.replicas + 1", {"spec": {"replicas": 1}})


@pytest.fixture()
def server():
    srv = APIServer(APIStore()).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    return RESTClient(server.url)


def make_policy(client, name, expression, message="denied by policy",
                resources=("pods",), operations=("*",),
                failure_policy="Fail", bind=True, ns_labels=None):
    client.create("validatingadmissionpolicies", {
        "kind": "ValidatingAdmissionPolicy",
        "metadata": {"name": name},
        "spec": {
            "matchConstraints": {"resourceRules": [
                {"resources": list(resources),
                 "operations": list(operations)}]},
            "validations": [{"expression": expression, "message": message}],
            "failurePolicy": failure_policy,
        }}, namespace=None)
    if bind:
        spec = {"policyName": name, "validationActions": ["Deny"]}
        if ns_labels is not None:
            spec["matchResources"] = {"namespaceSelector":
                                      {"matchLabels": ns_labels}}
        client.create("validatingadmissionpolicybindings", {
            "kind": "ValidatingAdmissionPolicyBinding",
            "metadata": {"name": f"{name}-binding"}, "spec": spec},
            namespace=None)


def pod(name, labels=None, cpu="100m"):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "labels": labels or {}},
            "spec": {"containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu}}}]}}


class TestValidatingAdmissionPolicy:
    def test_policy_rejects_live_write(self, server, client):
        make_policy(client, "require-team",
                    "has(object.metadata.labels.team)",
                    message="every pod needs a team label")
        with pytest.raises(APIError) as e:
            client.create("pods", pod("p1"))
        assert e.value.code == 422
        assert "every pod needs a team label" in str(e.value)
        client.create("pods", pod("p2", labels={"team": "infra"}))

    def test_policy_without_binding_is_inert(self, server, client):
        make_policy(client, "inert", "false", bind=False)
        client.create("pods", pod("p1"))  # must not raise

    def test_binding_namespace_selector(self, server, client):
        client.create("namespaces", {"kind": "Namespace",
                                     "metadata": {"name": "prod",
                                                  "labels": {"env": "prod"}}},
                      namespace=None)
        client.create("namespaces", {"kind": "Namespace",
                                     "metadata": {"name": "dev",
                                                  "labels": {"env": "dev"}}},
                      namespace=None)
        make_policy(client, "prod-only", "false", ns_labels={"env": "prod"})
        client.create("pods", dict(pod("p-dev"),
                                   metadata={"name": "p-dev",
                                             "namespace": "dev"}))
        with pytest.raises(APIError):
            client.create("pods", dict(pod("p-prod"),
                                       metadata={"name": "p-prod",
                                                 "namespace": "prod"}))

    def test_failure_policy_fail_vs_ignore(self, server, client):
        make_policy(client, "broken-fail", "object.spec..bogus(",
                    failure_policy="Fail")
        with pytest.raises(APIError) as e:
            client.create("pods", pod("p1"))
        assert e.value.code == 500
        client.delete("validatingadmissionpolicies", "broken-fail",
                      namespace=None)
        make_policy(client, "broken-ignore", "object.spec..bogus(",
                    failure_policy="Ignore")
        client.create("pods", pod("p2"))  # must not raise

    def test_update_operation_scoping(self, server, client):
        make_policy(client, "no-updates", "false",
                    operations=("UPDATE",))
        client.create("pods", pod("p1"))  # CREATE unaffected
        with pytest.raises(APIError):
            got = client.get("pods", "p1")
            got["metadata"]["labels"] = {"x": "y"}
            client.update("pods", got)

    def test_old_object_on_update(self, server, client):
        # scale-down forbidden: oldObject is the live pre-write object
        make_policy(client, "no-scale-down",
                    "oldObject == null || "
                    "object.spec.replicas >= oldObject.spec.replicas",
                    resources=("replicasets",), operations=("UPDATE",))
        client.create("replicasets", {
            "kind": "ReplicaSet", "metadata": {"name": "web"},
            "spec": {"replicas": 3,
                     "template": {"spec": {"containers": [{"name": "c"}]}}}})
        got = client.get("replicasets", "web")
        got["spec"]["replicas"] = 5
        client.update("replicasets", got)  # up is fine
        got = client.get("replicasets", "web")
        got["spec"]["replicas"] = 2
        with pytest.raises(APIError) as e:
            client.update("replicasets", got)
        assert e.value.code == 422

    def test_policy_delete_restores_writes(self, server, client):
        make_policy(client, "temp", "false")
        with pytest.raises(APIError):
            client.create("pods", pod("p1"))
        client.delete("validatingadmissionpolicies", "temp", namespace=None)
        client.create("pods", pod("p1"))


class _Hook(BaseHTTPRequestHandler):
    """Scriptable admission webhook: the test sets `responder` on the
    server object."""

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        review = json.loads(self.rfile.read(length))
        resp = self.server.responder(review)  # type: ignore[attr-defined]
        body = json.dumps({"apiVersion": "admission.k8s.io/v1",
                           "kind": "AdmissionReview",
                           "response": resp}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture()
def hook_server():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Hook)
    httpd.responder = lambda review: {"allowed": True}  # type: ignore[attr-defined]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield httpd
    httpd.shutdown()


def hook_url(httpd):
    return f"http://127.0.0.1:{httpd.server_address[1]}/admit"


class TestWebhooks:
    def test_validating_webhook_denies(self, server, client, hook_server):
        hook_server.responder = lambda review: {
            "allowed": False,
            "status": {"message": "nope from webhook", "code": 403}}
        client.create("validatingwebhookconfigurations", {
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "deny-pods"},
            "webhooks": [{"name": "deny.example.com",
                          "clientConfig": {"url": hook_url(hook_server)},
                          "rules": [{"resources": ["pods"],
                                     "operations": ["CREATE"]}]}]},
            namespace=None)
        with pytest.raises(APIError) as e:
            client.create("pods", pod("p1"))
        assert e.value.code == 403 and "nope from webhook" in str(e.value)
        # unmatched resource passes
        client.create("configmaps", {"kind": "ConfigMap",
                                     "metadata": {"name": "cm"}, "data": {}})

    def test_mutating_webhook_patches(self, server, client, hook_server):
        patch = [{"op": "add", "path": "/metadata/labels",
                  "value": {"injected": "true"}}]
        hook_server.responder = lambda review: {
            "allowed": True, "patchType": "JSONPatch",
            "patch": base64.b64encode(json.dumps(patch).encode()).decode()}
        client.create("mutatingwebhookconfigurations", {
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "label-injector"},
            "webhooks": [{"name": "inject.example.com",
                          "clientConfig": {"url": hook_url(hook_server)},
                          "rules": [{"resources": ["pods"],
                                     "operations": ["CREATE"]}]}]},
            namespace=None)
        client.create("pods", pod("p1"))
        got = client.get("pods", "p1")
        assert got["metadata"]["labels"]["injected"] == "true"

    def test_failure_policy_ignore_on_unreachable(self, server, client):
        client.create("validatingwebhookconfigurations", {
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "gone"},
            "webhooks": [{"name": "gone.example.com",
                          "clientConfig":
                              {"url": "http://127.0.0.1:9/admit"},
                          "timeoutSeconds": 1,
                          "failurePolicy": "Ignore",
                          "rules": [{"resources": ["pods"],
                                     "operations": ["*"]}]}]},
            namespace=None)
        client.create("pods", pod("p1"))  # must not raise

    def test_failure_policy_fail_on_unreachable(self, server, client):
        client.create("validatingwebhookconfigurations", {
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "gone-fail"},
            "webhooks": [{"name": "gone.example.com",
                          "clientConfig":
                              {"url": "http://127.0.0.1:9/admit"},
                          "timeoutSeconds": 1,
                          "rules": [{"resources": ["pods"],
                                     "operations": ["*"]}]}]},
            namespace=None)
        with pytest.raises(APIError) as e:
            client.create("pods", pod("p1"))
        assert e.value.code == 500

    def test_mutating_webhook_on_merge_patch(self, server, client,
                                             hook_server):
        client.create("pods", pod("p1"))
        patch = [{"op": "add", "path": "/metadata/labels/stamped",
                  "value": "yes"}]
        hook_server.responder = lambda review: {
            "allowed": True, "patchType": "JSONPatch",
            "patch": base64.b64encode(json.dumps(patch).encode()).decode()}
        client.create("mutatingwebhookconfigurations", {
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "stamper"},
            "webhooks": [{"name": "stamp.example.com",
                          "clientConfig": {"url": hook_url(hook_server)},
                          "rules": [{"resources": ["pods"],
                                     "operations": ["UPDATE"]}]}]},
            namespace=None)
        client.patch("pods", "p1", {"metadata": {"labels": {"edited": "1"}}})
        got = client.get("pods", "p1")
        assert got["metadata"]["labels"]["edited"] == "1"
        assert got["metadata"]["labels"]["stamped"] == "yes"

    def test_status_patch_skips_webhooks(self, server, client, hook_server):
        client.create("pods", pod("p1"))
        calls = []

        def responder(review):
            calls.append(review["request"]["operation"])
            return {"allowed": False, "status": {"message": "no"}}

        hook_server.responder = responder
        client.create("validatingwebhookconfigurations", {
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "blocker"},
            "webhooks": [{"name": "b.example.com",
                          "clientConfig": {"url": hook_url(hook_server)},
                          "rules": [{"resources": ["pods"],
                                     "operations": ["*"]}]}]},
            namespace=None)
        # status-subresource PATCH must bypass webhooks entirely
        client.request("PATCH",
                       "/api/v1/namespaces/default/pods/p1/status",
                       {"status": {"phase": "Running"}},
                       content_type="application/merge-patch+json")
        assert calls == []
        assert client.get("pods", "p1")["status"]["phase"] == "Running"

    def test_denial_code_clamped_to_error_range(self, server, client,
                                                hook_server):
        # a misbehaving webhook denying with code 200 must still produce
        # an HTTP error, not a success the client mistakes for a create
        hook_server.responder = lambda review: {
            "allowed": False, "status": {"message": "sneaky", "code": 200}}
        client.create("validatingwebhookconfigurations", {
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "sneaky"},
            "webhooks": [{"name": "s.example.com",
                          "clientConfig": {"url": hook_url(hook_server)},
                          "rules": [{"resources": ["pods"],
                                     "operations": ["*"]}]}]},
            namespace=None)
        with pytest.raises(APIError) as e:
            client.create("pods", pod("px"))
        assert 400 <= e.value.code <= 599

    def test_webhook_sees_admission_review(self, server, client,
                                           hook_server):
        seen = {}

        def responder(review):
            seen.update(review["request"])
            return {"allowed": True}

        hook_server.responder = responder
        client.create("validatingwebhookconfigurations", {
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "observer"},
            "webhooks": [{"name": "obs.example.com",
                          "clientConfig": {"url": hook_url(hook_server)},
                          "rules": [{"resources": ["pods"],
                                     "operations": ["*"]}]}]},
            namespace=None)
        client.create("pods", pod("p9"))
        assert seen["operation"] == "Create"
        assert seen["name"] == "p9"
        assert seen["object"]["metadata"]["name"] == "p9"
