"""Unified trace timeline (ISSUE 18): trace-event ring bounds and export
integrity (B/E balanced per track, monotonic ts per tid, batch stage slices
matching the flight record, Perfetto-format required keys), armed/disarmed
placement byte-parity in BOTH watch_coalesce modes with the mutation
detector forced, critical-path component additivity (parts sum to the
span's measured submit→bound latency), evict→replace flow arrows, the
/debug/trace + /debug/critpath endpoints, the schedtrace tracebuf counters,
and `ktl sched why` / `ktl sched trace --export` / the stats trace line."""

import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

import pytest

from kubernetes_tpu.obs import critpath, tracebuf
from kubernetes_tpu.obs.tracebuf import TraceBuffer, validate_export
from kubernetes_tpu.scheduler import Framework
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.flightrec import (
    critpath_snapshot,
    schedtrace_snapshot,
    trace_export,
)
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import (
    MakeNode,
    MakePod,
    mutation_detector_guard,
)
from kubernetes_tpu.utils.tracing import Trace


@pytest.fixture(autouse=True)
def _force_mutation_detector(monkeypatch):
    yield from mutation_detector_guard(monkeypatch)


@pytest.fixture(autouse=True)
def _trace_isolation():
    """Every test starts and ends with no armed or lingering buffer —
    a leaked ACTIVE would tap every other module's schedulers."""
    tracebuf.disarm()
    tracebuf.LAST = None
    yield
    tracebuf.disarm()
    tracebuf.LAST = None


def _nodes(n, cpu="16", mem="64Gi"):
    return [MakeNode(f"node-{i}").capacity(
        {"cpu": cpu, "memory": mem, "pods": "110"}).obj() for i in range(n)]


def _sched(store, **kw):
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=1024, solver="fast", **kw)
    sched.sync()
    return sched


def _placements(store):
    return sorted((p.key, p.spec.node_name, p.metadata.resource_version)
                  for p in store.list("pods")[0] if p.spec.node_name)


# -- ring + event unit surface --------------------------------------------------


class TestRing:
    def test_ring_bounded_under_3x_capacity_churn(self):
        buf = TraceBuffer(capacity=100)
        for i in range(300):
            buf.instant("churn", f"e{i}")
        st = buf.status()
        assert st["trace_events_total"] == 300
        assert st["trace_events_dropped_total"] == 200
        doc = buf.export()
        body = [ev for ev in doc["traceEvents"] if ev["ph"] != "M"]
        assert len(body) == 100
        # the ring keeps the most RECENT window
        assert body[-1]["name"] == "e299"
        assert validate_export(doc)["valid"]

    def test_arm_disarm_and_status(self):
        assert not tracebuf.enabled()
        assert tracebuf.status()["armed"] is False
        buf = tracebuf.arm(capacity=16)
        assert tracebuf.enabled() and tracebuf.ACTIVE is buf
        buf.instant("t", "x")
        assert tracebuf.status()["trace_events_total"] == 1
        got = tracebuf.disarm()
        assert got is buf and not tracebuf.enabled()
        # the finished capture stays readable (LAST serves /debug/trace)
        assert tracebuf.current() is buf
        assert tracebuf.status()["armed"] is False
        assert tracebuf.status()["trace_events_total"] == 1

    def test_disabled_check_is_one_attribute_load(self):
        ns = tracebuf.disabled_check_cost_ns(n=20_000, passes=3)
        assert 0.0 < ns < 10_000  # nanoseconds per check, not micro

    def test_batch_slices_sum_to_stage_seconds(self):
        buf = TraceBuffer(capacity=1000)
        stages = {"ingest": 0.001, "solve": 0.040, "assume": 0.002,
                  "dispatch": 0.0005}
        t_end = time.perf_counter()
        buf.note_batch("sched", t_end=t_end, stages=stages, pods=50,
                       scheduled=50, outcome="scheduled", solver="fast")
        doc = buf.export()
        slices = [ev for ev in doc["traceEvents"]
                  if ev["ph"] == "X" and ev["cat"] == "stage"]
        assert {ev["name"] for ev in slices} == set(stages)
        total_us = sum(ev["dur"] for ev in slices)
        assert total_us == pytest.approx(sum(stages.values()) * 1e6,
                                         rel=1e-6)
        # the B/E envelope spans exactly the stage total
        b = next(ev for ev in doc["traceEvents"] if ev["ph"] == "B")
        e = next(ev for ev in doc["traceEvents"] if ev["ph"] == "E")
        assert e["ts"] - b["ts"] == pytest.approx(total_us, rel=1e-6)
        assert b["args"]["pods"] == 50

    def test_breaker_transition_emits_instant_once(self):
        buf = TraceBuffer(capacity=100)
        t = time.perf_counter()
        for i, state in enumerate((None, "open", "open", None)):
            buf.note_batch("sched", t_end=t + i, stages={"solve": 0.01},
                           pods=1, scheduled=1, outcome="scheduled",
                           solver="fast", breaker=state)
        names = [ev["name"] for ev in buf.export()["traceEvents"]
                 if ev["ph"] == "i"]
        assert names == ["breaker:closed->open", "breaker:open->closed"]

    def test_validate_catches_unbalanced_and_ts_regression(self):
        bad = {"traceEvents": [
            {"name": "b", "ph": "B", "ts": 2.0, "pid": 1, "tid": 1},
            {"name": "i", "ph": "i", "ts": 1.0, "pid": 1, "tid": 1},
        ]}
        res = validate_export(bad)
        assert not res["valid"]
        assert any("unbalanced" in e for e in res["errors"])
        assert any("regressed" in e for e in res["errors"])
        assert not validate_export({"traceEvents": [{"ph": "X"}]})["valid"]
        assert not validate_export({})["valid"]

    def test_every_event_carries_required_keys(self):
        buf = TraceBuffer(capacity=100)
        buf.note_batch("sched", t_end=time.perf_counter(),
                       stages={"solve": 0.01}, pods=1, scheduled=1,
                       outcome="scheduled", solver="fast")
        buf.instant("chaos", "fault:x")
        buf.counter("resource", "memory", {"rss_mb": 10.0})
        buf.note_span("bind", "bind_chunk", 0.0, 0.001, cat="bind")
        for ev in buf.export()["traceEvents"]:
            for field in ("name", "ph", "ts", "pid", "tid"):
                assert field in ev, ev


# -- critical-path decomposition ------------------------------------------------


def _span(window=0, **stamps_ms):
    total = stamps_ms.get("bind_confirmed")
    return {"pod": f"ns/p-{id(stamps_ms) % 97}", "window": window,
            "pops": 1, "complete": True, "t0": 100.0,
            "stamps_ms": dict(stamps_ms, enqueue=0.0),
            "submit_to_bound_ms": total, "submit_to_running_ms": None}


class TestCritPath:
    def test_components_sum_exactly_to_submit_to_bound(self):
        table = {"tensorize": {"total_ms": 10.0},
                 "build_pod_batch": {"total_ms": 30.0},
                 "solve": {"total_ms": 60.0}}
        ratio = critpath.build_ratio(table)
        assert ratio == pytest.approx(0.4)
        sp = _span(pop=2.0, solve=12.0, assume=13.0, dispatch=13.5,
                   bind_confirmed=16.0, watch_delivered=18.0)
        comps = critpath.decompose(sp, ratio)
        core = {k: v for k, v in comps.items() if k != "watch"}
        assert sum(core.values()) == pytest.approx(16.0, abs=1e-9)
        assert comps["build"] == pytest.approx((12.0 - 2.0) * 0.4)
        assert comps["watch"] == pytest.approx(2.0)

    def test_missing_stamps_fold_into_next_edge(self):
        # no assume/dispatch stamps: bind absorbs the whole tail, the sum
        # property survives
        sp = _span(pop=1.0, solve=5.0, bind_confirmed=9.0)
        comps = critpath.decompose(sp, 0.0)
        assert "assume" not in comps and "dispatch" not in comps
        core = {k: v for k, v in comps.items() if k != "watch"}
        assert sum(core.values()) == pytest.approx(9.0)

    def test_unbound_span_skipped(self):
        assert critpath.decompose({"stamps_ms": {"enqueue": 0.0},
                                   "submit_to_bound_ms": None}, 0.0) is None

    def test_analyze_groups_by_window_and_names_dominant(self):
        spans = [_span(window=0, pop=50.0, solve=55.0, bind_confirmed=60.0)
                 for _ in range(10)]
        spans += [_span(window=1, pop=1.0, solve=40.0, bind_confirmed=42.0)
                  for _ in range(10)]
        out = critpath.analyze(spans)
        assert out["spans_analyzed"] == 20
        assert out["windows"][0]["dominant"] == "queue_wait"
        assert out["windows"][1]["dominant"] == "solve"
        for roll in out["windows"].values():
            # mean additivity is exact; p50 within the 10% acceptance band
            assert roll["sum_mean_ms"] == pytest.approx(
                roll["total_mean_ms"], rel=1e-9)
            assert roll["sum_p50_ms"] == pytest.approx(
                roll["total_p50_ms"], rel=0.10)
        share = out["overall"]["dominant_share"]
        assert share is not None and 0.0 < share <= 1.0


# -- armed/disarmed placement parity (both watch_coalesce modes) ----------------


@pytest.mark.parametrize("coalesce", [True, False])
def test_armed_disarmed_placement_byte_parity(coalesce):
    """Arming the trace buffer must never steer scheduling: placements,
    resource versions and store dumps are byte-identical with the buffer
    armed vs disarmed, in BOTH watch_coalesce modes, with the mutation
    detector forced (autouse)."""
    def run(armed):
        buf = None
        if armed:
            buf = tracebuf.arm(capacity=50_000)
        else:
            tracebuf.disarm()
            tracebuf.LAST = None
        try:
            store = APIStore()
            for n in _nodes(8):
                store.create("nodes", n)
            sched = _sched(store, columnar=coalesce)
            sched.watch_coalesce = coalesce
            store.create_many("pods", [
                MakePod(f"p-{i}").req({"cpu": "200m", "memory": "256Mi"})
                .obj() for i in range(128)], consume=True)
            sched.run_until_idle()
            sched.flush_binds()
            store.check_mutations()
            return _placements(store), sched.scheduled_count, buf
        finally:
            tracebuf.disarm()
    on_placed, on_count, buf = run(True)
    off_placed, off_count, _none = run(False)
    assert on_count == off_count == 128
    assert on_placed == off_placed
    # the armed leg actually captured the window
    assert buf is not None and buf.events_total > 0
    res = validate_export(buf.export())
    assert res["valid"], res["errors"]


# -- end-to-end: capture, critpath, flows, endpoints, CLI -----------------------


def _e2e_capture(n_pods=96):
    tracebuf.arm(capacity=50_000)
    store = APIStore()
    for n in _nodes(6):
        store.create("nodes", n)
    sched = _sched(store)
    store.create_many("pods", [
        MakePod(f"p-{i}").req({"cpu": "100m"}).obj()
        for i in range(n_pods)], consume=True)
    sched.run_until_idle()
    sched.flush_binds()
    return store, sched


def test_e2e_stage_slices_match_flight_record():
    _store, sched = _e2e_capture()
    doc = tracebuf.ACTIVE.export()
    slice_ms = sum(ev["dur"] for ev in doc["traceEvents"]
                   if ev["ph"] == "X" and ev.get("cat") == "stage") / 1000.0
    rec_ms = sum(sum(r["stages"].values()) for r in sched.flightrec.records())
    assert rec_ms > 0
    # same source dict (clock.stages), so only ms-rounding separates them
    assert slice_ms == pytest.approx(rec_ms, rel=0.02, abs=0.5)
    res = validate_export(doc)
    assert res["valid"], res["errors"]


def test_e2e_critpath_sums_within_tolerance():
    _store, sched = _e2e_capture()
    spans = [sp for sp in sched.podtrace.snapshot()["spans"]
             if sp.get("submit_to_bound_ms") is not None]
    assert spans
    ratio = critpath.build_ratio(sched.flightrec.stage_table())
    for sp in spans:
        comps = critpath.decompose(sp, ratio)
        core = sum(v for k, v in comps.items() if k != "watch")
        assert core == pytest.approx(sp["submit_to_bound_ms"], abs=0.01)
    out = critpath.analyze(spans, stage_table=sched.flightrec.stage_table())
    overall = out["overall"]
    assert overall["dominant"] in critpath.COMPONENTS
    assert overall["sum_mean_ms"] == pytest.approx(
        overall["total_mean_ms"], rel=1e-6, abs=0.02)
    # the acceptance band: quantile sums within 10%
    assert overall["sum_p50_ms"] == pytest.approx(
        overall["total_p50_ms"], rel=0.10, abs=0.5)
    assert overall["sum_p99_ms"] == pytest.approx(
        overall["total_p99_ms"], rel=0.10, abs=0.5)


def test_e2e_evict_replace_flow_arrows():
    tracebuf.arm(capacity=50_000)
    store = APIStore()
    for n in _nodes(6):
        store.create("nodes", n)
    sched = _sched(store)
    owner = [{"kind": "ReplicaSet", "name": "rs-flow", "uid": "u-rs-flow"}]
    first = []
    for i in range(8):
        p = MakePod(f"flow-{i}").req({"cpu": "100m"}).obj()
        p.metadata.owner_references = [dict(r) for r in owner]
        first.append(p)
    store.create_many("pods", first, consume=True)
    sched.run_until_idle()
    sched.flush_binds()
    for p in first[:4]:
        store.delete("pods", p.key)
    sched.run_until_idle()  # DELETED events -> podtrace.note_deleted
    reps = []
    for i in range(4):
        p = MakePod(f"flow-rep-{i}").req({"cpu": "100m"}).obj()
        p.metadata.owner_references = [dict(r) for r in owner]
        reps.append(p)
    store.create_many("pods", reps, consume=True)
    sched.run_until_idle()
    sched.flush_binds()
    spans = sched.podtrace.snapshot()["spans"]
    assert any(sp.get("replaces") for sp in spans)
    doc = tracebuf.ACTIVE.export(spans=spans)
    res = validate_export(doc)
    assert res["valid"], res["errors"]
    assert res["flow_pairs"] >= 1
    flows = [ev for ev in doc["traceEvents"] if ev["ph"] in ("s", "f")]
    assert all(ev["name"] == "replace" for ev in flows)


def test_log_if_long_lands_on_armed_buffer():
    buf = tracebuf.arm(capacity=1000)
    tr = Trace("SlowPath", pods=3)
    tr.step("first")
    tr.step("second", n=2)
    assert tr.log_if_long(0.0)
    names = [ev["name"] for ev in buf.export()["traceEvents"]
             if ev.get("cat") == "slowtrace"]
    assert names == ["SlowPath:first", "SlowPath:second"]
    # disarmed: same call is log-only (no buffer, no error)
    tracebuf.disarm()
    assert Trace("SlowPath").log_if_long(0.0)


def test_snapshot_counters_endpoints_and_cli(tmp_path):
    from kubernetes_tpu.cli.ktl import main as ktl_main
    from kubernetes_tpu.server import APIServer

    store, sched = _e2e_capture(n_pods=32)
    srv = APIServer(store).start()
    try:
        name = sched._bind_origin
        snap = schedtrace_snapshot()
        tb = snap[name]["tracebuf"]
        assert tb["armed"] is True
        assert tb["trace_events_total"] > 0
        assert tb["trace_events_dropped_total"] == 0
        assert sched.sched_stats()["tracebuf"]["armed"] is True
        with urllib.request.urlopen(f"{srv.url}/debug/trace") as resp:
            doc = json.loads(resp.read())
        res = validate_export(doc)
        assert res["valid"], res["errors"]
        with urllib.request.urlopen(f"{srv.url}/debug/critpath") as resp:
            cp = json.loads(resp.read())
        assert cp[name]["overall"]["dominant"] in critpath.COMPONENTS
        # ktl sched why: per-window dominant component table
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert ktl_main(["--server", srv.url, "sched", "why"]) == 0
        out = buf.getvalue()
        assert "dominant" in out and cp[name]["overall"]["dominant"] in out
        # ktl sched stats: the one-line trace status
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert ktl_main(["--server", srv.url, "sched", "stats"]) == 0
        assert "trace: armed" in buf.getvalue()
        # ktl sched trace --export: writes a Perfetto-loadable file
        dest = tmp_path / "trace.json"
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert ktl_main(["--server", srv.url, "sched", "trace",
                             "--export", str(dest)]) == 0
        exported = json.loads(dest.read_text())
        assert validate_export(exported)["valid"]
        assert str(dest) in buf.getvalue()
    finally:
        srv.stop()


def test_trace_export_unarmed_is_graceful():
    doc = trace_export()
    assert doc["traceEvents"] == []
    assert "error" in doc
    assert critpath_snapshot() == {} or isinstance(critpath_snapshot(), dict)
