"""ISSUE 15: columnar pod-row store — columnar-vs-dict byte-parity suite.

The columnar path (store/columnar.py + APIStore._bind_many_columnar) must be
BYTE-IDENTICAL to the dict store it accelerates: same placements, same RV
sequence, same event streams (per-object AND coalesced, lazy slots included)
across BOTH watch_coalesce modes, with the mutation detector forced (autouse
below). Plus: the lazy-row/lazy-event steady-state contract (zero
materialization until something reads), the native columnar prepare loop's
parity with its Python oracle, the ChaosChurn leg (native.commit /
store.bind_many faults against the columnar store: conservation clean,
mid-batch failure leaves the columns untouched), the no-numpy /
STORE_COLUMNAR=0 fallbacks, the nodes lock shard's runtime rank check, and
the bounded-history / resume-below-floor relist contract (ISSUE 15
satellites)."""

import json

import numpy as np
import pytest

from kubernetes_tpu.api.serialize import to_dict
from kubernetes_tpu.native import hostcommit
from kubernetes_tpu.store import (APIStore, CoalescedEvent, LazyBindBatch,
                                  ResourceVersionTooOldError)
from kubernetes_tpu.store import columnar as columnar_mod
from kubernetes_tpu.testing import (MakeNode, MakePod, assert_pod_conservation,
                                    mutation_detector_guard)


@pytest.fixture(autouse=True)
def _force_mutation_detector(monkeypatch):
    yield from mutation_detector_guard(monkeypatch)


NATIVE = hostcommit.available()
needs_native = pytest.mark.skipif(
    not NATIVE, reason="native commit engine unavailable (no g++?)")


def _dump(obj):
    return json.dumps(to_dict(obj), sort_keys=True, default=repr)


def _pods(n, prefix="p"):
    out = []
    for i in range(n):
        p = MakePod(f"{prefix}-{i}").req({"cpu": "100m",
                                          "memory": "64Mi"}).obj()
        p.metadata.uid = f"uid-{prefix}-{i}"
        out.append(p)
    return out


def _event_sig(ev):
    return (type(ev).__name__, ev.type, ev.kind, ev.resource_version,
            _dump(ev.obj), _dump(ev.prev) if ev.prev is not None else None)


def _stream_sig(watch):
    out = []
    for ev in watch.drain():
        if isinstance(ev, CoalescedEvent):
            out.append(("coalesced", ev.type, ev.kind, ev.resource_version,
                        ev.origin, tuple(_event_sig(e) for e in ev.events)))
        else:
            out.append(_event_sig(ev))
    return out


def _store_with_watchers(columnar, native=NATIVE):
    store = APIStore(columnar=columnar, native_commit=native)
    per_obj = store.watch(kind=("pods",))
    coal = store.watch(kind=("pods",), coalesce=True)
    return store, per_obj, coal


# ---------------------------------------------------------------------------
# store-level byte parity: columnar vs dict
# ---------------------------------------------------------------------------


def _bind_workload(columnar, native):
    """The full store-level workload: creates, a bind batch with every error
    class (missing pod, duplicate key within one batch — the commit-phase
    re-validate, a full re-bind attempt), a status write and a delete on a
    columnar-bound row, then rows + both event streams + a late replay."""
    store, per_obj, coal = _store_with_watchers(columnar, native)
    store.create_many("pods", _pods(64), consume=True)
    per_obj.drain(), coal.drain()
    rv0 = store.rv
    triples = [("default", f"p-{i}", f"node-{i % 7}") for i in range(64)]
    triples.append(("default", "p-3", "node-9"))   # dup: raced re-check
    triples.append(("default", "ghost", "node-0"))  # missing
    bound, errors = store.bind_many(triples, origin="t")
    bound2, errors2 = store.bind_many(triples[:4], origin="t")  # all bound
    store.update_pod_status("default", "p-5",
                            lambda st: setattr(st, "phase", "Running"))
    n_del, del_errs = store.delete_pods(
        ["default/p-0", "default/p-1", "default/nope"], origin="t")
    rows = sorted((p.key, _dump(p)) for p in store.list("pods")[0])
    late = store.watch(kind=("pods",), since_rv=rv0)
    out = (rv0, store.rv, bound, sorted(errors), bound2, sorted(errors2),
           n_del, sorted(del_errs), rows, _stream_sig(per_obj),
           _stream_sig(coal), _stream_sig(late))
    store.check_mutations()
    return out


@pytest.mark.skipif(np is None, reason="numpy required for the columnar path")
def test_bind_many_parity_columnar_vs_dict():
    a = _bind_workload(columnar=True, native=False)
    b = _bind_workload(columnar=False, native=False)
    assert a == b
    assert a[2] == 64 and len(a[3]) == 2  # bound, the two injected errors


@needs_native
def test_bind_many_parity_native_vs_python_columnar_prepare():
    """The C columnar prepare loop (hostcommit.cpp hc_columnar_prepare) vs
    its Python oracle (PodColumns.bind_prepare): identical everything."""
    a = _bind_workload(columnar=True, native=True)
    b = _bind_workload(columnar=True, native=False)
    assert a == b


@pytest.mark.parametrize("mode", ["eager", "share"])
def test_non_lazy_stores_fall_back_to_dict_path(mode):
    """The columnar commit is written against the lazy/deep-copy event
    contract; eager (STORE_LAZY_POD_EVENTS=0) and share
    (deep_copy_on_write=False) stores must run the dict path end to end —
    the `columnar` property says so, and binds still work."""
    store = APIStore(
        columnar=True,
        lazy_pod_events=(False if mode == "eager" else None),
        deep_copy_on_write=(mode != "share"),
        mutation_detector=(False if mode == "share" else None))
    assert store.columnar is False
    assert store.pod_columns() is None and store.columnar_stats() is None
    store.create_many("pods", _pods(8, "f"), consume=True)
    bound, errors = store.bind_many(
        [("default", f"f-{i}", "node-0") for i in range(8)])
    assert bound == 8 and not errors


def test_no_numpy_fallback(monkeypatch):
    """A rig without numpy runs the pure dict path end to end (the
    acceptance's no-numpy leg)."""
    monkeypatch.setattr(columnar_mod, "np", None)
    store = APIStore(columnar=True)
    assert store.columnar is False
    store.create_many("pods", _pods(4, "nn"), consume=True)
    bound, errors = store.bind_many(
        [("default", f"nn-{i}", "node-1") for i in range(4)])
    assert bound == 4 and not errors
    assert store.get("pods", "default/nn-0").spec.node_name == "node-1"


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("STORE_COLUMNAR", "0")
    assert APIStore().columnar is False
    monkeypatch.setenv("STORE_COLUMNAR", "1")
    assert APIStore().columnar is (np is not None)


# ---------------------------------------------------------------------------
# lazy-row / lazy-event steady-state contract
# ---------------------------------------------------------------------------


def test_steady_state_is_lazy_and_len_is_o1():
    """With only a coalescing watcher subscribed (the scheduler steady
    state), a columnar bind batch materializes NOTHING: the coalesced
    item's events support len() without building per-object events, and the
    store's dict rows stay untouched until a read reconciles them."""
    store = APIStore(mutation_detector=False)  # detector would force-eager
    if not store.columnar:
        pytest.skip("columnar path unavailable")
    coal = store.watch(kind=("pods",), coalesce=True)
    store.create_many("pods", _pods(32, "s"), consume=True)
    coal.drain()
    bound, errors = store.bind_many(
        [("default", f"s-{i}", f"node-{i % 3}") for i in range(32)],
        origin="me")
    assert bound == 32 and not errors
    (cev,) = [c for c in coal.drain() if c.type == "MODIFIED"]
    assert len(cev.events) == 32  # O(1): no materialization yet
    st = store.columnar_stats()
    assert st["diverged"] == 32 and st["materialized_total"] == 0
    batch = cev.events._batch
    assert isinstance(batch, LazyBindBatch) and batch._mat is None
    # first iteration materializes ONCE for every consumer
    evs = list(cev.events)
    assert evs[0].obj.spec.node_name == "node-0"
    assert evs[0].prev is not None and not evs[0].prev.spec.node_name
    assert list(cev.events)[0] is evs[0]
    # row materialization is independent and also at-most-once
    p = store.get("pods", "default/s-1")
    assert p.spec.node_name == "node-1"
    st = store.columnar_stats()
    assert st["diverged"] == 31 and st["materialized_total"] == 1


def test_rv_watermark_without_materialization():
    """CoalescedEvent.resource_version (the watch watermark) and the batch's
    contiguous rv range come from the columns, not from event objects."""
    store = APIStore(mutation_detector=False)
    if not store.columnar:
        pytest.skip("columnar path unavailable")
    coal = store.watch(kind=("pods",), coalesce=True)
    store.create_many("pods", _pods(10, "r"), consume=True)
    coal.drain()
    rv0 = store.rv
    store.bind_many([("default", f"r-{i}", "n") for i in range(10)],
                    origin="me")
    (cev,) = coal.drain()
    assert cev.resource_version == rv0 + 10 == store.rv
    evs = list(cev.events)
    assert [e.resource_version for e in evs] == list(range(rv0 + 1,
                                                           rv0 + 11))


def test_replay_mid_batch_expands_partially():
    """A watch resumed from an rv INSIDE a columnar batch's range replays
    exactly the tail of the batch (per-object, private clones)."""
    store = APIStore()
    store.create_many("pods", _pods(8, "m"), consume=True)
    rv0 = store.rv
    store.bind_many([("default", f"m-{i}", "n") for i in range(8)],
                    origin="me")
    mid = rv0 + 3
    w = store.watch(kind=("pods",), since_rv=mid)
    evs = w.drain()
    assert [e.resource_version for e in evs] == list(range(mid + 1,
                                                           rv0 + 9))
    for ev in evs:
        assert ev.obj.spec.node_name == "n"
        stored = store.get("pods", ev.obj.key)
        assert _dump(ev.obj) == _dump(stored)
    store.check_mutations()


def test_materialized_rows_keep_signature_memo_refs():
    """The signature-ref column contract (snapshot/tensorizer.py
    SIG_MEMO_KEYS): admission-primed memos in pod.__dict__ survive the lazy
    bind-clone materialization, so a resync/rebuild after a columnar bind
    storm keeps its class-signature dict hits."""
    store = APIStore(mutation_detector=False)
    if not store.columnar:
        pytest.skip("columnar path unavailable")
    pods = _pods(4, "g")
    sig = ("class", "sig")
    for p in pods:
        p.__dict__["_class_sig"] = (p.spec, p.metadata.labels, sig)
    store.create_many("pods", pods, consume=True)
    view = store.pod_columns()
    assert all(s[0] is not None for s in view.sig[:4])
    store.bind_many([("default", f"g-{i}", "n") for i in range(4)],
                    origin="me")
    assert store.get("pods", "default/g-0").spec.node_name == "n"
    live = store._objects["pods"]["default/g-0"]  # the materialized row
    assert live.spec.node_name == "n"
    assert live.__dict__["_class_sig"][2] is sig


def test_pod_columns_view_is_read_only():
    """The MU001 runtime complement: the view's numpy members refuse
    writes."""
    store = APIStore()
    if not store.columnar:
        pytest.skip("columnar path unavailable")
    store.create_many("pods", _pods(3, "v"), consume=True)
    view = store.pod_columns()
    assert view.n == 3 and int((view.node_id >= 0).sum()) == 0
    with pytest.raises(ValueError):
        view.node_id[0] = 3
    with pytest.raises(ValueError):
        view.row_rv[0] = 99
    # hot scalar columns carry what the scheduler reads
    assert view.keys[:3] == [f"default/v-{i}" for i in range(3)]
    assert list(view.priority[:3]) == [0, 0, 0]


def test_columnar_row_lifecycle_create_update_delete():
    """Column coherence across the dict-path writes: update/status/delete
    on columnar rows (incl. re-create reusing a freed row)."""
    store = APIStore()
    if not store.columnar:
        pytest.skip("columnar path unavailable")
    store.create_many("pods", _pods(4, "lc"), consume=True)
    store.bind_many([("default", "lc-0", "n-0")], origin="me")
    # update on a DIVERGED row: materializes first, then syncs columns
    cur = store.get("pods", "default/lc-0")
    cur.metadata.labels["x"] = "1"
    store.update("pods", cur)
    view = store.pod_columns()
    row = view.keys.index("default/lc-0")
    assert view.node_id[row] >= 0 and not view.diverged[row]
    # delete frees the row; re-create reuses it with fresh column state
    store.delete("pods", "default/lc-1")
    st0 = store.columnar_stats()
    p_new = MakePod("lc-new").req({"cpu": "100m"}).obj()
    store.create("pods", p_new)
    st1 = store.columnar_stats()
    assert st1["rows"] == st0["rows"] + 1 and st1["free"] == st0["free"] - 1
    # single bind on a clean row stays dict-path but syncs the columns
    store.bind("default", "lc-new", "n-9")
    view = store.pod_columns()
    row = view.keys.index("default/lc-new")
    assert view.node_names[view.node_id[row]] == "n-9"
    assert not view.diverged[row]
    store.check_mutations()


# ---------------------------------------------------------------------------
# chaos: the ChaosChurn columnar leg
# ---------------------------------------------------------------------------


@needs_native
def test_chaos_native_commit_fault_leaves_columns_untouched():
    """native.commit fires in the columnar phase gap — rows validated,
    NOTHING committed — so a mid-chunk fault leaves the columns (diverged
    bitmap, node ids, rv) and the dict rows exactly as before; a plain
    retry succeeds."""
    from kubernetes_tpu.chaos import faultinject as fi

    store, per_obj, coal = _store_with_watchers(columnar=True)
    assert store.columnar
    store.create_many("pods", _pods(16, "c"), consume=True)
    per_obj.drain(), coal.drain()
    rv0 = store.rv
    fi.arm([fi.FaultPlan("native.commit", "fail", count=1)])
    try:
        with pytest.raises(fi.FaultInjected):
            store.bind_many([("default", f"c-{i}", "node-0")
                             for i in range(16)])
        assert store.rv == rv0  # nothing committed
        st = store.columnar_stats()
        assert st["diverged"] == 0 and st["bound"] == 0
        assert not per_obj.drain() and not coal.drain()
        assert all(not p.spec.node_name
                   for p in store.list("pods")[0])
        bound, errors = store.bind_many(
            [("default", f"c-{i}", "node-0") for i in range(16)])
        assert bound == 16 and not errors
    finally:
        fi.disarm()
    store.check_mutations()


def test_chaos_bind_many_fault_against_columnar_store():
    """store.bind_many faults (pre-lock transient) against the columnar
    store: the caller's retry sees an untouched store."""
    from kubernetes_tpu.chaos import faultinject as fi

    store = APIStore()
    store.create_many("pods", _pods(8, "bf"), consume=True)
    rv0 = store.rv
    fi.arm([fi.FaultPlan("store.bind_many", "fail", count=1)])
    try:
        with pytest.raises(fi.FaultInjected):
            store.bind_many([("default", f"bf-{i}", "n") for i in range(8)])
        assert store.rv == rv0
        bound, errors = store.bind_many(
            [("default", f"bf-{i}", "n") for i in range(8)])
        assert bound == 8 and not errors
    finally:
        fi.disarm()


def test_chaos_e2e_conservation_columnar():
    """The ChaosChurn columnar leg: native.commit + store.bind_many faults
    under the real bind worker against a columnar store — the supervised
    retry absorbs them, every pod still binds exactly once (conservation
    report reads the flattened history through history_events)."""
    from kubernetes_tpu.chaos import faultinject as fi
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins

    store = APIStore()
    if not store.columnar:
        pytest.skip("columnar path unavailable")
    for i in range(8):
        store.create("nodes", MakeNode(f"node-{i}").capacity(
            {"cpu": "16", "memory": "64Gi", "pods": "110"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=256, solver="fast",
                           bind_retry_base_s=0.01)
    sched.bind_chunk = 64
    sched.sync()
    pods = _pods(256, "cc")
    keys = [p.key for p in pods]
    store.create_many("pods", pods, consume=True)
    plans = [fi.FaultPlan("store.bind_many", "fail", count=1)]
    if NATIVE:
        plans.append(fi.FaultPlan("native.commit", "fail", count=2))
    fi.arm(plans)
    try:
        sched.run_until_idle()
    finally:
        fi.disarm()
    sched.run_until_idle()
    sched.flush_binds()
    assert_pod_conservation(store, sched, keys)
    assert sched.scheduled_count == 256
    store.check_mutations()


# ---------------------------------------------------------------------------
# scheduler e2e byte parity, both coalesce modes
# ---------------------------------------------------------------------------


@pytest.mark.skipif(np is None, reason="numpy required for the columnar path")
@pytest.mark.parametrize("coalesce", [True, False])
def test_e2e_placement_parity_columnar_vs_dict(coalesce, monkeypatch):
    """The whole pipeline — ingest, build_pod_batch, solve, assume, bind —
    with the columnar store on vs off must produce byte-identical
    placements and store dumps, in BOTH watch_coalesce modes, with the
    mutation detector forced (autouse)."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins

    def run(columnar):
        store = APIStore(columnar=columnar)
        assert store.columnar is columnar
        for i in range(16):
            store.create("nodes", MakeNode(f"node-{i}").capacity(
                {"cpu": "16", "memory": "64Gi", "pods": "110"}).obj())
        sched = BatchScheduler(store, Framework(default_plugins()),
                               batch_size=1024, solver="fast",
                               columnar=coalesce)
        sched.watch_coalesce = coalesce
        sched.sync()
        store.create_many("pods", _pods(512, "e"), consume=True)
        sched.run_until_idle()
        pods, rv = store.list("pods")
        placements = sorted((p.key, p.spec.node_name,
                             p.metadata.resource_version) for p in pods)
        dump = sorted(_dump(p) for p in pods)
        transitions = {}
        for ev in store.history_events():
            if ev.kind == "pods" and ev.type == "MODIFIED" \
                    and ev.obj.spec.node_name \
                    and (ev.prev is None or not ev.prev.spec.node_name):
                transitions[ev.obj.key] = transitions.get(ev.obj.key, 0) + 1
        store.check_mutations()
        stats = sched.sched_stats()["store_columnar"]
        assert (stats is not None) is columnar
        return placements, rv, dump, sched.scheduled_count, transitions

    got_col = run(True)
    got_dict = run(False)
    assert got_col == got_dict
    assert got_col[3] == 512
    assert all(n == 1 for n in got_col[4].values())


# ---------------------------------------------------------------------------
# satellites: bounded history + relist contract, nodes shard runtime rank
# ---------------------------------------------------------------------------


def test_history_limit_bounded_default_and_relist_contract():
    """ISSUE 15 satellite: the 200k-event watch-replay leak must be
    impossible to reintroduce by forgetting the kwarg — the default bound
    is a few churn waves, not unlimited; a resume below the floor raises
    ResourceVersionTooOldError and the contractual relist+rewatch (fresh
    LIST rv) recovers."""
    s = APIStore()
    assert 0 < s._history_limit <= 50_000
    s._history_limit = 64  # time-compress the wave for the test
    s.create_many("pods", _pods(48, "h"), consume=True)
    rv_early = s.rv
    s.bind_many([("default", f"h-{i}", "n") for i in range(48)], origin="me")
    s.delete_pods([f"default/h-{i}" for i in range(48)], origin="me")
    assert s._history_n <= 64 + 1
    with pytest.raises(ResourceVersionTooOldError):
        s.watch(kind=("pods",), since_rv=1)
    # the relist contract: LIST, then watch from the returned rv
    _pods_now, rv = s.list("pods")
    w = s.watch(kind=("pods",), since_rv=rv)
    s.create("pods", MakePod("h-new").obj())
    evs = w.drain()
    assert [e.type for e in evs] == ["ADDED"]
    assert rv_early < s._history_floor_rv <= s.rv
    s.check_mutations()


def test_nodes_shard_runtime_rank_check():
    """The _OrderedRLock companion of the generalized LK001: ascending-rank
    acquisition is legal (pods -> nodes), descending raises."""
    from kubernetes_tpu.store import LockOrderViolation

    s = APIStore(lock_order_check=True)
    with s._lock:
        with s._pods_lock:
            with s._nodes_lock:
                pass
    with s._pods_lock:
        with s._nodes_lock:  # ascending, legal without the global lock
            pass
    with pytest.raises(LockOrderViolation):
        with s._nodes_lock:
            with s._pods_lock:
                pass
    with pytest.raises(LockOrderViolation):
        with s._nodes_lock:
            with s._lock:
                pass


def test_nodes_shard_concurrent_with_pod_bind_phase():
    """The point of the nodes shard: node reads/writes proceed while pod
    traffic runs, and the sharded ops' results stay correct (list_many
    takes the full chain for a consistent multi-kind snapshot)."""
    s = APIStore()
    s.create("nodes", MakeNode("n-0").capacity({"cpu": "8"}).obj())
    s.create_many("pods", _pods(4, "nx"), consume=True)
    n = s.get("nodes", "n-0")
    assert n.metadata.name == "n-0"
    lists, rv = s.list_many(("pods", "nodes"))
    assert len(lists["pods"]) == 4 and len(lists["nodes"]) == 1
    with s.transaction("nodes"):
        cur = s.get("nodes", "n-0")
        s.update("nodes", cur)
    with s.transaction():  # full chain, any sequence is safe under it
        s.get("pods", "default/nx-0")
        s.get("nodes", "n-0")
    assert s.rv > rv
