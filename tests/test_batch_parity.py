"""Batch-solver vs serial-oracle parity (SURVEY.md §4 'parity tier').

The greedy scan solver must produce the same assignment, pod by pod, as the
serial scheduler run over the same store contents in the same order — exact
parity, since both use identical integer formulas and lowest-index tie-breaks.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.scheduler import Framework, Scheduler
from kubernetes_tpu.scheduler.batch import BatchScheduler
from kubernetes_tpu.scheduler.plugins import default_plugins
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakeNode, MakePod


def run_one(cls, nodes, pods, solver=None, preload=()):
    """Build a store (preloaded pods are pre-bound state), run one scheduler
    class to idle, return the store."""
    store = APIStore()
    for n in nodes:
        store.create("nodes", n)
    for p in preload:
        store.create("pods", p)
    for p in pods:
        store.create("pods", p)
    kwargs = {"solver": solver} if solver else {}
    sched = cls(store, Framework(default_plugins()), **kwargs)
    sched.sync()
    sched.run_until_idle()
    return store


def run_both(nodes, pods, solver=None):
    """Run serial and batch schedulers over identical stores; return the two
    {pod name: node name} assignment maps."""
    results = []
    for cls in (Scheduler, BatchScheduler):
        store = run_one(cls, nodes, pods,
                        solver=solver if cls is BatchScheduler else None)
        got, _ = store.list("pods")
        results.append({p.metadata.name: p.spec.node_name for p in got})
    return results


def assert_parity(nodes, pods):
    serial, batch = run_both(nodes, pods)
    assert serial == batch, (
        "serial vs batch divergence:\n" +
        "\n".join(f"  {k}: serial={serial[k]!r} batch={batch[k]!r}"
                  for k in serial if serial[k] != batch[k])
    )
    return serial


class TestParity:
    def test_basic_fit_spread(self):
        nodes = [MakeNode(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj() for i in range(8)]
        pods = [MakePod(f"p{i}").req({"cpu": "1", "memory": "2Gi"}).obj() for i in range(24)]
        got = assert_parity(nodes, pods)
        assert all(v for v in got.values())

    def test_heterogeneous_nodes_and_requests(self):
        rng = random.Random(42)
        nodes = [
            MakeNode(f"n{i}").capacity({
                "cpu": str(rng.choice([2, 4, 8, 16])),
                "memory": f"{rng.choice([4, 8, 32])}Gi",
                "pods": str(rng.choice([5, 110])),
            }).obj()
            for i in range(12)
        ]
        pods = [
            MakePod(f"p{i}").req({
                "cpu": f"{rng.choice([100, 250, 500, 1000, 3000])}m",
                "memory": f"{rng.choice([128, 512, 2048])}Mi",
            }).priority(rng.choice([0, 0, 10])).obj()
            for i in range(40)
        ]
        assert_parity(nodes, pods)

    def test_overcommit_some_unschedulable(self):
        nodes = [MakeNode(f"n{i}").capacity({"cpu": "2"}).obj() for i in range(3)]
        pods = [MakePod(f"p{i}").req({"cpu": "1500m"}).obj() for i in range(6)]
        got = assert_parity(nodes, pods)
        assert sum(1 for v in got.values() if v) == 3
        assert sum(1 for v in got.values() if not v) == 3

    def test_best_effort_pods(self):
        # exercises non-zero defaults in scoring + balanced-allocation skip
        nodes = [MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj() for i in range(4)]
        pods = [MakePod(f"p{i}").req({}).obj() for i in range(10)]
        assert_parity(nodes, pods)

    def test_node_selector_and_affinity(self):
        nodes = []
        for i in range(6):
            labels = {"disk": "ssd" if i % 2 == 0 else "hdd", "zone": f"z{i % 3}"}
            nodes.append(MakeNode(f"n{i}").labels(labels).capacity({"cpu": "8"}).obj())
        pods = []
        for i in range(6):
            pods.append(MakePod(f"sel{i}").node_selector({"disk": "ssd"}).req({"cpu": "500m"}).obj())
        for i in range(4):
            pods.append(MakePod(f"aff{i}").node_affinity_in("zone", ["z0", "z1"])
                        .req({"cpu": "500m"}).obj())
        for i in range(4):
            pods.append(MakePod(f"pref{i}").preferred_node_affinity(10, "disk", ["hdd"])
                        .req({"cpu": "500m"}).obj())
        got = assert_parity(nodes, pods)
        for i in range(6):
            assert int(got[f"sel{i}"][1:]) % 2 == 0  # ssd nodes only

    def test_taints_and_tolerations(self):
        nodes = [
            MakeNode("tainted1").taints([{"key": "gpu", "value": "true", "effect": "NoSchedule"}])
            .capacity({"cpu": "8"}).obj(),
            MakeNode("soft").taints([{"key": "old", "value": "1", "effect": "PreferNoSchedule"}])
            .capacity({"cpu": "8"}).obj(),
            MakeNode("clean").capacity({"cpu": "8"}).obj(),
        ]
        pods = [MakePod(f"plain{i}").req({"cpu": "500m"}).obj() for i in range(4)]
        pods += [MakePod(f"tol{i}").toleration("gpu", "true", effect="NoSchedule")
                 .req({"cpu": "500m"}).obj() for i in range(2)]
        got = assert_parity(nodes, pods)
        for i in range(4):
            assert got[f"plain{i}"] != "tainted1"

    def test_unschedulable_and_node_name(self):
        nodes = [
            MakeNode("cordoned").unschedulable().capacity({"cpu": "8"}).obj(),
            MakeNode("open").capacity({"cpu": "8"}).obj(),
        ]
        pinned = MakePod("pinned").req({"cpu": "1"}).obj()
        pinned.spec.node_name = ""  # stays pending; use NodeName via spec? builder lacks it
        pods = [MakePod(f"p{i}").req({"cpu": "500m"}).obj() for i in range(3)]
        got = assert_parity(nodes, pods)
        assert all(v == "open" for k, v in got.items() if v)

    def test_host_ports(self):
        nodes = [MakeNode(f"n{i}").capacity({"cpu": "8"}).obj() for i in range(3)]
        pods = [MakePod(f"p{i}").req({"cpu": "100m"}, host_port=8080).obj() for i in range(4)]
        got = assert_parity(nodes, pods)
        assert sum(1 for v in got.values() if v) == 3  # one per node, 4th conflicts

    def test_image_locality(self):
        big = 800 * 1024 * 1024
        nodes = [MakeNode("warm").images({"model-server:latest": big}).capacity({"cpu": "8"}).obj(),
                 MakeNode("cold1").capacity({"cpu": "8"}).obj(),
                 MakeNode("cold2").capacity({"cpu": "8"}).obj()]
        pods = [MakePod(f"p{i}").req({"cpu": "100m"}).container("model-server:latest").obj()
                for i in range(2)]
        assert_parity(nodes, pods)

    def test_topology_spread_do_not_schedule(self):
        nodes = []
        for i in range(6):
            nodes.append(MakeNode(f"n{i}").labels(
                {"topology.kubernetes.io/zone": f"z{i % 3}"}).capacity({"cpu": "16"}).obj())
        pods = [
            MakePod(f"w{i}").labels({"app": "web"}).req({"cpu": "100m"})
            .topology_spread(1, "topology.kubernetes.io/zone", "DoNotSchedule", {"app": "web"})
            .obj()
            for i in range(12)
        ]
        got = assert_parity(nodes, pods)
        # perfectly spreadable: 4 per zone
        zones = {}
        for p, n in got.items():
            z = int(n[1:]) % 3
            zones[z] = zones.get(z, 0) + 1
        assert sorted(zones.values()) == [4, 4, 4]

    def test_topology_spread_schedule_anyway_scoring(self):
        nodes = []
        for i in range(4):
            nodes.append(MakeNode(f"n{i}").labels(
                {"topology.kubernetes.io/zone": "a" if i < 2 else "b"})
                .capacity({"cpu": "16"}).obj())
        pods = [
            MakePod(f"w{i}").labels({"app": "w"}).req({"cpu": "100m"})
            .topology_spread(1, "topology.kubernetes.io/zone", "ScheduleAnyway", {"app": "w"})
            .obj()
            for i in range(8)
        ]
        assert_parity(nodes, pods)

    def test_mixed_constraints_stress(self):
        rng = random.Random(7)
        nodes = []
        for i in range(10):
            labels = {"topology.kubernetes.io/zone": f"z{i % 4}", "tier": rng.choice(["a", "b"])}
            n = MakeNode(f"n{i}").labels(labels).capacity(
                {"cpu": "8", "memory": "16Gi", "pods": "20"})
            if i % 5 == 0:
                n = n.taints([{"key": "spot", "value": "true", "effect": "NoSchedule"}])
            nodes.append(n.obj())
        pods = []
        for i in range(30):
            p = MakePod(f"p{i}").labels({"grp": f"g{i % 3}"}).req({
                "cpu": f"{rng.choice([100, 500, 1000])}m",
                "memory": f"{rng.choice([256, 1024])}Mi"})
            if i % 3 == 0:
                p = p.topology_spread(2, "topology.kubernetes.io/zone", "DoNotSchedule",
                                      {"grp": f"g{i % 3}"})
            if i % 4 == 0:
                p = p.toleration("spot", "true", effect="NoSchedule")
            if i % 7 == 0:
                p = p.preferred_node_affinity(5, "tier", ["a"])
            pods.append(p.obj())
        assert_parity(nodes, pods)

    def test_interpod_affinity_falls_back_to_serial(self):
        # IPA classes route through the serial oracle inside BatchScheduler,
        # so results still match the pure serial run.
        nodes = [MakeNode(f"n{i}").capacity({"cpu": "8"}).obj() for i in range(3)]
        pods = [MakePod(f"w{i}").labels({"app": "web"}).req({"cpu": "100m"})
                .pod_anti_affinity("kubernetes.io/hostname", {"app": "web"}).obj()
                for i in range(3)]
        got = assert_parity(nodes, pods)
        assert len({v for v in got.values()}) == 3


def test_large_backlog_fully_scheduled_through_capped_pumps():
    """A backlog far above the per-pump event cap must still be fully
    scheduled: pump_events beyond the cap leaves events buffered for the
    next cycle instead of dropping them (the 100k north-star truncation)."""
    from kubernetes_tpu.scheduler import Framework
    from kubernetes_tpu.scheduler.batch import BatchScheduler
    from kubernetes_tpu.scheduler.plugins import default_plugins
    from kubernetes_tpu.store import APIStore
    from kubernetes_tpu.testing import MakeNode, MakePod

    store = APIStore()
    for i in range(200):
        store.create("nodes", MakeNode(f"node-{i}").capacity(
            {"cpu": "64", "memory": "256Gi", "pods": "200"}).obj())
    sched = BatchScheduler(store, Framework(default_plugins()),
                           batch_size=30_000, solver="fast")
    sched.sync()
    n = 25_000  # far above the 10k per-pump cap
    for i in range(n):
        store.create("pods", MakePod(f"b-{i}").req({"cpu": "100m"}).obj())
    sched.run_until_idle()
    sched.flush_binds()
    assert sched.scheduled_count == n
