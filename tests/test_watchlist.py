"""WatchList streaming (KEP-3157; reflector.go:121-143): LIST rides the
watch stream as initial ADDED events ending in an annotated bookmark."""

import json
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.server import APIServer, Informer, RESTClient
from kubernetes_tpu.store import APIStore
from kubernetes_tpu.testing import MakePod


@pytest.fixture()
def server():
    srv = APIServer(APIStore()).start()
    yield srv
    srv.stop()


class TestWatchListServer:
    def test_initial_events_then_end_bookmark_then_live(self, server):
        store = server.store
        for i in range(3):
            store.create("pods", MakePod(f"pre-{i}").obj())
        req = urllib.request.Request(
            f"{server.url}/api/v1/namespaces/default/pods?watch=true"
            f"&resourceVersion=-1&sendInitialEvents=true")
        resp = urllib.request.urlopen(req, timeout=10)
        seen = []
        end_rv = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            line = resp.readline()
            if not line.strip():
                continue
            ev = json.loads(line)
            meta = ev["object"].get("metadata") or {}
            if ev["type"] == "BOOKMARK":
                anns = meta.get("annotations") or {}
                if anns.get("k8s.io/initial-events-end") == "true":
                    end_rv = int(meta["resourceVersion"])
                    break
            else:
                seen.append((ev["type"], meta.get("name")))
        assert seen == [("ADDED", "pre-0"), ("ADDED", "pre-1"),
                        ("ADDED", "pre-2")]
        assert end_rv is not None and end_rv >= 3
        # live events continue on the SAME stream
        store.create("pods", MakePod("live").obj())
        deadline = time.monotonic() + 10
        got_live = False
        while time.monotonic() < deadline:
            line = resp.readline()
            if not line.strip():
                continue
            ev = json.loads(line)
            if ev["type"] == "ADDED" and \
                    ev["object"]["metadata"]["name"] == "live":
                got_live = True
                break
        assert got_live
        resp.close()


class TestWatchListInformer:
    def test_informer_primes_without_list(self, server):
        store = server.store
        for i in range(5):
            store.create("pods", MakePod(f"p{i}").obj())
        events = []
        inf = Informer(RESTClient(server.url), "pods",
                       on_event=lambda t, o: events.append(
                           (t, o.metadata.name)),
                       watch_list=True)
        inf.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(inf.cache) < 5:
            time.sleep(0.05)
        assert len(inf.cache) == 5
        store.create("pods", MakePod("new").obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                ("ADDED", "new") not in events:
            time.sleep(0.05)
        assert ("ADDED", "new") in events
        # initial sync emitted MODIFIED/ADDED swap deltas, not raw replays
        inf.stop()

    def test_informer_resyncs_after_disconnect(self, server):
        """A severed stream reconnects through a fresh initial-events sync:
        the cache converges to post-outage state with synthetic deltas, no
        spurious MODIFIED for untouched survivors."""
        store = server.store
        store.create("pods", MakePod("keep").obj())
        store.create("pods", MakePod("doomed").obj())
        events = []
        inf = Informer(RESTClient(server.url), "pods",
                       on_event=lambda t, o: events.append(
                           (t, o.metadata.name)),
                       watch_list=True)
        inf.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(inf.cache) < 2:
            time.sleep(0.05)
        assert len(inf.cache) == 2
        # sever every live stream mid-flight (the mux keeps serving new
        # connections; the client must reconnect + re-sync)
        with server._mux._lock:
            for st in server._mux._streams:
                st.sock.close()
        store.delete("pods", "default/doomed")
        store.create("pods", MakePod("born").obj())
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if "default/born" in inf.cache and \
                    "default/doomed" not in inf.cache:
                break
            time.sleep(0.05)
        assert "default/born" in inf.cache
        assert "default/doomed" not in inf.cache
        assert ("DELETED", "doomed") in events
        assert ("ADDED", "born") in events
        # 'keep' never changed: the resync must not replay it as MODIFIED
        assert ("MODIFIED", "keep") not in events
        inf.stop()
